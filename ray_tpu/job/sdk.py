"""JobSubmissionClient: the user-facing job API.

Design analog: reference ``dashboard/modules/job/sdk.py:40`` -- but instead
of REST against the dashboard, it connects to the cluster directly (the
control plane is the GCS; no separate HTTP tier is required for parity of
capability).
"""

from __future__ import annotations

from typing import Dict, List, Optional

import ray_tpu
from ray_tpu.job.job_manager import JobInfo, JobManager


class JobSubmissionClient:
    def __init__(self, address: Optional[str] = None):
        if not ray_tpu.is_initialized():
            ray_tpu.init(address=address)
        self._manager = JobManager()

    def submit_job(self, *, entrypoint: str,
                   submission_id: Optional[str] = None,
                   runtime_env: Optional[dict] = None,
                   metadata: Optional[Dict[str, str]] = None) -> str:
        env = dict((runtime_env or {}).get("env_vars", {}))
        norm = None
        if runtime_env:
            # Normalize driver-side: local working_dir/py_modules upload
            # to the GCS KV by content here so the (possibly remote)
            # supervisor can materialize them anywhere.
            from ray_tpu.runtime_env import normalize_runtime_env
            norm = normalize_runtime_env(runtime_env)
        return self._manager.submit_job(
            entrypoint, submission_id=submission_id, env=env,
            metadata=metadata, runtime_env=norm)

    def get_job_status(self, submission_id: str) -> str:
        return self._manager.get_job_status(submission_id)

    def get_job_info(self, submission_id: str) -> JobInfo:
        return self._manager.get_job_info(submission_id)

    def get_job_logs(self, submission_id: str) -> str:
        return self._manager.get_job_logs(submission_id)

    def stop_job(self, submission_id: str) -> bool:
        return self._manager.stop_job(submission_id)

    def list_jobs(self) -> List[JobInfo]:
        return self._manager.list_jobs()

    def wait_until_finished(self, submission_id: str,
                            timeout: float = 300.0) -> str:
        import time
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            status = self.get_job_status(submission_id)
            if status in ("SUCCEEDED", "FAILED", "STOPPED"):
                return status
            time.sleep(1.0)
        raise TimeoutError(
            f"job {submission_id} not finished after {timeout}s")
