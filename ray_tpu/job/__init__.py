"""Job submission: run driver scripts as supervised subprocesses on the
cluster.

Design analog: reference ``dashboard/modules/job/`` -- JobManager
(job_manager.py:490), JobSupervisor actor (job_manager.py:136),
JobSubmissionClient (sdk.py:40).
"""

from ray_tpu.job.job_manager import (JobManager, JobStatus, JobInfo)
from ray_tpu.job.sdk import JobSubmissionClient

__all__ = ["JobManager", "JobStatus", "JobInfo", "JobSubmissionClient"]
