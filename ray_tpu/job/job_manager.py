"""JobManager + JobSupervisor: cluster-side job execution.

Design analog: reference ``dashboard/modules/job/job_manager.py`` --
JobManager:490 (submit_job -> supervisor actor, status in GCS KV) and
JobSupervisor:136 (detached actor running the entrypoint as a subprocess,
polling it to a terminal status).

The entrypoint subprocess gets ``RT_ADDRESS`` pointing at the cluster, so a
driver script that calls ``ray_tpu.init()`` joins the same cluster it was
submitted to.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import tempfile
import time
import uuid
from dataclasses import dataclass, field
from typing import Dict, List, Optional

import ray_tpu
from ray_tpu._private import kv

JOB_NS = "job_submission"


class JobStatus:
    PENDING = "PENDING"
    RUNNING = "RUNNING"
    SUCCEEDED = "SUCCEEDED"
    FAILED = "FAILED"
    STOPPED = "STOPPED"

    TERMINAL = (SUCCEEDED, FAILED, STOPPED)


@dataclass
class JobInfo:
    submission_id: str
    entrypoint: str
    status: str = JobStatus.PENDING
    message: str = ""
    start_time: float = 0.0
    end_time: float = 0.0
    metadata: Dict[str, str] = field(default_factory=dict)

    def to_json(self) -> bytes:
        return json.dumps(self.__dict__).encode()

    @classmethod
    def from_json(cls, raw: bytes) -> "JobInfo":
        return cls(**json.loads(raw))


def _put_info(info: JobInfo):
    kv.kv_put(info.submission_id.encode(), info.to_json(), ns=JOB_NS)


def _get_info(submission_id: str) -> Optional[JobInfo]:
    raw = kv.kv_get(submission_id.encode(), ns=JOB_NS)
    return JobInfo.from_json(raw) if raw else None


@ray_tpu.remote(num_cpus=0)
class _JobSupervisor:
    """Detached actor supervising one entrypoint subprocess.

    Reference job_manager.py:136: the supervisor lives on the cluster so the
    job outlives the submitting client; logs stream to a file the client can
    poll."""

    def __init__(self, submission_id: str, entrypoint: str,
                 env: Optional[Dict[str, str]], log_path: str,
                 runtime_env: Optional[dict] = None):
        self.submission_id = submission_id
        self.entrypoint = entrypoint
        self.log_path = log_path
        self.proc: Optional[subprocess.Popen] = None
        penv = dict(os.environ)
        penv.update(env or {})
        penv["RT_ADDRESS"] = os.environ["RT_GCS_ADDRESS"]
        penv["RT_JOB_SUBMISSION_ID"] = submission_id
        # The supervisor worker imports ray_tpu via its runtime sys.path
        # (RT_DRIVER_SYS_PATH); a child python only sees PYTHONPATH, so
        # materialize the import path for the entrypoint driver.
        extra = [p for p in sys.path if p]
        if penv.get("PYTHONPATH"):
            extra.append(penv["PYTHONPATH"])
        penv["PYTHONPATH"] = os.pathsep.join(extra)
        cwd = None
        if runtime_env:
            # Job-level runtime env (reference: ray job submit
            # --runtime-env): working_dir becomes the entrypoint's cwd,
            # py_modules/pip site dirs prepend its PYTHONPATH, env_vars
            # merge — the same normalized/content-addressed layout the
            # worker path uses (runtime_env/runtime_env.py).
            import tempfile

            from ray_tpu.runtime_env.runtime_env import PKG_NS, materialize
            from ray_tpu._private.worker import get_core

            def _kv_get(key):
                return get_core().gcs_request(
                    {"type": "kv_get", "ns": PKG_NS, "key": key})

            mat = materialize(runtime_env, _kv_get, os.path.join(
                tempfile.gettempdir(), "rt_runtime_env"))
            if mat["paths"]:
                penv["PYTHONPATH"] = os.pathsep.join(
                    list(mat["paths"]) + [penv["PYTHONPATH"]])
            cwd = mat["workdir"] or None
            penv.update(runtime_env.get("env_vars", {}))
        self._cwd = cwd
        self._log_f = open(log_path, "wb")
        info = _get_info(submission_id) or JobInfo(submission_id, entrypoint)
        info.status = JobStatus.RUNNING
        info.start_time = time.time()
        _put_info(info)
        self.proc = subprocess.Popen(
            entrypoint, shell=True, env=penv, cwd=self._cwd,
            stdout=self._log_f, stderr=subprocess.STDOUT,
            start_new_session=True)

    def poll(self) -> str:
        """Advance state; returns current status."""
        info = _get_info(self.submission_id)
        if info.status in JobStatus.TERMINAL:
            return info.status
        rc = self.proc.poll()
        if rc is None:
            return JobStatus.RUNNING
        info.status = JobStatus.SUCCEEDED if rc == 0 else JobStatus.FAILED
        info.message = f"exit code {rc}"
        info.end_time = time.time()
        _put_info(info)
        self._log_f.flush()
        return info.status

    def stop(self) -> bool:
        if self.proc.poll() is None:
            # Kill the whole process group (entrypoint may spawn children).
            try:
                os.killpg(os.getpgid(self.proc.pid), 15)
            except Exception:
                self.proc.terminate()
            try:
                self.proc.wait(timeout=10)
            except subprocess.TimeoutExpired:
                try:
                    os.killpg(os.getpgid(self.proc.pid), 9)
                except Exception:
                    self.proc.kill()
        info = _get_info(self.submission_id)
        if info.status not in JobStatus.TERMINAL:
            info.status = JobStatus.STOPPED
            info.end_time = time.time()
            _put_info(info)
        return True

    def logs(self) -> bytes:
        self._log_f.flush()
        try:
            with open(self.log_path, "rb") as f:
                return f.read()
        except FileNotFoundError:
            return b""


class JobManager:
    """Client-side orchestration of supervisor actors (runs in any process
    connected to the cluster)."""

    def submit_job(self, entrypoint: str, *,
                   submission_id: Optional[str] = None,
                   env: Optional[Dict[str, str]] = None,
                   metadata: Optional[Dict[str, str]] = None,
                   runtime_env: Optional[dict] = None) -> str:
        submission_id = submission_id or f"rtjob_{uuid.uuid4().hex[:10]}"
        if _get_info(submission_id) is not None:
            raise ValueError(f"job {submission_id} already exists")
        log_path = os.path.join(tempfile.gettempdir(),
                                f"rt_job_{submission_id}.log")
        _put_info(JobInfo(submission_id, entrypoint,
                          metadata=dict(metadata or {})))
        sup = _JobSupervisor.options(
            name=f"_rt_job_supervisor_{submission_id}",
            lifetime="detached",
        ).remote(submission_id, entrypoint, env, log_path,
                 runtime_env=runtime_env)
        # Surface immediate spawn failures synchronously.
        ray_tpu.get(sup.poll.remote(), timeout=60)
        return submission_id

    def _supervisor(self, submission_id: str):
        try:
            return ray_tpu.get_actor(f"_rt_job_supervisor_{submission_id}")
        except Exception:
            return None

    def get_job_status(self, submission_id: str) -> str:
        sup = self._supervisor(submission_id)
        if sup is not None:
            try:
                return ray_tpu.get(sup.poll.remote(), timeout=30)
            except Exception:
                pass
        info = _get_info(submission_id)
        if info is None:
            raise ValueError(f"no such job {submission_id}")
        # Supervisor gone without a terminal status = it died under us.
        if info.status not in JobStatus.TERMINAL:
            info.status = JobStatus.FAILED
            info.message = "supervisor died"
            _put_info(info)
        return info.status

    def get_job_info(self, submission_id: str) -> JobInfo:
        self.get_job_status(submission_id)
        return _get_info(submission_id)

    def get_job_logs(self, submission_id: str) -> str:
        sup = self._supervisor(submission_id)
        if sup is None:
            return ""
        return ray_tpu.get(sup.logs.remote(), timeout=30).decode(
            "utf-8", "replace")

    def stop_job(self, submission_id: str) -> bool:
        sup = self._supervisor(submission_id)
        if sup is None:
            return False
        return ray_tpu.get(sup.stop.remote(), timeout=30)

    def list_jobs(self) -> List[JobInfo]:
        out = []
        for key in kv.kv_keys(ns=JOB_NS):
            raw = kv.kv_get(key, ns=JOB_NS)
            if raw:
                out.append(JobInfo.from_json(raw))
        return sorted(out, key=lambda j: j.start_time)
