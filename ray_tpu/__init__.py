"""ray_tpu: a TPU-native distributed computing and ML framework.

Public core API mirrors the reference's (``python/ray/__init__.py``):
init/shutdown, @remote, get/put/wait, actors, placement groups -- built on a
from-scratch runtime (see _private/) designed for JAX/XLA on Cloud TPU.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence, Union

from ray_tpu import exceptions  # noqa: F401
from ray_tpu._private.object_ref import (ObjectRef,  # noqa: F401
                                         ObjectRefGenerator,
                                         StreamingObjectRefGenerator)
from ray_tpu._private.worker import global_worker
from ray_tpu.actor import (ActorClass, ActorHandle,  # noqa: F401
                           exit_actor, method)
from ray_tpu.runtime_context import get_runtime_context  # noqa: F401
from ray_tpu.remote_function import RemoteFunction

__version__ = "0.1.0"


def init(address: Optional[str] = None, **kwargs) -> dict:
    """Start (or connect to) a cluster. See Worker.init for options."""
    return global_worker.init(address, **kwargs)


def shutdown():
    global_worker.shutdown()


def is_initialized() -> bool:
    return global_worker.connected


def cancel(ref, *, force: bool = False) -> bool:
    """Best-effort cancel of the task producing ``ref``; its ``get``
    raises TaskCancelledError (reference: ray.cancel).

    Normal tasks: pending ones never start; running ones get a
    KeyboardInterrupt on their execution thread; ``force=True`` kills the
    worker process.  Actor calls: cancellable while queued / resolving
    args / awaiting an async method; a sync method already executing
    cannot be interrupted, and ``force=True`` raises ValueError (use
    ``ray_tpu.kill`` to destroy the actor itself)."""
    from ray_tpu._private.worker import get_core
    return get_core().cancel_task(ref, force=force)


def remote(*args, **kwargs):
    """Decorator turning a function into a RemoteFunction or a class into an
    ActorClass.  Usable bare (@remote) or with options (@remote(num_cpus=2)).
    """
    if len(args) == 1 and not kwargs and (
        callable(args[0]) or isinstance(args[0], type)
    ):
        target = args[0]
        if isinstance(target, type):
            return ActorClass(target)
        return RemoteFunction(target)
    if args:
        raise TypeError("@remote takes keyword options only")

    def decorator(target):
        if isinstance(target, type):
            return ActorClass(target, kwargs)
        return RemoteFunction(target, kwargs)

    return decorator


def put(value: Any) -> ObjectRef:
    from ray_tpu._private.worker import get_core
    return get_core().put(value)


def get(refs: Union[ObjectRef, Sequence[ObjectRef]],
        *, timeout: Optional[float] = None):
    from ray_tpu._private.worker import get_core
    core = get_core()
    if isinstance(refs, ObjectRef):
        return core.get([refs], timeout)[0]
    if not isinstance(refs, (list, tuple)):
        raise TypeError(f"get() expects an ObjectRef or a list, got {type(refs)}")
    if not refs:
        return []
    return core.get(list(refs), timeout)


def wait(refs: List[ObjectRef], *, num_returns: int = 1,
         timeout: Optional[float] = None, fetch_local: bool = True):
    """Readiness is metadata-only (deciding 'ready' never moves value
    bytes); fetch_local=True (the reference's default) additionally starts
    pulling ready remote objects to this node in the background so a
    following get() is warm."""
    from ray_tpu._private.worker import get_core
    if not isinstance(refs, list):
        raise TypeError("wait() expects a list of ObjectRefs")
    if num_returns > len(refs):
        raise ValueError("num_returns exceeds the number of refs")
    return get_core().wait(refs, num_returns, timeout,
                           fetch_local=fetch_local)


def kill(actor: ActorHandle, *, no_restart: bool = True):
    from ray_tpu._private.worker import get_core
    get_core().kill_actor(actor._actor_id, no_restart)


def get_actor(name: str, namespace: Optional[str] = None) -> ActorHandle:
    from ray_tpu._private.worker import get_core
    info = get_core().get_named_actor(
        name, namespace or global_worker.namespace)
    if info is None:
        raise ValueError(f"no live actor named '{name}'")
    return ActorHandle(info["actor_id"], name,
                       _method_meta=info.get("method_meta") or {})


def cluster_resources() -> Dict[str, float]:
    from ray_tpu._private.worker import get_core
    return get_core().gcs_request({"type": "cluster_resources"})["total"]


def available_resources() -> Dict[str, float]:
    from ray_tpu._private.worker import get_core
    return get_core().gcs_request({"type": "cluster_resources"})["available"]


def nodes() -> List[dict]:
    from ray_tpu._private.worker import get_core
    return get_core().gcs_request({"type": "get_nodes"})


def timeline(filename=None):
    """Chrome-trace JSON of task executions (reference: `ray timeline`)."""
    from ray_tpu.util.state import timeline as _tl
    return _tl(filename)


def usage_report() -> dict:
    """Local-only usage snapshot (reference usage_lib without the
    phone-home); also written to the log dir at shutdown unless
    RT_USAGE_STATS=0."""
    from ray_tpu._private.usage_stats import usage_report as _ur
    return _ur()


# ray_tpu.util is part of the public surface (reference: `ray.util` is
# importable off the bare `import ray`); imported last to avoid cycles.
from ray_tpu import util  # noqa: E402,F401
