"""Simulated multi-node cluster on one machine, for tests.

Design analog: reference ``python/ray/cluster_utils.py`` (Cluster:99,
add_node:165) -- the mechanism behind all of the reference's "multi-node"
tests: real GCS + per-node daemons as separate local processes, each with its
own worker pool, resource pool, and object store segment.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import tempfile
import time
import uuid
from typing import Dict, List, Optional


class ClusterNode:
    def __init__(self, proc: subprocess.Popen, info: dict):
        self.proc = proc
        self.info = info

    @property
    def node_id(self) -> str:
        return self.info["node_id"]

    @property
    def raylet_address(self) -> str:
        return self.info["raylet_address"]

    def kill(self):
        """Hard-kill the node daemon (and its worker subtree via parent-watch)."""
        self.proc.kill()
        self.proc.wait()


class Cluster:
    def __init__(self, initialize_head: bool = True,
                 head_node_args: Optional[dict] = None):
        self.head_node: Optional[ClusterNode] = None
        self.worker_nodes: List[ClusterNode] = []
        self.gcs_address: Optional[str] = None
        self._head_args = dict(head_node_args or {})
        if initialize_head:
            self.head_node = self._start_node(head=True, **self._head_args)
            self.gcs_address = self.head_node.info["gcs_address"]

    @property
    def address(self) -> str:
        return self.gcs_address

    def _start_node(self, head: bool = False, num_cpus: int = 4,
                    resources: Optional[Dict[str, float]] = None,
                    object_store_memory: int = 256 * 1024 * 1024,
                    env: Optional[Dict[str, str]] = None,
                    labels: Optional[Dict[str, str]] = None,
                    gcs_persist_path: Optional[str] = None,
                    gcs_port: int = 0) -> ClusterNode:
        ready_file = os.path.join(
            tempfile.gettempdir(),
            f"rt_node_{os.getpid()}_{uuid.uuid4().hex[:8]}.json")
        res = dict(resources or {})
        res.setdefault("CPU", float(num_cpus))
        cmd = [sys.executable, "-m", "ray_tpu._private.daemon_main",
               "--ready-file", ready_file,
               "--resources", json.dumps(res),
               "--store-capacity", str(object_store_memory),
               "--no-tpu-detect"]
        if labels:
            cmd += ["--labels", json.dumps(labels)]
        if gcs_persist_path:
            cmd += ["--gcs-persist-path", gcs_persist_path]
        if head:
            cmd.append("--head")
            if gcs_port:
                # Fixed GCS port: a restarted head rebinds the same
                # address, so surviving worker raylets can redial it.
                cmd += ["--gcs-port", str(gcs_port)]
        else:
            cmd += ["--gcs-address", self.gcs_address]
        proc_env = dict(os.environ)
        proc_env.update(env or {})
        proc = subprocess.Popen(cmd, env=proc_env)
        deadline = time.monotonic() + 60
        while not os.path.exists(ready_file):
            if proc.poll() is not None:
                raise RuntimeError(f"node daemon exited rc={proc.returncode}")
            if time.monotonic() > deadline:
                raise TimeoutError("node daemon did not become ready")
            time.sleep(0.02)
        with open(ready_file) as f:
            info = json.load(f)
        os.unlink(ready_file)
        return ClusterNode(proc, info)

    def add_node(self, num_cpus: int = 4,
                 resources: Optional[Dict[str, float]] = None,
                 object_store_memory: int = 256 * 1024 * 1024,
                 env: Optional[Dict[str, str]] = None,
                 labels: Optional[Dict[str, str]] = None) -> ClusterNode:
        node = self._start_node(head=False, num_cpus=num_cpus,
                                resources=resources,
                                object_store_memory=object_store_memory,
                                env=env, labels=labels)
        self.worker_nodes.append(node)
        return node

    def restart_head(self) -> ClusterNode:
        """Kill and restart the head daemon with its original args.

        Meaningful for GCS fault-tolerance tests when the head was
        started with an explicit ``gcs_port`` (same address after
        restart) and a ``gcs_persist_path`` (durable tables survive);
        surviving worker raylets then re-register over their
        reconnecting GCS connections without a daemon respawn."""
        assert self.head_node is not None, "cluster has no head"
        self.head_node.kill()
        self.head_node = self._start_node(head=True, **self._head_args)
        self.gcs_address = self.head_node.info["gcs_address"]
        return self.head_node

    def remove_node(self, node: ClusterNode):
        node.kill()
        if node in self.worker_nodes:
            self.worker_nodes.remove(node)

    def wait_for_nodes(self, timeout: float = 30.0) -> int:
        """Block until all started nodes are registered & alive in the GCS."""
        import ray_tpu
        expected = 1 + len(self.worker_nodes)
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            try:
                alive = [n for n in ray_tpu.nodes() if n["alive"]]
                if len(alive) >= expected:
                    return len(alive)
            except Exception:
                pass
            time.sleep(0.1)
        raise TimeoutError(f"expected {expected} alive nodes")

    def shutdown(self):
        for node in self.worker_nodes:
            node.kill()
        self.worker_nodes.clear()
        if self.head_node is not None:
            self.head_node.kill()
            self.head_node = None
