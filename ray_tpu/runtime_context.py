"""Runtime context: introspection of the current task/actor/job.

Design analog: reference ``python/ray/runtime_context.py``
(``RuntimeContext`` behind ``ray.get_runtime_context()``: get_job_id,
get_node_id, get_task_id, get_actor_id, get_worker_id,
get_assigned_resources, was_current_actor_reconstructed).
"""

from __future__ import annotations

from typing import Any, Dict, Optional


class RuntimeContext:
    """Snapshot accessor over the connected CoreWorker + (in a worker)
    the live TaskExecutor."""

    def __init__(self, core, executor):
        self._core = core
        self._executor = executor

    def get_node_id(self) -> str:
        return self._core.node_id_hex

    def get_job_id(self) -> str:
        return self._core.job_id or ""

    def get_task_id(self) -> Optional[str]:
        """Task id while inside a task/actor call, else None."""
        if self._executor is None:
            return None
        return self._executor._current_task_id

    def get_actor_id(self) -> Optional[str]:
        if self._executor is None:
            return None
        return self._executor.actor_id

    def get_worker_id(self) -> str:
        import os
        return f"{self._core.node_id_hex[:8]}-{os.getpid()}"

    @property
    def worker_mode(self) -> str:
        return "worker" if self._executor is not None else "driver"

    def get(self) -> Dict[str, Any]:
        """Whole context as a dict (reference RuntimeContext.get)."""
        return {
            "node_id": self.get_node_id(),
            "job_id": self.get_job_id(),
            "task_id": self.get_task_id(),
            "actor_id": self.get_actor_id(),
            "worker_id": self.get_worker_id(),
            "worker_mode": self.worker_mode,
        }


def get_runtime_context() -> RuntimeContext:
    from ray_tpu._private.worker import get_core
    core = get_core()
    return RuntimeContext(core, getattr(core, "task_executor", None))
