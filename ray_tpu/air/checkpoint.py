"""Checkpoint envelope: dict <-> directory <-> bytes inter-convertible.

Design analog: reference ``python/ray/air/checkpoint.py:63`` (Checkpoint with
from_dict/to_dict/from_directory/to_directory/from_bytes/to_bytes/from_uri).
TPU-first twist: JAX pytrees are first-class -- ``from_pytree``/``to_pytree``
store leaves as .npy files inside the directory form (the sharded-array
equivalent of orbax's layout) so large params never round-trip through
pickle, and device arrays are pulled to host lazily.
"""

from __future__ import annotations

import json
import os
import pickle
import shutil
import tarfile
import tempfile
import uuid
from typing import Any, Dict, Optional

_DICT_FILE = "checkpoint_dict.pkl"
_PYTREE_DIR = "pytree"
_PYTREE_META = "pytree_structure.json"


class Checkpoint:
    """An immutable envelope around a training state snapshot.

    Exactly one of ``_data_dict`` / ``_local_path`` is set; conversions
    materialize the other form on demand (matching the reference's
    dict <-> directory duality).
    """

    def __init__(self, local_path: Optional[str] = None,
                 data_dict: Optional[Dict[str, Any]] = None):
        if (local_path is None) == (data_dict is None):
            raise ValueError(
                "exactly one of local_path / data_dict must be given "
                "(use Checkpoint.from_dict / Checkpoint.from_directory)")
        self._local_path = local_path
        self._data_dict = data_dict

    # -- constructors -----------------------------------------------------
    @classmethod
    def from_dict(cls, data: Dict[str, Any]) -> "Checkpoint":
        if not isinstance(data, dict):
            raise TypeError("from_dict expects a dict")
        return cls(data_dict=dict(data))

    @classmethod
    def from_directory(cls, path: str) -> "Checkpoint":
        if not os.path.isdir(path):
            raise ValueError(f"checkpoint directory not found: {path}")
        return cls(local_path=os.path.abspath(path))

    @classmethod
    def from_bytes(cls, blob: bytes) -> "Checkpoint":
        return cls.from_dict(pickle.loads(blob))

    @classmethod
    def from_pytree(cls, tree: Any, **extra) -> "Checkpoint":
        """Snapshot a JAX pytree (params/opt_state).  Leaves are converted to
        host numpy on materialization, not here, so this is cheap to call
        from inside a train loop."""
        return cls.from_dict({"__pytree__": tree, **extra})

    # -- conversions ------------------------------------------------------
    def to_dict(self) -> Dict[str, Any]:
        if self._data_dict is not None:
            return dict(self._data_dict)
        # Directory form -> dict.
        path = self._local_path
        dict_file = os.path.join(path, _DICT_FILE)
        if os.path.exists(dict_file):
            with open(dict_file, "rb") as f:
                data = pickle.load(f)
        else:
            data = {}
        tree_meta = os.path.join(path, _PYTREE_META)
        if os.path.exists(tree_meta):
            data["__pytree__"] = _load_pytree(path)
        # Any loose user files are exposed by path, not inlined.
        return data

    def to_directory(self, path: Optional[str] = None) -> str:
        if path is None:
            path = os.path.join(tempfile.gettempdir(),
                                f"rt_checkpoint_{uuid.uuid4().hex[:12]}")
        os.makedirs(path, exist_ok=True)
        if self._local_path is not None:
            if os.path.abspath(path) != os.path.abspath(self._local_path):
                _copy_tree(self._local_path, path)
            return path
        data = dict(self._data_dict)
        tree = data.pop("__pytree__", None)
        if tree is not None:
            _save_pytree(tree, path)
        with open(os.path.join(path, _DICT_FILE), "wb") as f:
            pickle.dump(data, f, protocol=pickle.HIGHEST_PROTOCOL)
        return path

    def to_bytes(self) -> bytes:
        return pickle.dumps(self.to_dict(), protocol=pickle.HIGHEST_PROTOCOL)

    def to_pytree(self) -> Any:
        data = self.to_dict()
        if "__pytree__" not in data:
            raise ValueError("checkpoint holds no pytree")
        return data["__pytree__"]

    # -- URI storage ------------------------------------------------------
    @classmethod
    def from_uri(cls, uri: str) -> "Checkpoint":
        """Fetch a checkpoint from URI storage (file://, gs://, ...) into a
        fresh local directory (reference air/checkpoint.py:63 from_uri).
        The directory is reaped at interpreter exit — preemption-retry
        loops calling from_uri repeatedly must not fill local disk."""
        from ray_tpu.air.storage import get_provider
        dest = os.path.join(tempfile.gettempdir(),
                            f"rt_checkpoint_{uuid.uuid4().hex[:12]}")
        get_provider(uri).download_dir(uri, dest)
        _reap_at_exit(dest)
        return cls.from_directory(dest)

    def to_uri(self, uri: str) -> str:
        """Upload the directory form to URI storage and return the URI."""
        from ray_tpu.air.storage import get_provider
        get_provider(uri).upload_dir(self.to_directory(), uri)
        return uri

    # -- misc -------------------------------------------------------------
    @property
    def path(self) -> Optional[str]:
        return self._local_path

    def as_pack(self) -> bytes:
        """Tar the directory form for shipping through the object store."""
        src = self.to_directory()
        with tempfile.NamedTemporaryFile(suffix=".tar", delete=False) as tf:
            tar_path = tf.name
        with tarfile.open(tar_path, "w") as tar:
            tar.add(src, arcname=".")
        with open(tar_path, "rb") as f:
            blob = f.read()
        os.unlink(tar_path)
        return blob

    @classmethod
    def from_pack(cls, blob: bytes) -> "Checkpoint":
        dest = os.path.join(tempfile.gettempdir(),
                            f"rt_checkpoint_{uuid.uuid4().hex[:12]}")
        os.makedirs(dest, exist_ok=True)
        with tempfile.NamedTemporaryFile(suffix=".tar", delete=False) as tf:
            tf.write(blob)
            tar_path = tf.name
        with tarfile.open(tar_path) as tar:
            tar.extractall(dest)  # noqa: S202 - internal blob
        os.unlink(tar_path)
        return cls.from_directory(dest)

    def __repr__(self):
        form = f"dir={self._local_path}" if self._local_path else "dict"
        return f"Checkpoint({form})"

    def __reduce__(self):
        # Serialize through the dict form so checkpoints travel through the
        # object store regardless of which node's filesystem they live on.
        return (Checkpoint.from_dict, (self.to_dict(),))


_REAP_DIRS: list = []


def _reap_at_exit(path: str) -> None:
    if not _REAP_DIRS:
        import atexit

        def _reap():
            for p in _REAP_DIRS:
                shutil.rmtree(p, ignore_errors=True)

        atexit.register(_reap)
    _REAP_DIRS.append(path)


# -- pytree <-> directory ------------------------------------------------

def _save_pytree(tree: Any, path: str):
    import jax
    import numpy as np

    leaves, treedef = jax.tree_util.tree_flatten(tree)
    tree_dir = os.path.join(path, _PYTREE_DIR)
    os.makedirs(tree_dir, exist_ok=True)
    leaf_kinds = []
    for i, leaf in enumerate(leaves):
        arr = np.asarray(leaf)
        np.save(os.path.join(tree_dir, f"leaf_{i}.npy"), arr)
        leaf_kinds.append("array")
    with open(os.path.join(path, _PYTREE_META), "w") as f:
        json.dump({"num_leaves": len(leaves), "leaf_kinds": leaf_kinds}, f)
    with open(os.path.join(tree_dir, "treedef.pkl"), "wb") as f:
        pickle.dump(treedef, f)


def _load_pytree(path: str) -> Any:
    import jax
    import numpy as np

    with open(os.path.join(path, _PYTREE_META)) as f:
        meta = json.load(f)
    tree_dir = os.path.join(path, _PYTREE_DIR)
    with open(os.path.join(tree_dir, "treedef.pkl"), "rb") as f:
        treedef = pickle.load(f)
    leaves = [np.load(os.path.join(tree_dir, f"leaf_{i}.npy"))
              for i in range(meta["num_leaves"])]
    return jax.tree_util.tree_unflatten(treedef, leaves)


def _copy_tree(src: str, dst: str):
    for name in os.listdir(src):
        s, d = os.path.join(src, name), os.path.join(dst, name)
        if os.path.isdir(s):
            shutil.copytree(s, d, dirs_exist_ok=True)
        else:
            shutil.copy2(s, d)
