"""Pluggable URI storage for checkpoints and experiment sync.

Design analog: reference ``python/ray/air/checkpoint.py:63`` (from_uri /
to_uri) + ``python/ray/tune/syncer.py`` (experiment-dir sync to cloud
storage).  On TPU pods, checkpoints that must survive slice preemption live
in object storage — a node-local path dies with the node.

Scheme registry: ``file://`` (and bare paths) copy through the local
filesystem; any other scheme (``gs://``, ``s3://``, ...) goes through an
fsspec-shaped provider if :mod:`fsspec` is importable, else raises with a
clear message.  ``register_storage_provider`` lets deployments plug their
own (e.g. a GCS client wired to pod service credentials).
"""

from __future__ import annotations

import os
import shutil
from typing import Dict, Optional, Tuple

__all__ = [
    "StorageProvider", "LocalFileProvider", "FsspecProvider",
    "get_provider", "register_storage_provider", "parse_uri", "is_uri",
]


def parse_uri(uri: str) -> Tuple[str, str]:
    """'scheme://path' -> (scheme, path); bare paths get scheme 'file'."""
    if "://" in uri:
        scheme, path = uri.split("://", 1)
        return scheme.lower(), path
    return "file", uri


def is_uri(path: Optional[str]) -> bool:
    return bool(path) and "://" in path


class StorageProvider:
    """Directory-granular remote storage interface."""

    def upload_dir(self, local: str, uri: str) -> None:
        raise NotImplementedError

    def download_dir(self, uri: str, local: str) -> None:
        raise NotImplementedError

    def exists(self, uri: str) -> bool:
        raise NotImplementedError

    def delete_dir(self, uri: str) -> None:
        raise NotImplementedError


class LocalFileProvider(StorageProvider):
    """file:// — also the path every test and the sim cluster exercises."""

    @staticmethod
    def _path(uri: str) -> str:
        return parse_uri(uri)[1]

    def upload_dir(self, local: str, uri: str) -> None:
        dest = self._path(uri)
        os.makedirs(dest, exist_ok=True)
        shutil.copytree(local, dest, dirs_exist_ok=True)

    def download_dir(self, uri: str, local: str) -> None:
        src = self._path(uri)
        if not os.path.isdir(src):
            raise FileNotFoundError(f"no checkpoint directory at {uri}")
        os.makedirs(local, exist_ok=True)
        shutil.copytree(src, local, dirs_exist_ok=True)

    def exists(self, uri: str) -> bool:
        return os.path.exists(self._path(uri))

    def delete_dir(self, uri: str) -> None:
        shutil.rmtree(self._path(uri), ignore_errors=True)


class FsspecProvider(StorageProvider):
    """Adapter over fsspec for cloud schemes (gs://, s3://, ...).

    fsspec is not a hard dependency: constructing the provider raises a
    clear ImportError when it (or the scheme's driver) is missing.
    """

    def __init__(self, scheme: str):
        try:
            import fsspec
        except ImportError as e:  # pragma: no cover - env without fsspec
            raise ImportError(
                f"URI scheme '{scheme}://' needs fsspec (or register a "
                f"provider via register_storage_provider)") from e
        self._fs = fsspec.filesystem(scheme)

    def upload_dir(self, local: str, uri: str) -> None:
        self._fs.put(local.rstrip("/") + "/", uri.rstrip("/") + "/",
                     recursive=True)

    def download_dir(self, uri: str, local: str) -> None:
        os.makedirs(local, exist_ok=True)
        self._fs.get(uri.rstrip("/") + "/", local.rstrip("/") + "/",
                     recursive=True)

    def exists(self, uri: str) -> bool:
        return self._fs.exists(uri)

    def delete_dir(self, uri: str) -> None:
        self._fs.rm(uri, recursive=True)


_PROVIDERS: Dict[str, StorageProvider] = {"file": LocalFileProvider()}


def register_storage_provider(scheme: str, provider: StorageProvider) -> None:
    _PROVIDERS[scheme.lower()] = provider


def get_provider(uri: str) -> StorageProvider:
    scheme, _ = parse_uri(uri)
    if scheme not in _PROVIDERS:
        _PROVIDERS[scheme] = FsspecProvider(scheme)
    return _PROVIDERS[scheme]
