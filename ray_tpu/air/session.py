"""Training/tuning session: the worker-side half of the report channel.

Design analog: reference ``python/ray/air/session.py`` (report:41,
get_checkpoint:94, get_world_rank/get_world_size/get_local_rank) backed by
``train/_internal/session.py:63`` (_TrainSession result queue).  Here a
session is a plain object installed per-process (one worker process per
host = one session; no thread juggling needed), and ``report`` enqueues to
whatever transport the installed session provides (Train: queue actor back
to the driver; Tune function-API: in-process queue).
"""

from __future__ import annotations

import threading
from typing import Any, Dict, Optional

from ray_tpu.air.checkpoint import Checkpoint

_session_lock = threading.Lock()
_session: Optional["_SessionBase"] = None


class _SessionBase:
    """Contract every concrete session implements."""

    world_rank: int = 0
    world_size: int = 1
    local_rank: int = 0
    local_world_size: int = 1
    node_rank: int = 0
    trial_name: str = ""
    trial_id: str = ""
    experiment_name: str = ""

    def report(self, metrics: Dict[str, Any],
               checkpoint: Optional[Checkpoint] = None) -> None:
        raise NotImplementedError

    def get_checkpoint(self) -> Optional[Checkpoint]:
        return None


def _set_session(session: Optional[_SessionBase]):
    global _session
    with _session_lock:
        _session = session


def _get_session(warn: bool = True) -> Optional[_SessionBase]:
    return _session


def report(metrics: Dict[str, Any],
           checkpoint: Optional[Checkpoint] = None) -> None:
    """Report metrics (and optionally a checkpoint) for this iteration.
    Must be called inside a train loop / tune function."""
    s = _get_session()
    if s is None:
        raise RuntimeError(
            "session.report() called outside a train/tune session")
    s.report(dict(metrics), checkpoint)


def get_checkpoint() -> Optional[Checkpoint]:
    s = _get_session()
    return s.get_checkpoint() if s else None


def get_world_rank() -> int:
    s = _get_session()
    return s.world_rank if s else 0


def get_world_size() -> int:
    s = _get_session()
    return s.world_size if s else 1


def get_local_rank() -> int:
    s = _get_session()
    return s.local_rank if s else 0


def get_local_world_size() -> int:
    s = _get_session()
    return s.local_world_size if s else 1


def get_node_rank() -> int:
    s = _get_session()
    return s.node_rank if s else 0


def get_trial_name() -> str:
    s = _get_session()
    return s.trial_name if s else ""


def get_trial_id() -> str:
    s = _get_session()
    return s.trial_id if s else ""


def get_experiment_name() -> str:
    s = _get_session()
    return s.experiment_name if s else ""
