"""AIR-style shared trainer/tuner surface.

Design analog: reference ``python/ray/air/`` -- Checkpoint
(air/checkpoint.py:63), ScalingConfig/FailureConfig/CheckpointConfig/
RunConfig (air/config.py:79,483,542,670), session.report (air/session.py:41),
Result (air/result.py).  Re-designed for JAX: checkpoints hold pytrees
natively (flax.serialization msgpack + numpy arrays), ScalingConfig speaks
TPU hosts/chips instead of GPUs.
"""

from ray_tpu.air.checkpoint import Checkpoint
from ray_tpu.air.config import (
    CheckpointConfig,
    FailureConfig,
    RunConfig,
    ScalingConfig,
)
from ray_tpu.air.result import Result
from ray_tpu.air import session

__all__ = [
    "Checkpoint",
    "CheckpointConfig",
    "FailureConfig",
    "RunConfig",
    "ScalingConfig",
    "Result",
    "session",
]
