"""AIR configuration dataclasses.

Design analog: reference ``python/ray/air/config.py`` -- ScalingConfig:79,
FailureConfig:483, CheckpointConfig:542, RunConfig:670.  ScalingConfig is
re-thought for TPU: the schedulable unit is a *host* of a slice (each worker
drives all local chips through one jax process), so ``use_tpu`` +
``chips_per_worker`` replace the reference's fractional-GPU model.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional


@dataclass
class ScalingConfig:
    """How many workers, on what resources, gang-placed how.

    num_workers: one worker actor per host (TPU) or per CPU slot.
    use_tpu: request TPU chips for each worker.
    chips_per_worker: TPU chips each worker drives (4 for a v4 host).
    resources_per_worker: extra custom resources per bundle.
    placement_strategy: PACK/SPREAD/STRICT_PACK/STRICT_SPREAD.
    """

    num_workers: int = 1
    use_tpu: bool = False
    chips_per_worker: int = 0
    resources_per_worker: Optional[Dict[str, float]] = None
    placement_strategy: str = "PACK"
    trainer_resources: Optional[Dict[str, float]] = None

    def bundle(self) -> Dict[str, float]:
        res = dict(self.resources_per_worker or {})
        res.setdefault("CPU", 1.0)
        if self.use_tpu or self.chips_per_worker:
            res["TPU"] = float(self.chips_per_worker or 1)
        return res

    def as_placement_group_bundles(self) -> List[Dict[str, float]]:
        head = dict(self.trainer_resources or {"CPU": 0.0})
        bundles = [b for b in [head] if any(v > 0 for v in b.values())]
        bundles += [self.bundle() for _ in range(self.num_workers)]
        return bundles

    @property
    def num_chips_total(self) -> int:
        return self.num_workers * max(1, self.chips_per_worker) \
            if (self.use_tpu or self.chips_per_worker) else 0


@dataclass
class FailureConfig:
    """max_failures: retries of the whole trial on worker/host loss.
    -1 means infinite (reference semantics, air/config.py:483)."""

    max_failures: int = 0
    fail_fast: bool = False


@dataclass
class CheckpointConfig:
    """num_to_keep: None keeps all. checkpoint_score_attribute orders kept
    checkpoints; checkpoint_frequency applies to class Trainables."""

    num_to_keep: Optional[int] = None
    checkpoint_score_attribute: Optional[str] = None
    checkpoint_score_order: str = "max"
    checkpoint_frequency: int = 0
    checkpoint_at_end: bool = False

    def __post_init__(self):
        if self.checkpoint_score_order not in ("max", "min"):
            raise ValueError("checkpoint_score_order must be 'max' or 'min'")


@dataclass
class RunConfig:
    name: Optional[str] = None
    storage_path: Optional[str] = None
    failure_config: FailureConfig = field(default_factory=FailureConfig)
    checkpoint_config: CheckpointConfig = field(default_factory=CheckpointConfig)
    stop: Optional[Dict[str, Any]] = None
    verbose: int = 1
    log_to_file: bool = False
