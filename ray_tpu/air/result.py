"""Result: the terminal report of a trial/run.

Design analog: reference ``python/ray/air/result.py`` (Result dataclass with
metrics/checkpoint/error/metrics_dataframe).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

from ray_tpu.air.checkpoint import Checkpoint


@dataclass
class Result:
    metrics: Optional[Dict[str, Any]] = None
    checkpoint: Optional[Checkpoint] = None
    error: Optional[Exception] = None
    path: Optional[str] = None
    metrics_history: List[Dict[str, Any]] = field(default_factory=list)

    @property
    def config(self) -> Optional[Dict[str, Any]]:
        return (self.metrics or {}).get("config")

    def __repr__(self):
        keys = sorted((self.metrics or {}).keys())
        return (f"Result(metrics_keys={keys[:8]}, "
                f"checkpoint={self.checkpoint is not None}, "
                f"error={type(self.error).__name__ if self.error else None})")
