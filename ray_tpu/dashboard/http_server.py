"""Dashboard: HTTP/JSON observability endpoint on the head node.

Design analog: reference ``dashboard/`` (DashboardHead head.py:70 + REST
modules + StateAggregator).  Scope here is the REST surface the state CLI
and external monitors consume, plus a dependency-free single-file live UI
at ``/`` (auto-refreshing summary cards + node/actor/job/task tables) in
place of the reference's React client; the JSON endpoints mirror
``ray list ...``/``ray summary`` and Prometheus-style metrics.  Implemented
as a dependency-free asyncio HTTP/1.1 GET server co-hosted with the GCS
(direct in-process table reads, no RPC hop).

Routes:
  GET /api/nodes | /api/actors | /api/tasks | /api/objects
      /api/placement_groups | /api/jobs | /api/cluster_summary
  GET /api/metrics      (Prometheus text exposition)
  GET /                 (live HTML dashboard)
"""

from __future__ import annotations

import asyncio
import json
import logging
import time
from typing import Optional

logger = logging.getLogger(__name__)


class DashboardHttpServer:
    def __init__(self, gcs):
        self.gcs = gcs
        self._server: Optional[asyncio.AbstractServer] = None

    async def start(self, port: int = 0) -> int:
        self._server = await asyncio.start_server(
            self._on_client, host="127.0.0.1", port=port)
        return self._server.sockets[0].getsockname()[1]

    async def close(self):
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()

    # ------------------------------------------------------------- serving

    async def _on_client(self, reader: asyncio.StreamReader,
                         writer: asyncio.StreamWriter):
        try:
            request_line = await asyncio.wait_for(reader.readline(), 10)
            parts = request_line.decode("latin-1").split()
            if len(parts) < 2 or parts[0] != "GET":
                await self._respond(writer, 405, b"method not allowed",
                                    "text/plain")
                return
            path, _, query = parts[1].partition("?")
            # Drain headers (ignored).
            while True:
                line = await asyncio.wait_for(reader.readline(), 10)
                if line in (b"\r\n", b"\n", b""):
                    break
            await self._route(writer, path, query)
        except (asyncio.TimeoutError, ConnectionError, OSError):
            pass
        finally:
            try:
                writer.close()
            except Exception:
                pass

    async def _respond(self, writer, status: int, body: bytes,
                       ctype: str = "application/json"):
        reason = {200: "OK", 400: "Bad Request", 404: "Not Found",
                  405: "Method Not Allowed"}.get(status, "")
        writer.write(
            f"HTTP/1.1 {status} {reason}\r\n"
            f"Content-Type: {ctype}\r\n"
            f"Content-Length: {len(body)}\r\n"
            f"Connection: close\r\n\r\n".encode() + body)
        await writer.drain()

    async def _route(self, writer, path: str, query: str = ""):
        g = self.gcs
        if path == "/":
            await self._respond(writer, 200, _INDEX_HTML, "text/html")
            return
        if path == "/api/metrics":
            await self._respond(writer, 200, self._prometheus().encode(),
                                "text/plain; version=0.0.4")
            return
        if path == "/api/profile":
            # /api/profile?pid=<pid>[&duration=<s>] -> live stack summary
            # of that worker (reference: dashboard worker profiling via
            # the per-node agent, modules/reporter/profile_manager.py).
            from urllib.parse import parse_qs
            q = parse_qs(query)
            if "pid" not in q:
                await self._respond(writer, 400,
                                    b'{"error": "pid= required"}')
                return
            try:
                out = await g._h_profile_worker(None, {
                    "pid": int(q["pid"][0]),
                    "duration": float(q.get("duration", ["3"])[0]),
                })
                await self._respond(writer, 200,
                                    json.dumps(out, default=str).encode())
            except (ValueError, TypeError) as e:
                await self._respond(writer, 400, json.dumps(
                    {"error": f"bad parameters: {e}"}).encode())
            except Exception as e:  # noqa: BLE001 - node died mid-profile
                await self._respond(writer, 200, json.dumps(
                    {"ok": False, "error": repr(e)}).encode())
            return
        if path == "/api/serve":
            # Controller-published status from GCS KV (see
            # ServeController._publish_status).
            raw = g.kv.get("serve", {}).get(b"status")
            await self._respond(
                writer, 200,
                raw if raw else b'{"deployments": {}}')
            return
        data = None
        if path == "/api/node_stats":
            data = g.node_stats
        elif path == "/api/nodes":
            data = [n.public() for n in g.nodes.values()]
        elif path == "/api/actors":
            data = [a.public() for a in g.actors.values()]
        elif path == "/api/tasks":
            data = list(g.task_events)
        elif path == "/api/objects":
            data = [{"object_id": oid, "owner": e.owner,
                     "locations": sorted(e.nodes),
                     "spilled": dict(e.spilled)}
                    for oid, e in g.object_dir.items()]
        elif path == "/api/placement_groups":
            data = [pg.public() for pg in g.placement_groups.values()]
        elif path == "/api/jobs":
            data = list(g.jobs.values())
        elif path == "/api/cluster_summary":
            data = self._summary()
        if data is None:
            await self._respond(writer, 404, b'{"error": "not found"}')
            return
        await self._respond(writer, 200,
                            json.dumps(data, default=str).encode())

    def _summary(self) -> dict:
        g = self.gcs
        total: dict = {}
        avail: dict = {}
        for n in g.nodes.values():
            if not n.alive:
                continue
            for k, v in n.resources_total.items():
                total[k] = total.get(k, 0.0) + v
            for k, v in n.resources_available.items():
                avail[k] = avail.get(k, 0.0) + v
        by_status: dict = {}
        for ev in g.task_events:
            by_status[ev.get("status", "?")] = \
                by_status.get(ev.get("status", "?"), 0) + 1
        return {
            "time": time.time(),
            "nodes": {"alive": sum(1 for n in g.nodes.values() if n.alive),
                      "dead": sum(1 for n in g.nodes.values()
                                  if not n.alive)},
            "resources": {"total": total, "available": avail},
            "actors": {"total": len(g.actors),
                       "alive": sum(1 for a in g.actors.values()
                                    if a.state == "ALIVE")},
            "tasks": {"by_status": by_status},
            "objects": len(g.object_dir),
            "placement_groups": len(g.placement_groups),
        }

    def _prometheus(self) -> str:
        """Cluster gauges + user metrics in Prometheus text exposition
        (reference: metrics agent's OpenCensus->Prometheus export)."""
        s = self._summary()
        lines = [
            "# TYPE ray_tpu_nodes_alive gauge",
            f"ray_tpu_nodes_alive {s['nodes']['alive']}",
            "# TYPE ray_tpu_actors_alive gauge",
            f"ray_tpu_actors_alive {s['actors']['alive']}",
            "# TYPE ray_tpu_objects_tracked gauge",
            f"ray_tpu_objects_tracked {s['objects']}",
        ]
        from ray_tpu.util.metrics import _escape_label, render_prometheus
        for k, v in s["resources"]["available"].items():
            lines.append(f'ray_tpu_resource_available'
                         f'{{resource="{_escape_label(k)}"}} {v}')
        # Control-plane liveness: event-loop lag of the GCS (its own
        # watchdog, in-process) and of every raylet (ridden in over node
        # stats).  Rendered through the shared exposition renderer with
        # the built-in prefix — these are system series, not user metrics.
        lag_records = []
        wd = getattr(self.gcs, "_watchdog", None)
        if wd is not None:
            lag_records.append({
                "name": "loop_lag_ms", "type": "gauge",
                "labels": {"component": "gcs"}, "value": wd.last_lag_ms})
        for node_id, st in self.gcs.node_stats.items():
            if "loop_lag_ms" in st:
                lag_records.append({
                    "name": "loop_lag_ms", "type": "gauge",
                    "labels": {"component": "raylet",
                               "node_id": node_id},
                    "value": st["loop_lag_ms"]})
        # Data-plane health (alongside loop_lag_ms): per-node corruption
        # detections, pull retry rounds, and spill fsync time from node
        # stats, plus the GCS-side corruption strikes AGAINST each node
        # (these outlive the node — a holder that served garbage and died
        # is still part of the story).
        # Control-plane partition counters ride the same stream: GCS
        # redials, degraded-mode entries, and resync re-advertisements.
        for node_id, st in self.gcs.node_stats.items():
            for name in ("spilled_objects", "restored_objects",
                         "objects_corrupted", "pull_retries",
                         "spill_fsync_ms", "gcs_reconnects",
                         "node_disconnects",
                         "resync_objects_readvertised",
                         "autotune_cache_hits", "autotune_cache_misses",
                         "autotune_tune_ms",
                         "router_retries", "circuit_open",
                         "streams_resumed", "drain_handoffs",
                         "ctrl_reresolves",
                         "train_recoveries", "preemptions",
                         "ckpt_write_ms", "ckpt_restore_ms",
                         "ckpt_corrupt_skipped"):
                if name in st:
                    lag_records.append({
                        "name": name, "type": "counter",
                        "labels": {"node_id": node_id},
                        "value": st[name]})
        for node_id, strikes in getattr(
                self.gcs, "object_invalidations", {}).items():
            lag_records.append({
                "name": "object_location_invalidations", "type": "counter",
                "labels": {"node_id": node_id}, "value": strikes})
        # User metrics: reuse the GCS's (name, labels) aggregation and the
        # shared exposition renderer (which sanitizes names) — per-process
        # raw records would emit duplicate series and drop histogram
        # buckets, and any per-endpoint renaming would give one metric two
        # series names depending on scrape point.
        # Autotune, serve-resilience, and train-resilience counters flow
        # through the user-metrics pipe (worker processes flush them like
        # any Counter) but are SYSTEM series: split them out under the
        # ray_tpu_ prefix so operators find cache hit rate, failover
        # counts, and checkpoint health next to the other health
        # series, not namespaced as user metrics.
        _SERVE_COUNTERS = ("router_retries", "circuit_open",
                           "streams_resumed", "drain_handoffs",
                           "ctrl_reresolves")
        _TRAIN_COUNTERS = ("train_recoveries", "preemptions",
                           "ckpt_write_ms", "ckpt_restore_ms",
                           "ckpt_corrupt_skipped")
        agg = self.gcs.aggregated_metrics()
        system = [m for m in agg
                  if str(m.get("name", "")).startswith("autotune_")
                  or str(m.get("name", "")) in _SERVE_COUNTERS
                  or str(m.get("name", "")) in _TRAIN_COUNTERS]
        user = [m for m in agg if m not in system]
        return "\n".join(lines) + "\n" + \
            render_prometheus(lag_records + system, prefix="ray_tpu_") + \
            render_prometheus(user)


# Single-file live UI (reference: the dashboard/client React app, scaled to
# one dependency-free page): auto-refreshing cluster summary, node/actor/
# job tables, and recent task activity, all straight off /api/*.
_INDEX_HTML = b"""<!doctype html>
<html><head><meta charset="utf-8"><title>ray_tpu dashboard</title>
<style>
 body{font-family:system-ui,sans-serif;margin:0;background:#f5f6f8;color:#1c2126}
 header{background:#1c2126;color:#fff;padding:10px 20px;display:flex;align-items:baseline;gap:16px}
 header h1{font-size:16px;margin:0} header span{color:#9aa4ad;font-size:12px}
 main{padding:16px 20px;max-width:1100px;margin:auto}
 .cards{display:flex;gap:12px;flex-wrap:wrap;margin-bottom:16px}
 .card{background:#fff;border-radius:8px;padding:10px 16px;box-shadow:0 1px 2px rgba(0,0,0,.08);min-width:110px}
 .card b{display:block;font-size:22px} .card span{font-size:12px;color:#67707a}
 h2{font-size:13px;text-transform:uppercase;letter-spacing:.05em;color:#67707a;margin:18px 0 6px}
 table{width:100%;border-collapse:collapse;background:#fff;border-radius:8px;overflow:hidden;box-shadow:0 1px 2px rgba(0,0,0,.08);font-size:13px}
 th,td{text-align:left;padding:6px 10px;border-bottom:1px solid #eef0f2;white-space:nowrap;overflow:hidden;text-overflow:ellipsis;max-width:260px}
 th{background:#fafbfc;font-weight:600;color:#49525b}
 .ok{color:#0a7d33;font-weight:600} .bad{color:#b3261e;font-weight:600}
 footer{color:#9aa4ad;font-size:11px;padding:14px 20px}
</style></head><body>
<header><h1>ray_tpu dashboard</h1><span id=upd></span>
<span><a href="/api/metrics" style="color:#9ec5fe">prometheus</a></span></header>
<main>
 <div class=cards id=cards></div>
 <h2>Nodes</h2><table id=nodes></table>
 <h2>Workers (per node)</h2><table id=workers></table>
 <h2>Actors</h2><table id=actors></table>
 <h2>Jobs</h2><table id=jobs></table>
 <h2>Recent tasks</h2><table id=tasks></table>
</main>
<footer>auto-refreshes every 2s &middot; raw endpoints: /api/nodes /api/actors
/api/tasks /api/objects /api/placement_groups /api/jobs /api/cluster_summary</footer>
<script>
const J=(u)=>fetch(u).then(r=>r.json());
const esc=(s)=>String(s??"").replace(/[&<>]/g,c=>({"&":"&amp;","<":"&lt;",">":"&gt;"}[c]));
function tbl(el,heads,rows){
 el.innerHTML="<tr>"+heads.map(h=>"<th>"+h+"</th>").join("")+"</tr>"+
  rows.map(r=>"<tr>"+r.map(c=>"<td>"+c+"</td>").join("")+"</tr>").join("");
}
async function tick(){
 try{
  const [sum,nodes,actors,jobs,tasks,nstats]=await Promise.all([
    J("/api/cluster_summary"),J("/api/nodes"),J("/api/actors"),
    J("/api/jobs"),J("/api/tasks"),J("/api/node_stats")]);
  const res=(sum.resources||{}).total||{}; const cards=document.getElementById("cards");
  const card=(v,l)=>`<div class=card><b>${v}</b><span>${l}</span></div>`;
  cards.innerHTML=card((sum.nodes||{}).alive??nodes.filter(n=>n.alive).length,"nodes alive")
   +card((sum.actors||{}).alive??actors.filter(a=>a.state=="ALIVE").length,"actors alive")
   +card(res.CPU??"-","CPUs")+card(res.TPU??"-","TPUs")
   +card(tasks.length,"task events");
  tbl(document.getElementById("nodes"),["node","address","alive","resources"],
   nodes.map(n=>[esc((n.node_id||"").slice(0,12)),esc(n.address),
    n.alive?'<span class=ok>alive</span>':'<span class=bad>dead</span>',
    esc(JSON.stringify(n.resources_total||n.resources||{}))]));
  const wrows=[];
  for(const [nid,st] of Object.entries(nstats||{})){
   const store=st.object_store||{};
   for(const w of (st.workers||[])){
    wrows.push([esc(nid.slice(0,12)),w.pid,esc((w.actor_id||"").slice(0,12)),
     w.busy?'<span class=bad>busy</span>':'<span class=ok>idle</span>',
     w.cpu_percent+"%",(w.rss_bytes/1048576).toFixed(1)+" MB",
     `<a href="/api/profile?pid=${w.pid}&duration=3" target=_blank>profile</a>`]);
   }
   wrows.push([esc(nid.slice(0,12)),"&mdash;","node load "+
    (st.load_avg||[]).map(x=>x.toFixed(2)).join(" / "),"",
    "store "+((store.bytes_used??0)/1048576).toFixed(1)+" MB",
    "mem avail "+((st.mem_available??0)/1073741824).toFixed(2)+" GB",""]);
  }
  tbl(document.getElementById("workers"),
   ["node","pid","actor","state","cpu","rss","" ],wrows.slice(0,60));
  tbl(document.getElementById("actors"),["actor","name","state","node"],
   actors.slice(0,50).map(a=>[esc((a.actor_id||"").slice(0,12)),esc(a.name||""),
    a.state=="ALIVE"?'<span class=ok>ALIVE</span>':'<span class=bad>'+esc(a.state)+'</span>',
    esc((a.node_id||"").slice(0,12))]));
  tbl(document.getElementById("jobs"),["job","state","started"],
   jobs.slice(0,30).map(j=>[esc(j.job_id||""),esc(j.state||""),
    j.start_time?new Date(j.start_time*1000).toLocaleTimeString():""]));
  tbl(document.getElementById("tasks"),["name","kind","status","duration"],
   tasks.slice(-30).reverse().map(t=>[esc(t.name||""),esc(t.kind||""),
    t.status=="FINISHED"?'<span class=ok>FINISHED</span>':'<span class=bad>'+esc(t.status)+'</span>',
    ((t.end-t.start)*1000).toFixed(1)+" ms"]));
  document.getElementById("upd").textContent="updated "+new Date().toLocaleTimeString();
 }catch(e){document.getElementById("upd").textContent="refresh failed: "+e;}
}
tick(); setInterval(tick,2000);
</script></body></html>
"""
