"""Dashboard: HTTP/JSON observability endpoint on the head node.

Design analog: reference ``dashboard/`` (DashboardHead head.py:70 + REST
modules + StateAggregator).  Scope here is the REST surface the state CLI
and external monitors consume — no React client; the JSON endpoints mirror
``ray list ...``/``ray summary`` and Prometheus-style metrics.  Implemented
as a dependency-free asyncio HTTP/1.1 GET server co-hosted with the GCS
(direct in-process table reads, no RPC hop).

Routes:
  GET /api/nodes | /api/actors | /api/tasks | /api/objects
      /api/placement_groups | /api/jobs | /api/cluster_summary
  GET /api/metrics      (Prometheus text exposition)
  GET /                 (tiny HTML index)
"""

from __future__ import annotations

import asyncio
import json
import logging
import time
from typing import Optional

logger = logging.getLogger(__name__)


class DashboardHttpServer:
    def __init__(self, gcs):
        self.gcs = gcs
        self._server: Optional[asyncio.AbstractServer] = None

    async def start(self, port: int = 0) -> int:
        self._server = await asyncio.start_server(
            self._on_client, host="127.0.0.1", port=port)
        return self._server.sockets[0].getsockname()[1]

    async def close(self):
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()

    # ------------------------------------------------------------- serving

    async def _on_client(self, reader: asyncio.StreamReader,
                         writer: asyncio.StreamWriter):
        try:
            request_line = await asyncio.wait_for(reader.readline(), 10)
            parts = request_line.decode("latin-1").split()
            if len(parts) < 2 or parts[0] != "GET":
                await self._respond(writer, 405, b"method not allowed",
                                    "text/plain")
                return
            path = parts[1].split("?", 1)[0]
            # Drain headers (ignored).
            while True:
                line = await asyncio.wait_for(reader.readline(), 10)
                if line in (b"\r\n", b"\n", b""):
                    break
            await self._route(writer, path)
        except (asyncio.TimeoutError, ConnectionError, OSError):
            pass
        finally:
            try:
                writer.close()
            except Exception:
                pass

    async def _respond(self, writer, status: int, body: bytes,
                       ctype: str = "application/json"):
        reason = {200: "OK", 404: "Not Found",
                  405: "Method Not Allowed"}.get(status, "")
        writer.write(
            f"HTTP/1.1 {status} {reason}\r\n"
            f"Content-Type: {ctype}\r\n"
            f"Content-Length: {len(body)}\r\n"
            f"Connection: close\r\n\r\n".encode() + body)
        await writer.drain()

    async def _route(self, writer, path: str):
        g = self.gcs
        if path == "/":
            body = (b"<html><body><h3>ray_tpu dashboard</h3><ul>" +
                    b"".join(f'<li><a href="/api/{p}">{p}</a></li>'.encode()
                             for p in ("nodes", "actors", "tasks", "objects",
                                       "placement_groups", "jobs",
                                       "cluster_summary", "metrics")) +
                    b"</ul></body></html>")
            await self._respond(writer, 200, body, "text/html")
            return
        if path == "/api/metrics":
            await self._respond(writer, 200, self._prometheus().encode(),
                                "text/plain; version=0.0.4")
            return
        data = None
        if path == "/api/nodes":
            data = [n.public() for n in g.nodes.values()]
        elif path == "/api/actors":
            data = [a.public() for a in g.actors.values()]
        elif path == "/api/tasks":
            data = list(g.task_events)
        elif path == "/api/objects":
            data = [{"object_id": oid, "owner": e.owner,
                     "locations": sorted(e.nodes),
                     "spilled": dict(e.spilled)}
                    for oid, e in g.object_dir.items()]
        elif path == "/api/placement_groups":
            data = [pg.public() for pg in g.placement_groups.values()]
        elif path == "/api/jobs":
            data = list(g.jobs.values())
        elif path == "/api/cluster_summary":
            data = self._summary()
        if data is None:
            await self._respond(writer, 404, b'{"error": "not found"}')
            return
        await self._respond(writer, 200,
                            json.dumps(data, default=str).encode())

    def _summary(self) -> dict:
        g = self.gcs
        total: dict = {}
        avail: dict = {}
        for n in g.nodes.values():
            if not n.alive:
                continue
            for k, v in n.resources_total.items():
                total[k] = total.get(k, 0.0) + v
            for k, v in n.resources_available.items():
                avail[k] = avail.get(k, 0.0) + v
        by_status: dict = {}
        for ev in g.task_events:
            by_status[ev.get("status", "?")] = \
                by_status.get(ev.get("status", "?"), 0) + 1
        return {
            "time": time.time(),
            "nodes": {"alive": sum(1 for n in g.nodes.values() if n.alive),
                      "dead": sum(1 for n in g.nodes.values()
                                  if not n.alive)},
            "resources": {"total": total, "available": avail},
            "actors": {"total": len(g.actors),
                       "alive": sum(1 for a in g.actors.values()
                                    if a.state == "ALIVE")},
            "tasks": {"by_status": by_status},
            "objects": len(g.object_dir),
            "placement_groups": len(g.placement_groups),
        }

    def _prometheus(self) -> str:
        """Cluster gauges + user metrics in Prometheus text exposition
        (reference: metrics agent's OpenCensus->Prometheus export)."""
        s = self._summary()
        lines = [
            "# TYPE ray_tpu_nodes_alive gauge",
            f"ray_tpu_nodes_alive {s['nodes']['alive']}",
            "# TYPE ray_tpu_actors_alive gauge",
            f"ray_tpu_actors_alive {s['actors']['alive']}",
            "# TYPE ray_tpu_objects_tracked gauge",
            f"ray_tpu_objects_tracked {s['objects']}",
        ]
        from ray_tpu.util.metrics import _escape_label, render_prometheus
        for k, v in s["resources"]["available"].items():
            lines.append(f'ray_tpu_resource_available'
                         f'{{resource="{_escape_label(k)}"}} {v}')
        # User metrics: reuse the GCS's (name, labels) aggregation and the
        # shared exposition renderer (which sanitizes names) — per-process
        # raw records would emit duplicate series and drop histogram
        # buckets, and any per-endpoint renaming would give one metric two
        # series names depending on scrape point.
        return "\n".join(lines) + "\n" + \
            render_prometheus(self.gcs.aggregated_metrics())
