from ray_tpu.dashboard.http_server import DashboardHttpServer

__all__ = ["DashboardHttpServer"]
