"""Cluster launcher: ``ray_tpu up / down`` from a YAML config.

Design analog: reference ``python/ray/autoscaler/_private/commands.py``
(``create_or_update_cluster`` behind ``ray up``, ``teardown_cluster``
behind ``ray down``) and the cluster YAML schema
(``autoscaler/ray-schema.json``).  TPU-first deltas: node types are
slice-shaped (a worker is a whole TPU slice, created atomically by the
provider), and instead of SSH-bootstrapping cloud VMs the launcher
drives a NodeProvider — TPUVMNodeProvider for real TPU fleets, mock /
local providers for tests and laptops.

YAML shape::

    cluster_name: my-cluster
    max_workers: 8
    idle_timeout_s: 120
    provider:
      type: mock          # mock | tpu_vm
      # provider-specific keys (tpu_vm: project, zone, ...)
    available_node_types:
      v4_8_slice:
        resources: {"CPU": 4, "tpu-slice:v4-8": 1}
        min_workers: 1
        max_workers: 4
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional

from ray_tpu.autoscaler.autoscaler import AutoscalerConfig
from ray_tpu.autoscaler.monitor import Monitor
from ray_tpu.autoscaler.node_provider import (NodeProvider, NodeTypeConfig)


@dataclasses.dataclass
class ClusterConfig:
    cluster_name: str
    provider: Dict[str, Any]
    node_types: List[NodeTypeConfig]
    max_workers: int = 20
    idle_timeout_s: float = 120.0

    @staticmethod
    def from_dict(d: Dict[str, Any]) -> "ClusterConfig":
        for key in ("cluster_name", "provider", "available_node_types"):
            if key not in d:
                raise ValueError(f"cluster config missing '{key}'")
        if "type" not in d["provider"]:
            raise ValueError("provider config needs a 'type'")
        node_types = []
        for name, spec in d["available_node_types"].items():
            unknown = set(spec) - {"resources", "min_workers",
                                   "max_workers"}
            if unknown:
                raise ValueError(f"node type {name!r}: unknown keys "
                                 f"{sorted(unknown)}")
            node_types.append(NodeTypeConfig(
                name=name,
                resources=dict(spec.get("resources", {})),
                min_workers=int(spec.get("min_workers", 0)),
                max_workers=int(spec.get("max_workers", 10))))
        return ClusterConfig(
            cluster_name=d["cluster_name"],
            provider=dict(d["provider"]),
            node_types=node_types,
            max_workers=int(d.get("max_workers", 20)),
            idle_timeout_s=float(d.get("idle_timeout_s", 120.0)))

    @staticmethod
    def from_file(path: str) -> "ClusterConfig":
        import yaml
        with open(path) as f:
            return ClusterConfig.from_dict(yaml.safe_load(f))


def _make_provider(cfg: ClusterConfig) -> NodeProvider:
    ptype = cfg.provider["type"]
    if ptype == "mock":
        from ray_tpu.autoscaler.node_provider import MockNodeProvider
        return MockNodeProvider()
    if ptype == "tpu_vm":
        from ray_tpu.autoscaler.tpu_vm_provider import TPUVMNodeProvider
        kwargs = {k: v for k, v in cfg.provider.items() if k != "type"}
        api = kwargs.pop("api", None)
        if api is None:
            raise ValueError(
                "provider type 'tpu_vm' needs an 'api' object (a TpuApi "
                "implementation bound to your cloud credentials); pass it "
                "via ClusterLauncher(config, provider=...) or the "
                "provider dict")
        return TPUVMNodeProvider(api, **kwargs)
    raise ValueError(f"unknown provider type {ptype!r} "
                     f"(available: mock, tpu_vm)")


class ClusterLauncher:
    """Owns one launched cluster: provider + autoscaler monitor.

    ``up()`` satisfies every node type's min_workers immediately (the
    reference's ``ray up`` bootstrap) and starts the monitor so demand
    scaling continues; ``down()`` stops the monitor and terminates every
    provider node.
    """

    def __init__(self, config: ClusterConfig,
                 provider: Optional[NodeProvider] = None,
                 load_source=None):
        self.config = config
        self.provider = provider or _make_provider(config)
        self._monitor: Optional[Monitor] = None
        self._load_source = load_source or (lambda: {
            "nodes": [], "pending_tasks": [], "pending_actors": [],
            "pending_pg_bundles": []})

    def up(self, start_monitor: bool = True) -> Dict[str, int]:
        launched: Dict[str, int] = {}
        counts: Dict[str, int] = {}
        for pn in self.provider.non_terminated_nodes():
            counts[pn.node_type] = counts.get(pn.node_type, 0) + 1
        for ntype in self.config.node_types:
            short = ntype.min_workers - counts.get(ntype.name, 0)
            if short > 0:
                self.provider.create_node(ntype, short)
                launched[ntype.name] = short
        if start_monitor:
            self._monitor = Monitor(
                self.provider,
                AutoscalerConfig(
                    node_types=self.config.node_types,
                    max_workers=self.config.max_workers,
                    idle_timeout_s=self.config.idle_timeout_s),
                load_source=self._load_source).start()
        return launched

    def down(self) -> int:
        if self._monitor is not None:
            self._monitor.stop()
            self._monitor = None
        nodes = self.provider.non_terminated_nodes()
        for pn in nodes:
            self.provider.terminate_node(pn.node_id)
        return len(nodes)
