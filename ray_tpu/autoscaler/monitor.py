"""Autoscaler monitor loop.

Design analog: reference ``autoscaler/_private/monitor.py:126`` -- a head-node
process that reads load from the GCS and drives StandardAutoscaler.update()
on a period.  Here it runs as a daemon thread in the process that owns the
provider (the driver or the head daemon), reading load through the connected
worker's GCS channel.
"""

from __future__ import annotations

import logging
import threading
from typing import Optional

from ray_tpu.autoscaler.autoscaler import AutoscalerConfig, StandardAutoscaler
from ray_tpu.autoscaler.node_provider import NodeProvider

logger = logging.getLogger(__name__)


class Monitor:
    def __init__(self, provider: NodeProvider, config: AutoscalerConfig,
                 update_interval_s: float = 1.0,
                 load_source=None):
        if load_source is None:
            def load_source():
                from ray_tpu._private.worker import get_core
                return get_core().gcs_request({"type": "get_load_metrics"})
        self.autoscaler = StandardAutoscaler(provider, config, load_source)
        self.update_interval_s = update_interval_s
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self):
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="rt-autoscaler-monitor")
        self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=10)

    def _run(self):
        while not self._stop.wait(self.update_interval_s):
            try:
                self.autoscaler.update()
            except Exception:
                logger.exception("autoscaler update failed")
