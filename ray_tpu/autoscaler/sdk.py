"""Programmatic autoscaler API.

Design analog: reference ``python/ray/autoscaler/sdk.py``
(``request_resources(num_cpus=..., bundles=[...])``): inject standing
resource demand into the GCS load view so the autoscaler scales up ahead
of the workload.  Each call REPLACES the previous request; clear with
``request_resources()``.
"""

from __future__ import annotations

from typing import Dict, List, Optional


def request_resources(*, num_cpus: Optional[int] = None,
                      bundles: Optional[List[Dict[str, float]]] = None
                      ) -> None:
    """Set the cluster's standing resource request.

    ``num_cpus=N`` is shorthand for N single-CPU bundles; ``bundles``
    are resource dicts (e.g. ``[{"tpu-slice:v4-8": 1}]``).  Passing
    neither clears the request.
    """
    out: List[Dict[str, float]] = []
    if num_cpus:
        out.extend({"CPU": 1.0} for _ in range(int(num_cpus)))
    if bundles:
        out.extend(dict(b) for b in bundles)
    from ray_tpu._private.worker import get_core
    get_core().gcs_request({"type": "set_resource_request",
                            "bundles": out})
