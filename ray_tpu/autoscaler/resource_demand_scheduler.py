"""Demand -> nodes-to-launch bin packing.

Design analog: reference ``autoscaler/_private/resource_demand_scheduler.py:103``
(get_nodes_to_launch: pack pending task/actor/PG demands onto existing free
capacity first, then onto hypothetical new nodes of the configured types).
"""

from __future__ import annotations

from typing import Dict, List, Tuple

from ray_tpu.autoscaler.node_provider import NodeTypeConfig


def fits(demand: Dict[str, float], available: Dict[str, float]) -> bool:
    return all(available.get(k, 0.0) >= v for k, v in demand.items())


def subtract(available: Dict[str, float], demand: Dict[str, float]) -> None:
    for k, v in demand.items():
        available[k] = available.get(k, 0.0) - v


class ResourceDemandScheduler:
    def __init__(self, node_types: List[NodeTypeConfig],
                 max_workers: int = 20):
        self.node_types = {t.name: t for t in node_types}
        self.max_workers = max_workers

    def get_nodes_to_launch(
        self,
        existing_free: List[Dict[str, float]],
        demands: List[Dict[str, float]],
        current_counts: Dict[str, int],
    ) -> Dict[str, int]:
        """First-fit demands onto existing free capacity, then bin-pack the
        unmet remainder onto new nodes, respecting per-type and global
        max_workers. Returns {node_type_name: count_to_launch}.

        `existing_free` is mutated-by-copy; `current_counts` is the number of
        non-terminated provider nodes per type.
        """
        free = [dict(f) for f in existing_free]
        unmet: List[Dict[str, float]] = []
        # Biggest demands first: classic FFD gives tighter packing and makes
        # gang shapes (PG bundles, slice-sized actors) claim whole nodes
        # before small tasks fragment them.
        for d in sorted(demands, key=lambda d: -sum(d.values())):
            for f in free:
                if fits(d, f):
                    subtract(f, d)
                    break
            else:
                unmet.append(d)

        to_launch: Dict[str, int] = {}
        counts = dict(current_counts)
        total = sum(counts.values())
        new_free: List[Tuple[str, Dict[str, float]]] = []
        for d in unmet:
            placed = False
            for ntype, f in new_free:
                if fits(d, f):
                    subtract(f, d)
                    placed = True
                    break
            if placed:
                continue
            # Launch the cheapest (smallest) node type that can hold the
            # demand at all.
            for t in sorted(self.node_types.values(),
                            key=lambda t: sum(t.resources.values())):
                if not fits(d, dict(t.resources)):
                    continue
                if counts.get(t.name, 0) >= t.max_workers:
                    continue
                if total >= self.max_workers:
                    continue
                f = dict(t.resources)
                subtract(f, d)
                new_free.append((t.name, f))
                to_launch[t.name] = to_launch.get(t.name, 0) + 1
                counts[t.name] = counts.get(t.name, 0) + 1
                total += 1
                placed = True
                break
            # Infeasible demands (fit no node type) are dropped here; the
            # reference logs them as infeasible and so do we at the caller.
        return to_launch

    def min_workers_to_launch(
            self, current_counts: Dict[str, int]) -> Dict[str, int]:
        """Nodes needed to satisfy each type's min_workers floor."""
        out = {}
        for t in self.node_types.values():
            short = t.min_workers - current_counts.get(t.name, 0)
            if short > 0:
                out[t.name] = short
        return out
