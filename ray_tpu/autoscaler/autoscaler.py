"""StandardAutoscaler: one update() = read load, launch shortfall, reap idle.

Design analog: reference ``autoscaler/_private/autoscaler.py:167``
(StandardAutoscaler.update: launch from ResourceDemandScheduler output,
terminate nodes idle past idle_timeout, enforce min/max workers).
"""

from __future__ import annotations

import logging
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional

from ray_tpu.autoscaler.node_provider import (NODE_TYPE_LABEL, NodeProvider,
                                              NodeTypeConfig)
from ray_tpu.autoscaler.resource_demand_scheduler import (
    ResourceDemandScheduler)

logger = logging.getLogger(__name__)


@dataclass
class AutoscalerConfig:
    node_types: List[NodeTypeConfig] = field(default_factory=list)
    max_workers: int = 20
    idle_timeout_s: float = 60.0
    # Scale-up batching: at most this many nodes launched per update.
    max_launch_batch: int = 5


class StandardAutoscaler:
    """Drives a NodeProvider from a load-metrics callable.

    `load_source()` must return the GCS `get_load_metrics` dict:
    {nodes: [...], pending_tasks: [...], pending_actors: [...],
     pending_pg_bundles: [...]}.
    """

    def __init__(self, provider: NodeProvider, config: AutoscalerConfig,
                 load_source: Callable[[], dict]):
        self.provider = provider
        self.config = config
        self.load_source = load_source
        self.scheduler = ResourceDemandScheduler(
            config.node_types, max_workers=config.max_workers)
        # GCS node hex -> monotonic time it became idle (demand-free).
        self._idle_since: Dict[str, float] = {}

    def update(self) -> Dict[str, int]:
        """One reconciliation pass. Returns {node_type: launched_count}."""
        load = self.load_source()
        provider_nodes = self.provider.non_terminated_nodes()
        counts: Dict[str, int] = {}
        for pn in provider_nodes:
            counts[pn.node_type] = counts.get(pn.node_type, 0) + 1

        demands = (list(load.get("pending_tasks", [])) +
                   list(load.get("pending_actors", [])) +
                   list(load.get("pending_pg_bundles", [])))
        alive = [n for n in load.get("nodes", []) if n.get("alive")]
        free = [dict(n.get("resources_available", {})) for n in alive]

        to_launch = self.scheduler.get_nodes_to_launch(free, demands, counts)
        for name, short in self.scheduler.min_workers_to_launch(
                counts).items():
            to_launch[name] = max(to_launch.get(name, 0), short)

        launched: Dict[str, int] = {}
        budget = self.config.max_launch_batch
        for name, n in to_launch.items():
            n = min(n, budget)
            if n <= 0:
                continue
            t = self.scheduler.node_types[name]
            logger.info("autoscaler: launching %d x %s for %d pending "
                        "demands", n, name, len(demands))
            self.provider.create_node(t, n)
            launched[name] = n
            budget -= n

        self._terminate_idle(alive, demands, provider_nodes, counts)
        return launched

    # ------------------------------------------------------------ scale down

    def _terminate_idle(self, alive_gcs_nodes: List[dict],
                        demands: List[dict], provider_nodes, counts) -> None:
        """Terminate provider nodes that have been fully idle (all resources
        free, no pending demand anywhere) past idle_timeout, keeping each
        type's min_workers."""
        now = time.monotonic()
        by_launch_label: Dict[str, dict] = {}
        for n in alive_gcs_nodes:
            lid = (n.get("labels") or {}).get("rt-launch-id")
            if lid:
                by_launch_label[lid] = n

        for pn in provider_nodes:
            gcs_node = by_launch_label.get(pn.node_id) or \
                by_launch_label.get(pn.labels.get("rt-launch-id", ""))
            if gcs_node is None:
                continue  # not yet registered; never kill during startup
            total = gcs_node.get("resources_total", {})
            availd = gcs_node.get("resources_available", {})
            busy = any(availd.get(k, 0.0) < v for k, v in total.items())
            if busy or demands:
                self._idle_since.pop(pn.node_id, None)
                continue
            first = self._idle_since.setdefault(pn.node_id, now)
            ntype = self.scheduler.node_types.get(pn.node_type)
            floor = ntype.min_workers if ntype else 0
            if (now - first >= self.config.idle_timeout_s and
                    counts.get(pn.node_type, 0) > floor):
                logger.info("autoscaler: terminating idle node %s (%s)",
                            pn.node_id, pn.node_type)
                self.provider.terminate_node(pn.node_id)
                counts[pn.node_type] -= 1
                self._idle_since.pop(pn.node_id, None)
