"""Node providers: the pluggable "cloud" behind the autoscaler.

Design analog: reference ``python/ray/autoscaler/node_provider.py:13``
(NodeProvider base: non_terminated_nodes / create_node / terminate_node /
node_tags) and ``autoscaler/_private/fake_multi_node/node_provider.py:237``
(FakeMultiNodeProvider -- nodes as local processes, the test backend).
"""

from __future__ import annotations

import threading
import uuid
from dataclasses import dataclass, field
from typing import Dict, List, Optional

# Label key carrying the provider node-type name on launched nodes; the
# autoscaler uses it to map live GCS nodes back to provider node types
# (reference: TAG_RAY_USER_NODE_TYPE).
NODE_TYPE_LABEL = "rt-node-type"
LAUNCH_ID_LABEL = "rt-launch-id"


@dataclass
class NodeTypeConfig:
    """One launchable node shape (reference: available_node_types entries in
    the cluster YAML, ray-schema.json).

    For TPU, a node type is typically one *slice* (e.g. v4-8): `resources`
    describes the whole slice and the provider brings up all of its hosts
    atomically -- a slice is all-or-nothing, per SURVEY hard part (e).
    """
    name: str
    resources: Dict[str, float]
    min_workers: int = 0
    max_workers: int = 10


@dataclass
class ProviderNode:
    node_id: str
    node_type: str
    labels: Dict[str, str] = field(default_factory=dict)


class NodeProvider:
    """Abstract provider. Implementations must be thread-safe: the monitor
    loop calls from its own thread."""

    def non_terminated_nodes(self) -> List[ProviderNode]:
        raise NotImplementedError

    def create_node(self, node_type: NodeTypeConfig, count: int,
                    labels: Optional[Dict[str, str]] = None) -> List[str]:
        raise NotImplementedError

    def terminate_node(self, node_id: str) -> None:
        raise NotImplementedError


class MockNodeProvider(NodeProvider):
    """Records create/terminate calls; for unit tests (reference:
    test_autoscaler.py MockProvider)."""

    def __init__(self):
        self._lock = threading.Lock()
        self.nodes: Dict[str, ProviderNode] = {}
        self.create_calls: List[tuple] = []
        self.terminate_calls: List[str] = []

    def non_terminated_nodes(self) -> List[ProviderNode]:
        with self._lock:
            return list(self.nodes.values())

    def create_node(self, node_type, count, labels=None):
        created = []
        with self._lock:
            self.create_calls.append((node_type.name, count))
            for _ in range(count):
                nid = uuid.uuid4().hex[:12]
                self.nodes[nid] = ProviderNode(
                    node_id=nid, node_type=node_type.name,
                    labels=dict(labels or {},
                                **{NODE_TYPE_LABEL: node_type.name}))
                created.append(nid)
        return created

    def terminate_node(self, node_id):
        with self._lock:
            self.terminate_calls.append(node_id)
            self.nodes.pop(node_id, None)


class LocalNodeProvider(NodeProvider):
    """Launches real node daemons on this machine via `cluster_utils.Cluster`
    -- the FakeMultiNodeProvider equivalent, used for end-to-end autoscaler
    tests and local elastic clusters."""

    def __init__(self, cluster):
        self._cluster = cluster
        self._lock = threading.Lock()
        self._nodes: Dict[str, object] = {}   # provider id -> ClusterNode

    def non_terminated_nodes(self) -> List[ProviderNode]:
        with self._lock:
            out = []
            for pid, cn in list(self._nodes.items()):
                if cn.proc.poll() is None:
                    out.append(ProviderNode(
                        node_id=pid,
                        node_type=cn.info.get("labels", {}).get(
                            NODE_TYPE_LABEL, ""),
                        labels=cn.info.get("labels", {})))
                else:
                    del self._nodes[pid]
            return out

    def create_node(self, node_type, count, labels=None):
        created = []
        for _ in range(count):
            pid = uuid.uuid4().hex[:12]
            merged = dict(labels or {})
            merged[NODE_TYPE_LABEL] = node_type.name
            merged[LAUNCH_ID_LABEL] = pid
            cn = self._cluster.add_node(
                resources=dict(node_type.resources), labels=merged)
            cn.info.setdefault("labels", merged)
            with self._lock:
                self._nodes[pid] = cn
            created.append(pid)
        return created

    def terminate_node(self, node_id):
        with self._lock:
            cn = self._nodes.pop(node_id, None)
        if cn is not None:
            self._cluster.remove_node(cn)
