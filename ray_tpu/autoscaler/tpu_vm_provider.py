"""TPU-VM node provider: slice-atomic provisioning against a cloud API.

Design analog: reference ``python/ray/autoscaler/_private/gcp/node_provider
.py`` (GCPNodeProvider: API-backed create/terminate with operation polling)
— reshaped for TPU pods, where the provisioning unit is a SLICE (a gang of
hosts sharing ICI), not an instance:

  * slice atomicity — a v4-32 slice is 4 hosts that exist together or not
    at all; a partially-created slice is torn down, never surfaced.
  * async provisioning — the cloud API returns long-running operations;
    the provider polls them off the autoscaler's critical path and
    surfaces nodes only when the whole slice is READY.
  * error taxonomy — QUOTA/CAPACITY errors (common for TPU pools) are
    retried with backoff up to a budget; permanent errors mark the launch
    failed so the autoscaler's demand loop can pick a different shape.

The cloud API is injected (``TpuApi`` protocol) so the provisioning state
machine is fully testable without GCP: tests drive it with a fake API that
injects capacity errors and partial-slice failures.  Wiring an actual GCP
client is a deployment concern (create_node/delete_node/get_operation are
1:1 with the TPU VM REST verbs).
"""

from __future__ import annotations

import threading
import time
import uuid
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from ray_tpu.autoscaler.node_provider import (NODE_TYPE_LABEL, NodeProvider,
                                              NodeTypeConfig, ProviderNode)

# operation states reported by TpuApi.get_operation
PENDING, READY, FAILED = "PENDING", "READY", "FAILED"


class TpuCapacityError(RuntimeError):
    """Transient: no capacity / quota right now — retry with backoff."""


class TpuApi:
    """Injected cloud surface (1:1 with the TPU-VM REST verbs)."""

    def create_slice(self, accelerator_type: str, hosts: int,
                     labels: Dict[str, str]) -> str:
        """Begin creating one slice (all its hosts); returns operation id.
        Raises TpuCapacityError when the pool has no capacity."""
        raise NotImplementedError

    def get_operation(self, op_id: str) -> Dict:
        """{"state": PENDING|READY|FAILED, "hosts": [host_id, ...],
        "error": str|None}.  READY means EVERY host of the slice is up."""
        raise NotImplementedError

    def delete_slice(self, slice_id: str) -> None:
        raise NotImplementedError


@dataclass
class _Launch:
    op_id: str
    node_type: str
    labels: Dict[str, str]
    attempts: int = 0
    next_poll: float = 0.0
    # retry bookkeeping for capacity-failed creates (op_id == "")
    accel: str = ""
    hosts: int = 1


@dataclass
class _Slice:
    slice_id: str
    node_type: str
    hosts: List[str]
    labels: Dict[str, str] = field(default_factory=dict)


class TPUVMNodeProvider(NodeProvider):
    """Slice-atomic async provider over an injected TpuApi."""

    def __init__(self, api: TpuApi, *,
                 accelerator_types: Optional[Dict[str, str]] = None,
                 max_create_retries: int = 5,
                 retry_backoff_s: float = 2.0):
        self._api = api
        self._accel = accelerator_types or {}
        self._max_retries = max_create_retries
        self._backoff = retry_backoff_s
        self._lock = threading.Lock()
        self._slices: Dict[str, _Slice] = {}
        self._launches: List[_Launch] = []
        self.failed_launches: List[Dict] = []   # surfaced to the monitor

    # -- NodeProvider surface --------------------------------------------

    def non_terminated_nodes(self) -> List[ProviderNode]:
        self._poll_launches()
        with self._lock:
            out = []
            for s in self._slices.values():
                for h in s.hosts:
                    out.append(ProviderNode(node_id=h,
                                            node_type=s.node_type,
                                            labels=dict(s.labels)))
            return out

    def create_node(self, node_type: NodeTypeConfig, count: int,
                    labels: Optional[Dict[str, str]] = None) -> List[str]:
        """Begin `count` slice launches; returns operation ids (nodes
        surface via non_terminated_nodes once their slice is READY)."""
        labels = {**(labels or {}), NODE_TYPE_LABEL: node_type.name}
        accel = self._accel.get(node_type.name, node_type.name)
        hosts = max(1, int(node_type.resources.get("hosts", 1)))
        ops = []
        for _ in range(count):
            op = self._begin_launch(accel, hosts, node_type.name, labels)
            if op is not None:
                ops.append(op)
        return ops

    def terminate_node(self, node_id: str) -> None:
        with self._lock:
            for sid, s in self._slices.items():
                if node_id in s.hosts:
                    break
            else:
                return
        # Terminating ANY host tears down the whole slice — a slice with a
        # missing host is not a smaller slice, it's a broken one (no ICI
        # wraparound).  Delete FIRST, untrack after: a failed delete must
        # leave the slice visible so it can be re-terminated, not orphan a
        # live (billing) slice.
        self._api.delete_slice(sid)
        with self._lock:
            self._slices.pop(sid, None)

    # -- provisioning state machine --------------------------------------

    def _begin_launch(self, accel, hosts, type_name, labels,
                      attempts: int = 0) -> Optional[str]:
        try:
            op_id = self._api.create_slice(accel, hosts, labels)
        except TpuCapacityError as e:
            if attempts >= self._max_retries:
                self.failed_launches.append(
                    {"node_type": type_name, "error": str(e)})
                return None
            with self._lock:
                self._launches.append(_Launch(
                    op_id="", node_type=type_name, labels=labels,
                    attempts=attempts + 1,
                    next_poll=time.monotonic() +
                    self._backoff * (2 ** attempts),
                    accel=accel, hosts=hosts))
            return None
        with self._lock:
            self._launches.append(_Launch(op_id=op_id, node_type=type_name,
                                          labels=labels, attempts=attempts))
        return op_id

    def _poll_launches(self) -> None:
        now = time.monotonic()
        with self._lock:
            launches, self._launches = self._launches, []
        for ln in launches:
            if ln.op_id == "":
                # a backoff-scheduled retry of a capacity failure
                if now >= ln.next_poll:
                    self._begin_launch(ln.accel, ln.hosts, ln.node_type,
                                       ln.labels, attempts=ln.attempts)
                else:
                    with self._lock:
                        self._launches.append(ln)
                continue
            op = self._api.get_operation(ln.op_id)
            if op["state"] == PENDING:
                with self._lock:
                    self._launches.append(ln)
            elif op["state"] == READY:
                with self._lock:
                    self._slices[ln.op_id] = _Slice(
                        slice_id=ln.op_id, node_type=ln.node_type,
                        hosts=list(op["hosts"]), labels=ln.labels)
            else:  # FAILED — tear down any partially-created hosts
                try:
                    self._api.delete_slice(ln.op_id)
                except Exception:
                    pass
                self.failed_launches.append(
                    {"node_type": ln.node_type,
                     "error": op.get("error") or "operation failed"})
