"""Elastic cluster scaling.

Design analog: reference ``python/ray/autoscaler/_private/`` --
StandardAutoscaler (autoscaler.py:167), ResourceDemandScheduler
(resource_demand_scheduler.py:103), Monitor (monitor.py:126), NodeProvider
(autoscaler/node_provider.py:13).

TPU-first divergence: the scaling unit is a *node type* that may be an entire
TPU slice (all hosts of a slice come and go together -- a slice is atomic,
unlike the reference's per-VM granularity).
"""

from ray_tpu.autoscaler.node_provider import (NodeProvider, NodeTypeConfig,
                                              LocalNodeProvider)
from ray_tpu.autoscaler.resource_demand_scheduler import (
    ResourceDemandScheduler, fits, subtract)
from ray_tpu.autoscaler.autoscaler import StandardAutoscaler, AutoscalerConfig
from ray_tpu.autoscaler.monitor import Monitor

__all__ = [
    "NodeProvider", "NodeTypeConfig", "LocalNodeProvider",
    "ResourceDemandScheduler", "StandardAutoscaler", "AutoscalerConfig",
    "Monitor", "fits", "subtract",
]
