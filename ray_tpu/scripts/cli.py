"""`python -m ray_tpu` CLI: cluster lifecycle, observability, jobs.

Design analog: reference ``python/ray/scripts/scripts.py`` -- `ray start:529`,
`ray stop`, `ray status`, `ray list ...` (experimental/state CLI), `ray
timeline`, `ray memory`, `ray job submit/status/logs/stop/list`, `ray
microbenchmark`.

Cluster bookkeeping lives in a session file (default
``/tmp/ray_tpu/cluster.json``) recording daemon PIDs + the GCS address, the
CLI's equivalent of the reference's ``/tmp/ray/ray_current_cluster``.
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import subprocess
import sys
import tempfile
import time
import uuid

SESSION_DIR = os.environ.get("RT_SESSION_DIR",
                             os.path.join(tempfile.gettempdir(), "ray_tpu"))
SESSION_FILE = os.path.join(SESSION_DIR, "cluster.json")


def _load_session() -> dict:
    try:
        with open(SESSION_FILE) as f:
            return json.load(f)
    except (FileNotFoundError, json.JSONDecodeError):
        return {"nodes": []}


def _save_session(sess: dict):
    os.makedirs(SESSION_DIR, exist_ok=True)
    tmp = SESSION_FILE + ".tmp"
    with open(tmp, "w") as f:
        json.dump(sess, f, indent=2)
    os.replace(tmp, SESSION_FILE)


def _connect(args):
    import ray_tpu
    address = getattr(args, "address", None) or \
        os.environ.get("RT_ADDRESS") or _load_session().get("gcs_address")
    if not address:
        sys.exit("error: no running cluster found (no --address, RT_ADDRESS, "
                 f"or {SESSION_FILE})")
    ray_tpu.init(address=address)
    return ray_tpu


# --------------------------------------------------------------- start/stop


def cmd_start(args):
    sess = _load_session()
    ready_file = os.path.join(
        SESSION_DIR, f"node_{uuid.uuid4().hex[:8]}.json")
    os.makedirs(SESSION_DIR, exist_ok=True)
    resources = json.loads(args.resources) if args.resources else {}
    if args.num_cpus is not None:
        resources["CPU"] = float(args.num_cpus)
    cmd = [sys.executable, "-m", "ray_tpu._private.daemon_main",
           "--ready-file", ready_file,
           "--store-capacity", str(args.object_store_memory),
           "--no-parent-watch"]
    if resources:
        cmd += ["--resources", json.dumps(resources)]
    if args.head:
        cmd += ["--head", "--gcs-port", str(args.port),
                "--dashboard-port", str(args.dashboard_port),
                "--gcs-persist-path",
                os.path.join(SESSION_DIR, "gcs_snapshot.json")]
    else:
        address = args.address or sess.get("gcs_address")
        if not address:
            sys.exit("error: worker start needs --address (or a head in the "
                     "session file)")
        cmd += ["--gcs-address", address]
    log_path = os.path.join(SESSION_DIR,
                            f"daemon_{uuid.uuid4().hex[:8]}.log")
    with open(log_path, "ab") as logf:
        proc = subprocess.Popen(cmd, stdout=logf, stderr=subprocess.STDOUT,
                                start_new_session=True)
    deadline = time.monotonic() + 60
    while not os.path.exists(ready_file):
        if proc.poll() is not None:
            sys.exit(f"node daemon exited rc={proc.returncode}; "
                     f"log: {log_path}")
        if time.monotonic() > deadline:
            sys.exit(f"node daemon not ready after 60s; log: {log_path}")
        time.sleep(0.2)
    with open(ready_file) as f:
        info = json.load(f)
    sess.setdefault("nodes", []).append(
        {"pid": proc.pid, "node_id": info["node_id"], "head": args.head,
         "log": log_path})
    if args.head:
        sess["gcs_address"] = info["gcs_address"]
    _save_session(sess)
    print(f"node started: node_id={info['node_id']} pid={proc.pid}")
    if args.head:
        print(f"GCS address: {info['gcs_address']}")
        if info.get("dashboard_address"):
            print(f"dashboard: http://{info['dashboard_address']}/")
        print(f"connect with: ray_tpu.init(address=\"{info['gcs_address']}\")"
              f"  # or RT_ADDRESS={info['gcs_address']}")
    if args.block:
        try:
            proc.wait()
        except KeyboardInterrupt:
            proc.terminate()


def cmd_stop(args):
    sess = _load_session()
    stopped = 0
    for node in sess.get("nodes", []):
        try:
            os.kill(node["pid"], signal.SIGTERM)
            stopped += 1
        except ProcessLookupError:
            pass
    # Head last is unnecessary: SIGTERM is graceful in daemon_main.
    for node in sess.get("nodes", []):
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            try:
                os.kill(node["pid"], 0)
            except ProcessLookupError:
                break
            time.sleep(0.1)
        else:
            try:
                os.kill(node["pid"], signal.SIGKILL)
            except ProcessLookupError:
                pass
    _save_session({"nodes": []})
    # A deliberate stop is a clean teardown: drop the GCS snapshot so the
    # next `start --head` is a fresh cluster, not a resurrection of the old
    # one's detached actors/jobs/KV. (Crash recovery keeps the snapshot
    # because the daemon dies without coming through here.)
    try:
        os.unlink(os.path.join(SESSION_DIR, "gcs_snapshot.json"))
    except OSError:
        pass
    print(f"stopped {stopped} node daemon(s)")


# ------------------------------------------------------------ observability


def cmd_status(args):
    rt = _connect(args)
    from ray_tpu.util import state
    s = state.cluster_summary()
    print(json.dumps(s, indent=2, default=str))


def cmd_list(args):
    rt = _connect(args)
    from ray_tpu.util import state
    fn = {
        "nodes": state.list_nodes,
        "actors": state.list_actors,
        "tasks": state.list_tasks,
        "objects": state.list_objects,
        "placement-groups": state.list_placement_groups,
        "workers": state.list_workers,
    }[args.what]
    rows = fn()
    print(json.dumps(rows[:args.limit], indent=2, default=str))
    if len(rows) > args.limit:
        print(f"... {len(rows) - args.limit} more (use --limit)",
              file=sys.stderr)


def cmd_timeline(args):
    rt = _connect(args)
    events = rt.timeline(args.output)
    print(f"wrote {len(events)} events to {args.output}")


def cmd_memory(args):
    rt = _connect(args)
    from ray_tpu.util import state
    print(json.dumps(state.list_objects(), indent=2, default=str))


def cmd_microbenchmark(args):
    from ray_tpu._private.microbenchmark import main as bench_main
    bench_main()


def cmd_usage(args):
    _connect(args)
    import ray_tpu
    print(json.dumps(ray_tpu.usage_report(), indent=2, default=str))


def cmd_debug(args):
    """List active rpdb sessions and attach (reference: ``ray debug``)."""
    _connect(args)
    from ray_tpu.util import rpdb
    sessions = rpdb.list_sessions()
    if not sessions:
        print("no active debugger sessions")
        return
    for i, s in enumerate(sessions):
        print(f"[{i}] pid {s['pid']}  {s['function']} at "
              f"{s['filename']}:{s['lineno']}  ({s['host']}:{s['port']})")
    idx = args.index
    if idx is None:
        if len(sessions) == 1:
            idx = 0
        else:
            idx = int(input("attach to which session? "))
    print(f"attaching to [{idx}]; type 'c' to continue the task")
    rpdb.connect(sessions[idx])


_LAUNCHERS: dict = {}   # cluster_name -> ClusterLauncher (this process)


def cmd_up(args):
    """Launch a cluster from a YAML config (reference: ray up)."""
    from ray_tpu.autoscaler.launcher import ClusterConfig, ClusterLauncher
    cfg = ClusterConfig.from_file(args.config_file)
    launcher = ClusterLauncher(cfg)
    launched = launcher.up(start_monitor=not args.no_monitor)
    _LAUNCHERS[cfg.cluster_name] = launcher
    print(json.dumps({"cluster": cfg.cluster_name, "launched": launched}))
    if not args.no_monitor and not args.no_block:
        print("autoscaler monitor running; Ctrl-C to tear down")
        try:
            import time as _t
            while True:
                _t.sleep(3600)
        except KeyboardInterrupt:
            n = launcher.down()
            print(f"terminated {n} nodes")


def cmd_down(args):
    from ray_tpu.autoscaler.launcher import ClusterConfig, ClusterLauncher
    cfg = ClusterConfig.from_file(args.config_file)
    launcher = _LAUNCHERS.pop(cfg.cluster_name, None) or \
        ClusterLauncher(cfg)
    n = launcher.down()
    print(f"terminated {n} nodes of cluster {cfg.cluster_name}")


def cmd_serve(args):
    """serve deploy/status/delete/shutdown (reference: serve CLI in
    python/ray/serve/scripts.py over the REST schema)."""
    _connect(args)
    from ray_tpu import serve
    if args.serve_cmd == "deploy":
        from ray_tpu.serve.schema import (ServeApplicationSchema,
                                          deploy_application)
        st = deploy_application(ServeApplicationSchema.from_file(
            args.config_file))
        print(json.dumps(st, indent=2, default=str))
    elif args.serve_cmd == "status":
        print(json.dumps(serve.status(), indent=2, default=str))
    elif args.serve_cmd == "delete":
        serve.delete(args.name)
        print(f"deleted: {args.name}")
    elif args.serve_cmd == "shutdown":
        serve.shutdown()
        print("serve shut down")


# --------------------------------------------------------------------- jobs


def cmd_job(args):
    from ray_tpu.job import JobSubmissionClient
    client = JobSubmissionClient(
        getattr(args, "address", None) or
        os.environ.get("RT_ADDRESS") or _load_session().get("gcs_address"))
    if args.job_cmd == "submit":
        import shlex
        ep = args.entrypoint
        if ep and ep[0] == "--":
            ep = ep[1:]
        renv = None
        if getattr(args, "runtime_env_json", None):
            renv = json.loads(args.runtime_env_json)
        sid = client.submit_job(entrypoint=shlex.join(ep),
                                runtime_env=renv)
        print(f"submitted: {sid}")
        if args.wait:
            status = client.wait_until_finished(sid, timeout=args.timeout)
            print(client.get_job_logs(sid), end="")
            print(f"status: {status}")
            sys.exit(0 if status == "SUCCEEDED" else 1)
    elif args.job_cmd == "status":
        print(client.get_job_status(args.id))
    elif args.job_cmd == "logs":
        print(client.get_job_logs(args.id), end="")
    elif args.job_cmd == "stop":
        print(client.stop_job(args.id))
    elif args.job_cmd == "list":
        for info in client.list_jobs():
            print(f"{info.submission_id}  {info.status:10s}  "
                  f"{info.entrypoint}")


# --------------------------------------------------------------------- main


def main(argv=None):
    p = argparse.ArgumentParser(
        prog="ray_tpu", description="ray_tpu cluster CLI")
    sub = p.add_subparsers(dest="cmd", required=True)

    sp = sub.add_parser("start", help="start a node daemon on this host")
    sp.add_argument("--head", action="store_true")
    sp.add_argument("--address", help="GCS address to join (worker nodes)")
    sp.add_argument("--port", type=int, default=6380,
                    help="GCS port (head only)")
    sp.add_argument("--dashboard-port", type=int, default=8265,
                    help="HTTP dashboard port (head only; -1 disables)")
    sp.add_argument("--num-cpus", type=int, default=None)
    sp.add_argument("--resources", help="JSON resource dict")
    sp.add_argument("--object-store-memory", type=int,
                    default=512 * 1024 * 1024)
    sp.add_argument("--block", action="store_true")
    sp.set_defaults(fn=cmd_start)

    sp = sub.add_parser("stop", help="stop all node daemons in the session")
    sp.set_defaults(fn=cmd_stop)

    for name, fn in [("status", cmd_status)]:
        sp = sub.add_parser(name)
        sp.add_argument("--address")
        sp.set_defaults(fn=fn)

    sp = sub.add_parser("list", help="list cluster state")
    sp.add_argument("what", choices=["nodes", "actors", "tasks", "objects",
                                     "placement-groups", "workers"])
    sp.add_argument("--address")
    sp.add_argument("--limit", type=int, default=100)
    sp.set_defaults(fn=cmd_list)

    sp = sub.add_parser("usage", help="print the local usage report")
    sp.add_argument("--address", default=None)
    sp.set_defaults(fn=cmd_usage)

    sp = sub.add_parser("timeline", help="dump Chrome trace of task events")
    sp.add_argument("--address")
    sp.add_argument("--output", default="timeline.json")
    sp.set_defaults(fn=cmd_timeline)

    sp = sub.add_parser("memory", help="dump the cluster object directory")
    sp.add_argument("--address")
    sp.set_defaults(fn=cmd_memory)

    sp = sub.add_parser("microbenchmark", help="run the perf microbenchmark")
    sp.set_defaults(fn=cmd_microbenchmark)

    sp = sub.add_parser("up", help="launch a cluster from a YAML config")
    sp.add_argument("config_file")
    sp.add_argument("--no-monitor", action="store_true",
                    help="bootstrap min_workers only; no autoscaling loop")
    sp.add_argument("--no-block", action="store_true",
                    help="return immediately after bootstrap")
    sp.set_defaults(fn=cmd_up)

    sp = sub.add_parser("down", help="tear down a launched cluster")
    sp.add_argument("config_file")
    sp.set_defaults(fn=cmd_down)

    sp = sub.add_parser("serve", help="manage Serve deployments")
    ssub = sp.add_subparsers(dest="serve_cmd", required=True)
    s = ssub.add_parser("deploy")
    s.add_argument("config_file", help="YAML/JSON application config")
    s.add_argument("--address")
    for name in ("status", "shutdown"):
        s = ssub.add_parser(name)
        s.add_argument("--address")
    s = ssub.add_parser("delete")
    s.add_argument("name")
    s.add_argument("--address")
    sp.set_defaults(fn=cmd_serve)

    sp = sub.add_parser("debug",
                        help="attach to an rpdb breakpoint in a worker")
    sp.add_argument("--address")
    sp.add_argument("--index", type=int, default=None,
                    help="session index (default: prompt, or 0 if single)")
    sp.set_defaults(fn=cmd_debug)

    sp = sub.add_parser("job")
    jsub = sp.add_subparsers(dest="job_cmd", required=True)
    j = jsub.add_parser("submit")
    j.add_argument("--address")
    j.add_argument("--wait", action="store_true")
    j.add_argument("--timeout", type=float, default=600.0)
    j.add_argument("--runtime-env-json", dest="runtime_env_json",
                   help='JSON runtime env, e.g. '
                        '\'{"working_dir": ".", "pip": [...]}\'')
    j.add_argument("entrypoint", nargs=argparse.REMAINDER,
                   help="shell command to run as the job driver")
    for name in ["status", "logs", "stop"]:
        j = jsub.add_parser(name)
        j.add_argument("id")
        j.add_argument("--address")
    j = jsub.add_parser("list")
    j.add_argument("--address")
    sp.set_defaults(fn=cmd_job)

    args = p.parse_args(argv)
    args.fn(args)


if __name__ == "__main__":
    main()
