"""ModelCatalog + attention-core policy.

Design analogs: reference ``rllib/models/catalog.py:189`` (ModelCatalog:
model_config -> network), ``rllib/models/torch/attention_net.py:37``
(GTrXLNet: transformer memory with GRU-style gating) and
``recurrent_net.py`` (LSTMWrapper).  TPU-first: the attention core is a
fixed-window memory (a [K, E] ring carried through ``lax.scan``), so the
whole sequence forward is one fused program with static shapes — no
dynamic-length attention masks.

``ModelCatalog.policy_for(config)`` routes a model config to the policy
implementation, mirroring how the reference's catalog picks
FullyConnectedNetwork / LSTMWrapper / AttentionWrapper from
``model={"use_lstm": ..., "use_attention": ...}``.
"""

from __future__ import annotations

from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ray_tpu.rllib.policy import Categorical, DiagGaussian, Policy, \
    _orthogonal
from ray_tpu.rllib.sample_batch import (ACTIONS, ACTION_LOGP, ADVANTAGES,
                                        OBS, VALUE_TARGETS, VF_PREDS)
from ray_tpu.rllib.recurrent import (RESETS, STATE_IN,  # noqa: F401
                                     StatefulPPOPolicy,
                                     masked_seq_forward)


class ModelCatalog:
    """Route a model config to a policy implementation (reference
    ``ModelCatalog.get_model_v2``)."""

    @staticmethod
    def policy_for(config: Dict[str, Any]) -> str:
        base = config.get("policy", "ppo")
        model = config.get("model") or {}
        # Memory wrappers only exist for the PPO family here; silently
        # swapping a DQN/SAC/IMPALA policy for a PPO one would break
        # those algorithms' training_step contracts (update_target etc).
        if base == "ppo":
            if model.get("use_attention"):
                return "attention_ppo"
            if model.get("use_lstm"):
                return "recurrent_ppo"
        elif model.get("use_attention") or model.get("use_lstm"):
            raise ValueError(
                f"model memory wrappers (use_lstm/use_attention) are only "
                f"supported with the ppo policy, not {base!r}")
        return base


# ------------------------------------------------- attention (GTrXL-lite)

def attn_init(rng: jax.Array, obs_dim: int, num_outputs: int,
              embed: int = 64, memory: int = 8,
              head_scale: float = 0.01) -> Dict:
    k = jax.random.split(rng, 7)
    return {
        "embed": {"w": _orthogonal(k[0], (obs_dim, embed), jnp.sqrt(2.0)),
                  "b": jnp.zeros((embed,))},
        # Relative slot embedding over the K-deep memory ring.
        "pos": jax.random.normal(k[1], (memory, embed)) * 0.02,
        "q": {"w": _orthogonal(k[2], (embed, embed), 1.0)},
        "kv": {"w": _orthogonal(k[3], (embed, 2 * embed), 1.0)},
        # GRU-style gate on the attention residual (the GTrXL trick that
        # stabilizes early RL training: start mostly-identity).
        "gate": {"w": _orthogonal(k[4], (2 * embed, embed), 1.0),
                 "b": jnp.full((embed,), 2.0)},   # bias>0 -> pass-through
        "pi": {"w": _orthogonal(k[5], (embed, num_outputs), head_scale),
               "b": jnp.zeros((num_outputs,))},
        "vf": {"w": _orthogonal(k[6], (embed, 1), 1.0),
               "b": jnp.zeros((1,))},
    }


def attn_step(params: Dict, state: jax.Array, obs: jax.Array
              ) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """One timestep.  state [n, K, E] is a ring of the last K embeddings
    (slot K-1 = most recent); obs [n, D] -> (pi, v, new_state)."""
    x = jnp.tanh(obs @ params["embed"]["w"] + params["embed"]["b"])
    # Shift the memory and append the current embedding.
    state = jnp.concatenate([state[:, 1:], x[:, None, :]], axis=1)
    mem = state + params["pos"][None]
    q = x @ params["q"]["w"]                          # [n, E]
    kv = mem @ params["kv"]["w"]                      # [n, K, 2E]
    keys, vals = jnp.split(kv, 2, axis=-1)
    E = q.shape[-1]
    att = jax.nn.softmax(
        jnp.einsum("ne,nke->nk", q, keys) / jnp.sqrt(E), axis=-1)
    ctx = jnp.einsum("nk,nke->ne", att, vals)
    g = jax.nn.sigmoid(
        jnp.concatenate([x, ctx], axis=-1) @ params["gate"]["w"]
        + params["gate"]["b"])
    h = g * x + (1.0 - g) * jnp.tanh(ctx)
    pi = h @ params["pi"]["w"] + params["pi"]["b"]
    v = (h @ params["vf"]["w"] + params["vf"]["b"])[:, 0]
    return pi, v, state


def attn_seq_forward(params: Dict, state0: jax.Array, obs: jax.Array,
                     resets: jax.Array) -> Tuple[jax.Array, jax.Array]:
    """Time-major [T, n, D] forward with in-scan episode resets (same
    contract as lstm_seq_forward)."""
    return masked_seq_forward(attn_step, params, state0, obs, resets)


class AttentionPPOPolicy(StatefulPPOPolicy):
    """PPO over the windowed-attention memory core; all PPO machinery
    (jitted act/update, fragment loss, state plumbing) comes from
    StatefulPPOPolicy — only the core differs."""

    def _init_params(self, rng, obs_dim, num_outputs, config):
        model = config.get("model") or {}
        self.embed = int(model.get("attention_dim", 64))
        self.memory = int(model.get("attention_memory", 8))
        return attn_init(rng, obs_dim, num_outputs,
                         embed=self.embed, memory=self.memory)

    def _step_fn(self):
        return attn_step

    def _state_shape(self):
        return (self.memory, self.embed)
