"""ModelCatalog + attention-core policy.

Design analogs: reference ``rllib/models/catalog.py:189`` (ModelCatalog:
model_config -> network), ``rllib/models/torch/attention_net.py:37``
(GTrXLNet: transformer memory with GRU-style gating) and
``recurrent_net.py`` (LSTMWrapper).  TPU-first: the attention core is a
fixed-window memory (a [K, E] ring carried through ``lax.scan``), so the
whole sequence forward is one fused program with static shapes — no
dynamic-length attention masks.

``ModelCatalog.policy_for(config)`` routes a model config to the policy
implementation, mirroring how the reference's catalog picks
FullyConnectedNetwork / LSTMWrapper / AttentionWrapper from
``model={"use_lstm": ..., "use_attention": ...}``.
"""

from __future__ import annotations

from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ray_tpu.rllib.policy import Categorical, DiagGaussian, Policy, \
    _orthogonal
from ray_tpu.rllib.sample_batch import (ACTIONS, ACTION_LOGP, ADVANTAGES,
                                        OBS, VALUE_TARGETS, VF_PREDS)
from ray_tpu.rllib.recurrent import RESETS, STATE_IN


class ModelCatalog:
    """Route a model config to a policy implementation (reference
    ``ModelCatalog.get_model_v2``)."""

    @staticmethod
    def policy_for(config: Dict[str, Any]) -> str:
        base = config.get("policy", "ppo")
        model = config.get("model") or {}
        # Memory wrappers only exist for the PPO family here; silently
        # swapping a DQN/SAC/IMPALA policy for a PPO one would break
        # those algorithms' training_step contracts (update_target etc).
        if base == "ppo":
            if model.get("use_attention"):
                return "attention_ppo"
            if model.get("use_lstm"):
                return "recurrent_ppo"
        elif model.get("use_attention") or model.get("use_lstm"):
            raise ValueError(
                f"model memory wrappers (use_lstm/use_attention) are only "
                f"supported with the ppo policy, not {base!r}")
        return base


# ------------------------------------------------- attention (GTrXL-lite)

def attn_init(rng: jax.Array, obs_dim: int, num_outputs: int,
              embed: int = 64, memory: int = 8,
              head_scale: float = 0.01) -> Dict:
    k = jax.random.split(rng, 7)
    return {
        "embed": {"w": _orthogonal(k[0], (obs_dim, embed), jnp.sqrt(2.0)),
                  "b": jnp.zeros((embed,))},
        # Relative slot embedding over the K-deep memory ring.
        "pos": jax.random.normal(k[1], (memory, embed)) * 0.02,
        "q": {"w": _orthogonal(k[2], (embed, embed), 1.0)},
        "kv": {"w": _orthogonal(k[3], (embed, 2 * embed), 1.0)},
        # GRU-style gate on the attention residual (the GTrXL trick that
        # stabilizes early RL training: start mostly-identity).
        "gate": {"w": _orthogonal(k[4], (2 * embed, embed), 1.0),
                 "b": jnp.full((embed,), 2.0)},   # bias>0 -> pass-through
        "pi": {"w": _orthogonal(k[5], (embed, num_outputs), head_scale),
               "b": jnp.zeros((num_outputs,))},
        "vf": {"w": _orthogonal(k[6], (embed, 1), 1.0),
               "b": jnp.zeros((1,))},
    }


def attn_step(params: Dict, state: jax.Array, obs: jax.Array
              ) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """One timestep.  state [n, K, E] is a ring of the last K embeddings
    (slot K-1 = most recent); obs [n, D] -> (pi, v, new_state)."""
    x = jnp.tanh(obs @ params["embed"]["w"] + params["embed"]["b"])
    # Shift the memory and append the current embedding.
    state = jnp.concatenate([state[:, 1:], x[:, None, :]], axis=1)
    mem = state + params["pos"][None]
    q = x @ params["q"]["w"]                          # [n, E]
    kv = mem @ params["kv"]["w"]                      # [n, K, 2E]
    keys, vals = jnp.split(kv, 2, axis=-1)
    E = q.shape[-1]
    att = jax.nn.softmax(
        jnp.einsum("ne,nke->nk", q, keys) / jnp.sqrt(E), axis=-1)
    ctx = jnp.einsum("nk,nke->ne", att, vals)
    g = jax.nn.sigmoid(
        jnp.concatenate([x, ctx], axis=-1) @ params["gate"]["w"]
        + params["gate"]["b"])
    h = g * x + (1.0 - g) * jnp.tanh(ctx)
    pi = h @ params["pi"]["w"] + params["pi"]["b"]
    v = (h @ params["vf"]["w"] + params["vf"]["b"])[:, 0]
    return pi, v, state


def attn_seq_forward(params: Dict, state0: jax.Array, obs: jax.Array,
                     resets: jax.Array) -> Tuple[jax.Array, jax.Array]:
    """Time-major [T, n, D] forward with in-scan episode resets (same
    contract as lstm_seq_forward)."""

    def body(state, inp):
        o_t, r_t = inp
        state = state * (1.0 - r_t)[:, None, None]
        pi, v, state = attn_step(params, state, o_t)
        return state, (pi, v)

    _, (pi, v) = jax.lax.scan(body, state0, (obs, resets))
    return pi, v


class AttentionPPOPolicy(Policy):
    """PPO over the windowed-attention memory core; trains on [T, n]
    fragments with the same state plumbing as RecurrentPPOPolicy."""

    recurrent = True

    def __init__(self, obs_dim: int, action_space, config: Dict[str, Any],
                 seed: int = 0):
        self.config = config
        self.discrete = action_space.kind == "discrete"
        self.dist = Categorical if self.discrete else DiagGaussian
        num_outputs = (action_space.n if self.discrete
                       else 2 * int(np.prod(action_space.shape)))
        model = config.get("model") or {}
        self.embed = int(model.get("attention_dim", 64))
        self.memory = int(model.get("attention_memory", 8))
        self._rng = jax.random.PRNGKey(seed)
        self._rng, init_rng = jax.random.split(self._rng)
        self.params = attn_init(init_rng, obs_dim, num_outputs,
                                embed=self.embed, memory=self.memory)
        import optax
        self._tx = optax.chain(
            optax.clip_by_global_norm(config.get("grad_clip", 0.5)),
            optax.adam(config.get("lr", 3e-4)))
        self.opt_state = self._tx.init(self.params)
        self._state = None
        dist = self.dist

        @jax.jit
        def _act(params, rng, state, obs):
            pi, v, state = attn_step(params, state, obs)
            actions = dist.sample(rng, pi)
            return actions, dist.logp(pi, actions), v, state
        self._act = _act

        clip = config.get("clip_param", 0.2)
        vf_coeff = config.get("vf_loss_coeff", 0.5)
        ent_coeff = config.get("entropy_coeff", 0.01)
        num_epochs = config.get("num_sgd_iter", 4)

        def _loss(params, batch):
            pi, v = attn_seq_forward(params, batch[STATE_IN], batch[OBS],
                                     batch[RESETS])
            T, n = v.shape
            flat_pi = pi.reshape((T * n,) + pi.shape[2:])
            acts = batch[ACTIONS].reshape((T * n,)
                                          + batch[ACTIONS].shape[2:])
            logp = dist.logp(flat_pi, acts).reshape(T, n)
            ratio = jnp.exp(logp - batch[ACTION_LOGP])
            adv = batch[ADVANTAGES]
            surr = jnp.minimum(ratio * adv,
                               jnp.clip(ratio, 1 - clip, 1 + clip) * adv)
            vf_err = (v - batch[VALUE_TARGETS]) ** 2
            entropy = dist.entropy(flat_pi)
            total = (-jnp.mean(surr) + vf_coeff * jnp.mean(vf_err)
                     - ent_coeff * jnp.mean(entropy))
            return total, {"policy_loss": -jnp.mean(surr),
                           "vf_loss": jnp.mean(vf_err),
                           "entropy": jnp.mean(entropy),
                           "total_loss": total}

        @jax.jit
        def _update(params, opt_state, batch):
            import optax as _optax

            def epoch(carry, _):
                params, opt_state = carry
                (_, stats), grads = jax.value_and_grad(
                    _loss, has_aux=True)(params, batch)
                updates, opt_state = self._tx.update(grads, opt_state)
                params = _optax.apply_updates(params, updates)
                return (params, opt_state), stats

            (params, opt_state), stats = jax.lax.scan(
                epoch, (params, opt_state), jnp.arange(num_epochs))
            return params, opt_state, jax.tree.map(lambda s: s[-1], stats)
        self._update = _update

    # -- rollout side (same contract the rollout worker drives) ----------

    def _ensure_state(self, n: int):
        if self._state is None or self._state.shape[0] != n:
            self._state = jnp.zeros((n, self.memory, self.embed),
                                    jnp.float32)

    def state_snapshot(self) -> np.ndarray:
        return np.asarray(self._state)

    def notify_dones(self, done: np.ndarray) -> None:
        if done.any():
            mask = jnp.asarray(~done, jnp.float32)[:, None, None]
            self._state = self._state * mask

    def compute_actions(self, obs: np.ndarray) -> Dict[str, np.ndarray]:
        self._ensure_state(obs.shape[0])
        self._rng, rng = jax.random.split(self._rng)
        actions, logp, v, self._state = self._act(
            self.params, rng, self._state, jnp.asarray(obs, jnp.float32))
        return {ACTIONS: np.asarray(actions),
                ACTION_LOGP: np.asarray(logp), VF_PREDS: np.asarray(v)}

    def compute_values(self, obs: np.ndarray) -> np.ndarray:
        self._ensure_state(obs.shape[0])
        _, v, _ = attn_step(self.params, self._state,
                            jnp.asarray(obs, jnp.float32))
        return np.asarray(v)

    # -- learner side -----------------------------------------------------

    def learn_on_batch(self, batch) -> Dict[str, float]:
        adv = np.asarray(batch[ADVANTAGES], np.float32)
        batch = dict(batch)
        batch[ADVANTAGES] = (adv - adv.mean()) / (adv.std() + 1e-8)
        device_batch = {
            k: jnp.asarray(np.asarray(
                v, None if k == ACTIONS else np.float32))
            for k, v in batch.items()}
        self.params, self.opt_state, stats = self._update(
            self.params, self.opt_state, device_batch)
        return {k: float(v) for k, v in stats.items()}

    def get_weights(self):
        return jax.tree.map(np.asarray, self.params)

    def set_weights(self, weights):
        self.params = jax.tree.map(jnp.asarray, weights)
