"""TD3: twin-delayed deep deterministic policy gradient.

Design analog: reference ``rllib/algorithms/td3/td3.py`` (DDPG +
the three TD3 fixes: twin critics, delayed policy updates, target policy
smoothing).  TPU-first: the whole update — both critics every step, actor
+ targets every ``policy_delay`` steps via lax.cond — is ONE jitted
program; exploration noise is explicit-PRNG Gaussian on the host side of
the actor.  Shares the replay-driven Algorithm shape with SAC/DQN.
"""

from __future__ import annotations

from typing import Any, Dict

import numpy as np

import jax
import jax.numpy as jnp

from ray_tpu.rllib.algorithm import Algorithm, AlgorithmConfig
from ray_tpu.rllib.policy import Policy
from ray_tpu.rllib.replay_buffer import ReplayBuffer
from ray_tpu.rllib.sac import _mlp, _mlp_init, _q_forward
from ray_tpu.rllib.sample_batch import (ACTIONS, DONES, NEXT_OBS, OBS,
                                        REWARDS, SampleBatch)


class TD3Config(AlgorithmConfig):
    def __init__(self):
        super().__init__(TD3)
        self._config.update({
            "policy": "td3",
            "hiddens": (64, 64),
            "actor_lr": 1e-3,
            "critic_lr": 1e-3,
            "tau": 0.005,
            "policy_delay": 2,
            "exploration_noise": 0.1,       # of action scale, rollout side
            "target_noise": 0.2,            # smoothing noise on targets
            "target_noise_clip": 0.5,
            "train_batch_size": 256,
            "buffer_size": 100_000,
            "learning_starts": 1500,
            "num_train_iters": 8,
            "rollout_fragment_length": 8,
            "num_envs_per_worker": 8,
            "gamma": 0.99,
        })


class TD3Policy(Policy):
    replay_style = True

    def __init__(self, obs_dim: int, action_space, config: Dict[str, Any],
                 seed: int = 0):
        if action_space.kind != "box":
            raise ValueError("TD3 requires a continuous (box) action space")
        self.config = config
        act_dim = int(np.prod(action_space.shape)) or 1
        self.act_dim = act_dim
        self.act_scale = float(action_space.high)
        hid = tuple(config.get("hiddens", (64, 64)))
        key = jax.random.PRNGKey(seed)
        ka, k1, k2 = jax.random.split(key, 3)
        actor = _mlp_init(ka, (obs_dim,) + hid + (act_dim,))
        q1 = _mlp_init(k1, (obs_dim + act_dim,) + hid + (1,))
        q2 = _mlp_init(k2, (obs_dim + act_dim,) + hid + (1,))
        self.params = {"actor": actor, "q1": q1, "q2": q2}
        self.target = jax.tree.map(jnp.copy, self.params)

        import optax
        self._tx = {"actor": optax.adam(config.get("actor_lr", 1e-3)),
                    "critic": optax.adam(config.get("critic_lr", 1e-3))}
        self.opt_state = {
            "actor": self._tx["actor"].init(actor),
            "critic": self._tx["critic"].init({"q1": q1, "q2": q2}),
        }
        self._key = jax.random.PRNGKey(seed + 7)
        self._updates = 0
        gamma = config.get("gamma", 0.99)
        tau = config.get("tau", 0.005)
        delay = config.get("policy_delay", 2)
        scale = self.act_scale
        expl = config.get("exploration_noise", 0.1) * scale
        tnoise = config.get("target_noise", 0.2) * scale
        tclip = config.get("target_noise_clip", 0.5) * scale

        def _pi(actor, obs):
            return jnp.tanh(_mlp(actor, obs)) * scale

        @jax.jit
        def _act(actor, obs, key, deterministic):
            a = _pi(actor, obs)
            noise = expl * jax.random.normal(key, a.shape)
            return jnp.where(deterministic, a,
                             jnp.clip(a + noise, -scale, scale))
        self._act_fn = _act

        @jax.jit
        def _update(params, target, opt_state, batch, key, step):
            # -- twin-critic update with target policy smoothing
            noise = jnp.clip(
                tnoise * jax.random.normal(key, (batch[OBS].shape[0],
                                                 act_dim)),
                -tclip, tclip)
            a_next = jnp.clip(_pi(target["actor"], batch[NEXT_OBS]) + noise,
                              -scale, scale)
            qn = jnp.minimum(
                _q_forward(target["q1"], batch[NEXT_OBS], a_next),
                _q_forward(target["q2"], batch[NEXT_OBS], a_next))
            backup = jax.lax.stop_gradient(
                batch[REWARDS] + gamma
                * (1.0 - batch[DONES].astype(jnp.float32)) * qn)

            def critic_loss(qs):
                l1 = jnp.mean((_q_forward(qs["q1"], batch[OBS],
                                          batch[ACTIONS]) - backup) ** 2)
                l2 = jnp.mean((_q_forward(qs["q2"], batch[OBS],
                                          batch[ACTIONS]) - backup) ** 2)
                return l1 + l2

            qs = {"q1": params["q1"], "q2": params["q2"]}
            closs, cgrads = jax.value_and_grad(critic_loss)(qs)
            cupd, opt_c = self._tx["critic"].update(
                cgrads, opt_state["critic"])
            import optax as _ox
            qs = _ox.apply_updates(qs, cupd)

            # -- delayed actor + target updates (lax.cond keeps the whole
            # step one compiled program; the predicate is a traced scalar)
            def do_actor(_):
                def actor_loss(actor):
                    a = _pi(actor, batch[OBS])
                    return -jnp.mean(_q_forward(qs["q1"], batch[OBS], a))
                aloss, agrads = jax.value_and_grad(actor_loss)(
                    params["actor"])
                aupd, opt_a = self._tx["actor"].update(
                    agrads, opt_state["actor"])
                actor = _ox.apply_updates(params["actor"], aupd)
                new = {"actor": actor, **qs}
                tgt = jax.tree.map(lambda t, o: (1 - tau) * t + tau * o,
                                   target, new)
                return actor, opt_a, tgt, aloss

            def skip_actor(_):
                return (params["actor"], opt_state["actor"], target,
                        jnp.zeros(()))

            actor, opt_a, target_new, aloss = jax.lax.cond(
                step % delay == 0, do_actor, skip_actor, operand=None)
            params = {"actor": actor, "q1": qs["q1"], "q2": qs["q2"]}
            opt_state = {"actor": opt_a, "critic": opt_c}
            stats = {"critic_loss": closs, "actor_loss": aloss,
                     "mean_q": jnp.mean(backup)}
            return params, target_new, opt_state, stats
        self._update = _update

    # -- rollout side -----------------------------------------------------

    def compute_actions(self, obs: np.ndarray) -> Dict[str, np.ndarray]:
        self._key, k = jax.random.split(self._key)
        a = self._act_fn(self.params["actor"],
                         jnp.asarray(obs, jnp.float32), k, False)
        return {ACTIONS: np.asarray(a, np.float32)}

    # -- learner side -----------------------------------------------------

    def learn_on_batch(self, batch: SampleBatch) -> Dict[str, Any]:
        device_batch = {
            OBS: jnp.asarray(np.asarray(batch[OBS], np.float32)),
            NEXT_OBS: jnp.asarray(np.asarray(batch[NEXT_OBS], np.float32)),
            ACTIONS: jnp.asarray(
                np.asarray(batch[ACTIONS], np.float32).reshape(
                    batch.count, self.act_dim)),
            REWARDS: jnp.asarray(np.asarray(batch[REWARDS], np.float32)),
            DONES: jnp.asarray(np.asarray(batch[DONES])),
        }
        self._key, k = jax.random.split(self._key)
        self.params, self.target, self.opt_state, stats = self._update(
            self.params, self.target, self.opt_state, device_batch, k,
            jnp.asarray(self._updates, jnp.int32))
        self._updates += 1
        return {k2: float(v) for k2, v in stats.items()}

    def update_target(self):
        pass  # polyak-averaged inside the delayed update

    def get_weights(self):
        return jax.tree.map(np.asarray, self.params)

    def set_weights(self, weights):
        self.params = jax.tree.map(jnp.asarray, weights)


class TD3(Algorithm):
    def setup(self, config: Dict[str, Any]) -> None:
        config = dict(config)
        config.setdefault("policy", "td3")
        super().setup(config)
        self.replay = ReplayBuffer(config.get("buffer_size", 100_000),
                                   seed=config.get("seed", 0))

    def training_step(self) -> Dict[str, Any]:
        c = self.config
        batch = self.workers.synchronous_sample()
        self._timesteps_total += batch.count
        self.replay.add(batch)
        stats: Dict[str, Any] = {}
        policy = self.workers.local_worker.policy
        if len(self.replay) >= c.get("learning_starts", 1500):
            for _ in range(c.get("num_train_iters", 8)):
                train = self.replay.sample(c.get("train_batch_size", 256))
                stats = policy.learn_on_batch(train)
            self.workers.sync_weights()
        return {"info": {"learner": stats}, **stats}
