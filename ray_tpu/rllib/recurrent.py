"""Recurrent (LSTM) policy support for PPO.

Design analog: reference ``rllib/models/torch/recurrent_net.py``
(LSTMWrapper: obs embed -> LSTM -> pi/vf heads) and the sequence-aware
PPO loss in ``torch_policy_v2.py`` (time-major forward with per-episode
state resets).  TPU-first deltas: the network is a pure pytree, the
sequence forward is a ``lax.scan`` over time (static shapes, one fused
program), and the whole PPO update — epochs included — is a single
jitted call, so fragment training costs one dispatch.

State plumbing mirrors the reference's sampler contract: the rollout
worker snapshots the hidden state at fragment start (``state_in``),
carries it across steps, and zeroes finished envs' rows; the learner
replays the same resets inside the scan via the shifted ``dones`` mask.
"""

from __future__ import annotations

from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ray_tpu.rllib.policy import (Categorical, DiagGaussian, Policy,
                                  _orthogonal)
from ray_tpu.rllib.sample_batch import (ACTIONS, ACTION_LOGP, ADVANTAGES,
                                        DONES, OBS, VALUE_TARGETS, VF_PREDS)

STATE_IN = "state_in"       # [n, 2, H] fragment-start LSTM state
RESETS = "resets"           # [T, n] 1.0 where state must zero BEFORE step t


# -- LSTM actor-critic ----------------------------------------------------

def lstm_init(rng: jax.Array, obs_dim: int, num_outputs: int,
              embed: int = 64, hidden: int = 64,
              head_scale: float = 0.01) -> Dict:
    k = jax.random.split(rng, 5)
    return {
        "embed": {"w": _orthogonal(k[0], (obs_dim, embed), jnp.sqrt(2.0)),
                  "b": jnp.zeros((embed,))},
        # One fused kernel for the 4 gates (i, f, g, o): [E+H, 4H].
        "lstm": {"w": _orthogonal(k[1], (embed + hidden, 4 * hidden), 1.0),
                 "b": jnp.zeros((4 * hidden,))},
        "pi": {"w": _orthogonal(k[2], (hidden, num_outputs), head_scale),
               "b": jnp.zeros((num_outputs,))},
        "vf": {"w": _orthogonal(k[3], (hidden, 1), 1.0),
               "b": jnp.zeros((1,))},
    }


def _lstm_cell(params, h, c, x):
    z = jnp.concatenate([x, h], axis=-1) @ params["lstm"]["w"] \
        + params["lstm"]["b"]
    i, f, g, o = jnp.split(z, 4, axis=-1)
    c = jax.nn.sigmoid(f + 1.0) * c + jax.nn.sigmoid(i) * jnp.tanh(g)
    h = jax.nn.sigmoid(o) * jnp.tanh(c)
    return h, c


def lstm_step(params: Dict, state: jax.Array, obs: jax.Array
              ) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """One timestep: state [n, 2, H], obs [n, D] -> (pi, v, new_state)."""
    x = jnp.tanh(obs @ params["embed"]["w"] + params["embed"]["b"])
    h, c = state[:, 0], state[:, 1]
    h, c = _lstm_cell(params, h, c, x)
    pi = h @ params["pi"]["w"] + params["pi"]["b"]
    v = (h @ params["vf"]["w"] + params["vf"]["b"])[:, 0]
    return pi, v, jnp.stack([h, c], axis=1)


def masked_seq_forward(step_fn, params: Dict, state0: jax.Array,
                       obs: jax.Array, resets: jax.Array
                       ) -> Tuple[jax.Array, jax.Array]:
    """Time-major sequence forward with in-scan episode resets, generic
    over the per-step core (LSTM, attention ring, ...).

    obs [T, n, D], resets [T, n] (1.0 zeroes the carried state before
    consuming obs[t] — i.e. env n finished at t-1).  -> pi [T, n, O],
    v [T, n]."""

    def body(state, inp):
        o_t, r_t = inp
        state = state * (1.0 - r_t)[:, None, None]
        pi, v, state = step_fn(params, state, o_t)
        return state, (pi, v)

    _, (pi, v) = jax.lax.scan(body, state0, (obs, resets))
    return pi, v


def lstm_seq_forward(params, state0, obs, resets):
    return masked_seq_forward(lstm_step, params, state0, obs, resets)


# -- policy ---------------------------------------------------------------

class StatefulPPOPolicy(Policy):
    """Shared PPO machinery for policies with a carried per-env state
    (LSTM core, attention-memory core); trains on [T, n] fragments.

    Subclasses provide the core: ``_init_params(rng, obs_dim,
    num_outputs, config)``, ``_step_fn()`` (the (params, state, obs) ->
    (pi, v, state) function), and ``_state_shape()`` (trailing dims of
    the per-env state).  Everything else — the jitted act fn, the
    sequence loss over ``masked_seq_forward``, the epoch-scanned update,
    the rollout-side state plumbing — lives here once.

    The update is one jitted program: epochs x full-fragment gradient
    steps (sequences cannot be flat-shuffled — minibatching, when the env
    count is large, slices the n axis, preserving time order).
    """

    recurrent = True

    def _init_params(self, rng, obs_dim: int, num_outputs: int,
                     config: Dict[str, Any]):
        raise NotImplementedError

    def _step_fn(self):
        raise NotImplementedError

    def _state_shape(self) -> Tuple[int, ...]:
        raise NotImplementedError

    def __init__(self, obs_dim: int, action_space, config: Dict[str, Any],
                 seed: int = 0):
        self.config = config
        self.discrete = action_space.kind == "discrete"
        self.dist = Categorical if self.discrete else DiagGaussian
        num_outputs = (action_space.n if self.discrete
                       else 2 * int(np.prod(action_space.shape)))
        self._rng = jax.random.PRNGKey(seed)
        self._rng, init_rng = jax.random.split(self._rng)
        self.params = self._init_params(init_rng, obs_dim, num_outputs,
                                        config)
        step_fn = self._step_fn()
        self._step = step_fn
        import optax
        self._tx = optax.chain(
            optax.clip_by_global_norm(config.get("grad_clip", 0.5)),
            optax.adam(config.get("lr", 3e-4)))
        self.opt_state = self._tx.init(self.params)
        self._state = None      # lazy: [n, 2, H] once n is known

        dist = self.dist

        @jax.jit
        def _act(params, rng, state, obs):
            pi, v, state = step_fn(params, state, obs)
            actions = dist.sample(rng, pi)
            return actions, dist.logp(pi, actions), v, state
        self._act = _act

        clip = config.get("clip_param", 0.2)
        vf_coeff = config.get("vf_loss_coeff", 0.5)
        ent_coeff = config.get("entropy_coeff", 0.01)
        num_epochs = config.get("num_sgd_iter", 4)

        def _loss(params, batch):
            pi, v = masked_seq_forward(step_fn, params, batch[STATE_IN],
                                       batch[OBS], batch[RESETS])
            T, n = v.shape
            flat_pi = pi.reshape((T * n,) + pi.shape[2:])
            acts = batch[ACTIONS].reshape((T * n,)
                                          + batch[ACTIONS].shape[2:])
            logp = dist.logp(flat_pi, acts).reshape(T, n)
            ratio = jnp.exp(logp - batch[ACTION_LOGP])
            adv = batch[ADVANTAGES]
            surr = jnp.minimum(ratio * adv,
                               jnp.clip(ratio, 1 - clip, 1 + clip) * adv)
            vf_err = (v - batch[VALUE_TARGETS]) ** 2
            entropy = dist.entropy(flat_pi)
            total = (-jnp.mean(surr) + vf_coeff * jnp.mean(vf_err)
                     - ent_coeff * jnp.mean(entropy))
            return total, {"policy_loss": -jnp.mean(surr),
                           "vf_loss": jnp.mean(vf_err),
                           "entropy": jnp.mean(entropy),
                           "total_loss": total}

        @jax.jit
        def _update(params, opt_state, batch):
            def epoch(carry, _):
                params, opt_state = carry
                (_, stats), grads = jax.value_and_grad(
                    _loss, has_aux=True)(params, batch)
                updates, opt_state = self._tx.update(grads, opt_state)
                import optax as _optax
                params = _optax.apply_updates(params, updates)
                return (params, opt_state), stats

            (params, opt_state), stats = jax.lax.scan(
                epoch, (params, opt_state), jnp.arange(num_epochs))
            return params, opt_state, jax.tree.map(lambda s: s[-1], stats)
        self._update = _update

    # -- rollout side -----------------------------------------------------

    def _ensure_state(self, n: int):
        if self._state is None or self._state.shape[0] != n:
            self._state = jnp.zeros((n,) + self._state_shape(),
                                    jnp.float32)

    def state_snapshot(self) -> np.ndarray:
        return np.asarray(self._state)

    def notify_dones(self, done: np.ndarray) -> None:
        """Zero finished envs' state (worker calls after each step)."""
        if done.any():
            mask = jnp.asarray(~done, jnp.float32)[:, None, None]
            self._state = self._state * mask

    def compute_actions(self, obs: np.ndarray) -> Dict[str, np.ndarray]:
        self._ensure_state(obs.shape[0])
        self._rng, rng = jax.random.split(self._rng)
        actions, logp, v, self._state = self._act(
            self.params, rng, self._state, jnp.asarray(obs, jnp.float32))
        return {ACTIONS: np.asarray(actions),
                ACTION_LOGP: np.asarray(logp), VF_PREDS: np.asarray(v)}

    def compute_values(self, obs: np.ndarray) -> np.ndarray:
        """Value at the CURRENT state without advancing it (bootstrap)."""
        self._ensure_state(obs.shape[0])
        _, v, _ = self._step(self.params, self._state,
                             jnp.asarray(obs, jnp.float32))
        return np.asarray(v)

    # -- learner side -----------------------------------------------------

    def learn_on_batch(self, batch) -> Dict[str, float]:
        adv = np.asarray(batch[ADVANTAGES], np.float32)
        batch = dict(batch)
        batch[ADVANTAGES] = (adv - adv.mean()) / (adv.std() + 1e-8)
        device_batch = {
            k: jnp.asarray(np.asarray(
                v, None if k == ACTIONS else np.float32))
            for k, v in batch.items()}
        self.params, self.opt_state, stats = self._update(
            self.params, self.opt_state, device_batch)
        return {k: float(v) for k, v in stats.items()}

    def get_weights(self):
        return jax.tree.map(np.asarray, self.params)

    def set_weights(self, weights):
        self.params = jax.tree.map(jnp.asarray, weights)


class RecurrentPPOPolicy(StatefulPPOPolicy):
    """PPO over the fused-gate LSTM core (reference LSTMWrapper)."""

    def _init_params(self, rng, obs_dim, num_outputs, config):
        self.hidden = int(config.get("lstm_cell_size", 64))
        return lstm_init(rng, obs_dim, num_outputs,
                         embed=int(config.get("lstm_embed", 64)),
                         hidden=self.hidden)

    def _step_fn(self):
        return lstm_step

    def _state_shape(self):
        return (2, self.hidden)
