"""IMPALA: asynchronous actors + V-trace off-policy correction.

Design analog: reference ``rllib/algorithms/impala/impala.py:533``
(training_step drains completed sample futures and immediately re-issues
them — actors never block on the learner) with the learner-side prefetch
pipeline of ``execution/multi_gpu_learner_thread.py:20`` /
``_MultiGPULoaderThread:187``: a host loader thread converts the next
batch to device arrays while the current update runs, double-buffering
host->TPU transfers.

TPU-first: the whole V-trace computation + policy update is ONE jitted
program (lax.scan over reversed time); actors are host-CPU processes whose
stale-policy drift is exactly what V-trace's rho/c clipping corrects.
"""

from __future__ import annotations

import queue
import threading
from typing import Any, Dict, List, Optional

import numpy as np

import jax
import jax.numpy as jnp

from ray_tpu.rllib.algorithm import Algorithm, AlgorithmConfig
from ray_tpu.rllib.policy import (Categorical, Policy, ac_forward, ac_init)
from ray_tpu.rllib.sample_batch import (ACTIONS, ACTION_LOGP, DONES, OBS,
                                        REWARDS, SampleBatch)


class ImpalaConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__(Impala)
        self._config.update({
            "policy": "impala",
            "hiddens": (64, 64),
            "lr": 6e-4,
            "gamma": 0.99,
            "vtrace_rho_clip": 1.0,
            "vtrace_c_clip": 1.0,
            "vf_loss_coeff": 0.5,
            "entropy_coeff": 0.01,
            "grad_clip": 40.0,
            "broadcast_interval": 1,      # weight sync every N updates
            "num_batches_per_step": 4,    # learner updates per training_step
            "rollout_fragment_length": 64,
            "num_envs_per_worker": 8,
            "num_rollout_workers": 2,
        })


def vtrace(behavior_logp, target_logp, rewards, dones, values, bootstrap,
           gamma, rho_clip=1.0, c_clip=1.0):
    """V-trace targets (Espeholt et al. 2018), batch-major [B, T] inputs.
    Returns (vs targets [B, T], pg advantages [B, T])."""
    rho = jnp.minimum(jnp.exp(target_logp - behavior_logp), rho_clip)
    c = jnp.minimum(jnp.exp(target_logp - behavior_logp), c_clip)
    not_done = 1.0 - dones.astype(jnp.float32)
    # values_{t+1} with bootstrap at the end, zeroed across terminations.
    values_next = jnp.concatenate(
        [values[:, 1:], bootstrap[:, None]], axis=1) * not_done
    deltas = rho * (rewards + gamma * values_next - values)

    def body(acc, xs):
        delta_t, c_t, nd_t = xs
        acc = delta_t + gamma * nd_t * c_t * acc
        return acc, acc

    # scan over reversed time (time axis -> leading for scan)
    xs = (jnp.swapaxes(deltas, 0, 1)[::-1],
          jnp.swapaxes(c, 0, 1)[::-1],
          jnp.swapaxes(not_done, 0, 1)[::-1])
    _, acc = jax.lax.scan(body, jnp.zeros_like(deltas[:, 0]), xs)
    vs_minus_v = jnp.swapaxes(acc[::-1], 0, 1)
    vs = values + vs_minus_v
    vs_next = jnp.concatenate([vs[:, 1:], bootstrap[:, None]],
                              axis=1) * not_done
    pg_adv = rho * (rewards + gamma * vs_next - values)
    return jax.lax.stop_gradient(vs), jax.lax.stop_gradient(pg_adv)


class ImpalaPolicy(Policy):
    """Actor-critic policy with a jitted V-trace update."""

    sequence_style = True

    def __init__(self, obs_dim: int, action_space, config: Dict[str, Any],
                 seed: int = 0):
        if action_space.kind != "discrete":
            raise ValueError("this IMPALA implementation is discrete-only")
        self.config = config
        self._rng = jax.random.PRNGKey(seed)
        self._rng, init_rng = jax.random.split(self._rng)
        self.params = ac_init(init_rng, obs_dim, action_space.n,
                              tuple(config.get("hiddens", (64, 64))))
        import optax
        self._tx = optax.chain(
            optax.clip_by_global_norm(config.get("grad_clip", 40.0)),
            optax.adam(config.get("lr", 6e-4)))
        self.opt_state = self._tx.init(self.params)

        @jax.jit
        def _act(params, rng, obs):
            pi, v = ac_forward(params, obs)
            actions = Categorical.sample(rng, pi)
            return actions, Categorical.logp(pi, actions)
        self._act = _act

        gamma = config.get("gamma", 0.99)
        rho_clip = config.get("vtrace_rho_clip", 1.0)
        c_clip = config.get("vtrace_c_clip", 1.0)
        vf_coeff = config.get("vf_loss_coeff", 0.5)
        ent_coeff = config.get("entropy_coeff", 0.01)

        # Multi-device learner: V-trace update shard_mapped over a ("dp",)
        # mesh, batch (B) sharded, grads pmean'd (see rllib/learner.py).
        self._n_learn = int(config.get("num_learner_devices", 1) or 1)
        axis = "dp" if self._n_learn > 1 else None

        def _update(params, opt_state, batch):
            B, T = batch[REWARDS].shape
            flat_obs = batch[OBS].reshape((B * T,) + batch[OBS].shape[2:])

            def loss_fn(p):
                pi, v = ac_forward(p, flat_obs)
                logp = Categorical.logp(
                    pi, batch[ACTIONS].reshape((B * T,)))
                entropy = Categorical.entropy(pi)
                v = v.reshape((B, T))
                logp_bt = logp.reshape((B, T))
                _, boot_v = ac_forward(p, batch["bootstrap_obs"])
                vs, pg_adv = vtrace(
                    batch[ACTION_LOGP], logp_bt, batch[REWARDS],
                    batch[DONES], v, boot_v, gamma, rho_clip, c_clip)
                pg_loss = -jnp.mean(logp_bt * pg_adv)
                vf_loss = 0.5 * jnp.mean((vs - v) ** 2)
                ent = jnp.mean(entropy)
                total = pg_loss + vf_coeff * vf_loss - ent_coeff * ent
                return total, {"policy_loss": pg_loss, "vf_loss": vf_loss,
                               "entropy": ent, "total_loss": total}

            (_, stats), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(params)
            if axis is not None:
                grads = jax.lax.pmean(grads, axis)
                stats = jax.lax.pmean(stats, axis)
            import optax as _ox
            updates, opt_state = self._tx.update(grads, opt_state)
            params = _ox.apply_updates(params, updates)
            return params, opt_state, stats

        if axis is not None:
            from ray_tpu.rllib.learner import learner_mesh, shard_update
            self._mesh = learner_mesh(self._n_learn)
            self._update = shard_update(_update, self._mesh)
        else:
            self._update = jax.jit(_update)

    def compute_actions(self, obs: np.ndarray) -> Dict[str, np.ndarray]:
        self._rng, rng = jax.random.split(self._rng)
        a, logp = self._act(self.params, rng, jnp.asarray(obs, jnp.float32))
        return {ACTIONS: np.asarray(a), ACTION_LOGP: np.asarray(logp)}

    def learn_on_batch(self, batch) -> Dict[str, float]:
        """batch is already device-resident (the loader thread put it)."""
        if self._n_learn > 1:
            from ray_tpu.rllib.learner import trim_batch
            batch = trim_batch(batch, self._n_learn)
        self.params, self.opt_state, stats = self._update(
            self.params, self.opt_state, batch)
        return {k: float(v) for k, v in stats.items()}

    def get_weights(self):
        return jax.tree.map(np.asarray, self.params)

    def set_weights(self, weights):
        self.params = jax.tree.map(jnp.asarray, weights)


def _to_device(batch: SampleBatch) -> Dict[str, jnp.ndarray]:
    return {
        OBS: jnp.asarray(np.asarray(batch[OBS], np.float32)),
        ACTIONS: jnp.asarray(np.asarray(batch[ACTIONS])),
        ACTION_LOGP: jnp.asarray(np.asarray(batch[ACTION_LOGP],
                                            np.float32)),
        REWARDS: jnp.asarray(np.asarray(batch[REWARDS], np.float32)),
        DONES: jnp.asarray(np.asarray(batch[DONES])),
        "bootstrap_obs": jnp.asarray(np.asarray(batch["bootstrap_obs"],
                                                np.float32)),
    }


class _LoaderThread(threading.Thread):
    """Host->device prefetch: converts the next host batch to device
    arrays while the learner updates on the current one (reference
    _MultiGPULoaderThread:187)."""

    def __init__(self, in_q: "queue.Queue", out_q: "queue.Queue"):
        super().__init__(daemon=True, name="impala-loader")
        self.in_q = in_q
        self.out_q = out_q

    def run(self):
        while True:
            item = self.in_q.get()
            if item is None:
                self.out_q.put(None)
                return
            self.out_q.put(_to_device(item))


class Impala(Algorithm):
    def setup(self, config: Dict[str, Any]) -> None:
        config = dict(config)
        config.setdefault("policy", "impala")
        super().setup(config)
        self._host_q: "queue.Queue" = queue.Queue(maxsize=4)
        self._device_q: "queue.Queue" = queue.Queue(maxsize=2)
        self._loader = _LoaderThread(self._host_q, self._device_q)
        self._loader.start()
        self._inflight: Dict[str, Any] = {}   # ref hex -> (ref, worker)
        self._in_pipeline = 0                 # batches put but not consumed
        self._updates = 0
        self.workers.ready()
        self._kick_all()

    def _kick_all(self):
        for w in self.workers.remote_workers:
            ref = w.sample.remote()
            self._inflight[ref.hex()] = (ref, w)

    def training_step(self) -> Dict[str, Any]:
        import ray_tpu
        c = self.config
        stats: Dict[str, float] = {}
        n_batches = 0
        policy = self.workers.local_worker.policy
        target = c.get("num_batches_per_step", 4)
        while n_batches < target:
            if self._inflight:
                # Harvest completed fragments ahead of need (bounded): the
                # loader thread then converts batch k+1 to device arrays
                # while the learner updates on batch k — a single-batch
                # drain would serialize loader and learner.  The pipeline
                # depth cap matters: host_q/device_q are bounded, and a
                # blocking host_q.put from this (learner) thread with the
                # loader blocked on device_q.put is a deadlock.
                PIPELINE_DEPTH = 2
                refs = [r for r, _ in self._inflight.values()]
                if self._in_pipeline == 0:
                    done, _ = ray_tpu.wait(refs, num_returns=1, timeout=120)
                    if not done:
                        # Nothing completed within the poll window (slow
                        # jit compile / starved host): re-poll rather than
                        # blocking on an empty device queue forever.
                        continue
                elif self._in_pipeline <= PIPELINE_DEPTH:
                    done, _ = ray_tpu.wait(refs, num_returns=len(refs),
                                           timeout=0)
                else:
                    done = []
                done = done[:max(0, PIPELINE_DEPTH + 1 -
                                 self._in_pipeline)]
                for ref in done:
                    _, worker = self._inflight.pop(ref.hex())
                    batch = ray_tpu.get(ref)
                    b, t = batch[REWARDS].shape
                    self._timesteps_total += b * t
                    self._host_q.put(batch)
                    self._in_pipeline += 1
                    # Re-issue IMMEDIATELY: the actor never idles waiting
                    # for the learner (the async heart of IMPALA).
                    nref = worker.sample.remote()
                    self._inflight[nref.hex()] = (nref, worker)
            else:  # no remote workers: sample locally
                self._host_q.put(self.workers.local_worker.sample())
                self._in_pipeline += 1
            device_batch = self._device_q.get()
            self._in_pipeline -= 1
            stats = policy.learn_on_batch(device_batch)
            n_batches += 1
            self._updates += 1
            if self._updates % c.get("broadcast_interval", 1) == 0:
                self.workers.sync_weights()
        return {"info": {"learner": stats}, "num_updates": self._updates,
                **{f"learner_{k}": v for k, v in stats.items()}}

    def cleanup(self) -> None:
        try:
            self._host_q.put(None)
        except Exception:
            pass
        super().cleanup()
