"""PPO: clipped-surrogate policy optimization.

Design analog: reference ``rllib/algorithms/ppo/ppo.py:333``
(``training_step``: synchronous parallel sampling -> minibatch SGD ->
weight broadcast).  TPU-first deltas: the whole SGD phase (epochs x
minibatches) is ONE jitted program on the learner (lax.scan, see
PPOPolicy._update); rollout workers are host-CPU actors.
"""

from __future__ import annotations

from typing import Any, Dict

from ray_tpu.rllib.algorithm import Algorithm, AlgorithmConfig


class PPOConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__(PPO)
        self._config.update({
            "lambda": 0.95,
            "clip_param": 0.2,
            "vf_clip_param": 10.0,
            "vf_loss_coeff": 0.5,
            "entropy_coeff": 0.01,
            "num_sgd_iter": 4,
            "sgd_minibatch_size": 128,
            "grad_clip": 0.5,
            "lr": 3e-4,
            "hiddens": (64, 64),
            "num_envs_per_worker": 8,
            "rollout_fragment_length": 128,
        })


class PPO(Algorithm):
    def training_step(self) -> Dict[str, Any]:
        train_batch = self.workers.synchronous_sample()
        self._timesteps_total += train_batch.count
        stats = self.workers.local_worker.policy.learn_on_batch(train_batch)
        self.workers.sync_weights()
        return {"info": {"learner": stats},
                "train_batch_size": train_batch.count,
                **{f"learner_{k}": v for k, v in stats.items()}}


class RecurrentPPOConfig(PPOConfig):
    """PPO with an LSTM core (see rllib/recurrent.py) for POMDP/memory
    tasks.  Reference analog: PPOConfig().training(model={"use_lstm":
    True}) routing through rllib/models/torch/recurrent_net.py.
    Fragments are time-major per worker; sample with the local worker
    (num_rollout_workers=0) — cross-worker fragment concat is not wired.
    """

    def __init__(self):
        super().__init__()
        self._config.update({
            "policy": "recurrent_ppo",
            "lstm_cell_size": 64,
            "lstm_embed": 64,
            "num_rollout_workers": 0,
        })
        self.algo_class = RecurrentPPO


class RecurrentPPO(PPO):
    def __init__(self, config=None, **kwargs):
        config = dict(config or {})
        config.setdefault("policy", "recurrent_ppo")
        super().__init__(config=config, **kwargs)
