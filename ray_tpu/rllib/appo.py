"""APPO: asynchronous PPO (IMPALA machinery + clipped surrogate).

Design analog: reference ``rllib/algorithms/appo/appo.py`` — IMPALA's
async actor/learner pipeline, but the learner applies PPO's clipped
surrogate over V-trace-corrected advantages instead of the plain
policy-gradient loss (clipping bounds the update against the stale
behavior policy; V-trace corrects the value targets).  All the
machinery — async fragment harvesting, host->device loader thread,
broadcast interval — is inherited from ``rllib/impala.py``; only the
jitted loss differs.
"""

from __future__ import annotations

from typing import Any, Dict

import jax
import jax.numpy as jnp

from ray_tpu.rllib.algorithm import AlgorithmConfig
from ray_tpu.rllib.impala import Impala, ImpalaConfig, ImpalaPolicy, vtrace
from ray_tpu.rllib.policy import Categorical, ac_forward
from ray_tpu.rllib.sample_batch import (ACTIONS, ACTION_LOGP, DONES, OBS,
                                        REWARDS)


class APPOConfig(ImpalaConfig):
    def __init__(self):
        super().__init__()
        self._config.update({
            "policy": "appo",
            "clip_param": 0.2,
            "lr": 5e-4,
        })
        self.algo_class = APPO


class APPOPolicy(ImpalaPolicy):
    """IMPALA policy with the update swapped for a clipped surrogate."""

    def __init__(self, obs_dim: int, action_space, config: Dict[str, Any],
                 seed: int = 0):
        super().__init__(obs_dim, action_space, config, seed=seed)
        gamma = config.get("gamma", 0.99)
        rho_clip = config.get("vtrace_rho_clip", 1.0)
        c_clip = config.get("vtrace_c_clip", 1.0)
        vf_coeff = config.get("vf_loss_coeff", 0.5)
        ent_coeff = config.get("entropy_coeff", 0.01)
        clip = config.get("clip_param", 0.2)

        @jax.jit
        def _update(params, opt_state, batch):
            B, T = batch[REWARDS].shape
            flat_obs = batch[OBS].reshape((B * T,) + batch[OBS].shape[2:])

            def loss_fn(p):
                pi, v = ac_forward(p, flat_obs)
                logp = Categorical.logp(
                    pi, batch[ACTIONS].reshape((B * T,)))
                entropy = Categorical.entropy(pi)
                v = v.reshape((B, T))
                logp_bt = logp.reshape((B, T))
                _, boot_v = ac_forward(p, batch["bootstrap_obs"])
                vs, pg_adv = vtrace(
                    batch[ACTION_LOGP], logp_bt, batch[REWARDS],
                    batch[DONES], v, boot_v, gamma, rho_clip, c_clip)
                # PPO clip against the BEHAVIOR policy's logp: the async
                # gap is exactly the ratio being clipped (reference
                # appo_torch_policy.py surrogate over vtrace advantages).
                ratio = jnp.exp(logp_bt - batch[ACTION_LOGP])
                surr = jnp.minimum(
                    ratio * pg_adv,
                    jnp.clip(ratio, 1 - clip, 1 + clip) * pg_adv)
                pg_loss = -jnp.mean(surr)
                vf_loss = 0.5 * jnp.mean((vs - v) ** 2)
                ent = jnp.mean(entropy)
                total = pg_loss + vf_coeff * vf_loss - ent_coeff * ent
                return total, {"policy_loss": pg_loss, "vf_loss": vf_loss,
                               "entropy": ent, "total_loss": total}

            (_, stats), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(params)
            import optax as _ox
            updates, opt_state = self._tx.update(grads, opt_state)
            params = _ox.apply_updates(params, updates)
            return params, opt_state, stats
        self._update = _update


class APPO(Impala):
    def setup(self, config: Dict[str, Any]) -> None:
        config = dict(config)
        config.setdefault("policy", "appo")
        super().setup(config)
