"""Exploration modules: parameter-space noise and RND curiosity.

Design analog: reference ``rllib/utils/exploration/`` —
``parameter_noise.py`` (Plappert et al. 2018: perturb the policy's
weights instead of its actions, with the noise scale adapted so the
induced action divergence matches an epsilon-equivalent target) and
``random_encoder.py``/``curiosity.py`` (intrinsic novelty bonuses; RND,
Burda et al. 2018: a fixed random target network and a trained
predictor — prediction error is high exactly on states never visited).

TPU-first deltas: both modules are pure jitted programs over the policy
pytree (perturbation is a tree-map of Gaussian draws; the RND
predictor update is one fused forward/backward), plugged into the DQN
family via config:

    DQNConfig().training(exploration="parameter_noise")
    DQNConfig().training(rnd_coeff=0.5)     # intrinsic reward weight
"""

from __future__ import annotations

from typing import Any, Dict, Tuple

import numpy as np

import jax
import jax.numpy as jnp


class ParameterNoise:
    """Adaptive parameter-space noise for a Q-network.

    Keeps a perturbed copy of the policy params for acting; after each
    re-perturbation the noise scale adapts toward ``target_divergence``
    (the fraction of states whose greedy action changed — the
    epsilon-equivalent distance of the DQN parameter-noise paper).
    """

    def __init__(self, seed: int = 0, initial_sigma: float = 0.05,
                 target_divergence: float = 0.1,
                 adapt_factor: float = 1.01):
        self.sigma = float(initial_sigma)
        self.target = float(target_divergence)
        self.adapt = float(adapt_factor)
        self._rng = jax.random.PRNGKey(seed ^ 0x5eed)

        @jax.jit
        def _perturb(params, rng, sigma):
            leaves, treedef = jax.tree.flatten(params)
            keys = jax.random.split(rng, len(leaves))
            noisy = [p + sigma * jax.random.normal(k, p.shape, p.dtype)
                     for p, k in zip(leaves, keys)]
            return jax.tree.unflatten(treedef, noisy)

        self._perturb = _perturb

    def perturb(self, params):
        """Fresh perturbed copy of ``params`` at the current sigma."""
        self._rng, k = jax.random.split(self._rng)
        return self._perturb(params, k, self.sigma)

    def adapt_sigma(self, clean_actions: np.ndarray,
                    noisy_actions: np.ndarray) -> float:
        """Grow sigma while the perturbed policy acts like the clean one,
        shrink it when the action divergence overshoots the target."""
        div = float(np.mean(np.asarray(clean_actions)
                            != np.asarray(noisy_actions)))
        if div < self.target:
            self.sigma *= self.adapt
        else:
            self.sigma /= self.adapt
        return self.sigma


def _mlp_init(rng, sizes):
    ks = jax.random.split(rng, len(sizes) - 1)
    return [{"w": jax.random.normal(ks[i], (sizes[i], sizes[i + 1]))
             * np.sqrt(2.0 / sizes[i]),
             "b": jnp.zeros((sizes[i + 1],))}
            for i in range(len(sizes) - 1)]


def _mlp(params, x):
    for i, p in enumerate(params):
        x = x @ p["w"] + p["b"]
        if i < len(params) - 1:
            x = jax.nn.relu(x)
    return x


class RNDCuriosity:
    """Random Network Distillation intrinsic reward.

    A FIXED random target embedding f(s) and a trained predictor g(s);
    intrinsic reward is ||g(s) - f(s)||^2, normalized by a running std so
    the bonus scale is stationary as the predictor catches up.
    """

    def __init__(self, obs_dim: int, seed: int = 0, embed: int = 32,
                 lr: float = 1e-3):
        k1, k2 = jax.random.split(jax.random.PRNGKey(seed ^ 0xc0de))
        self.target = _mlp_init(k1, (obs_dim, 64, embed))
        self.predictor = _mlp_init(k2, (obs_dim, 64, embed))
        import optax
        self._tx = optax.adam(lr)
        self.opt_state = self._tx.init(self.predictor)
        # running SECOND MOMENT of raw errors — per-batch variance would
        # collapse to ~0 on homogeneous batches (all next-obs identical
        # early in a sparse env) and blow the bonus up by 1/sqrt(eps)
        self._running_sq = 1.0
        self._count = 1e-4

        @jax.jit
        def _step(pred, opt_state, target, obs):
            """One fused program: per-row novelty errors against the
            CURRENT predictor + the predictor's gradient step."""
            obs = obs.reshape(obs.shape[0], -1)   # image obs flatten

            def loss_fn(p):
                e = _mlp(p, obs) - _mlp(target, obs)
                per_row = jnp.mean(e * e, axis=-1)
                return jnp.mean(per_row), per_row

            (_, per_row), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(pred)
            updates, opt_state = self._tx.update(grads, opt_state)
            import optax as _ox
            return _ox.apply_updates(pred, updates), opt_state, per_row

        @jax.jit
        def _errors(pred, target, obs):
            obs = obs.reshape(obs.shape[0], -1)
            e = _mlp(pred, obs) - _mlp(target, obs)
            return jnp.mean(e * e, axis=-1)

        self._step = _step
        self._errors_fn = _errors

    def _normalize(self, err: np.ndarray) -> np.ndarray:
        # RMS normalization: typical bonus is O(1), novel states larger.
        # (A running moment over BATCH MEANS stays well-conditioned even
        # when individual batches are homogeneous.)
        self._count += 1
        self._running_sq += (float(np.mean(err * err)) + 1e-12
                             - self._running_sq) / min(self._count, 100.0)
        return err / (self._running_sq ** 0.5 + 1e-8)

    def intrinsic(self, obs: np.ndarray) -> np.ndarray:
        """Normalized novelty bonus (read-only; see intrinsic_and_train
        for the fused learner-path variant)."""
        err = np.asarray(self._errors_fn(self.predictor, self.target,
                                         jnp.asarray(obs, jnp.float32)))
        return self._normalize(err)

    def intrinsic_and_train(self, obs: np.ndarray) -> np.ndarray:
        """Errors + predictor update in ONE jitted call (hot learner
        path: one device transfer, one program)."""
        self.predictor, self.opt_state, err = self._step(
            self.predictor, self.opt_state, self.target,
            jnp.asarray(obs, jnp.float32))
        return self._normalize(np.asarray(err))

    def train(self, obs: np.ndarray) -> float:
        return float(np.mean(self.intrinsic_and_train(obs)))
