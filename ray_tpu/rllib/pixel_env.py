"""MinAtar-class pixel environments, natively vectorized in numpy.

Design analog: the reference's learning evidence for value/policy methods
is ALE Atari (``rllib/tuned_examples/ppo/atari-ppo.yaml``); no ALE/gym
exists in this image, so these are original miniature arcade games in the
MinAtar style (10x10 multi-channel binary images, same observation class)
— NOT ports of MinAtar's code.  The whole env batch steps as one numpy
program (SURVEY.md §2.4 rollout parallelism), so a single host thread
feeds hundreds of environments.

Games:
- ``BreakoutMini-v0``: paddle/ball/brick-wall; +1 per brick, episode ends
  when the ball passes the paddle.  obs 10x10x4 (paddle, ball, trail,
  bricks), 3 actions.
- ``FreewayMini-v0``: cross 8 lanes of deterministic traffic; +1 per
  crossing, collisions push the agent back.  obs 10x10x3 (agent, cars,
  car-direction), 3 actions, fixed 250-step episodes.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from ray_tpu.rllib.env import Space, VectorEnv, register_env

G = 10   # grid side


class BreakoutMiniVectorEnv(VectorEnv):
    """Vectorized mini-Breakout on a 10x10 grid.

    State per env: ball position/velocity, paddle column, 3x10 brick wall.
    The ball moves diagonally one cell per step, bouncing off walls, the
    ceiling, bricks (destroying them, +1) and the width-2 paddle on the
    bottom row; missing the ball ends the episode.  A cleared wall
    respawns, so returns are unbounded at perfect play (episode cap
    ``max_episode_steps``)."""

    BRICK_ROWS = (1, 2, 3)

    def __init__(self, num_envs: int = 1, max_episode_steps: int = 500,
                 ball_period: int = 2, seed: int = 0):
        # ball_period=2: the ball advances every other tick, so the paddle
        # (1 cell/tick) can cover the full width — makes sustained rallies
        # learnable; ball_period=1 is the speed-parity hard mode.
        super().__init__(num_envs)
        self.ball_period = ball_period
        self.observation_space = Space("box", shape=(G, G, 4), low=0.0,
                                       high=1.0)
        self.action_space = Space("discrete", n=3)  # stay / left / right
        self.max_episode_steps = max_episode_steps
        self._rng = np.random.default_rng(seed)
        n = num_envs
        self.ball_y = np.zeros(n, np.int64)
        self.ball_x = np.zeros(n, np.int64)
        self.dy = np.ones(n, np.int64)
        self.dx = np.ones(n, np.int64)
        self.prev_y = np.zeros(n, np.int64)
        self.prev_x = np.zeros(n, np.int64)
        self.pad = np.zeros(n, np.int64)
        self.bricks = np.zeros((n, len(self.BRICK_ROWS), G), bool)
        self._steps = np.zeros(n, np.int64)

    def _reset_envs(self, idx: np.ndarray) -> None:
        k = len(idx)
        self.ball_y[idx] = 4
        self.ball_x[idx] = self._rng.integers(0, G, k)
        self.dy[idx] = 1
        self.dx[idx] = self._rng.choice((-1, 1), k)
        self.prev_y[idx] = self.ball_y[idx]
        self.prev_x[idx] = self.ball_x[idx]
        self.pad[idx] = self._rng.integers(0, G - 1, k)
        self.bricks[idx] = True
        self._steps[idx] = 0

    def _obs(self) -> np.ndarray:
        n = self.num_envs
        obs = np.zeros((n, G, G, 4), np.float32)
        e = np.arange(n)
        obs[e, G - 1, self.pad, 0] = 1.0
        obs[e, G - 1, np.minimum(self.pad + 1, G - 1), 0] = 1.0
        obs[e, self.ball_y, self.ball_x, 1] = 1.0
        obs[e, self.prev_y, self.prev_x, 2] = 1.0
        obs[:, self.BRICK_ROWS[0]:self.BRICK_ROWS[-1] + 1, :, 3] = \
            self.bricks
        return obs

    def vector_reset(self, seed: Optional[int] = None) -> np.ndarray:
        if seed is not None:
            self._rng = np.random.default_rng(seed)
        self._reset_envs(np.arange(self.num_envs))
        return self._obs()

    def vector_step(self, actions: np.ndarray):
        n = self.num_envs
        e = np.arange(n)
        a = np.asarray(actions)
        self.pad = np.clip(self.pad + (a == 2).astype(np.int64)
                           - (a == 1).astype(np.int64), 0, G - 2)
        # per-env tick parity: the ball advances only on its move ticks
        # (resets desynchronize env clocks, so parity is per env)
        move = (self._steps % self.ball_period) == 0
        self.prev_y = np.where(move, self.ball_y, self.prev_y)
        self.prev_x = np.where(move, self.ball_x, self.prev_x)

        # side walls reflect horizontal velocity
        nx = self.ball_x + self.dx
        bounce_x = (nx < 0) | (nx >= G)
        self.dx = np.where(move & bounce_x, -self.dx, self.dx)
        nx = self.ball_x + self.dx
        # ceiling reflects vertical velocity
        ny = self.ball_y + self.dy
        bounce_y = ny < 0
        self.dy = np.where(move & bounce_y, -self.dy, self.dy)
        ny = self.ball_y + self.dy

        # brick hit: remove brick, reflect, ball holds position this step
        reward = np.zeros(n, np.float32)
        row_idx = ny - self.BRICK_ROWS[0]
        # move-mask first: nx/ny are only in-range for envs whose ball
        # actually advanced (bounces were skipped for the rest)
        in_wall = move & (ny >= self.BRICK_ROWS[0]) \
            & (ny <= self.BRICK_ROWS[-1])
        hit = np.zeros(n, bool)
        hit[in_wall] = self.bricks[e[in_wall], row_idx[in_wall],
                                   nx[in_wall]]
        if hit.any():
            self.bricks[e[hit], row_idx[hit], nx[hit]] = False
            reward[hit] = 1.0
            self.dy[hit] = -self.dy[hit]
            ny[hit] = self.ball_y[hit]
            nx[hit] = self.ball_x[hit]
        # cleared wall respawns
        cleared = ~self.bricks.any(axis=(1, 2))
        if cleared.any():
            self.bricks[cleared] = True

        # bottom row: paddle bounce or lost ball
        at_bottom = move & (ny >= G - 1)
        on_pad = at_bottom & ((nx == self.pad) | (nx == self.pad + 1))
        self.dy = np.where(on_pad, -1, self.dy)
        ny = np.where(on_pad, G - 1, ny)
        terminated = at_bottom & ~on_pad
        ny = np.minimum(ny, G - 1)

        self.ball_y = np.where(move, ny, self.ball_y)
        self.ball_x = np.where(move, nx, self.ball_x)
        self._steps += 1
        truncated = self._steps >= self.max_episode_steps
        done = terminated | truncated
        info = {"terminal_obs": self._obs(), "truncated": truncated}
        if done.any():
            self._reset_envs(np.nonzero(done)[0])
        return self._obs(), reward, done, info


class FreewayMiniVectorEnv(VectorEnv):
    """Vectorized mini-Freeway: reach the top row through 8 traffic lanes.

    Car positions are a pure function of the global step counter
    (per-lane speed/direction/offset), so the only per-env state is the
    agent's row and the step clock.  Collision sends the agent back to the
    start row; reaching row 0 scores +1 and also resets the agent.
    Episodes are fixed-length (always truncated)."""

    COL = 4                       # the agent climbs a fixed column
    # per-lane (rows 1..8): direction, period (move every p steps), offset
    LANES = [(+1, 1, 0), (-1, 2, 3), (+1, 2, 5), (-1, 1, 2),
             (+1, 3, 7), (-1, 2, 1), (+1, 1, 4), (-1, 3, 6)]

    def __init__(self, num_envs: int = 1, max_episode_steps: int = 250,
                 seed: int = 0):
        super().__init__(num_envs)
        self.observation_space = Space("box", shape=(G, G, 3), low=0.0,
                                       high=1.0)
        self.action_space = Space("discrete", n=3)  # stay / up / down
        self.max_episode_steps = max_episode_steps
        self._rng = np.random.default_rng(seed)
        self.row = np.full(num_envs, G - 1, np.int64)
        self._t = np.zeros(num_envs, np.int64)
        self._steps = np.zeros(num_envs, np.int64)

    def _car_cols(self, t: np.ndarray) -> np.ndarray:
        """[n, 8] car column per lane at per-env time t."""
        cols = np.empty((len(t), len(self.LANES)), np.int64)
        for i, (d, p, off) in enumerate(self.LANES):
            cols[:, i] = (off + d * (t // p)) % G
        return cols

    def _obs(self) -> np.ndarray:
        n = self.num_envs
        obs = np.zeros((n, G, G, 3), np.float32)
        e = np.arange(n)
        obs[e, self.row, self.COL, 0] = 1.0
        cols = self._car_cols(self._t)
        for i, (d, _p, _o) in enumerate(self.LANES):
            obs[e, i + 1, cols[:, i], 1] = 1.0
            obs[e, i + 1, cols[:, i], 2] = 1.0 if d > 0 else 0.0
        return obs

    def vector_reset(self, seed: Optional[int] = None) -> np.ndarray:
        if seed is not None:
            self._rng = np.random.default_rng(seed)
        self.row[:] = G - 1
        self._t = self._rng.integers(0, 60, self.num_envs)
        self._steps[:] = 0
        return self._obs()

    def vector_step(self, actions: np.ndarray):
        n = self.num_envs
        a = np.asarray(actions)
        self.row = np.clip(self.row - (a == 1).astype(np.int64)
                           + (a == 2).astype(np.int64), 0, G - 1)
        self._t += 1
        cols = self._car_cols(self._t)
        in_lane = (self.row >= 1) & (self.row <= len(self.LANES))
        lane_idx = np.clip(self.row - 1, 0, len(self.LANES) - 1)
        crash = in_lane & (cols[np.arange(n), lane_idx] == self.COL)
        self.row[crash] = G - 1

        reward = (self.row == 0).astype(np.float32)
        self.row[self.row == 0] = G - 1   # scored: restart the climb

        self._steps += 1
        done = self._steps >= self.max_episode_steps
        info = {"terminal_obs": self._obs(),
                "truncated": done.copy()}
        if done.any():
            idx = np.nonzero(done)[0]
            self.row[idx] = G - 1
            self._steps[idx] = 0
            self._t[idx] = self._rng.integers(0, 60, len(idx))
        return self._obs(), reward, done, info


register_env("BreakoutMini-v0", BreakoutMiniVectorEnv)
register_env("FreewayMini-v0", FreewayMiniVectorEnv)
