"""DDPG: deterministic policy gradient (the pre-TD3 baseline).

Design analog: reference ``rllib/algorithms/ddpg/ddpg.py``.  TD3 is DDPG
plus twin critics, target smoothing, and delayed actor updates — so this
implementation IS the TD3 machinery with those three switched off
(policy_delay=1, target_noise=0; the twin critic's min() degenerates
gracefully but we keep q2 training — harmless and shares the jitted
update).  Kept as its own algorithm/config for API parity with the
reference's separate DDPG entry point.
"""

from __future__ import annotations

from ray_tpu.rllib.td3 import TD3, TD3Config, TD3Policy


class DDPGConfig(TD3Config):
    def __init__(self):
        super().__init__()
        self._config.update({
            "policy": "ddpg",
            "policy_delay": 1,          # actor updates every step
            "target_noise": 0.0,        # no target policy smoothing
            "target_noise_clip": 0.0,
            "exploration_noise": 0.1,
        })
        self.algo_class = DDPG


class DDPGPolicy(TD3Policy):
    pass


class DDPG(TD3):
    def setup(self, config) -> None:
        config = dict(config)
        config.setdefault("policy", "ddpg")
        config.setdefault("policy_delay", 1)
        config.setdefault("target_noise", 0.0)
        super().setup(config)
