"""WorkerSet: local learner-side worker + remote rollout actors.

Design analog: reference ``rllib/evaluation/worker_set.py:77`` (local +
remote workers, ``sync_weights`` broadcast, ``probe_unhealthy_workers`` /
restore via ``rllib/utils/actor_manager.py``).  Weights travel through the
object store once per broadcast (one put, N gets).
"""

from __future__ import annotations

import logging
from typing import Any, Callable, Dict, List, Optional

import ray_tpu
from ray_tpu.rllib.rollout_worker import RolloutWorker
from ray_tpu.rllib.sample_batch import SampleBatch

logger = logging.getLogger(__name__)


class WorkerSet:
    def __init__(self, config: Dict[str, Any]):
        self.config = config
        self._remote_cls = ray_tpu.remote(
            num_cpus=config.get("num_cpus_per_worker", 1),
            max_restarts=0)(RolloutWorker)
        # Local worker exists even with 0 remotes (it holds the reference
        # policy the learner updates).
        self.local_worker = RolloutWorker(config, worker_index=0)
        if getattr(self.local_worker.policy, "recurrent", False) and \
                config.get("num_rollout_workers", 0) > 0:
            # Recurrent fragments are [T, n] with per-fragment state;
            # concat_samples would join them along TIME while state_in
            # joins along envs — silently corrupting sequences.  Fail at
            # config time instead of deep inside jit.
            raise ValueError(
                "recurrent policies sample with the local worker only; "
                "set num_rollout_workers=0 (cross-worker fragment concat "
                "is not wired)")
        self.remote_workers: List[Any] = []
        for i in range(config.get("num_rollout_workers", 0)):
            self.remote_workers.append(self._make_remote(i + 1))
        self._worker_indices = list(
            range(1, len(self.remote_workers) + 1))
        # Experience output (reference: config.offline_data(output=...)
        # attaching an OutputWriter to sampling): every sampled batch is
        # also persisted as a dataset shard for offline training.
        self._output_writer = None
        if config.get("output"):
            from ray_tpu.rllib.offline import DatasetWriter
            self._output_writer = DatasetWriter(config["output"])
        # Client-server RL (reference: PolicyServerInput as config.input):
        # external simulator processes drive episodes over TCP; sample()
        # returns their experiences instead of rollout-worker batches.
        self.server_input = None
        if config.get("input") == "policy_server":
            from ray_tpu.rllib.policy_server import PolicyServerInput
            self.server_input = PolicyServerInput(
                self.local_worker.policy, config)

    def _make_remote(self, index: int):
        return self._remote_cls.remote(self.config, index)

    def ready(self, timeout: float = 120.0) -> None:
        """Block until every remote worker answers a ping (actor creation +
        first jit compile can take seconds; probing before that would
        misread 'starting' as 'unhealthy')."""
        if self.remote_workers:
            ray_tpu.get([w.ping.remote() for w in self.remote_workers],
                        timeout=timeout)

    # -- sampling ---------------------------------------------------------
    def synchronous_sample(self) -> SampleBatch:
        """One round of parallel sampling across all workers (reference
        rollout_ops.synchronous_parallel_sample)."""
        if self.server_input is not None:
            batch = self.server_input.sample()
            if self._output_writer is not None:
                self._output_writer.write(batch)
            return batch
        if not self.remote_workers:
            batch = self.local_worker.sample()
        else:
            refs = [w.sample.remote() for w in self.remote_workers]
            batches = ray_tpu.get(refs, timeout=300.0)
            batch = SampleBatch.concat_samples(batches)
        if self._output_writer is not None:
            self._output_writer.write(batch)
        return batch

    def collect_metrics(self) -> Dict[str, Any]:
        rewards: List[float] = []
        lens: List[int] = []
        if self.server_input is not None:
            # matches synchronous_sample's precedence: with a policy
            # server, rollout workers never sample, so their metrics
            # would be permanently empty
            m = self.server_input.get_metrics()
            rewards.extend(m["episode_rewards"])
            lens.extend(m["episode_lens"])
        elif self.remote_workers:
            for m in ray_tpu.get(
                    [w.get_metrics.remote() for w in self.remote_workers],
                    timeout=60.0):
                rewards.extend(m["episode_rewards"])
                lens.extend(m["episode_lens"])
        else:
            m = self.local_worker.get_metrics()
            rewards.extend(m["episode_rewards"])
            lens.extend(m["episode_lens"])
        return {"episode_rewards": rewards, "episode_lens": lens}

    # -- weight sync ------------------------------------------------------
    def sync_weights(self) -> None:
        """Broadcast the local worker's weights to all remote workers."""
        if not self.remote_workers:
            return
        ref = ray_tpu.put(self.local_worker.get_weights())
        ray_tpu.get([w.set_weights.remote(ref)
                     for w in self.remote_workers], timeout=60.0)

    # -- fault tolerance --------------------------------------------------
    def probe_unhealthy_workers(self, timeout: float = 5.0) -> List[int]:
        """Indices (into remote_workers) of workers that fail a ping."""
        if not self.remote_workers:
            return []
        refs = {w.ping.remote(): i
                for i, w in enumerate(self.remote_workers)}
        ready, not_ready = ray_tpu.wait(
            list(refs), num_returns=len(refs), timeout=timeout)
        bad = {refs[r] for r in not_ready}
        for r in ready:
            try:
                ray_tpu.get(r)
            except Exception:
                bad.add(refs[r])
        return sorted(bad)

    def restore_unhealthy_workers(self, indices: List[int]) -> int:
        """Replace dead workers with fresh actors carrying current weights."""
        if not indices:
            return 0
        weights_ref = ray_tpu.put(self.local_worker.get_weights())
        for i in indices:
            old = self.remote_workers[i]
            try:
                ray_tpu.kill(old)
            except Exception:
                pass
            w = self._make_remote(i + 1)
            w.set_weights.remote(weights_ref)
            self.remote_workers[i] = w
            logger.warning("restored rollout worker %d", i + 1)
        return len(indices)

    def foreach_worker(self, fn: Callable) -> List[Any]:
        """fn(worker) on local + all remotes (reference
        worker_set.foreach_worker)."""
        out = [fn(self.local_worker)]
        if self.remote_workers:
            out.extend(ray_tpu.get(
                [w.apply.remote(fn) for w in self.remote_workers],
                timeout=120.0))
        return out

    def stop(self) -> None:
        if self.server_input is not None:
            self.server_input.stop()
        for w in self.remote_workers:
            try:
                ray_tpu.kill(w)
            except Exception:
                pass
        self.remote_workers = []

    def __len__(self) -> int:
        return len(self.remote_workers)
