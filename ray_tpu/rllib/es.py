"""ES: OpenAI-style evolution strategies.

Design analog: reference ``rllib/algorithms/es/es.py`` (shared noise
table, antithetic perturbation rollouts on remote workers, centered-rank
fitness shaping).  The distributed shape is distinct from every
gradient-based algorithm here: workers never see gradients — the learner
broadcasts flat parameters, each worker evaluates theta +/- sigma*eps for
noise it reconstructs from integer seeds, and only (seed, return) pairs
travel back, so the wire cost per rollout is a few floats regardless of
model size.  TPU-first: the perturbed-parameter evaluation batch is pure
numpy on host CPU actors (tiny MLPs; jit overhead would dominate), while
the framework's object store / actor plumbing carries the broadcast.
"""

from __future__ import annotations

from typing import Any, Dict, List

import numpy as np

import ray_tpu
from ray_tpu.rllib.algorithm import Algorithm, AlgorithmConfig
from ray_tpu.rllib.env import make_vector_env


class ESConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__(ES)
        self._config.update({
            "num_rollout_workers": 2,
            "episodes_per_worker": 8,    # antithetic PAIRS per worker/step
            "sigma": 0.05,               # perturbation stddev
            "lr": 0.03,
            "l2_coeff": 0.005,
            "hiddens": (32, 32),
            "episode_horizon": 500,
        })


def _mlp_shapes(obs_dim: int, hiddens, num_actions: int) -> List[tuple]:
    sizes = (obs_dim,) + tuple(hiddens) + (num_actions,)
    shapes = []
    for i in range(len(sizes) - 1):
        shapes.append((sizes[i], sizes[i + 1]))
        shapes.append((sizes[i + 1],))
    return shapes


def _unflatten(theta: np.ndarray, shapes: List[tuple]) -> List[np.ndarray]:
    out, off = [], 0
    for s in shapes:
        n = int(np.prod(s))
        out.append(theta[off:off + n].reshape(s))
        off += n
    return out


def _policy_act(layers: List[np.ndarray], obs: np.ndarray) -> np.ndarray:
    """Deterministic greedy MLP forward over a [N, obs_dim] batch."""
    x = obs
    for i in range(0, len(layers) - 2, 2):
        x = np.tanh(x @ layers[i] + layers[i + 1])
    logits = x @ layers[-2] + layers[-1]
    return np.argmax(logits, axis=-1)


class ESEvalWorker:
    """Evaluates antithetic perturbations of a flat parameter vector.

    Noise is reconstructed locally from integer seeds (the shared-noise-
    table idea without the table: default_rng(seed) IS the shared source),
    so the result message is (seeds, returns+, returns-, steps) only.
    """

    def __init__(self, config: Dict[str, Any], worker_index: int):
        self.config = config
        self.env = make_vector_env(config["env"], 1,
                                   seed=1000 * worker_index,
                                   **config.get("env_config", {}))
        obs_dim = int(np.prod(self.env.observation_space.shape))
        self.shapes = _mlp_shapes(obs_dim,
                                  tuple(config.get("hiddens", (32, 32))),
                                  self.env.action_space.n)
        self.horizon = config.get("episode_horizon", 500)
        self._episode_seed = worker_index * 7919

    def ping(self) -> bool:
        return True

    def _episode_return(self, theta: np.ndarray) -> tuple:
        layers = _unflatten(theta, self.shapes)
        self._episode_seed += 1
        obs = self.env.vector_reset(seed=self._episode_seed)
        total, steps = 0.0, 0
        for _ in range(self.horizon):
            a = _policy_act(layers, np.asarray(obs, np.float32))
            obs, rew, done, _ = self.env.vector_step(a)
            total += float(rew[0])
            steps += 1
            if bool(done[0]):
                break
        return total, steps

    def evaluate(self, theta_ref, seeds: List[int], sigma: float) -> Dict:
        theta = np.asarray(theta_ref, np.float32)
        pos, neg, steps = [], [], 0
        for seed in seeds:
            eps = np.random.default_rng(seed).standard_normal(
                theta.shape[0]).astype(np.float32)
            r_pos, s1 = self._episode_return(theta + sigma * eps)
            r_neg, s2 = self._episode_return(theta - sigma * eps)
            pos.append(r_pos)
            neg.append(r_neg)
            steps += s1 + s2
        return {"seeds": list(seeds), "pos": pos, "neg": neg,
                "steps": steps}


def _centered_ranks(x: np.ndarray) -> np.ndarray:
    """Fitness shaping: map returns to [-0.5, 0.5] by rank (reference
    es.py compute_centered_ranks) — scale-free, outlier-immune."""
    ranks = np.empty(x.size, dtype=np.float32)
    ranks[x.ravel().argsort()] = np.arange(x.size, dtype=np.float32)
    return (ranks / (x.size - 1) - 0.5).reshape(x.shape)


class ES(Algorithm):
    """Gradient-free learner: broadcast theta, collect ranked antithetic
    returns, ascend sum_i rank_i * eps_i."""

    def setup(self, config: Dict[str, Any]) -> None:
        # No WorkerSet/policy machinery: ES has its own worker protocol.
        env = make_vector_env(config["env"], 1,
                              **config.get("env_config", {}))
        obs_dim = int(np.prod(env.observation_space.shape))
        self.shapes = _mlp_shapes(obs_dim,
                                  tuple(config.get("hiddens", (32, 32))),
                                  env.action_space.n)
        n = sum(int(np.prod(s)) for s in self.shapes)
        rng = np.random.default_rng(config.get("seed", 0))
        # Small init; the search distribution provides exploration.
        self.theta = (0.1 * rng.standard_normal(n)).astype(np.float32)
        self._rng = rng
        self._velocity = np.zeros_like(self.theta)   # momentum-SGD
        cls = ray_tpu.remote(num_cpus=1)(ESEvalWorker)
        self.workers_es = [
            cls.remote(config, i + 1)
            for i in range(config.get("num_rollout_workers", 2))]
        ray_tpu.get([w.ping.remote() for w in self.workers_es],
                    timeout=120.0)
        self._timesteps_total = 0
        self._reward_history: List[float] = []

    def training_step(self) -> Dict[str, Any]:
        c = self.config
        sigma = c.get("sigma", 0.05)
        pairs = c.get("episodes_per_worker", 8)
        theta_ref = ray_tpu.put(self.theta)
        seed_base = int(self._rng.integers(0, 2 ** 31 - 1))
        refs, all_seeds = [], []
        for i, w in enumerate(self.workers_es):
            seeds = [seed_base + i * pairs + j for j in range(pairs)]
            all_seeds.extend(seeds)
            refs.append(w.evaluate.remote(theta_ref, seeds, sigma))
        results = ray_tpu.get(refs, timeout=600.0)

        seeds, pos, neg = [], [], []
        for r in results:
            seeds.extend(r["seeds"])
            pos.extend(r["pos"])
            neg.extend(r["neg"])
            self._timesteps_total += r["steps"]
        pos, neg = np.asarray(pos, np.float32), np.asarray(neg, np.float32)
        ranks = _centered_ranks(np.stack([pos, neg], axis=1))
        weights = ranks[:, 0] - ranks[:, 1]   # antithetic pairing

        grad = np.zeros_like(self.theta)
        for w_i, seed in zip(weights, seeds):
            eps = np.random.default_rng(seed).standard_normal(
                self.theta.shape[0]).astype(np.float32)
            grad += w_i * eps
        grad /= (len(seeds) * sigma)
        grad -= c.get("l2_coeff", 0.005) * self.theta
        self._velocity = (0.9 * self._velocity
                          + c.get("lr", 0.03) * grad)
        self.theta = self.theta + self._velocity

        mean_reward = float(np.mean(np.concatenate([pos, neg])))
        self._reward_history.append(mean_reward)
        return {
            "episode_reward_mean": mean_reward,
            "episode_reward_max": float(np.max(np.concatenate([pos, neg]))),
            "num_env_steps_sampled": self._timesteps_total,
            "info": {"learner": {"grad_norm": float(np.linalg.norm(grad)),
                                 "theta_norm": float(
                                     np.linalg.norm(self.theta))}},
        }

    def step(self) -> Dict[str, Any]:
        return self.training_step()

    # -- Trainable contract ----------------------------------------------
    def save_checkpoint(self) -> Dict[str, Any]:
        return {"theta": self.theta, "velocity": self._velocity,
                "timesteps": self._timesteps_total}

    def load_checkpoint(self, checkpoint) -> None:
        if not checkpoint:
            return
        self.theta = np.asarray(checkpoint["theta"], np.float32)
        self._velocity = np.asarray(checkpoint["velocity"], np.float32)
        self._timesteps_total = checkpoint.get("timesteps", 0)

    def cleanup(self) -> None:
        for w in self.workers_es:
            try:
                ray_tpu.kill(w)
            except Exception:
                pass
