"""DQN: double Q-learning with a target network and prioritized replay.

Design analog: reference ``rllib/algorithms/dqn/dqn.py`` (training_step:
sample fragments -> store in replay -> N learner updates -> target sync)
and ``dqn_torch_policy.py`` (double-DQN loss, per-row TD error feeding
priority updates).  TPU-first: the Q-update (including the target
network's forward) is one jitted program; epsilon-greedy lives host-side
in the rollout workers.
"""

from __future__ import annotations

from typing import Any, Dict

import numpy as np

import jax
import jax.numpy as jnp

from ray_tpu.rllib.algorithm import Algorithm, AlgorithmConfig
from ray_tpu.rllib.policy import Policy, ac_init, head_forward
from ray_tpu.rllib.replay_buffer import PrioritizedReplayBuffer, ReplayBuffer
from ray_tpu.rllib.sample_batch import (ACTIONS, DONES, NEXT_OBS, OBS,
                                        REWARDS, SampleBatch)


class DQNConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__(DQN)
        self._config.update({
            "policy": "dqn",
            "hiddens": (64, 64),
            "lr": 5e-4,
            "train_batch_size": 64,
            "buffer_size": 50_000,
            "learning_starts": 1000,
            "prioritized_replay": True,
            "prioritized_replay_alpha": 0.6,
            "prioritized_replay_beta": 0.4,
            "target_network_update_freq": 500,   # env steps
            "num_train_iters": 8,                # updates per training_step
            "double_q": True,
            "epsilon_initial": 1.0,
            "epsilon_final": 0.02,
            "epsilon_timesteps": 10_000,
            "rollout_fragment_length": 4,
            "num_envs_per_worker": 8,
            "gamma": 0.99,
        })


class DQNPolicy(Policy):
    """Q-network policy; ``replay_style`` makes workers collect raw
    transitions instead of GAE fragments."""

    replay_style = True

    def __init__(self, obs_dim: int, action_space, config: Dict[str, Any],
                 seed: int = 0):
        if action_space.kind != "discrete":
            raise ValueError("DQN requires a discrete action space")
        self.config = config
        self.num_actions = action_space.n
        self._rng = np.random.default_rng(seed)
        key = jax.random.PRNGKey(seed)
        self.params = ac_init(key, obs_dim, self.num_actions,
                              tuple(config.get("hiddens", (64, 64))),
                              value_head=False)
        self.target_params = jax.tree.map(jnp.copy, self.params)
        import optax
        self._tx = optax.adam(config.get("lr", 5e-4))
        self.opt_state = self._tx.init(self.params)
        self._steps_seen = 0
        # Exploration modules (reference rllib/utils/exploration/):
        # parameter-space noise replaces epsilon-greedy; RND curiosity
        # adds an intrinsic novelty bonus at learn time.
        self._param_noise = None
        if config.get("exploration") == "parameter_noise":
            from ray_tpu.rllib.exploration import ParameterNoise
            self._param_noise = ParameterNoise(
                seed=seed,
                initial_sigma=config.get("param_noise_sigma", 0.05),
                target_divergence=config.get(
                    "param_noise_target", 0.1))
            self._noisy_params = self._param_noise.perturb(self.params)
            self._since_perturb = 0
        self._rnd = None
        if config.get("rnd_coeff", 0.0) > 0.0:
            from ray_tpu.rllib.exploration import RNDCuriosity
            self._rnd = RNDCuriosity(obs_dim, seed=seed)

        gamma = config.get("gamma", 0.99)
        double_q = config.get("double_q", True)

        @jax.jit
        def _q(params, obs):
            return head_forward(params, obs)
        self._q = _q

        @jax.jit
        def _update(params, target_params, opt_state, batch, weights):
            def loss_fn(p):
                q = head_forward(p, batch[OBS])
                q_sel = jnp.take_along_axis(
                    q, batch[ACTIONS][:, None].astype(jnp.int32), 1)[:, 0]
                q_next_t = head_forward(target_params, batch[NEXT_OBS])
                if double_q:
                    q_next_o = head_forward(p, batch[NEXT_OBS])
                    best = jnp.argmax(q_next_o, axis=1)
                else:
                    best = jnp.argmax(q_next_t, axis=1)
                q_next = jnp.take_along_axis(q_next_t, best[:, None], 1)[:, 0]
                target = batch[REWARDS] + gamma * (
                    1.0 - batch[DONES].astype(jnp.float32)
                ) * jax.lax.stop_gradient(q_next)
                td = q_sel - target
                # Huber on weighted TD errors (priority-corrected).
                loss = jnp.mean(weights * jnp.where(
                    jnp.abs(td) < 1.0, 0.5 * td ** 2, jnp.abs(td) - 0.5))
                return loss, td

            (loss, td), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(params)
            updates, opt_state = self._tx.update(grads, opt_state)
            import optax as _ox
            params = _ox.apply_updates(params, updates)
            return params, opt_state, loss, jnp.abs(td)
        self._update = _update

    # -- rollout side -----------------------------------------------------

    def _epsilon_at(self, global_steps: int) -> float:
        c = self.config
        frac = min(1.0, global_steps /
                   max(1, c.get("epsilon_timesteps", 10_000)))
        return c.get("epsilon_initial", 1.0) + frac * (
            c.get("epsilon_final", 0.02) - c.get("epsilon_initial", 1.0))

    def _epsilon(self) -> float:
        # epsilon_timesteps is a GLOBAL env-step budget: with N samplers
        # each seeing 1/N of the steps, scale local steps back up so the
        # schedule anneals at the configured global rate.
        samplers = max(1, self.config.get("num_rollout_workers", 0))
        return self._epsilon_at(self._steps_seen * samplers)

    def compute_actions(self, obs: np.ndarray) -> Dict[str, np.ndarray]:
        jobs = jnp.asarray(obs, jnp.float32)
        self._steps_seen += len(obs)
        if self._param_noise is not None:
            # parameter-space exploration: act greedily under the
            # PERTURBED network; re-perturb + adapt sigma periodically
            # (temporally consistent exploration, unlike per-step eps)
            self._since_perturb += len(obs)
            if self._since_perturb >= self.config.get(
                    "param_noise_interval", 64):
                clean = np.asarray(self._q(self.params,
                                           jobs)).argmax(axis=1)
                noisy = np.asarray(self._q(self._noisy_params,
                                           jobs)).argmax(axis=1)
                self._param_noise.adapt_sigma(clean, noisy)
                self._noisy_params = self._param_noise.perturb(
                    self.params)
                self._since_perturb = 0
            q = np.asarray(self._q(self._noisy_params, jobs))
            return {ACTIONS: q.argmax(axis=1)}
        q = np.asarray(self._q(self.params, jobs))
        greedy = q.argmax(axis=1)
        eps = self._epsilon()
        explore = self._rng.random(len(obs)) < eps
        random_a = self._rng.integers(0, self.num_actions, len(obs))
        return {ACTIONS: np.where(explore, random_a, greedy)}

    # -- learner side -----------------------------------------------------

    def learn_on_batch(self, batch: SampleBatch) -> Dict[str, Any]:
        if self._rnd is not None:
            # intrinsic novelty bonus on the NEXT state (the state the
            # action discovered); errors + predictor update fused in one
            # jitted call
            nxt = np.asarray(batch[NEXT_OBS], np.float32)
            bonus = self._rnd.intrinsic_and_train(nxt)
            batch = SampleBatch({**batch,
                                 REWARDS: np.asarray(
                                     batch[REWARDS], np.float32)
                                 + self.config.get("rnd_coeff", 0.0)
                                 * bonus})
        weights = jnp.asarray(
            np.asarray(batch.get("weights",
                                 np.ones(batch.count)), np.float32))
        device_batch = {
            OBS: jnp.asarray(np.asarray(batch[OBS], np.float32)),
            NEXT_OBS: jnp.asarray(np.asarray(batch[NEXT_OBS], np.float32)),
            ACTIONS: jnp.asarray(np.asarray(batch[ACTIONS])),
            REWARDS: jnp.asarray(np.asarray(batch[REWARDS], np.float32)),
            DONES: jnp.asarray(np.asarray(batch[DONES])),
        }
        self.params, self.opt_state, loss, td = self._update(
            self.params, self.target_params, self.opt_state, device_batch,
            weights)
        return {"loss": float(loss), "td_errors": np.asarray(td),
                "mean_q_td": float(td.mean())}

    def update_target(self):
        self.target_params = jax.tree.map(jnp.copy, self.params)

    def get_weights(self):
        return jax.tree.map(np.asarray, self.params)

    def set_weights(self, weights):
        self.params = jax.tree.map(jnp.asarray, weights)
        if self._param_noise is not None:
            # act under a perturbation of the FRESH weights immediately
            # (stale noisy params would ignore a weight sync for up to
            # param_noise_interval steps)
            self._noisy_params = self._param_noise.perturb(self.params)
            self._since_perturb = 0


class DQN(Algorithm):
    def setup(self, config: Dict[str, Any]) -> None:
        config = dict(config)
        config.setdefault("policy", "dqn")
        super().setup(config)
        if config.get("prioritized_replay", True):
            self.replay = PrioritizedReplayBuffer(
                config.get("buffer_size", 50_000),
                alpha=config.get("prioritized_replay_alpha", 0.6),
                seed=config.get("seed", 0))
        else:
            self.replay = ReplayBuffer(config.get("buffer_size", 50_000),
                                       seed=config.get("seed", 0))
        self._since_target_sync = 0

    def training_step(self) -> Dict[str, Any]:
        c = self.config
        batch = self.workers.synchronous_sample()
        self._timesteps_total += batch.count
        self._since_target_sync += batch.count
        self.replay.add(batch)

        stats: Dict[str, Any] = {}
        policy = self.workers.local_worker.policy
        if len(self.replay) >= c.get("learning_starts", 1000):
            for _ in range(c.get("num_train_iters", 8)):
                if isinstance(self.replay, PrioritizedReplayBuffer):
                    train = self.replay.sample(
                        c.get("train_batch_size", 64),
                        beta=c.get("prioritized_replay_beta", 0.4))
                else:
                    train = self.replay.sample(
                        c.get("train_batch_size", 64))
                stats = policy.learn_on_batch(train)
                if isinstance(self.replay, PrioritizedReplayBuffer):
                    self.replay.update_priorities(
                        train["batch_indexes"], stats.pop("td_errors"))
                else:
                    stats.pop("td_errors", None)
            if self._since_target_sync >= c.get(
                    "target_network_update_freq", 500):
                policy.update_target()
                self._since_target_sync = 0
            self.workers.sync_weights()
        return {"info": {"learner": {k: v for k, v in stats.items()
                                     if np.isscalar(v)}},
                "buffer_size": len(self.replay),
                # Report from GLOBAL timesteps: the local policy never
                # samples when remote workers exist, so its own counter
                # would misreport a frozen epsilon_initial.
                "epsilon": policy._epsilon_at(self._timesteps_total)}
