"""Offline RL: dataset IO, behavior cloning, CQL, off-policy estimation.

Design analog: reference ``rllib/offline/`` — ``json_writer.py`` /
``json_reader.py`` (experience output/input), ``dataset_writer.py``,
``estimators/importance_sampling.py``, and the BC/CQL algorithms under
``rllib/algorithms/bc|cql``.  TPU-first deltas: shards are ``.npz``
(columnar numpy, mmap-able, no per-row JSON parse — batches device_put
whole), and both BC and CQL updates are single jitted programs.
"""

from __future__ import annotations

import json
import os
import time
from typing import Any, Dict, Iterator, List, Optional

import numpy as np

from ray_tpu.rllib.algorithm import Algorithm, AlgorithmConfig
from ray_tpu.rllib.sample_batch import (ACTIONS, ACTION_LOGP, DONES,
                                        NEXT_OBS, OBS, REWARDS, SampleBatch)


# ------------------------------------------------------------ dataset IO

class DatasetWriter:
    """Writes SampleBatches as numbered .npz shards under a directory.

    Reference analog: ``rllib/offline/json_writer.py`` (OutputWriter
    contract) — columnar npz instead of row JSON so the read side feeds
    the device without parsing.
    """

    def __init__(self, path: str):
        self.path = path
        os.makedirs(path, exist_ok=True)
        self._seq = 0
        self._meta = {"created_at": time.time(), "shards": 0, "rows": 0}

    def write(self, batch: SampleBatch) -> str:
        shard = os.path.join(self.path,
                             f"shard-{os.getpid()}-{self._seq:05d}.npz")
        self._seq += 1
        # Write via an open handle so np.savez can't append '.npz' to the
        # temp name — a temp ending in .npz would match the reader's shard
        # filter and a crash mid-write would poison the dataset.
        tmp = shard + ".tmp"
        with open(tmp, "wb") as f:
            np.savez(f, **{k: np.asarray(v) for k, v in batch.items()})
        os.replace(tmp, shard)
        self._meta["shards"] += 1
        self._meta["rows"] += batch.count
        with open(os.path.join(self.path, f"meta-{os.getpid()}.json"),
                  "w") as f:
            json.dump(self._meta, f)
        return shard


class DatasetReader:
    """Reads a DatasetWriter directory back as SampleBatches.

    ``iter_batches`` cycles shards forever (training); ``read_all``
    concatenates everything (small datasets / evaluation).  Reference
    analog: ``rllib/offline/json_reader.py`` (InputReader.next).
    """

    def __init__(self, path: str, shuffle: bool = True, seed: int = 0):
        self.path = path
        self.shuffle = shuffle
        self._rng = np.random.default_rng(seed)
        self._shards = sorted(
            os.path.join(path, f) for f in os.listdir(path)
            if f.endswith(".npz"))
        if not self._shards:
            raise FileNotFoundError(f"no .npz shards under {path!r}")

    def _load(self, shard: str) -> SampleBatch:
        with np.load(shard) as z:
            return SampleBatch({k: z[k] for k in z.files})

    def read_all(self) -> SampleBatch:
        return SampleBatch.concat_samples(
            [self._load(s) for s in self._shards])

    def iter_batches(self, batch_size: int,
                     data: Optional[SampleBatch] = None
                     ) -> Iterator[SampleBatch]:
        """Infinite minibatch stream over the whole dataset.  ``data``
        overrides the source batch (MARWIL passes the dataset with its
        precomputed returns column attached)."""
        if data is None:
            data = self.read_all()
        n = data.count
        # A dataset smaller than one batch still yields (the whole thing,
        # shuffled) — range() would otherwise be empty and the generator
        # would spin forever without yielding.
        batch_size = min(batch_size, n)
        while True:
            idx = (self._rng.permutation(n) if self.shuffle
                   else np.arange(n))
            for lo in range(0, n - batch_size + 1, batch_size):
                take = idx[lo:lo + batch_size]
                yield SampleBatch({k: v[take] for k, v in data.items()})


# ----------------------------------------------- off-policy estimation

class ImportanceSamplingEstimator:
    """Ordinary + weighted per-episode IS estimates of a target policy's
    value from behavior data (reference:
    ``rllib/offline/estimators/importance_sampling.py``).

    Needs ``action_logp`` of the BEHAVIOR policy in the batch and a
    target policy exposing ``logp_for(obs, actions)``.
    """

    def __init__(self, gamma: float = 0.99):
        self.gamma = gamma

    def estimate(self, batch: SampleBatch, target_policy) -> Dict[str, float]:
        logp_new = np.asarray(
            target_policy.logp_for(batch[OBS], batch[ACTIONS]))
        ratios = np.exp(logp_new - np.asarray(batch[ACTION_LOGP]))
        dones = np.asarray(batch[DONES]).astype(bool)
        rewards = np.asarray(batch[REWARDS])
        v_is, v_wis_num, v_wis_den = [], [], []
        start = 0
        for end in list(np.nonzero(dones)[0] + 1) or [len(rewards)]:
            w = float(np.prod(np.clip(ratios[start:end], 1e-4, 1e4)))
            disc = self.gamma ** np.arange(end - start)
            ret = float(np.sum(rewards[start:end] * disc))
            v_is.append(w * ret)
            v_wis_num.append(w * ret)
            v_wis_den.append(w)
            start = end
        return {
            "v_is": float(np.mean(v_is)) if v_is else 0.0,
            "v_wis": (float(np.sum(v_wis_num) / max(np.sum(v_wis_den),
                                                    1e-8))
                      if v_wis_den else 0.0),
            "num_episodes": len(v_is),
        }


# ------------------------------------------------------------------- BC

class BCPolicy:
    """Behavior cloning: maximize logp of dataset actions.

    Shares the MLP actor network with PPO (``ac_init``/``ac_forward``);
    the value head is unused.  Rollout workers use it for evaluation
    only.  Reference analog: ``rllib/algorithms/bc/bc.py`` (MARWIL with
    beta=0).
    """

    def __init__(self, obs_dim: int, action_space, config: Dict[str, Any],
                 seed: int = 0):
        import jax
        import jax.numpy as jnp
        import optax

        from ray_tpu.rllib.policy import (Categorical, DiagGaussian,
                                          ac_forward, ac_init)
        self.config = config
        self.discrete = action_space.kind == "discrete"
        self.dist = Categorical if self.discrete else DiagGaussian
        num_outputs = (action_space.n if self.discrete
                       else 2 * int(np.prod(action_space.shape)))
        self._rng = jax.random.PRNGKey(seed)
        self._rng, init_rng = jax.random.split(self._rng)
        self.params = ac_init(init_rng, obs_dim, num_outputs,
                              tuple(config.get("hiddens", (64, 64))))
        self._tx = optax.adam(config.get("lr", 1e-3))
        self.opt_state = self._tx.init(self.params)
        dist = self.dist

        @jax.jit
        def _act(params, rng, obs):
            pi, _ = ac_forward(params, obs)
            # Greedy eval: BC imitates; sampling noise only hurts.
            if self.discrete:
                actions = jnp.argmax(pi, axis=-1)
            else:
                actions = DiagGaussian.split(pi)[0]
            return actions, dist.logp(pi, actions)
        self._act = _act

        @jax.jit
        def _logp(params, obs, actions):
            pi, _ = ac_forward(params, obs)
            return dist.logp(pi, actions)
        self._logp = _logp

        @jax.jit
        def _update(params, opt_state, obs, actions):
            def loss(p):
                pi, _ = ac_forward(p, obs)
                return -jnp.mean(dist.logp(pi, actions))

            l, grads = jax.value_and_grad(loss)(params)
            updates, opt_state = self._tx.update(grads, opt_state)
            params = optax.apply_updates(params, updates)
            return params, opt_state, l
        self._update = _update

    def compute_actions(self, obs: np.ndarray) -> Dict[str, np.ndarray]:
        import jax
        import jax.numpy as jnp
        self._rng, rng = jax.random.split(self._rng)
        actions, logp = self._act(self.params, rng,
                                  jnp.asarray(obs, np.float32))
        return {ACTIONS: np.asarray(actions), ACTION_LOGP: np.asarray(logp),
                "vf_preds": np.zeros((obs.shape[0],), np.float32)}

    def compute_values(self, obs: np.ndarray) -> np.ndarray:
        # No value head in BC; evaluation sampling only needs a shape.
        return np.zeros((obs.shape[0],), np.float32)

    def logp_for(self, obs: np.ndarray, actions: np.ndarray) -> np.ndarray:
        import jax.numpy as jnp
        return np.asarray(self._logp(
            self.params, jnp.asarray(obs, np.float32),
            jnp.asarray(actions)))

    def learn_on_batch(self, batch: SampleBatch) -> Dict[str, float]:
        import jax.numpy as jnp
        self.params, self.opt_state, loss = self._update(
            self.params, self.opt_state,
            jnp.asarray(np.asarray(batch[OBS], np.float32)),
            jnp.asarray(np.asarray(batch[ACTIONS])))
        return {"bc_loss": float(loss)}

    def get_weights(self):
        import jax
        return jax.tree.map(np.asarray, self.params)

    def set_weights(self, weights):
        import jax
        import jax.numpy as jnp
        self.params = jax.tree.map(jnp.asarray, weights)


class BCConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__(BC)
        self._config.update({
            "policy": "bc",
            "input": None,              # dataset dir (DatasetWriter layout)
            "train_batch_size": 512,
            "sgd_iters_per_step": 16,
            "lr": 1e-3,
            "hiddens": (64, 64),
            "num_rollout_workers": 0,   # env used for evaluation only
        })

    def offline_data(self, *, input: str) -> "BCConfig":  # noqa: A002
        self._config["input"] = input
        return self


class BC(Algorithm):
    """Train from a logged dataset; evaluate by rolling the env."""

    def setup(self, config: Dict[str, Any]) -> None:
        super().setup(config)
        if not config.get("input"):
            raise ValueError("BC requires config['input'] (dataset dir)")
        self._reader = DatasetReader(config["input"],
                                     seed=config.get("seed", 0))
        self._batches = self._reader.iter_batches(
            config.get("train_batch_size", 512))

    def training_step(self) -> Dict[str, Any]:
        policy = self.workers.local_worker.policy
        stats: Dict[str, float] = {}
        for _ in range(self.config.get("sgd_iters_per_step", 16)):
            batch = next(self._batches)
            stats = policy.learn_on_batch(batch)
            self._timesteps_total += batch.count
        self.workers.sync_weights()
        # Evaluation rollout: fills episode metrics with the cloned
        # policy's actual env performance.
        self.workers.synchronous_sample()
        return {"info": {"learner": stats},
                **{f"learner_{k}": v for k, v in stats.items()}}


# ------------------------------------------------------------------ CQL

class CQLConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__(CQL)
        self._config.update({
            "policy": "dqn",            # Q-network policy for evaluation
            "input": None,
            "train_batch_size": 512,
            "sgd_iters_per_step": 16,
            "cql_alpha": 1.0,
            "lr": 5e-4,
            "gamma": 0.99,
            # Evaluation rollouts should reflect the learned Q greedily.
            "epsilon_initial": 0.02,
            "epsilon_final": 0.02,
            "target_update_freq": 8,    # in training_steps
            "hiddens": (64, 64),
            "num_rollout_workers": 0,
        })

    def offline_data(self, *, input: str) -> "CQLConfig":  # noqa: A002
        self._config["input"] = input
        return self


class CQL(Algorithm):
    """Discrete-action conservative Q-learning over a logged dataset.

    Loss = TD error + alpha * (logsumexp_a Q(s, a) - Q(s, a_data)):
    push down out-of-distribution action values, push up the data's
    (reference: ``rllib/algorithms/cql/cql.py``; discrete form per the
    CQL(H) objective).  Reuses the DQN policy's network so the result
    evaluates/acts exactly like a trained DQN.
    """

    def setup(self, config: Dict[str, Any]) -> None:
        super().setup(config)
        if not config.get("input"):
            raise ValueError("CQL requires config['input'] (dataset dir)")
        self._reader = DatasetReader(config["input"],
                                     seed=config.get("seed", 0))
        self._batches = self._reader.iter_batches(
            config.get("train_batch_size", 512))
        self._build_update()

    def _build_update(self):
        import jax
        import jax.numpy as jnp
        import optax

        from ray_tpu.rllib.policy import head_forward
        policy = self.workers.local_worker.policy
        alpha = self.config.get("cql_alpha", 1.0)
        gamma = self.config.get("gamma", 0.99)
        self._tx = optax.adam(self.config.get("lr", 5e-4))
        self._opt_state = self._tx.init(policy.params)
        self._target = jax.tree.map(jnp.asarray, policy.params)

        @jax.jit
        def _update(params, target, opt_state, obs, actions, rewards,
                    next_obs, dones):
            def loss(p):
                q = head_forward(p, obs)
                q_data = jnp.take_along_axis(
                    q, actions[:, None].astype(jnp.int32), axis=-1)[:, 0]
                q_next = head_forward(target, next_obs)
                td_target = rewards + gamma * (1.0 - dones) * jnp.max(
                    q_next, axis=-1)
                td = jnp.mean((q_data - jax.lax.stop_gradient(td_target))
                              ** 2)
                conservative = jnp.mean(
                    jax.scipy.special.logsumexp(q, axis=-1) - q_data)
                return td + alpha * conservative, (td, conservative)

            (l, (td, cons)), grads = jax.value_and_grad(
                loss, has_aux=True)(params)
            updates, opt_state = self._tx.update(grads, opt_state)
            params = optax.apply_updates(params, updates)
            return params, opt_state, l, td, cons
        self._update = _update

    def training_step(self) -> Dict[str, Any]:
        import jax
        import jax.numpy as jnp
        policy = self.workers.local_worker.policy
        stats: Dict[str, float] = {}
        for _ in range(self.config.get("sgd_iters_per_step", 16)):
            b = next(self._batches)
            policy.params, self._opt_state, l, td, cons = self._update(
                policy.params, self._target, self._opt_state,
                jnp.asarray(np.asarray(b[OBS], np.float32)),
                jnp.asarray(np.asarray(b[ACTIONS])),
                jnp.asarray(np.asarray(b[REWARDS], np.float32)),
                jnp.asarray(np.asarray(b[NEXT_OBS], np.float32)),
                jnp.asarray(np.asarray(b[DONES], np.float32)))
            stats = {"cql_loss": float(l), "td_loss": float(td),
                     "conservative_gap": float(cons)}
            self._timesteps_total += b.count
        if self.iteration % self.config.get("target_update_freq", 8) == 0:
            self._target = jax.tree.map(jnp.asarray, policy.params)
        self.workers.sync_weights()
        self.workers.synchronous_sample()   # evaluation metrics
        return {"info": {"learner": stats},
                **{f"learner_{k}": v for k, v in stats.items()}}


# --------------------------------------------------------------- MARWIL

def compute_mc_returns(rewards: np.ndarray, dones: np.ndarray,
                       gamma: float) -> np.ndarray:
    """Per-episode Monte-Carlo discounted returns over row-ordered data
    (episodes cut at dones; a truncated final segment is treated as an
    episode).  Reference analog: postprocessing.compute_advantages with
    use_gae=False, use_critic=False."""
    returns = np.zeros_like(rewards, dtype=np.float64)
    acc = 0.0
    for t in range(len(rewards) - 1, -1, -1):
        if dones[t]:
            acc = 0.0
        acc = rewards[t] + gamma * acc
        returns[t] = acc
    return returns.astype(np.float32)


class MARWILPolicy(BCPolicy):
    """Monotonic advantage re-weighted imitation learning.

    BC weighted by exp(beta * advantage): the value head estimates V(s),
    advantage = MC-return - V, and the exp weight focuses cloning on
    better-than-average trajectories.  beta=0 degrades exactly to BC
    (reference: ``rllib/algorithms/marwil/marwil.py`` — its BC subclass
    is literally beta=0).  The advantage-norm moving average that keeps
    exp() in range is carried as policy state, like the reference's
    ``update_averaged_estimate``.
    """

    def __init__(self, obs_dim: int, action_space, config: Dict[str, Any],
                 seed: int = 0):
        super().__init__(obs_dim, action_space, config, seed=seed)
        import jax
        import jax.numpy as jnp
        import optax

        from ray_tpu.rllib.policy import ac_forward
        dist = self.dist
        beta = config.get("beta", 1.0)
        vf_coeff = config.get("vf_coeff", 1.0)
        ma_rate = config.get("moving_average_sqd_adv_norm_update_rate",
                             1e-2)
        self._ma_adv_sq = jnp.asarray(
            config.get("moving_average_sqd_adv_norm_start", 100.0))

        @jax.jit
        def _update(params, opt_state, ma_adv_sq, obs, actions, returns):
            def loss(p):
                pi, v = ac_forward(p, obs)
                adv = returns - v
                adv_sg = jax.lax.stop_gradient(adv)
                # exp-weight with the advantage normalized by the moving
                # RMS; clip for numerical safety like the reference.
                w = jnp.exp(jnp.clip(
                    beta * adv_sg / jnp.sqrt(ma_adv_sq + 1e-8),
                    -3.0, 3.0))
                pg = -jnp.mean(jax.lax.stop_gradient(w)
                               * dist.logp(pi, actions))
                vf = jnp.mean(adv ** 2)
                return pg + vf_coeff * vf, (pg, vf, adv_sg)

            (l, (pg, vf, adv)), grads = jax.value_and_grad(
                loss, has_aux=True)(params)
            updates, opt_state = self._tx.update(grads, opt_state)
            params = optax.apply_updates(params, updates)
            ma_adv_sq = ma_adv_sq + ma_rate * (
                jnp.mean(adv ** 2) - ma_adv_sq)
            return params, opt_state, ma_adv_sq, l, pg, vf
        self._marwil_update = _update

    def learn_on_batch(self, batch: SampleBatch) -> Dict[str, float]:
        import jax.numpy as jnp
        if "returns" not in batch:
            raise ValueError("MARWIL batches need a 'returns' column "
                             "(MARWIL.setup precomputes it)")
        (self.params, self.opt_state, self._ma_adv_sq, l, pg,
         vf) = self._marwil_update(
            self.params, self.opt_state, self._ma_adv_sq,
            jnp.asarray(np.asarray(batch[OBS], np.float32)),
            jnp.asarray(np.asarray(batch[ACTIONS])),
            jnp.asarray(np.asarray(batch["returns"], np.float32)))
        return {"marwil_loss": float(l), "policy_loss": float(pg),
                "vf_loss": float(vf)}


class MARWILConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__(MARWIL)
        self._config.update({
            "policy": "marwil",
            "input": None,
            "beta": 1.0,
            "vf_coeff": 1.0,
            "gamma": 0.99,
            "train_batch_size": 512,
            "sgd_iters_per_step": 16,
            "lr": 1e-3,
            "hiddens": (64, 64),
            "num_rollout_workers": 0,
        })

    def offline_data(self, *, input: str) -> "MARWILConfig":  # noqa: A002
        self._config["input"] = input
        return self


class MARWIL(Algorithm):
    """Offline advantage-weighted cloning from a logged dataset.

    MC returns are computed ONCE over the row-ordered dataset (before any
    shuffling — episode structure is positional) and carried as an extra
    column through the minibatch stream.
    """

    def setup(self, config: Dict[str, Any]) -> None:
        config = dict(config)
        config.setdefault("policy", "marwil")
        super().setup(config)
        if not config.get("input"):
            raise ValueError("MARWIL requires config['input'] "
                             "(dataset dir)")
        reader = DatasetReader(config["input"], seed=config.get("seed", 0))
        data = reader.read_all()
        returns = compute_mc_returns(
            np.asarray(data[REWARDS], np.float64),
            np.asarray(data[DONES]).astype(bool),
            config.get("gamma", 0.99))
        # z-score once over the dataset: the value head then regresses an
        # O(1) target, so its gradient through the shared trunk can't
        # drown the cloning term, and advantages start in exp()'s sweet
        # spot.  (Weighting is scale-free — only relative adv matters.)
        returns = ((returns - returns.mean())
                   / (returns.std() + 1e-8)).astype(np.float32)
        cols = dict(data)
        cols["returns"] = returns
        self._reader = reader
        self._data = SampleBatch(cols)

    def training_step(self) -> Dict[str, Any]:
        if not hasattr(self, "_batches"):
            self._batches = self._reader.iter_batches(
                self.config.get("train_batch_size", 512), data=self._data)
        policy = self.workers.local_worker.policy
        stats: Dict[str, float] = {}
        for _ in range(self.config.get("sgd_iters_per_step", 16)):
            batch = next(self._batches)
            stats = policy.learn_on_batch(batch)
            self._timesteps_total += batch.count
        self.workers.sync_weights()
        self.workers.synchronous_sample()   # evaluation metrics
        return {"info": {"learner": stats},
                **{f"learner_{k}": v for k, v in stats.items()}}
