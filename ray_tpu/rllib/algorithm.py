"""Algorithm: the RL training loop as a Tune Trainable.

Design analog: reference ``rllib/algorithms/algorithm.py:143`` (Algorithm
is a Trainable whose ``step`` runs ``training_step`` then collects
metrics) and ``algorithm_config.py:152`` (fluent config builder).
"""

from __future__ import annotations

import collections
from typing import Any, Dict, Optional, Type

import numpy as np

from ray_tpu.rllib.worker_set import WorkerSet
from ray_tpu.tune.trainable import Trainable


class AlgorithmConfig:
    """Fluent builder: ``PPOConfig().environment("CartPole-v1")
    .rollouts(num_rollout_workers=2).training(lr=1e-3).build()``."""

    def __init__(self, algo_class: Optional[Type["Algorithm"]] = None):
        self.algo_class = algo_class
        self._config: Dict[str, Any] = {
            "env": None,
            "env_config": {},
            "num_rollout_workers": 0,
            "num_envs_per_worker": 1,
            "rollout_fragment_length": 128,
            "num_cpus_per_worker": 1,
            "gamma": 0.99,
            "lr": 3e-4,
            "seed": 0,
            "restore_unhealthy_workers": True,
            "metrics_num_episodes_for_smoothing": 100,
        }

    def environment(self, env: str, env_config: Optional[Dict] = None
                    ) -> "AlgorithmConfig":
        self._config["env"] = env
        if env_config is not None:
            self._config["env_config"] = env_config
        return self

    def rollouts(self, **kwargs) -> "AlgorithmConfig":
        self._config.update(kwargs)
        return self

    def training(self, **kwargs) -> "AlgorithmConfig":
        self._config.update(kwargs)
        return self

    def debugging(self, *, seed: int = 0) -> "AlgorithmConfig":
        self._config["seed"] = seed
        return self

    def resources(self, **kwargs) -> "AlgorithmConfig":
        self._config.update(kwargs)
        return self

    def to_dict(self) -> Dict[str, Any]:
        return dict(self._config)

    def build(self) -> "Algorithm":
        if self.algo_class is None:
            raise ValueError("config has no algo_class bound")
        return self.algo_class(config=self.to_dict())


class Algorithm(Trainable):
    """Subclasses implement ``training_step() -> result dict``."""

    def setup(self, config: Dict[str, Any]) -> None:
        self.workers = WorkerSet(config)
        self._episode_rewards: collections.deque = collections.deque(
            maxlen=config.get("metrics_num_episodes_for_smoothing", 100))
        self._episode_lens: collections.deque = collections.deque(
            maxlen=config.get("metrics_num_episodes_for_smoothing", 100))
        self._timesteps_total = 0

    def training_step(self) -> Dict[str, Any]:
        raise NotImplementedError

    def step(self) -> Dict[str, Any]:
        if self.config.get("restore_unhealthy_workers", True):
            bad = self.workers.probe_unhealthy_workers()
            if bad:
                self.workers.restore_unhealthy_workers(bad)
        result = self.training_step()
        m = self.workers.collect_metrics()
        self._episode_rewards.extend(m["episode_rewards"])
        self._episode_lens.extend(m["episode_lens"])
        if self._episode_rewards:
            result["episode_reward_mean"] = float(
                np.mean(self._episode_rewards))
            result["episode_reward_max"] = float(
                np.max(self._episode_rewards))
            result["episode_len_mean"] = float(np.mean(self._episode_lens))
        result["num_env_steps_sampled"] = self._timesteps_total
        return result

    def evaluate(self, num_episodes: int = 10,
                 timeout_s: float = 300.0) -> Dict[str, Any]:
        """Run evaluation episodes with the CURRENT policy on a fresh env
        (reference: ``Algorithm.evaluate`` / evaluation workers).  Uses
        its own env instance so training-side episode metrics and env
        state are untouched."""
        import time as _time

        import numpy as _np

        from ray_tpu.rllib.env import make_vector_env
        cfg = self.config
        env = make_vector_env(cfg["env"], 1,
                              seed=cfg.get("seed", 0) + 977,
                              **cfg.get("env_config", {}))
        policy = self.workers.local_worker.policy
        rewards, lens = [], []
        deadline = _time.monotonic() + timeout_s
        # Recurrent policies carry rollout state on the policy object;
        # with local sampling that state is mid-episode training state —
        # snapshot it and restore after evaluation so eval never perturbs
        # training (ADVICE r4).
        saved_state = getattr(policy, "_state", None)
        try:
            for _ in range(num_episodes):
                if _time.monotonic() > deadline:
                    break
                if hasattr(policy, "_ensure_state"):
                    policy._state = None
                    policy._ensure_state(1)
                obs = env.vector_reset()
                total, steps = 0.0, 0
                for _ in range(cfg.get("evaluation_max_steps", 1000)):
                    out = policy.compute_actions(
                        _np.asarray(obs, _np.float32))
                    obs, rew, done, _info = env.vector_step(out["actions"])
                    total += float(rew[0])
                    steps += 1
                    if hasattr(policy, "notify_dones"):
                        policy.notify_dones(done)
                    if bool(done[0]):
                        break
                rewards.append(total)
                lens.append(steps)
        finally:
            if hasattr(policy, "_ensure_state"):
                policy._state = saved_state
        return {
            "evaluation": {
                "episode_reward_mean": float(_np.mean(rewards))
                if rewards else float("nan"),
                "episode_reward_min": float(_np.min(rewards))
                if rewards else float("nan"),
                "episode_reward_max": float(_np.max(rewards))
                if rewards else float("nan"),
                "episode_len_mean": float(_np.mean(lens))
                if lens else float("nan"),
                "num_episodes": len(rewards),
            }
        }

    # -- checkpointing (Trainable contract) -------------------------------
    def save_checkpoint(self) -> Dict[str, Any]:
        return {"weights": self.workers.local_worker.get_weights(),
                "timesteps": self._timesteps_total}

    def load_checkpoint(self, checkpoint: Optional[Dict[str, Any]]) -> None:
        if not checkpoint:
            return
        self.workers.local_worker.set_weights(checkpoint["weights"])
        self._timesteps_total = checkpoint.get("timesteps", 0)
        self.workers.sync_weights()

    def get_policy(self):
        return self.workers.local_worker.policy

    def cleanup(self) -> None:
        self.workers.stop()

    @classmethod
    def default_resource_request(cls, config: Dict[str, Any]
                                 ) -> Dict[str, float]:
        return {"CPU": 1.0 + config.get("num_rollout_workers", 0)
                * config.get("num_cpus_per_worker", 1)}
