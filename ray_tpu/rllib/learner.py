"""Multi-device RL learner plumbing: dp-mesh sharded policy updates.

Design analog: reference ``rllib/execution/multi_gpu_learner_thread.py:20``
and ``rllib/core/rl_trainer/trainer_runner.py:21`` — N learner GPUs, each
loading a batch shard, gradients allreduced by NCCL, one weight copy
broadcast back to rollout workers.

TPU-first delta: there is no learner *thread pool* — the learner is ONE
``shard_map`` program over a ``jax.sharding.Mesh``.  Each device receives
its shard of the train batch (``PartitionSpec("dp")`` on the leading axis),
runs the same minibatch-SGD/V-trace scan on it, and gradients are
``lax.pmean``-ed over the mesh axis inside jit, so XLA emits the
all-reduce on ICI exactly where NCCL would run.  Params/optimizer state
stay replicated (RL policy nets are KB–MB scale; batch, not params, is
what needs scaling out — fsdp would add collectives for no memory win).
Rollout workers remain host-CPU actors; weight broadcast reuses
``WorkerSet.sync_weights``.
"""

from __future__ import annotations

from typing import Dict, Optional

import numpy as np

import jax

from ray_tpu.util import jax_compat

jax_compat.install()

DP_AXIS = "dp"


def learner_mesh(num_devices: Optional[int] = None) -> "jax.sharding.Mesh":
    """A 1-D ("dp",) mesh over the first ``num_devices`` local devices."""
    devs = jax.devices()
    n = num_devices or len(devs)
    if n > len(devs):
        raise ValueError(
            f"num_learner_devices={n} but only {len(devs)} devices visible")
    return jax.sharding.Mesh(np.asarray(devs[:n]), (DP_AXIS,))


def shard_update(update_fn, mesh, n_state_outputs: int = 2):
    """Wrap a per-shard ``update_fn(params, opt_state, *rest, batch)`` into
    a jitted shard_map over ``mesh``: everything replicated except the
    trailing ``batch`` arg, whose pytree leaves shard on dim 0 over dp.

    ``update_fn`` must pmean its grads/stats over ``DP_AXIS`` itself (the
    policy closures do), which keeps the replicated outputs consistent.
    """
    P = jax.sharding.PartitionSpec

    def wrapped(*args):
        n_in = len(args)
        in_specs = tuple([P()] * (n_in - 1) + [P(DP_AXIS)])
        out_specs = tuple([P()] * (n_state_outputs + 1))
        return jax.shard_map(update_fn, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_vma=False)(*args)

    return jax.jit(wrapped)


def trim_batch(batch: Dict[str, np.ndarray], multiple: int
               ) -> Dict[str, np.ndarray]:
    """Trim every leading dim to a multiple of the mesh size so shards are
    equal (dropping <multiple trailing rows, same as the reference's
    per-GPU loader truncation)."""
    if multiple <= 1:
        return batch
    n = next(iter(batch.values())).shape[0]
    keep = (n // multiple) * multiple
    if keep == n:
        return batch
    if keep == 0:
        raise ValueError(f"batch of {n} rows cannot shard over "
                         f"{multiple} learner devices")
    return {k: v[:keep] for k, v in batch.items()}
