"""QMIX: cooperative multi-agent Q-learning with monotonic value mixing.

Design analog: reference ``rllib/algorithms/qmix/qmix.py`` +
``qmix_policy.py`` (per-agent Q networks whose chosen-action values feed a
monotonic mixing network conditioned on the global state; TD targets
through a target mixer; episode replay).  TPU-first deltas: the whole
update (per-agent Q forward, hypernetwork mixer, double-Q targets, huber
loss, Adam step) is ONE jitted program over a transition batch; the mixer
enforces monotonicity with ``abs()`` on hypernetwork weights so
``argmax_a Q_i`` = argmax of ``Q_tot`` per agent (the factorization QMIX
is built on).

``mixer=`` selects the ablation family the reference exposes as separate
algorithms: "qmix" (state-conditioned hypernetwork), "vdn" (plain sum,
reference VDN), "none" (independent Q-learning — each agent treats the
team reward as its own).  One implementation, three credit-assignment
semantics, which is what the two-step-game learning test discriminates.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

import numpy as np

import jax
import jax.numpy as jnp

from ray_tpu.rllib.algorithm import AlgorithmConfig
from ray_tpu.rllib.multi_agent import MA_ENV_REGISTRY
from ray_tpu.tune.trainable import Trainable


class QMIXConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__(QMIX)
        self._config.update({
            "mixer": "qmix",             # "qmix" | "vdn" | "none"
            "hiddens": (64,),
            "mixing_embed_dim": 32,
            "lr": 5e-4,
            "gamma": 0.99,
            "train_batch_size": 64,
            "buffer_size": 5000,
            "learning_starts": 64,
            "target_network_update_freq": 100,   # updates
            "epsilon_initial": 1.0,
            "epsilon_final": 0.05,
            "epsilon_timesteps": 4000,
            "num_train_iters": 4,
            "double_q": True,
        })


def _mlp_init(rng, sizes):
    ks = jax.random.split(rng, len(sizes) - 1)
    return [{"w": jax.random.normal(ks[i], (sizes[i], sizes[i + 1]))
             * np.sqrt(2.0 / sizes[i]),
             "b": jnp.zeros((sizes[i + 1],))}
            for i in range(len(sizes) - 1)]


def _mlp(params, x, final_linear=True):
    for i, p in enumerate(params):
        x = x @ p["w"] + p["b"]
        if i < len(params) - 1 or not final_linear:
            x = jax.nn.relu(x)
    return x


def mixer_init(rng, n_agents: int, state_dim: int, embed: int) -> Dict:
    """Hypernetworks mapping global state -> mixing weights/biases
    (reference qmix_policy.QMixer)."""
    k = jax.random.split(rng, 4)
    return {
        "hyper_w1": _mlp_init(k[0], (state_dim, 64, n_agents * embed)),
        "hyper_b1": _mlp_init(k[1], (state_dim, embed)),
        "hyper_w2": _mlp_init(k[2], (state_dim, 64, embed)),
        "hyper_v": _mlp_init(k[3], (state_dim, 64, 1)),
    }


def mix(mparams: Dict, agent_qs: jax.Array, state: jax.Array) -> jax.Array:
    """agent_qs [B, n] + state [B, S] -> Q_tot [B].  abs() on the
    hypernetwork outputs keeps dQ_tot/dQ_i >= 0 (monotonicity)."""
    B, n = agent_qs.shape
    embed = mparams["hyper_b1"][-1]["b"].shape[0]
    w1 = jnp.abs(_mlp(mparams["hyper_w1"], state)).reshape(B, n, embed)
    b1 = _mlp(mparams["hyper_b1"], state)
    h = jax.nn.elu(jnp.einsum("bn,bne->be", agent_qs, w1) + b1)
    w2 = jnp.abs(_mlp(mparams["hyper_w2"], state))
    v = _mlp(mparams["hyper_v"], state)[:, 0]
    return jnp.sum(h * w2, axis=-1) + v


class QMIX(Trainable):
    """Episode-driving trainer for the mixing family (qmix/vdn/none)."""

    def setup(self, config: Dict[str, Any]) -> None:
        self.config = c = config
        env_name = c["env"]
        self.env = MA_ENV_REGISTRY[env_name](**c.get("env_config", {}))
        self.agents = list(self.env.agents)
        n = len(self.agents)
        obs_dim = int(np.prod(self.env.observation_space.shape))
        self.n_actions = self.env.action_space.n
        state_fn = getattr(self.env, "state", None)
        self._state_dim = (len(state_fn()) if state_fn is not None
                           else obs_dim * n)
        self._rng = jax.random.PRNGKey(c.get("seed", 0))
        self._rng, k1, k2 = jax.random.split(self._rng, 3)
        hid = tuple(c.get("hiddens", (64,)))
        self.q_params = _mlp_init(
            k1, (obs_dim,) + hid + (self.n_actions,))
        self.mixer_kind = c.get("mixer", "qmix")
        self.m_params = mixer_init(
            k2, n, self._state_dim, c.get("mixing_embed_dim", 32)) \
            if self.mixer_kind == "qmix" else {}
        self.t_q = jax.tree.map(jnp.copy, self.q_params)
        self.t_m = jax.tree.map(jnp.copy, self.m_params)

        import optax
        self._tx = optax.adam(c.get("lr", 5e-4))
        self.opt_state = self._tx.init((self.q_params, self.m_params))
        gamma = c.get("gamma", 0.99)
        double_q = c.get("double_q", True)
        mixer_kind = self.mixer_kind

        def q_all(qp, obs):   # obs [B, n, O] -> [B, n, A]
            B = obs.shape[0]
            flat = obs.reshape(B * n, -1)
            return _mlp(qp, flat).reshape(B, n, self.n_actions)

        def total(qp, mp, qs, state):
            if mixer_kind == "qmix":
                return mix(mp, qs, state)
            return jnp.sum(qs, axis=-1)   # vdn; "none" never calls this

        def loss_fn(params, targets, batch):
            qp, mp = params
            t_q, t_m = targets
            qs = q_all(qp, batch["obs"])
            chosen = jnp.take_along_axis(
                qs, batch["actions"][..., None], axis=-1)[..., 0]  # [B,n]
            tq = q_all(t_q, batch["next_obs"])
            if double_q:
                sel = jnp.argmax(q_all(qp, batch["next_obs"]), axis=-1)
            else:
                sel = jnp.argmax(tq, axis=-1)
            tgt_q = jnp.take_along_axis(tq, sel[..., None],
                                        axis=-1)[..., 0]
            notdone = 1.0 - batch["dones"].astype(jnp.float32)
            if mixer_kind == "none":
                # independent Q-learning: per-agent TD on team reward
                y = batch["rewards"][:, None] \
                    + gamma * notdone[:, None] * tgt_q
                td = chosen - jax.lax.stop_gradient(y)
            else:
                q_tot = total(qp, mp, chosen, batch["state"])
                t_tot = total(t_q, t_m, tgt_q, batch["next_state"])
                y = batch["rewards"] + gamma * notdone * t_tot
                td = q_tot - jax.lax.stop_gradient(y)
            return jnp.mean(jnp.where(jnp.abs(td) < 1.0,
                                      0.5 * td * td,
                                      jnp.abs(td) - 0.5))

        @jax.jit
        def _update(params, targets, opt_state, batch):
            import optax as _ox
            loss, grads = jax.value_and_grad(loss_fn)(params, targets,
                                                      batch)
            updates, opt_state = self._tx.update(grads, opt_state)
            return _ox.apply_updates(params, updates), opt_state, loss

        self._update = _update

        @jax.jit
        def _greedy(qp, obs):
            return jnp.argmax(q_all(qp, obs[None])[0], axis=-1)

        self._greedy = _greedy

        from ray_tpu.rllib.replay_buffer import ReplayBuffer
        self._buffer = ReplayBuffer(capacity=c.get("buffer_size", 5000),
                                    seed=c.get("seed", 0))
        self._steps = 0
        self._updates = 0
        self._episode_rewards: List[float] = []
        self._np_rng = np.random.default_rng(c.get("seed", 0))

    # -- rollout ----------------------------------------------------------

    def _global_state(self, obs: Dict[str, np.ndarray]) -> np.ndarray:
        fn = getattr(self.env, "state", None)
        if fn is not None:
            return np.asarray(fn(), np.float32)
        return np.concatenate([obs[a].ravel() for a in self.agents])

    def _epsilon(self) -> float:
        c = self.config
        frac = min(1.0, self._steps / max(1, c.get("epsilon_timesteps",
                                                   4000)))
        return c.get("epsilon_initial", 1.0) + frac * (
            c.get("epsilon_final", 0.05)
            - c.get("epsilon_initial", 1.0))

    def _run_episode(self) -> float:
        obs = self.env.reset()
        state = self._global_state(obs)
        total = 0.0
        done = False
        while not done:
            eps = self._epsilon()
            stacked = np.stack([obs[a] for a in self.agents])
            greedy = np.asarray(self._greedy(self.q_params, stacked))
            acts = {}
            for i, a in enumerate(self.agents):
                if self._np_rng.random() < eps:
                    acts[a] = int(self._np_rng.integers(self.n_actions))
                else:
                    acts[a] = int(greedy[i])
            nobs, rewards, dones, _ = self.env.step(acts)
            nstate = self._global_state(nobs)
            done = dones["__all__"]
            r = float(rewards[self.agents[0]])   # shared team reward
            from ray_tpu.rllib.sample_batch import SampleBatch
            self._buffer.add(SampleBatch({
                "obs": stacked[None],
                "actions": np.asarray([acts[a] for a in self.agents],
                                      np.int32)[None],
                "rewards": np.asarray([r], np.float32),
                "dones": np.asarray([done]),
                "state": state[None],
                "next_obs": np.stack([nobs[a]
                                      for a in self.agents])[None],
                "next_state": nstate[None],
            }))
            total += r
            obs, state = nobs, nstate
            self._steps += 1
        return total

    # -- training ---------------------------------------------------------

    def _sample_batch(self) -> Dict[str, jnp.ndarray]:
        batch = self._buffer.sample(
            self.config.get("train_batch_size", 64))
        return {k: jnp.asarray(batch[k])
                for k in ("obs", "actions", "rewards", "dones", "state",
                          "next_obs", "next_state")}

    def step(self) -> Dict[str, Any]:
        c = self.config
        for _ in range(8):
            self._episode_rewards.append(self._run_episode())
        self._episode_rewards = self._episode_rewards[-100:]
        loss = float("nan")
        if len(self._buffer) >= c.get("learning_starts", 64):
            for _ in range(c.get("num_train_iters", 4)):
                (self.q_params, self.m_params), self.opt_state, ls = \
                    self._update((self.q_params, self.m_params),
                                 (self.t_q, self.t_m),
                                 self.opt_state, self._sample_batch())
                loss = float(ls)
                self._updates += 1
                if self._updates % c.get("target_network_update_freq",
                                         100) == 0:
                    self.t_q = jax.tree.map(jnp.copy, self.q_params)
                    self.t_m = jax.tree.map(jnp.copy, self.m_params)
        return {
            "episode_reward_mean": float(np.mean(self._episode_rewards)),
            "loss": loss,
            "num_env_steps_sampled": self._steps,
        }

    def greedy_episode_reward(self) -> float:
        """One epsilon-0 episode (evaluation)."""
        obs = self.env.reset()
        total, done = 0.0, False
        while not done:
            stacked = np.stack([obs[a] for a in self.agents])
            greedy = np.asarray(self._greedy(self.q_params, stacked))
            acts = {a: int(greedy[i])
                    for i, a in enumerate(self.agents)}
            obs, rewards, dones, _ = self.env.step(acts)
            total += float(rewards[self.agents[0]])
            done = dones["__all__"]
        return total

    def save_checkpoint(self) -> Dict[str, Any]:
        return {"q": jax.tree.map(np.asarray, self.q_params),
                "m": jax.tree.map(np.asarray, self.m_params),
                "steps": self._steps}

    def load_checkpoint(self, ckpt) -> None:
        self.q_params = jax.tree.map(jnp.asarray, ckpt["q"])
        self.m_params = jax.tree.map(jnp.asarray, ckpt["m"])
        self.t_q = jax.tree.map(jnp.copy, self.q_params)
        self.t_m = jax.tree.map(jnp.copy, self.m_params)
        self._steps = ckpt.get("steps", 0)
