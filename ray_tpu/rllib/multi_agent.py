"""Multi-agent RL: env contract, per-policy batches, mapped PPO training.

Design analog: reference ``rllib/env/multi_agent_env.py`` (dict-keyed
obs/action/reward/done protocol), ``rllib/policy/sample_batch.py:1218``
(MultiAgentBatch), and the ``multiagent`` config block
(policies + policy_mapping_fn).  Agents map to policies through a user
function; mapping every agent to one policy id gives shared-parameter
self-play, mapping them to distinct ids trains independent policies.

TPU-first: per step, each policy runs ONE batched compute_actions over
every (env, agent) pair mapped to it — the host drives k env copies in
numpy and the device sees policy-wide batches, never per-agent calls.
The learner side reuses the jitted PPO update per policy.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from ray_tpu.rllib.algorithm import Algorithm, AlgorithmConfig
from ray_tpu.rllib.policy import PPOPolicy, compute_gae
from ray_tpu.rllib.sample_batch import (ACTIONS, ACTION_LOGP, ADVANTAGES,
                                        DONES, OBS, REWARDS, SampleBatch,
                                        VALUE_TARGETS, VF_PREDS)
from ray_tpu.tune.trainable import Trainable


class MultiAgentEnv:
    """Simultaneous-move multi-agent env.

    reset() -> {agent_id: obs}; step({agent_id: action}) ->
    (obs_dict, reward_dict, done_dict, info_dict) where done_dict carries
    the special "__all__" key (reference multi_agent_env.py contract).
    """

    agents: List[str]
    observation_space = None     # Space shared by all agents
    action_space = None

    def reset(self, seed: Optional[int] = None) -> Dict[str, np.ndarray]:
        raise NotImplementedError

    def step(self, actions: Dict[str, Any]):
        raise NotImplementedError


class MultiAgentBatch:
    """Per-policy SampleBatches + the env-step count they came from
    (reference sample_batch.py:1218)."""

    def __init__(self, policy_batches: Dict[str, SampleBatch],
                 env_steps: int):
        self.policy_batches = policy_batches
        self.count = env_steps

    def __getitem__(self, policy_id: str) -> SampleBatch:
        return self.policy_batches[policy_id]


class CoordinationGameEnv(MultiAgentEnv):
    """Two agents see a one-hot target and must BOTH pick it to score.

    Cooperative matrix game with a shared reward: +1 per step when both
    actions equal the target, else 0.  Random play scores ~T/16; the
    learned optimum is T.  Exists so multi-agent learning tests have a
    fast, deterministic threshold (the reference uses rock-paper-scissors
    and two-step-game examples the same way).
    """

    N_TARGETS = 4

    def __init__(self, episode_len: int = 16, seed: int = 0):
        from ray_tpu.rllib.env import Space
        self.agents = ["agent_0", "agent_1"]
        self.observation_space = Space("box",
                                       shape=(self.N_TARGETS + 2,))
        self.action_space = Space("discrete", n=self.N_TARGETS)
        self.episode_len = episode_len
        self._rng = np.random.default_rng(seed)
        self._t = 0
        self._target = 0

    def _obs(self) -> Dict[str, np.ndarray]:
        out = {}
        for i, a in enumerate(self.agents):
            v = np.zeros(self.N_TARGETS + 2, np.float32)
            v[self._target] = 1.0
            v[self.N_TARGETS + i] = 1.0        # agent identity feature
            out[a] = v
        return out

    def reset(self, seed: Optional[int] = None) -> Dict[str, np.ndarray]:
        if seed is not None:
            self._rng = np.random.default_rng(seed)
        self._t = 0
        self._target = int(self._rng.integers(self.N_TARGETS))
        return self._obs()

    def step(self, actions: Dict[str, Any]):
        hit = all(int(actions[a]) == self._target for a in self.agents)
        r = 1.0 if hit else 0.0
        self._t += 1
        done = self._t >= self.episode_len
        self._target = int(self._rng.integers(self.N_TARGETS))
        obs = self._obs()
        rewards = {a: r for a in self.agents}
        dones = {a: done for a in self.agents}
        dones["__all__"] = done
        return obs, rewards, dones, {}


class TwoStepGameEnv(MultiAgentEnv):
    """The QMIX paper's two-step cooperative game (Rashid et al. 2018).

    Step 1: agent_0's action picks the second-stage game (agent_1's
    first action is ignored).  Step 2A pays 7 regardless; step 2B pays
    [[0, 1], [1, 8]].  The optimum (8) requires agent_0 to choose the
    risky branch AND both agents to coordinate on action 1 there —
    independent learners settle on the safe 7, which is exactly the
    credit-assignment gap value factorization exists to close.
    Reference analog: ``rllib/examples/env/two_step_game.py`` (the env
    the reference's QMIX tests learn on).
    """

    PAYOFF_2B = ((0.0, 1.0), (1.0, 8.0))

    def __init__(self, seed: int = 0):
        from ray_tpu.rllib.env import Space
        self.agents = ["agent_0", "agent_1"]
        self.observation_space = Space("box", shape=(5,))
        self.action_space = Space("discrete", n=2)
        self._state = 0     # 0 = first step, 1 = 2A, 2 = 2B

    def state(self) -> np.ndarray:
        v = np.zeros(3, np.float32)
        v[self._state] = 1.0
        return v

    def _obs(self) -> Dict[str, np.ndarray]:
        out = {}
        for i, a in enumerate(self.agents):
            v = np.zeros(5, np.float32)
            v[self._state] = 1.0
            v[3 + i] = 1.0
            out[a] = v
        return out

    def reset(self, seed: Optional[int] = None) -> Dict[str, np.ndarray]:
        self._state = 0
        return self._obs()

    def step(self, actions: Dict[str, Any]):
        if self._state == 0:
            self._state = 1 if int(actions["agent_0"]) == 0 else 2
            r, done = 0.0, False
        elif self._state == 1:
            r, done = 7.0, True
        else:
            r = self.PAYOFF_2B[int(actions["agent_0"])][
                int(actions["agent_1"])]
            done = True
        obs = self._obs()
        rewards = {a: r for a in self.agents}
        dones = {a: done for a in self.agents}
        dones["__all__"] = done
        return obs, rewards, dones, {}


MA_ENV_REGISTRY: Dict[str, Callable[..., MultiAgentEnv]] = {
    "CoordinationGame-v0": CoordinationGameEnv,
    "TwoStepGame-v0": TwoStepGameEnv,
}


class MultiAgentPPOConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__(MultiAgentPPO)
        self._config.update({
            "lambda": 0.95,
            "clip_param": 0.2,
            "vf_clip_param": 10.0,
            "vf_loss_coeff": 0.5,
            "entropy_coeff": 0.01,
            "num_sgd_iter": 4,
            "sgd_minibatch_size": 128,
            "grad_clip": 0.5,
            "lr": 3e-4,
            "hiddens": (64, 64),
            "num_envs_per_worker": 8,
            "rollout_fragment_length": 64,
            "gamma": 0.99,
        })

    def multi_agent(self, *, policies: Dict[str, dict],
                    policy_mapping_fn: Callable[[str], str]
                    ) -> "MultiAgentPPOConfig":
        self._config["multiagent"] = {
            "policies": policies,
            "policy_mapping_fn": policy_mapping_fn,
        }
        return self


class MultiAgentRolloutSampler:
    """Drives k env copies; batches per-policy action computation."""

    def __init__(self, config: Dict[str, Any]):
        self.config = config
        env_spec = config["env"]
        maker = MA_ENV_REGISTRY.get(env_spec, env_spec)
        if not callable(maker):
            raise ValueError(f"unknown multi-agent env {env_spec!r}")
        k = config.get("num_envs_per_worker", 8)
        seed = config.get("seed", 0)
        self.envs = [maker(**config.get("env_config", {}))
                     for _ in range(k)]
        self.obs = [e.reset(seed=seed * 1000 + i)
                    for i, e in enumerate(self.envs)]
        self.agents = list(self.envs[0].agents)
        ma = config.get("multiagent") or {
            "policies": {"default": {}},
            "policy_mapping_fn": lambda aid: "default",
        }
        self.mapping = ma["policy_mapping_fn"]
        obs_dim = int(np.prod(self.envs[0].observation_space.shape))
        self.policies: Dict[str, PPOPolicy] = {}
        for pid, overrides in ma["policies"].items():
            pconf = {**config, **(overrides or {})}
            self.policies[pid] = PPOPolicy(
                obs_dim, self.envs[0].action_space, pconf, seed=seed)
        # (env_idx, agent_id) pairs per policy — fixed agent sets.
        self.pairs: Dict[str, List[Tuple[int, str]]] = {}
        for i in range(len(self.envs)):
            for a in self.agents:
                pid = self.mapping(a)
                if pid not in self.policies:
                    raise ValueError(
                        f"policy_mapping_fn({a!r}) -> {pid!r}, which is not "
                        f"in policies {sorted(self.policies)}")
                self.pairs.setdefault(pid, []).append((i, a))
        unmapped = set(self.policies) - set(self.pairs)
        if unmapped:
            raise ValueError(
                f"policies {sorted(unmapped)} are configured but "
                f"policy_mapping_fn maps no agent to them")
        self._episode_reward = np.zeros(len(self.envs))
        self.completed_rewards: List[float] = []

    def sample(self) -> MultiAgentBatch:
        T = self.config.get("rollout_fragment_length", 64)
        gamma = self.config.get("gamma", 0.99)
        lam = self.config.get("lambda", 0.95)
        k = len(self.envs)
        buf = {pid: {key: [] for key in
                     (OBS, ACTIONS, ACTION_LOGP, REWARDS, DONES, VF_PREDS)}
               for pid in self.policies}
        for _ in range(T):
            # one batched forward per policy across its (env, agent) pairs
            acts: Dict[Tuple[int, str], Any] = {}
            for pid, pairs in self.pairs.items():
                obs_mat = np.stack([self.obs[i][a] for i, a in pairs])
                out = self.policies[pid].compute_actions(obs_mat)
                for j, (i, a) in enumerate(pairs):
                    acts[(i, a)] = (out[ACTIONS][j], out[ACTION_LOGP][j],
                                    out[VF_PREDS][j])
                buf[pid][OBS].append(obs_mat)
                buf[pid][ACTIONS].append(out[ACTIONS])
                buf[pid][ACTION_LOGP].append(out[ACTION_LOGP])
                buf[pid][VF_PREDS].append(out[VF_PREDS])
            rew_step = {pid: np.zeros(len(pairs))
                        for pid, pairs in self.pairs.items()}
            done_step = {pid: np.zeros(len(pairs), bool)
                         for pid, pairs in self.pairs.items()}
            for i, env in enumerate(self.envs):
                actions = {a: acts[(i, a)][0] for a in self.agents}
                obs, rewards, dones, _ = env.step(actions)
                self.obs[i] = obs
                self._episode_reward[i] += sum(rewards.values())
                if dones.get("__all__"):
                    self.completed_rewards.append(
                        float(self._episode_reward[i]))
                    self._episode_reward[i] = 0.0
                    self.obs[i] = env.reset()
                for pid, pairs in self.pairs.items():
                    for j, (ei, a) in enumerate(pairs):
                        if ei == i:
                            rew_step[pid][j] = rewards[a]
                            done_step[pid][j] = dones.get(
                                a, dones.get("__all__", False))
            for pid in self.policies:
                buf[pid][REWARDS].append(rew_step[pid])
                buf[pid][DONES].append(done_step[pid])

        batches = {}
        for pid, policy in self.policies.items():
            pairs = self.pairs[pid]
            last_obs = np.stack([self.obs[i][a] for i, a in pairs])
            last_v = policy.compute_values(last_obs)
            arr = {key: np.stack(v) for key, v in buf[pid].items()}  # [T,K]
            adv, vt = compute_gae(arr[REWARDS].astype(np.float32),
                                  arr[VF_PREDS].astype(np.float32),
                                  arr[DONES], last_v, gamma, lam)

            def flat(a):
                return np.concatenate([a[:, j] for j in range(len(pairs))])

            batches[pid] = SampleBatch({
                OBS: flat(arr[OBS]), ACTIONS: flat(arr[ACTIONS]),
                ACTION_LOGP: flat(arr[ACTION_LOGP]),
                VF_PREDS: flat(arr[VF_PREDS]),
                ADVANTAGES: flat(adv), VALUE_TARGETS: flat(vt),
            })
        return MultiAgentBatch(batches, T * k)


class MultiAgentPPO(Trainable):
    """Synchronous multi-agent PPO over mapped policies.

    Single-process sampler (the multi-agent worker fan-out composes the
    same way the single-agent WorkerSet does; kept local until a workload
    needs it — reference rllib trains multi-agent through the same
    training_step with MultiAgentBatch).
    """

    def setup(self, config: Dict[str, Any]) -> None:
        self.sampler = MultiAgentRolloutSampler(config)
        self._timesteps_total = 0
        import collections
        self._episode_rewards = collections.deque(maxlen=100)

    def step(self) -> Dict[str, Any]:
        batch = self.sampler.sample()
        self._timesteps_total += batch.count
        stats = {}
        for pid, policy in self.sampler.policies.items():
            stats[pid] = policy.learn_on_batch(batch[pid])
        self._episode_rewards.extend(self.sampler.completed_rewards)
        self.sampler.completed_rewards.clear()
        result = {"info": {"learner": stats},
                  "num_env_steps_sampled": self._timesteps_total}
        if self._episode_rewards:
            result["episode_reward_mean"] = float(
                np.mean(self._episode_rewards))
        return result

    def save_checkpoint(self) -> Dict[str, Any]:
        return {pid: p.get_weights()
                for pid, p in self.sampler.policies.items()}

    def load_checkpoint(self, checkpoint) -> None:
        if not checkpoint:
            return
        for pid, w in checkpoint.items():
            self.sampler.policies[pid].set_weights(w)

    def cleanup(self) -> None:
        pass
