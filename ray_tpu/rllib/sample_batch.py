"""SampleBatch: the columnar container rollout data travels in.

Design analog: reference ``rllib/policy/sample_batch.py:96`` (dict of
equal-length arrays with concat/shuffle/minibatch utilities).  Kept numpy
-first: batches are built on host CPUs by rollout workers and device_put
once, sharded, into the TPU learner.
"""

from __future__ import annotations

from typing import Dict, Iterator, List, Sequence

import numpy as np

OBS = "obs"
ACTIONS = "actions"
REWARDS = "rewards"
DONES = "dones"
NEXT_OBS = "next_obs"
ACTION_LOGP = "action_logp"
VF_PREDS = "vf_preds"
ADVANTAGES = "advantages"
VALUE_TARGETS = "value_targets"


class SampleBatch(dict):
    """A dict of numpy arrays sharing a leading (time/batch) dimension."""

    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        for k, v in list(self.items()):
            self[k] = np.asarray(v)

    @property
    def count(self) -> int:
        for v in self.values():
            return len(v)
        return 0

    def __len__(self) -> int:  # len(batch) == row count, as in reference
        return self.count

    @staticmethod
    def concat_samples(batches: Sequence["SampleBatch"]) -> "SampleBatch":
        if not batches:
            return SampleBatch()
        keys = batches[0].keys()
        return SampleBatch({
            k: np.concatenate([np.asarray(b[k]) for b in batches], axis=0)
            for k in keys})

    def shuffle(self, rng: np.random.Generator) -> "SampleBatch":
        perm = rng.permutation(self.count)
        return SampleBatch({k: v[perm] for k, v in self.items()})

    def slice(self, start: int, end: int) -> "SampleBatch":
        return SampleBatch({k: v[start:end] for k, v in self.items()})

    def minibatches(self, minibatch_size: int,
                    rng: np.random.Generator) -> Iterator["SampleBatch"]:
        """Shuffled minibatches; drops the ragged tail so every minibatch
        has a static shape (XLA recompiles on shape change)."""
        shuffled = self.shuffle(rng)
        for start in range(0, self.count - minibatch_size + 1,
                           minibatch_size):
            yield shuffled.slice(start, start + minibatch_size)

    def split_by_episode(self) -> List["SampleBatch"]:
        """Split a time-ordered batch at done boundaries."""
        dones = np.asarray(self[DONES])
        ends = np.nonzero(dones)[0]
        out, start = [], 0
        for e in ends:
            out.append(self.slice(start, e + 1))
            start = e + 1
        if start < self.count:
            out.append(self.slice(start, self.count))
        return out
