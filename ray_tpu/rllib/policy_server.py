"""Client-server RL: serve a policy to external simulator processes.

Design analog: reference ``rllib/env/policy_server_input.py:1``
(``PolicyServerInput``: an input reader that runs an HTTP server; external
``PolicyClient`` processes (``rllib/env/policy_client.py:1``) drive
episodes in simulators RLlib does not control, actions are computed
server-side, and the logged experiences become the algorithm's train
batches) and ``rllib/env/external_env.py:1`` (the episode-command
protocol: start_episode / get_action / log_returns / end_episode).

Here the transport is newline-delimited JSON over TCP (the framework's
in-tree ingress style — no HTTP dependency), the server is a background
thread inside the algorithm process, and inference is server-side on the
learner's policy, so clients always act on the freshest weights without
ever holding them.

Usage (server / learner process)::

    algo = (PPOConfig().environment("CartPole-v1")   # spaces only
            .rollouts(input="policy_server",
                      policy_server_port=9900)
            .build())
    while True: algo.train()

Usage (external simulator process)::

    client = PolicyClient("127.0.0.1:9900")
    eid = client.start_episode()
    a = client.get_action(eid, obs)
    client.log_returns(eid, reward)
    client.end_episode(eid, obs)
"""

from __future__ import annotations

import json
import socket
import threading
import uuid
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ray_tpu.rllib.sample_batch import (ACTIONS, ACTION_LOGP, ADVANTAGES,
                                        DONES, OBS, REWARDS, SampleBatch,
                                        VALUE_TARGETS, VF_PREDS)


class _Episode:
    __slots__ = ("obs", "actions", "logps", "vfs", "rewards", "final_obs")

    def __init__(self):
        self.obs: List = []
        self.actions: List = []
        self.logps: List = []
        self.vfs: List = []
        self.rewards: List = []


class PolicyServerInput:
    """TCP policy server + experience collector; ``sample()`` is the
    algorithm-facing side (drop-in for the rollout-sampling path)."""

    def __init__(self, policy, config: Dict[str, Any]):
        # The server drives the policy's PURE jitted actor (params, rng,
        # obs) -> (actions, logp, values) with a PRNG the server owns:
        # calling the stateful compute_actions here would race the
        # learner thread's own rng split.  Only actor-critic on-policy
        # policies expose this surface — fail at build, not per request.
        if not hasattr(policy, "_act") or \
                not hasattr(policy, "compute_values") or \
                hasattr(policy, "_ensure_state"):
            # recurrent policies carry rollout state whose _act signature
            # differs — reject them here too, not per request
            raise ValueError(
                "input='policy_server' needs a non-recurrent actor-critic "
                f"on-policy policy (PPO-family); got "
                f"{type(policy).__name__}")
        self._policy = policy
        import jax
        self._jax = jax
        self._jrng = jax.random.PRNGKey(config.get("seed", 0) + 31337)
        self._gamma = config.get("gamma", 0.99)
        self._lambda = config.get("lambda", 0.95)
        # One train batch per fragment of completed external steps.
        # (num_envs_per_worker is meaningless here: external clients, not
        # per-worker envs, produce the experience.)
        self._min_steps = config.get("rollout_fragment_length", 128)
        self._lock = threading.Lock()
        # Inference serializes on its own lock so a slow (first, jit
        # compiling) compute_actions never blocks end_episode/sample
        # bookkeeping on the main lock.
        self._infer_lock = threading.Lock()
        self._episodes: Dict[str, _Episode] = {}
        self._completed: List[Tuple[_Episode, bool]] = []  # (ep, terminated)
        self._completed_steps = 0
        self._have_steps = threading.Condition(self._lock)
        self.episode_rewards: List[float] = []
        self.episode_lens: List[int] = []
        host = config.get("policy_server_host", "127.0.0.1")
        port = config.get("policy_server_port", 0)
        self._srv = socket.create_server((host, port))
        self.address = "%s:%d" % self._srv.getsockname()[:2]
        self._shutdown = False
        self._thread = threading.Thread(target=self._serve, daemon=True,
                                        name="rt-policy-server")
        self._thread.start()

    # -- server side ------------------------------------------------------

    def _serve(self) -> None:
        self._srv.settimeout(0.5)
        while not self._shutdown:
            try:
                conn, _ = self._srv.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            t = threading.Thread(target=self._serve_conn, args=(conn,),
                                 daemon=True)
            t.start()

    def _serve_conn(self, conn: socket.socket) -> None:
        f = conn.makefile("rwb")
        try:
            for line in f:
                try:
                    reply = self._handle(json.loads(line))
                except Exception as e:  # protocol error -> client sees it
                    reply = {"error": repr(e)}
                f.write((json.dumps(reply) + "\n").encode())
                f.flush()
        except (ConnectionResetError, BrokenPipeError, ValueError):
            pass
        finally:
            try:
                conn.close()
            except OSError:
                pass

    def _handle(self, msg: dict) -> dict:
        cmd = msg.get("cmd")
        if cmd == "start_episode":
            eid = uuid.uuid4().hex[:16]
            with self._lock:
                self._episodes[eid] = _Episode()
            return {"episode_id": eid}
        eid = msg.get("episode_id")
        if cmd == "get_action":
            obs = np.asarray(msg["obs"], np.float32)[None]
            with self._infer_lock:
                self._jrng, rng = self._jax.random.split(self._jrng)
                actions, logp, v = self._policy._act(
                    self._policy.params, rng, obs)
            act = np.asarray(actions)[0]
            with self._lock:
                ep = self._episodes[eid]
                ep.obs.append(obs[0])
                ep.actions.append(act)
                ep.logps.append(float(np.asarray(logp)[0]))
                ep.vfs.append(float(np.asarray(v)[0]))
                ep.rewards.append(0.0)   # filled by log_returns
            return {"action": act.tolist() if hasattr(act, "tolist")
                    else act}
        if cmd == "log_returns":
            with self._lock:
                ep = self._episodes[eid]
                if not ep.rewards:
                    raise ValueError("log_returns before get_action")
                ep.rewards[-1] += float(msg["reward"])
            return {"ok": True}
        if cmd == "end_episode":
            with self._have_steps:
                ep = self._episodes.pop(eid)
                if ep.obs:
                    terminated = not msg.get("truncated", False)
                    if not terminated:
                        if msg.get("obs") is None:
                            raise ValueError(
                                "truncated end_episode requires the final "
                                "obs (the learner bootstraps its value)")
                        # bootstrap value from the final observation
                        ep.final_obs = np.asarray(
                            msg["obs"], np.float32)
                    self._completed.append((ep, terminated))
                    self._completed_steps += len(ep.obs)
                    self.episode_rewards.append(float(sum(ep.rewards)))
                    self.episode_lens.append(len(ep.obs))
                    self._have_steps.notify_all()
            return {"ok": True}
        raise ValueError(f"unknown cmd {cmd!r}")

    # -- algorithm side ---------------------------------------------------

    def sample(self, timeout: float = 300.0) -> SampleBatch:
        """Block until enough completed-episode steps arrived, then build
        one train batch (per-episode GAE, terminated episodes bootstrap
        0, truncated ones bootstrap the policy's value at the final
        obs)."""
        from ray_tpu.rllib.policy import compute_gae
        with self._have_steps:
            ok = self._have_steps.wait_for(
                lambda: self._completed_steps >= self._min_steps,
                timeout=timeout)
            if not ok and not self._completed:
                raise TimeoutError(
                    f"policy server collected no episodes in {timeout}s "
                    f"(no client connected to {self.address}?)")
            eps, self._completed = self._completed, []
            self._completed_steps = 0
        parts: List[SampleBatch] = []
        for ep, terminated in eps:
            T = len(ep.obs)
            rew = np.asarray(ep.rewards, np.float32)[:, None]
            vfs = np.asarray(ep.vfs, np.float32)[:, None]
            dones = np.zeros((T, 1), bool)
            dones[-1, 0] = terminated
            if terminated:
                boot = np.zeros((1,), np.float32)
            else:
                boot = self._policy.compute_values(
                    np.asarray(getattr(ep, "final_obs"))[None])
            adv, targets = compute_gae(rew, vfs, dones, boot,
                                       self._gamma, self._lambda)
            parts.append(SampleBatch({
                OBS: np.asarray(ep.obs, np.float32),
                ACTIONS: np.asarray(ep.actions),
                ACTION_LOGP: np.asarray(ep.logps, np.float32),
                VF_PREDS: vfs[:, 0],
                REWARDS: rew[:, 0],
                DONES: dones[:, 0],
                ADVANTAGES: adv[:, 0],
                VALUE_TARGETS: targets[:, 0],
            }))
        return SampleBatch.concat_samples(parts)

    def get_metrics(self) -> Dict[str, Any]:
        with self._lock:
            r, self.episode_rewards = self.episode_rewards, []
            ln, self.episode_lens = self.episode_lens, []
        return {"episode_rewards": r, "episode_lens": ln}

    def stop(self) -> None:
        self._shutdown = True
        try:
            self._srv.close()
        except OSError:
            pass


class PolicyClient:
    """External-process client (reference: rllib/env/policy_client.py:1).

    Thread-safe for sequential use; one TCP connection, newline JSON."""

    def __init__(self, address: str, timeout: float = 60.0):
        host, _, port = address.rpartition(":")
        self._sock = socket.create_connection((host or "127.0.0.1",
                                               int(port)), timeout=timeout)
        self._f = self._sock.makefile("rwb")
        self._lock = threading.Lock()

    def _call(self, msg: dict) -> dict:
        with self._lock:
            self._f.write((json.dumps(msg) + "\n").encode())
            self._f.flush()
            line = self._f.readline()
        if not line:
            raise ConnectionError("policy server closed the connection")
        reply = json.loads(line)
        if "error" in reply:
            raise RuntimeError(f"policy server error: {reply['error']}")
        return reply

    def start_episode(self) -> str:
        return self._call({"cmd": "start_episode"})["episode_id"]

    def get_action(self, episode_id: str, obs) -> Any:
        obs = np.asarray(obs, np.float32)
        return self._call({"cmd": "get_action", "episode_id": episode_id,
                           "obs": obs.tolist()})["action"]

    def log_returns(self, episode_id: str, reward: float) -> None:
        self._call({"cmd": "log_returns", "episode_id": episode_id,
                    "reward": float(reward)})

    def end_episode(self, episode_id: str, obs=None,
                    truncated: bool = False) -> None:
        msg = {"cmd": "end_episode", "episode_id": episode_id,
               "truncated": bool(truncated)}
        if obs is not None:
            msg["obs"] = np.asarray(obs, np.float32).tolist()
        self._call(msg)

    def close(self) -> None:
        try:
            self._sock.close()
        except OSError:
            pass
