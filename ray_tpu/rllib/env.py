"""RL environments: the Env contract, a vectorized wrapper, and built-in
tasks (CartPole, Pendulum) implemented directly in numpy.

Design analog: the reference wraps gym environments and vectorizes them in
``rllib/env/vector_env.py``; this framework ships its own envs (no gym in
the image) with the same step/reset semantics, natively vectorized — the
whole env batch steps as one numpy program, which is what a host feeding a
TPU learner wants (SURVEY.md §2.4 rollout parallelism).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict, Optional, Tuple

import numpy as np


@dataclass
class Space:
    """Minimal space descriptor (discrete n or continuous box shape)."""

    kind: str                      # "discrete" | "box"
    n: int = 0                     # discrete action count
    shape: Tuple[int, ...] = ()    # box shape
    low: float = -np.inf
    high: float = np.inf


class Env:
    """Single-env contract: reset() -> obs; step(a) -> (obs, r, done, info).

    Matches the classic gym API shape (reference rollout workers assume it:
    rllib/evaluation/sampler.py) without depending on gym.
    """

    observation_space: Space
    action_space: Space

    def reset(self, seed: Optional[int] = None) -> np.ndarray:
        raise NotImplementedError

    def step(self, action) -> Tuple[np.ndarray, float, bool, Dict[str, Any]]:
        raise NotImplementedError


class VectorEnv:
    """N independent env instances stepped as one batched numpy program.

    Auto-resets finished sub-envs (the obs returned for a done env is the
    first obs of its next episode; the pre-reset terminal obs is in
    ``info["terminal_obs"]``) — same contract as the reference's
    ``VectorEnv.vector_step`` (rllib/env/vector_env.py).
    """

    def __init__(self, num_envs: int):
        self.num_envs = num_envs

    def vector_reset(self, seed: Optional[int] = None) -> np.ndarray:
        raise NotImplementedError

    def vector_step(self, actions: np.ndarray
                    ) -> Tuple[np.ndarray, np.ndarray, np.ndarray, Dict]:
        raise NotImplementedError


class CartPoleVectorEnv(VectorEnv):
    """Vectorized CartPole with the standard physics constants.

    Dynamics follow the classic control formulation (pole on a cart,
    Euler-integrated at tau=0.02); episode ends at |x|>2.4, |theta|>12deg,
    or ``max_episode_steps``. Reward +1 per live step.
    """

    def __init__(self, num_envs: int = 1, max_episode_steps: int = 500,
                 seed: int = 0):
        super().__init__(num_envs)
        self.observation_space = Space("box", shape=(4,))
        self.action_space = Space("discrete", n=2)
        self.max_episode_steps = max_episode_steps
        self._rng = np.random.default_rng(seed)
        self._state = np.zeros((num_envs, 4), np.float64)
        self._steps = np.zeros((num_envs,), np.int64)

        self.gravity = 9.8
        self.masscart = 1.0
        self.masspole = 0.1
        self.total_mass = self.masscart + self.masspole
        self.length = 0.5          # half pole length
        self.polemass_length = self.masspole * self.length
        self.force_mag = 10.0
        self.tau = 0.02
        self.x_threshold = 2.4
        self.theta_threshold = 12 * 2 * np.pi / 360

    def _sample_state(self, n: int) -> np.ndarray:
        return self._rng.uniform(-0.05, 0.05, size=(n, 4))

    def vector_reset(self, seed: Optional[int] = None) -> np.ndarray:
        if seed is not None:
            self._rng = np.random.default_rng(seed)
        self._state = self._sample_state(self.num_envs)
        self._steps[:] = 0
        return self._state.astype(np.float32)

    def vector_step(self, actions: np.ndarray):
        x, x_dot, theta, theta_dot = self._state.T
        force = np.where(np.asarray(actions) == 1,
                         self.force_mag, -self.force_mag)
        costheta = np.cos(theta)
        sintheta = np.sin(theta)
        temp = (force + self.polemass_length * theta_dot ** 2 * sintheta
                ) / self.total_mass
        thetaacc = (self.gravity * sintheta - costheta * temp) / (
            self.length * (4.0 / 3.0
                           - self.masspole * costheta ** 2 / self.total_mass))
        xacc = temp - self.polemass_length * thetaacc * costheta \
            / self.total_mass
        x = x + self.tau * x_dot
        x_dot = x_dot + self.tau * xacc
        theta = theta + self.tau * theta_dot
        theta_dot = theta_dot + self.tau * thetaacc
        self._state = np.stack([x, x_dot, theta, theta_dot], axis=1)
        self._steps += 1

        terminated = ((np.abs(x) > self.x_threshold)
                      | (np.abs(theta) > self.theta_threshold))
        truncated = self._steps >= self.max_episode_steps
        done = terminated | truncated
        reward = np.ones((self.num_envs,), np.float32)

        info = {"terminal_obs": self._state.astype(np.float32),
                "truncated": truncated}
        if done.any():
            idx = np.nonzero(done)[0]
            self._state[idx] = self._sample_state(len(idx))
            self._steps[idx] = 0
        return (self._state.astype(np.float32), reward,
                done, info)


class PendulumVectorEnv(VectorEnv):
    """Vectorized Pendulum (continuous control): swing a pole upright.

    obs = (cos th, sin th, th_dot); action = 1-d torque in [-2, 2];
    reward = -(th^2 + 0.1 th_dot^2 + 0.001 a^2); 200-step episodes.
    """

    def __init__(self, num_envs: int = 1, max_episode_steps: int = 200,
                 seed: int = 0):
        super().__init__(num_envs)
        self.observation_space = Space("box", shape=(3,))
        self.action_space = Space("box", shape=(1,), low=-2.0, high=2.0)
        self.max_episode_steps = max_episode_steps
        self._rng = np.random.default_rng(seed)
        self._th = np.zeros((num_envs,))
        self._thdot = np.zeros((num_envs,))
        self._steps = np.zeros((num_envs,), np.int64)
        self.g, self.m, self.length, self.dt = 10.0, 1.0, 1.0, 0.05

    def _obs(self) -> np.ndarray:
        return np.stack([np.cos(self._th), np.sin(self._th),
                         self._thdot], axis=1).astype(np.float32)

    def _sample(self, n):
        return (self._rng.uniform(-np.pi, np.pi, n),
                self._rng.uniform(-1.0, 1.0, n))

    def vector_reset(self, seed: Optional[int] = None) -> np.ndarray:
        if seed is not None:
            self._rng = np.random.default_rng(seed)
        self._th, self._thdot = self._sample(self.num_envs)
        self._steps[:] = 0
        return self._obs()

    def vector_step(self, actions: np.ndarray):
        u = np.clip(np.asarray(actions, np.float64).reshape(
            self.num_envs), -2.0, 2.0)
        th, thdot = self._th, self._thdot
        norm_th = ((th + np.pi) % (2 * np.pi)) - np.pi
        cost = norm_th ** 2 + 0.1 * thdot ** 2 + 0.001 * u ** 2
        thdot = thdot + (3 * self.g / (2 * self.length) * np.sin(th)
                         + 3.0 / (self.m * self.length ** 2) * u) * self.dt
        thdot = np.clip(thdot, -8.0, 8.0)
        th = th + thdot * self.dt
        self._th, self._thdot = th, thdot
        self._steps += 1
        done = self._steps >= self.max_episode_steps
        info = {"terminal_obs": self._obs(),
                "truncated": done.copy()}
        if done.any():
            idx = np.nonzero(done)[0]
            nth, nthdot = self._sample(len(idx))
            self._th[idx] = nth
            self._thdot[idx] = nthdot
            self._steps[idx] = 0
        return self._obs(), (-cost).astype(np.float32), done, info


class RepeatPreviousVectorEnv(VectorEnv):
    """Memory probe: emit the token seen on the PREVIOUS step.

    Observation is a one-hot token drawn uniformly each step; reward 1.0
    when the action equals the token shown one step earlier (0 on the
    first step of an episode).  A memoryless policy peaks at 1/n_tokens
    expected reward per step; a recurrent policy solves it exactly — the
    standard smoke test for whether hidden state actually carries
    information (reference analog: RepeatAfterMeEnv in
    rllib/examples/envs/classes/repeat_after_me_env.py — behavior
    re-derived, not ported).
    """

    def __init__(self, num_envs: int = 1, n_tokens: int = 3,
                 episode_len: int = 32, seed: int = 0):
        super().__init__(num_envs)
        self.n_tokens = n_tokens
        self.episode_len = episode_len
        self.observation_space = Space("box", shape=(n_tokens,))
        self.action_space = Space("discrete", n=n_tokens)
        self._rng = np.random.default_rng(seed)
        self._token = np.zeros((num_envs,), np.int64)
        self._prev = np.zeros((num_envs,), np.int64)
        self._steps = np.zeros((num_envs,), np.int64)

    def _one_hot(self) -> np.ndarray:
        out = np.zeros((self.num_envs, self.n_tokens), np.float32)
        out[np.arange(self.num_envs), self._token] = 1.0
        return out

    def vector_reset(self, seed: Optional[int] = None) -> np.ndarray:
        if seed is not None:
            self._rng = np.random.default_rng(seed)
        self._token = self._rng.integers(0, self.n_tokens,
                                         size=self.num_envs)
        self._prev[:] = -1          # no reward defined for the first step
        self._steps[:] = 0
        return self._one_hot()

    def vector_step(self, actions: np.ndarray):
        actions = np.asarray(actions)
        reward = (actions == self._prev).astype(np.float32)
        reward[self._prev < 0] = 0.0
        self._prev = self._token.copy()
        self._token = self._rng.integers(0, self.n_tokens,
                                         size=self.num_envs)
        self._steps += 1
        truncated = self._steps >= self.episode_len
        done = truncated.copy()
        info = {"terminal_obs": self._one_hot(), "truncated": truncated}
        if done.any():
            idx = np.nonzero(done)[0]
            self._prev[idx] = -1
            self._steps[idx] = 0
            self._token[idx] = self._rng.integers(0, self.n_tokens,
                                                  size=len(idx))
        return self._one_hot(), reward, done, info


class SparseChainVectorEnv(VectorEnv):
    """Exploration stress test (the NChain/DeepSea family): a length-N
    chain where only the far-right state pays (+1) but a small distractor
    (+0.01) pays for sitting at the start.  Greedy/epsilon agents latch
    onto the distractor; novelty-driven exploration (RND) finds the end.
    obs = one-hot position; actions: 0 = left, 1 = right.
    """

    def __init__(self, num_envs: int = 1, length: int = 16,
                 max_episode_steps: int = 32, seed: int = 0):
        super().__init__(num_envs)
        self.length = length
        self.observation_space = Space("box", shape=(length,), low=0.0,
                                       high=1.0)
        self.action_space = Space("discrete", n=2)
        self.max_episode_steps = max_episode_steps
        self.pos = np.zeros(num_envs, np.int64)
        self._steps = np.zeros(num_envs, np.int64)

    def _obs(self) -> np.ndarray:
        return np.eye(self.length,
                      dtype=np.float32)[self.pos]

    def vector_reset(self, seed: Optional[int] = None) -> np.ndarray:
        self.pos[:] = 0
        self._steps[:] = 0
        return self._obs()

    def vector_step(self, actions: np.ndarray):
        a = np.asarray(actions)
        self.pos = np.clip(self.pos + np.where(a == 1, 1, -1), 0,
                           self.length - 1)
        self._steps += 1
        at_goal = self.pos == self.length - 1
        reward = np.where(at_goal, 1.0,
                          np.where(self.pos == 0, 0.01, 0.0)
                          ).astype(np.float32)
        truncated = self._steps >= self.max_episode_steps
        done = at_goal | truncated
        info = {"terminal_obs": self._obs(), "truncated": truncated}
        if done.any():
            idx = np.nonzero(done)[0]
            self.pos[idx] = 0
            self._steps[idx] = 0
        return self._obs(), reward, done, info



_ENV_REGISTRY = {
    "CartPole-v1": CartPoleVectorEnv,
    "Pendulum-v1": PendulumVectorEnv,
    "RepeatPrevious-v0": RepeatPreviousVectorEnv,
    "SparseChain-v0": SparseChainVectorEnv,
}


def register_env(name: str, cls) -> None:
    """Register a VectorEnv class under a name (reference analog:
    ray.tune.registry.register_env)."""
    _ENV_REGISTRY[name] = cls


def make_vector_env(name: str, num_envs: int, seed: int = 0,
                    **kwargs) -> VectorEnv:
    if name not in _ENV_REGISTRY:
        # Built-in extras register on first use (the pixel suite).
        import ray_tpu.rllib.pixel_env  # noqa: F401
    if name not in _ENV_REGISTRY:
        raise KeyError(
            f"unknown env {name!r}; registered: {sorted(_ENV_REGISTRY)}")
    return _ENV_REGISTRY[name](num_envs=num_envs, seed=seed, **kwargs)
