"""ray_tpu.rllib: RL training library (reference analog: rllib/).

PPO first (reference rllib/algorithms/ppo/), on the Podracer split: env
rollouts on host-CPU actors, one jitted learner program on the device.
"""

from ray_tpu.rllib.a2c import A2C, A2CConfig, A2CPolicy
from ray_tpu.rllib.algorithm import Algorithm, AlgorithmConfig
from ray_tpu.rllib.appo import APPO, APPOConfig, APPOPolicy
from ray_tpu.rllib.ddpg import DDPG, DDPGConfig, DDPGPolicy
from ray_tpu.rllib.dqn import DQN, DQNConfig, DQNPolicy
from ray_tpu.rllib.es import ES, ESConfig
from ray_tpu.rllib.td3 import TD3, TD3Config, TD3Policy
from ray_tpu.rllib.env import (CartPoleVectorEnv, Env, PendulumVectorEnv,
                               Space, VectorEnv, make_vector_env,
                               register_env)
from ray_tpu.rllib.catalog import AttentionPPOPolicy, ModelCatalog
from ray_tpu.rllib.impala import Impala, ImpalaConfig, ImpalaPolicy
from ray_tpu.rllib.apex_dqn import ApexDQN, ApexDQNConfig
from ray_tpu.rllib.qmix import QMIX, QMIXConfig
from ray_tpu.rllib.policy_server import PolicyClient, PolicyServerInput
from ray_tpu.rllib.offline import (BC, BCConfig, BCPolicy, CQL, CQLConfig,
                                   DatasetReader, DatasetWriter,
                                   ImportanceSamplingEstimator, MARWIL,
                                   MARWILConfig, MARWILPolicy)
from ray_tpu.rllib.policy import Policy, PPOPolicy, compute_gae
from ray_tpu.rllib.ppo import (PPO, PPOConfig, RecurrentPPO,
                               RecurrentPPOConfig)
from ray_tpu.rllib.recurrent import RecurrentPPOPolicy
from ray_tpu.rllib.replay_buffer import (PrioritizedReplayBuffer,
                                         ReplayBuffer)
from ray_tpu.rllib.multi_agent import (CoordinationGameEnv, MultiAgentBatch,
                                       MultiAgentEnv, MultiAgentPPO,
                                       MultiAgentPPOConfig)
from ray_tpu.rllib.rollout_worker import RolloutWorker
from ray_tpu.rllib.sac import SAC, SACConfig, SACPolicy
from ray_tpu.rllib.sample_batch import SampleBatch
from ray_tpu.rllib.worker_set import WorkerSet

__all__ = [
    "A2C", "A2CConfig", "A2CPolicy", "APPO", "APPOConfig", "APPOPolicy",
    "Algorithm", "AlgorithmConfig", "AttentionPPOPolicy", "BC", "BCConfig",
    "BCPolicy", "ModelCatalog",
    "CartPoleVectorEnv", "CQL", "CQLConfig", "DatasetReader",
    "DatasetWriter", "DDPG", "DDPGConfig", "DDPGPolicy",
    "DQN", "DQNConfig", "DQNPolicy", "ES", "ESConfig",
    "Env", "Impala",
    "ImpalaConfig", "ImpalaPolicy", "ImportanceSamplingEstimator",
    "MARWIL", "MARWILConfig", "MARWILPolicy",
    "ApexDQN", "ApexDQNConfig",
    "PendulumVectorEnv", "Policy", "PolicyClient", "PolicyServerInput",
    "PPO", "PPOConfig", "PPOPolicy", "QMIX", "QMIXConfig",
    "PrioritizedReplayBuffer", "RecurrentPPO", "RecurrentPPOConfig",
    "RecurrentPPOPolicy", "ReplayBuffer", "RolloutWorker", "SampleBatch",
    "Space", "TD3", "TD3Config", "TD3Policy", "VectorEnv", "WorkerSet",
    "compute_gae", "make_vector_env", "register_env",
]

from ray_tpu._private.usage_stats import record_library_usage as _rlu
_rlu("rllib")
del _rlu
