"""A2C: synchronous advantage actor-critic.

Design analog: reference ``rllib/algorithms/a2c/a2c.py`` (synchronous
parallel sampling -> ONE gradient step on the whole batch -> broadcast;
the non-clipped, non-epoch little sibling of PPO).  TPU-first: the update
is a single jitted program; rollout workers are host-CPU actors sharing
PPO's GAE postprocessing (policy.py compute_gae).
"""

from __future__ import annotations

from typing import Any, Dict

import numpy as np

import jax
import jax.numpy as jnp

from ray_tpu.rllib.algorithm import Algorithm, AlgorithmConfig
from ray_tpu.rllib.policy import (Categorical, DiagGaussian, Policy,
                                  ac_forward, ac_init)
from ray_tpu.rllib.sample_batch import (ACTIONS, ACTION_LOGP, ADVANTAGES,
                                        OBS, SampleBatch, VALUE_TARGETS,
                                        VF_PREDS)


class A2CConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__(A2C)
        self._config.update({
            "policy": "a2c",
            "lambda": 1.0,                  # plain returns by default
            "vf_loss_coeff": 0.5,
            "entropy_coeff": 0.01,
            "grad_clip": 0.5,
            "lr": 1e-3,
            "hiddens": (64, 64),
            "num_envs_per_worker": 8,
            "rollout_fragment_length": 32,
        })


class A2CPolicy(Policy):
    """Vanilla policy-gradient + value loss, one gradient step per train
    batch (no ratio clipping, no minibatch epochs — that's PPO)."""

    def __init__(self, obs_dim: int, action_space, config: Dict[str, Any],
                 seed: int = 0):
        self.config = config
        self.discrete = action_space.kind == "discrete"
        self.dist = Categorical if self.discrete else DiagGaussian
        num_outputs = (action_space.n if self.discrete
                       else 2 * int(np.prod(action_space.shape)))
        self._rng = jax.random.PRNGKey(seed)
        self._rng, init_rng = jax.random.split(self._rng)
        self.params = ac_init(init_rng, obs_dim, num_outputs,
                              tuple(config.get("hiddens", (64, 64))))
        import optax
        self._tx = optax.chain(
            optax.clip_by_global_norm(config.get("grad_clip", 0.5)),
            optax.adam(config.get("lr", 1e-3)))
        self.opt_state = self._tx.init(self.params)

        dist = self.dist
        vf_coeff = config.get("vf_loss_coeff", 0.5)
        ent_coeff = config.get("entropy_coeff", 0.01)

        @jax.jit
        def _act(params, rng, obs):
            pi, v = ac_forward(params, obs)
            actions = dist.sample(rng, pi)
            return actions, dist.logp(pi, actions), v
        self._act = _act

        def _loss(params, batch):
            pi, v = ac_forward(params, batch[OBS])
            logp = dist.logp(pi, batch[ACTIONS])
            pg = -jnp.mean(logp * batch[ADVANTAGES])
            vf = jnp.mean((v - batch[VALUE_TARGETS]) ** 2)
            ent = jnp.mean(dist.entropy(pi))
            total = pg + vf_coeff * vf - ent_coeff * ent
            return total, {"policy_loss": pg, "vf_loss": vf,
                           "entropy": ent, "total_loss": total}

        @jax.jit
        def _update(params, opt_state, batch):
            (_, stats), grads = jax.value_and_grad(
                _loss, has_aux=True)(params, batch)
            updates, opt_state = self._tx.update(grads, opt_state)
            import optax as _ox
            params = _ox.apply_updates(params, updates)
            return params, opt_state, stats
        self._update = _update

    def compute_actions(self, obs: np.ndarray) -> Dict[str, np.ndarray]:
        self._rng, rng = jax.random.split(self._rng)
        actions, logp, v = self._act(self.params, rng,
                                     jnp.asarray(obs, jnp.float32))
        return {ACTIONS: np.asarray(actions), ACTION_LOGP: np.asarray(logp),
                VF_PREDS: np.asarray(v)}

    def compute_values(self, obs: np.ndarray) -> np.ndarray:
        _, v = ac_forward(self.params, jnp.asarray(obs, jnp.float32))
        return np.asarray(v)

    def learn_on_batch(self, batch: SampleBatch) -> Dict[str, float]:
        adv = np.asarray(batch[ADVANTAGES], np.float32)
        batch = dict(batch)
        batch[ADVANTAGES] = (adv - adv.mean()) / (adv.std() + 1e-8)
        device_batch = {
            k: jnp.asarray(np.asarray(v, np.float32 if k != ACTIONS
                                      else None))
            for k, v in batch.items()}
        self.params, self.opt_state, stats = self._update(
            self.params, self.opt_state, device_batch)
        return {k: float(v) for k, v in stats.items()}

    def get_weights(self):
        return jax.tree.map(np.asarray, self.params)

    def set_weights(self, weights):
        self.params = jax.tree.map(jnp.asarray, weights)


class A2C(Algorithm):
    def __init__(self, config=None, **kwargs):
        config = dict(config or {})
        config.setdefault("policy", "a2c")
        super().__init__(config=config, **kwargs)

    def training_step(self) -> Dict[str, Any]:
        train_batch = self.workers.synchronous_sample()
        self._timesteps_total += train_batch.count
        stats = self.workers.local_worker.policy.learn_on_batch(train_batch)
        self.workers.sync_weights()
        return {"info": {"learner": stats},
                "train_batch_size": train_batch.count,
                **{f"learner_{k}": v for k, v in stats.items()}}
