"""Gymnasium/gym interop: wrap external envs into the native VectorEnv.

Design analog: reference ``rllib/env/vector_env.py`` (``VectorEnv.
vectorize_gym_envs`` wrapping N gym envs behind the vector contract) and
the env-creator registry accepting gym classes.  gym/gymnasium is NOT a
dependency — the wrapper only needs the duck-typed surface
(``reset()/step()``, ``observation_space``/``action_space`` with
``shape``/``n``), so it works with either package when the user has one
installed, and with any object matching the API (the unit tests use a
stub).

Usage::

    from ray_tpu.rllib.gym_compat import GymVectorEnv, register_gym_env
    register_gym_env("MyGym-v0", lambda cfg: gymnasium.make("CartPole-v1"))
    algo = PPOConfig().environment("MyGym-v0").build()
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional

import numpy as np

from ray_tpu.rllib.env import Space, VectorEnv, register_env


def _convert_space(space) -> Space:
    """gym(nasium) Discrete/Box (duck-typed) -> native Space."""
    n = getattr(space, "n", None)
    if n is not None:
        return Space("discrete", n=int(n))
    shape = tuple(getattr(space, "shape"))
    low = getattr(space, "low", -np.inf)
    high = getattr(space, "high", np.inf)
    low = float(np.min(low)) if np.ndim(low) else float(low)
    high = float(np.max(high)) if np.ndim(high) else float(high)
    return Space("box", shape=shape, low=low, high=high)


def _split_reset(out):
    """gymnasium returns (obs, info); classic gym returns obs."""
    if isinstance(out, tuple) and len(out) == 2 and isinstance(
            out[1], dict):
        return out[0]
    return out


class GymVectorEnv(VectorEnv):
    """N independent gym(nasium) env instances behind the native
    ``VectorEnv`` contract (auto-reset, ``terminal_obs``/``truncated``
    in info — same semantics as the built-in envs)."""

    def __init__(self, env_creator: Callable[[Dict], Any],
                 num_envs: int = 1, seed: int = 0,
                 env_config: Optional[Dict] = None, **kwargs):
        super().__init__(num_envs)
        cfg = dict(env_config or {})
        cfg.update(kwargs)
        self._envs = [env_creator(cfg) for _ in range(num_envs)]
        self._seed = seed
        e0 = self._envs[0]
        self.observation_space = _convert_space(e0.observation_space)
        self.action_space = _convert_space(e0.action_space)

    def _reset_one(self, i: int, seed: Optional[int]) -> np.ndarray:
        env = self._envs[i]
        try:
            out = env.reset(seed=seed)
        except TypeError:   # classic gym: no seed kwarg
            out = env.reset()
        return np.asarray(_split_reset(out), np.float32)

    def vector_reset(self, seed: Optional[int] = None) -> np.ndarray:
        base = self._seed if seed is None else seed
        return np.stack([self._reset_one(i, base + i)
                         for i in range(self.num_envs)])

    def vector_step(self, actions: np.ndarray):
        obs, rews, dones, truncs = [], [], [], []
        for i, env in enumerate(self._envs):
            out = env.step(np.asarray(actions[i]).item()
                           if self.action_space.kind == "discrete"
                           else np.asarray(actions[i]))
            if len(out) == 5:       # gymnasium: term/trunc split
                o, r, term, trunc, _ = out
            else:                   # classic gym: done only
                o, r, term, _ = out
                trunc = False
            obs.append(np.asarray(o, np.float32))
            rews.append(float(r))
            dones.append(bool(term) or bool(trunc))
            truncs.append(bool(trunc))
        terminal = np.stack(obs)
        info = {"terminal_obs": terminal,
                "truncated": np.asarray(truncs)}
        for i, d in enumerate(dones):
            if d:
                obs[i] = self._reset_one(i, None)
        return (np.stack(obs), np.asarray(rews, np.float32),
                np.asarray(dones), info)


def register_gym_env(name: str,
                     env_creator: Callable[[Dict], Any]) -> None:
    """Register a gym(nasium) env factory under a name usable in any
    algorithm config (reference: tune.registry.register_env with a gym
    creator)."""

    def make(num_envs: int = 1, seed: int = 0, **kwargs):
        return GymVectorEnv(env_creator, num_envs=num_envs, seed=seed,
                            **kwargs)

    register_env(name, make)
