"""Ape-X DQN: distributed prioritized experience replay.

Design analog: reference ``rllib/algorithms/apex_dqn/apex_dqn.py``
(Horgan et al. 2018): many rollout workers with per-worker exploration
epsilons feed sharded prioritized-replay ACTORS; the learner samples from
the shards asynchronously, pushes updated priorities back, and
broadcasts fresh weights on an interval.  TPU-first deltas: the learner
is the same single jitted double-Q/huber program as DQN (optionally
shard_mapped over a dp mesh via ``num_learner_devices``); replay shards
are plain actors around the columnar ``PrioritizedReplayBuffer``;
sampling, priority updates, and weight broadcast all ride the normal
actor transport.

Per-worker epsilons follow the paper: eps_i = base^(1 + i/(N-1) * alpha)
with base=0.4, alpha=7 — worker 0 explores at 0.4, the last at ~0.0016,
so the replay pool always mixes broad exploration with near-greedy
trajectories.
"""

from __future__ import annotations

from typing import Any, Dict, List

import numpy as np

import ray_tpu
from ray_tpu.rllib.algorithm import Algorithm, AlgorithmConfig
from ray_tpu.rllib.replay_buffer import PrioritizedReplayBuffer
from ray_tpu.rllib.sample_batch import SampleBatch


class ApexDQNConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__(ApexDQN)
        self._config.update({
            "policy": "dqn",
            "hiddens": (64, 64),
            "lr": 5e-4,
            "train_batch_size": 64,
            "buffer_size": 50_000,          # per shard
            "num_replay_shards": 2,
            "learning_starts": 1000,        # total across shards
            "prioritized_replay_alpha": 0.6,
            "prioritized_replay_beta": 0.4,
            "target_network_update_freq": 50,    # learner updates
            "num_train_iters": 8,           # updates per training_step
            "broadcast_interval": 4,        # updates between weight pushes
            "double_q": True,
            "apex_epsilon_base": 0.4,
            "apex_epsilon_alpha": 7.0,
            "rollout_fragment_length": 16,
            "num_envs_per_worker": 4,
            "num_rollout_workers": 2,
            "gamma": 0.99,
        })


class ReplayShard:
    """Actor wrapping one prioritized replay shard (reference: the
    replay actors of apex_dqn's execution plan)."""

    def __init__(self, capacity: int, alpha: float, seed: int):
        self._buf = PrioritizedReplayBuffer(capacity, alpha=alpha,
                                            seed=seed)

    def add(self, batch: SampleBatch) -> int:
        self._buf.add(batch)
        return len(self._buf)

    def sample(self, n: int, beta: float):
        if len(self._buf) < n:
            return None
        return self._buf.sample(n, beta=beta)

    def update_priorities(self, idx, td) -> None:
        self._buf.update_priorities(np.asarray(idx), np.asarray(td))

    def size(self) -> int:
        return len(self._buf)


def _pin_epsilon(e: float):
    """Constant-epsilon pin shipped to a rollout worker (the Ape-X
    ladder replaces the annealed schedule)."""
    def fn(worker):
        worker.policy.config["epsilon_initial"] = e
        worker.policy.config["epsilon_final"] = e
        return e
    return fn


class ApexDQN(Algorithm):
    def setup(self, config: Dict[str, Any]) -> None:
        config = dict(config)
        config.setdefault("policy", "dqn")
        n_workers = config.get("num_rollout_workers", 2)
        if n_workers < 1:
            raise ValueError("ApexDQN needs num_rollout_workers >= 1")
        super().setup(config)
        c = self.config
        # Pin each worker's epsilon to the Ape-X ladder (constant per
        # worker, not annealed — the ladder IS the exploration schedule).
        base = c.get("apex_epsilon_base", 0.4)
        alpha = c.get("apex_epsilon_alpha", 7.0)
        n = len(self.workers.remote_workers)
        eps = [base ** (1 + (i / max(1, n - 1)) * alpha)
               for i in range(n)]

        self._worker_eps = eps
        ray_tpu.get([w.apply.remote(_pin_epsilon(e))
                     for w, e in zip(self.workers.remote_workers, eps)],
                    timeout=120)

        shard_cls = ray_tpu.remote(num_cpus=0.25)(ReplayShard)
        self.replay_shards: List[Any] = [
            shard_cls.remote(c.get("buffer_size", 50_000),
                             c.get("prioritized_replay_alpha", 0.6),
                             c.get("seed", 0) + i)
            for i in range(c.get("num_replay_shards", 2))]
        self._shard_rr = 0
        self._inflight: Dict[str, Any] = {}
        self._updates = 0
        self._since_target = 0
        self.workers.ready()
        self._reconcile_workers()

    def _reconcile_workers(self) -> None:
        """Every live worker must have exactly one in-flight sample and
        its ladder epsilon.  Also covers workers REPLACED by
        restore_unhealthy_workers: the fresh actor gets its slot's
        epsilon re-pinned (a restored policy would otherwise revert to
        the annealed default) and a first sample issued."""
        inflight_ids = {id(w) for _, w in self._inflight.values()}
        for i, w in enumerate(self.workers.remote_workers):
            if id(w) not in inflight_ids:
                e = self._worker_eps[i % len(self._worker_eps)]
                w.apply.remote(_pin_epsilon(e))   # ordered before sample
                ref = w.sample.remote()
                self._inflight[ref.hex()] = (ref, w)

    def _harvest(self) -> int:
        """Move completed sample batches into replay shards and re-issue
        the workers immediately (the async heart of Ape-X)."""
        refs = [r for r, _ in self._inflight.values()]
        done, _ = ray_tpu.wait(refs, num_returns=len(refs), timeout=0)
        moved = 0
        live = {id(w) for w in self.workers.remote_workers}
        for ref in done:
            _, worker = self._inflight.pop(ref.hex())
            try:
                batch = ray_tpu.get(ref)
            except Exception:
                # worker died mid-sample; Algorithm.step's restore path
                # replaces it and _reconcile_workers re-enlists it
                continue
            self._timesteps_total += batch.count
            moved += batch.count
            shard = self.replay_shards[self._shard_rr
                                       % len(self.replay_shards)]
            self._shard_rr += 1
            shard.add.remote(batch)      # fire-and-forget
            if id(worker) in live:
                nref = worker.sample.remote()
                self._inflight[nref.hex()] = (nref, worker)
        self._reconcile_workers()
        return moved

    def training_step(self) -> Dict[str, Any]:
        c = self.config
        policy = self.workers.local_worker.policy
        stats: Dict[str, Any] = {}
        target_adds = c.get("learning_starts", 1000)
        # Fill phase: block until the shards hold enough experience.
        import time as _time
        deadline = _time.monotonic() + 300
        while _time.monotonic() < deadline:
            self._harvest()
            sizes = ray_tpu.get([s.size.remote()
                                 for s in self.replay_shards],
                                timeout=60)
            if sum(sizes) >= target_adds:
                break
            _time.sleep(0.05)

        n_updates = 0
        update_deadline = _time.monotonic() + 300
        while n_updates < c.get("num_train_iters", 8):
            if _time.monotonic() > update_deadline:
                raise TimeoutError(
                    "ApexDQN made no learner progress in 300s "
                    f"(shard sizes: {ray_tpu.get([s2.size.remote() for s2 in self.replay_shards], timeout=60)})")
            self._harvest()
            shard = self.replay_shards[self._updates
                                       % len(self.replay_shards)]
            train = ray_tpu.get(shard.sample.remote(
                c.get("train_batch_size", 64),
                c.get("prioritized_replay_beta", 0.4)), timeout=60)
            if train is None:
                _time.sleep(0.05)
                continue
            stats = policy.learn_on_batch(train)
            shard.update_priorities.remote(          # fire-and-forget
                train["batch_indexes"], stats.pop("td_errors"))
            n_updates += 1
            self._updates += 1
            self._since_target += 1
            if self._since_target >= c.get(
                    "target_network_update_freq", 50):
                policy.update_target()
                self._since_target = 0
            if self._updates % c.get("broadcast_interval", 4) == 0:
                self.workers.sync_weights()
        return {"info": {"learner": stats},
                "num_updates": self._updates,
                "worker_epsilons": self._worker_eps,
                **{f"learner_{k}": v for k, v in stats.items()
                   if np.isscalar(v)}}

    def cleanup(self) -> None:
        for s in self.replay_shards:
            try:
                ray_tpu.kill(s)
            except Exception:
                pass
        super().cleanup()
