"""Replay buffers: uniform ring + proportional prioritized.

Design analog: reference ``rllib/utils/replay_buffers/`` — ReplayBuffer
(uniform) and PrioritizedReplayBuffer (proportional sampling with
importance weights, Schaul et al.).  Columnar storage (one ring array per
SampleBatch key) so a sample() is pure fancy indexing — the sampled batch
device_puts as one contiguous transfer.
"""

from __future__ import annotations

from typing import Dict, Optional

import numpy as np

from ray_tpu.rllib.sample_batch import SampleBatch


class ReplayBuffer:
    def __init__(self, capacity: int = 100_000, seed: Optional[int] = None):
        self.capacity = capacity
        self._cols: Dict[str, np.ndarray] = {}
        self._size = 0
        self._next = 0
        self._rng = np.random.default_rng(seed)

    def __len__(self) -> int:
        return self._size

    def add(self, batch: SampleBatch) -> np.ndarray:
        """Insert every row; returns the storage indices used."""
        n = batch.count
        if not self._cols:
            for k, v in batch.items():
                v = np.asarray(v)
                self._cols[k] = np.zeros((self.capacity,) + v.shape[1:],
                                         v.dtype)
        idx = (self._next + np.arange(n)) % self.capacity
        for k, v in batch.items():
            self._cols[k][idx] = np.asarray(v)
        self._next = int((self._next + n) % self.capacity)
        self._size = int(min(self._size + n, self.capacity))
        return idx

    def sample(self, num_items: int) -> SampleBatch:
        idx = self._rng.integers(0, self._size, size=num_items)
        return self._take(idx)

    def _take(self, idx: np.ndarray) -> SampleBatch:
        out = SampleBatch({k: c[idx] for k, c in self._cols.items()})
        out["batch_indexes"] = idx
        return out


class PrioritizedReplayBuffer(ReplayBuffer):
    """Proportional prioritization: P(i) ∝ p_i^alpha, importance weights
    w_i = (N * P(i))^-beta / max w (reference
    utils/replay_buffers/prioritized_replay_buffer.py)."""

    def __init__(self, capacity: int = 100_000, alpha: float = 0.6,
                 seed: Optional[int] = None):
        super().__init__(capacity, seed)
        self.alpha = alpha
        self._prios = np.zeros((capacity,), np.float64)
        self._max_prio = 1.0

    def add(self, batch: SampleBatch) -> np.ndarray:
        idx = super().add(batch)
        # New experience gets max priority so it's seen at least once.
        self._prios[idx] = self._max_prio
        return idx

    def sample(self, num_items: int, beta: float = 0.4) -> SampleBatch:
        p = self._prios[:self._size] ** self.alpha
        probs = p / p.sum()
        idx = self._rng.choice(self._size, size=num_items, p=probs)
        out = self._take(idx)
        w = (self._size * probs[idx]) ** (-beta)
        out["weights"] = (w / w.max()).astype(np.float32)
        return out

    def update_priorities(self, idx: np.ndarray, priorities: np.ndarray):
        priorities = np.abs(np.asarray(priorities, np.float64)) + 1e-6
        self._prios[np.asarray(idx)] = priorities
        self._max_prio = max(self._max_prio, float(priorities.max()))
