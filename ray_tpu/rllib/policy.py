"""Policies: jax actor-critic networks + the Policy contract.

Design analog: reference ``rllib/policy/policy.py`` + ``torch_policy_v2.py``
(compute_actions / loss / learn_on_batch / get-set_weights).  TPU-first
deltas: the network is a pure-jax pytree (no framework Module), action
sampling is a jitted function driven by a PRNG key, and the PPO update is a
single jitted program whose minibatch SGD loop lives INSIDE jit
(lax.scan over epochs x minibatches) so one dispatch per training step
reaches the device.
"""

from __future__ import annotations

import functools
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ray_tpu.rllib.sample_batch import (
    ACTIONS, ACTION_LOGP, ADVANTAGES, DONES, OBS, REWARDS, SampleBatch,
    VALUE_TARGETS, VF_PREDS)


# -- actor-critic network (shared tanh trunk, logits + value heads) -------

def _orthogonal(rng, shape, scale):
    """Orthogonal init (standard for PPO; keeps early policy near-uniform)."""
    a = jax.random.normal(rng, shape)
    q, r = jnp.linalg.qr(a if shape[0] >= shape[1] else a.T)
    q = q * jnp.sign(jnp.diag(r))
    if shape[0] < shape[1]:
        q = q.T
    return scale * q[:shape[0], :shape[1]]


def ac_init(rng: jax.Array, obs_dim: int, num_outputs: int,
            hiddens=(64, 64), value_head: bool = True,
            head_scale: float = 0.01) -> Dict:
    keys = jax.random.split(rng, len(hiddens) + 2)
    params, sizes = {}, (obs_dim,) + tuple(hiddens)
    for i in range(len(hiddens)):
        params[f"trunk{i}"] = {
            "w": _orthogonal(keys[i], (sizes[i], sizes[i + 1]),
                             jnp.sqrt(2.0)),
            "b": jnp.zeros((sizes[i + 1],))}
    params["pi"] = {"w": _orthogonal(keys[-2], (sizes[-1], num_outputs),
                                     head_scale),
                    "b": jnp.zeros((num_outputs,))}
    if value_head:
        params["vf"] = {"w": _orthogonal(keys[-1], (sizes[-1], 1), 1.0),
                        "b": jnp.zeros((1,))}
    return params


def head_forward(params: Dict, obs: jax.Array) -> jax.Array:
    """Trunk + pi head only (Q-values for DQN-style policies)."""
    x = _flatten_obs(obs)
    i = 0
    while f"trunk{i}" in params:
        p = params[f"trunk{i}"]
        x = jnp.tanh(x @ p["w"] + p["b"])
        i += 1
    return x @ params["pi"]["w"] + params["pi"]["b"]


def _flatten_obs(obs: jax.Array) -> jax.Array:
    """Image observations (e.g. the 10x10xC MinAtar-class envs) flatten at
    the network boundary; vector obs pass through."""
    return obs.reshape(obs.shape[0], -1) if obs.ndim > 2 else obs


def ac_forward(params: Dict, obs: jax.Array) -> Tuple[jax.Array, jax.Array]:
    """-> (pi_out [B, num_outputs], value [B])."""
    x = _flatten_obs(obs)
    i = 0
    while f"trunk{i}" in params:
        p = params[f"trunk{i}"]
        x = jnp.tanh(x @ p["w"] + p["b"])
        i += 1
    pi = x @ params["pi"]["w"] + params["pi"]["b"]
    v = (x @ params["vf"]["w"] + params["vf"]["b"])[:, 0]
    return pi, v


# -- distributions --------------------------------------------------------

class Categorical:
    """Discrete action head over logits."""

    @staticmethod
    def sample(rng, logits):
        return jax.random.categorical(rng, logits, axis=-1)

    @staticmethod
    def logp(logits, actions):
        return jnp.take_along_axis(
            jax.nn.log_softmax(logits), actions[:, None].astype(jnp.int32),
            axis=-1)[:, 0]

    @staticmethod
    def entropy(logits):
        logp = jax.nn.log_softmax(logits)
        return -jnp.sum(jnp.exp(logp) * logp, axis=-1)


class DiagGaussian:
    """Continuous action head: first half means, second half log-stds."""

    @staticmethod
    def split(out):
        d = out.shape[-1] // 2
        return out[..., :d], jnp.clip(out[..., d:], -5.0, 2.0)

    @staticmethod
    def sample(rng, out):
        mean, log_std = DiagGaussian.split(out)
        return mean + jnp.exp(log_std) * jax.random.normal(rng, mean.shape)

    @staticmethod
    def logp(out, actions):
        mean, log_std = DiagGaussian.split(out)
        var = jnp.exp(2 * log_std)
        ll = -0.5 * ((actions - mean) ** 2 / var
                     + 2 * log_std + jnp.log(2 * jnp.pi))
        return jnp.sum(ll, axis=-1)

    @staticmethod
    def entropy(out):
        _, log_std = DiagGaussian.split(out)
        return jnp.sum(log_std + 0.5 * jnp.log(2 * jnp.pi * jnp.e), axis=-1)


# -- GAE ------------------------------------------------------------------

def compute_gae(rewards: np.ndarray, values: np.ndarray, dones: np.ndarray,
                last_values: np.ndarray, gamma: float, lam: float
                ) -> Tuple[np.ndarray, np.ndarray]:
    """Generalized advantage estimation over [T, N] rollout arrays.

    ``dones`` cuts bootstrapping at episode ends; ``last_values`` bootstraps
    the final step.  Host-side numpy (T is small; the learner is the TPU
    program, not this scan).  Reference analog:
    rllib/evaluation/postprocessing.py compute_gae_for_sample_batch.
    """
    T = rewards.shape[0]
    adv = np.zeros_like(rewards)
    lastgaelam = np.zeros_like(last_values)
    nextvalues = last_values
    for t in reversed(range(T)):
        nonterminal = 1.0 - dones[t].astype(rewards.dtype)
        delta = rewards[t] + gamma * nextvalues * nonterminal - values[t]
        lastgaelam = delta + gamma * lam * nonterminal * lastgaelam
        adv[t] = lastgaelam
        nextvalues = values[t]
    return adv, adv + values


# -- Policy ---------------------------------------------------------------

class Policy:
    """Contract the rollout worker and learner drive."""

    def compute_actions(self, obs: np.ndarray) -> Dict[str, np.ndarray]:
        raise NotImplementedError

    def get_weights(self):
        raise NotImplementedError

    def set_weights(self, weights):
        raise NotImplementedError


class PPOPolicy(Policy):
    """Actor-critic PPO policy over a jax pytree.

    The minibatch-SGD update is one jitted program (``_update``): epochs x
    minibatches scanned with lax.scan, clipped-surrogate + value + entropy
    loss.  On a multi-device mesh the caller shards the train batch along
    the leading axis; grads reduce via the mesh's compiled collectives.
    """

    def __init__(self, obs_dim: int, action_space, config: Dict[str, Any],
                 seed: int = 0):
        self.config = config
        self.discrete = action_space.kind == "discrete"
        self.dist = Categorical if self.discrete else DiagGaussian
        num_outputs = (action_space.n if self.discrete
                       else 2 * int(np.prod(action_space.shape)))
        self._rng = jax.random.PRNGKey(seed)
        self._rng, init_rng = jax.random.split(self._rng)
        self.params = ac_init(init_rng, obs_dim, num_outputs,
                              tuple(config.get("hiddens", (64, 64))))
        import optax
        self._tx = optax.chain(
            optax.clip_by_global_norm(config.get("grad_clip", 0.5)),
            optax.adam(config.get("lr", 3e-4)))
        self.opt_state = self._tx.init(self.params)

        dist = self.dist

        @jax.jit
        def _act(params, rng, obs):
            pi, v = ac_forward(params, obs)
            actions = dist.sample(rng, pi)
            return actions, dist.logp(pi, actions), v
        self._act = _act

        clip = config.get("clip_param", 0.2)
        vf_coeff = config.get("vf_loss_coeff", 0.5)
        ent_coeff = config.get("entropy_coeff", 0.01)
        vf_clip = config.get("vf_clip_param", 10.0)

        def _loss(params, mb):
            pi, v = ac_forward(params, mb[OBS])
            logp = dist.logp(pi, mb[ACTIONS])
            ratio = jnp.exp(logp - mb[ACTION_LOGP])
            adv = mb[ADVANTAGES]
            surr = jnp.minimum(
                ratio * adv,
                jnp.clip(ratio, 1 - clip, 1 + clip) * adv)
            # Pessimistic vf clip: MAX of unclipped / clipped squared error
            # (min would zero the gradient exactly when v drifts furthest).
            vf_err = jnp.maximum((v - mb[VALUE_TARGETS]) ** 2,
                                 (mb[VF_PREDS]
                                  + jnp.clip(v - mb[VF_PREDS],
                                             -vf_clip, vf_clip)
                                  - mb[VALUE_TARGETS]) ** 2)
            entropy = dist.entropy(pi)
            total = (-jnp.mean(surr) + vf_coeff * jnp.mean(vf_err)
                     - ent_coeff * jnp.mean(entropy))
            stats = {"policy_loss": -jnp.mean(surr),
                     "vf_loss": jnp.mean(vf_err),
                     "entropy": jnp.mean(entropy),
                     "total_loss": total,
                     "approx_kl": jnp.mean(mb[ACTION_LOGP] - logp)}
            return total, stats

        num_epochs = config.get("num_sgd_iter", 4)
        mb_size = config.get("sgd_minibatch_size", 128)
        # Multi-device learner (reference: multi_gpu_learner_thread.py):
        # the SAME update program shard_maps over a ("dp",) mesh — each
        # device SGDs on its batch shard, grads pmean over the axis per
        # minibatch step, params stay replicated bit-identically.
        self._n_learn = int(config.get("num_learner_devices", 1) or 1)
        axis = "dp" if self._n_learn > 1 else None

        def _update(params, opt_state, rng, batch):
            n = batch[OBS].shape[0]   # LOCAL rows under shard_map
            # sgd_minibatch_size is GLOBAL: each device takes its 1/N
            # slice so step count and effective batch match dp=1.
            mb = min(max(1, mb_size // self._n_learn), n)
            num_mb = n // mb
            if axis is not None:
                # decorrelate shard-local shuffles across devices
                rng = jax.random.fold_in(rng, jax.lax.axis_index(axis))

            def epoch_body(carry, epoch_rng):
                params, opt_state = carry
                perm = jax.random.permutation(epoch_rng, n)
                shuffled = {k: v[perm] for k, v in batch.items()}
                mbs = {k: v[: num_mb * mb].reshape(
                           (num_mb, mb) + v.shape[1:])
                       for k, v in shuffled.items()}

                def mb_body(carry, mb):
                    params, opt_state = carry
                    (_, stats), grads = jax.value_and_grad(
                        _loss, has_aux=True)(params, mb)
                    if axis is not None:
                        grads = jax.lax.pmean(grads, axis)
                        stats = jax.lax.pmean(stats, axis)
                    updates, opt_state = self._tx.update(grads, opt_state)
                    params = optax.apply_updates(params, updates)
                    return (params, opt_state), stats

                (params, opt_state), stats = jax.lax.scan(
                    mb_body, (params, opt_state), mbs)
                return (params, opt_state), stats

            epoch_rngs = jax.random.split(rng, num_epochs)
            (params, opt_state), stats = jax.lax.scan(
                epoch_body, (params, opt_state), epoch_rngs)
            last_stats = jax.tree.map(lambda s: s[-1, -1], stats)
            return params, opt_state, last_stats

        if axis is not None:
            from ray_tpu.rllib.learner import learner_mesh, shard_update
            self._mesh = learner_mesh(self._n_learn)
            self._update = shard_update(_update, self._mesh)
        else:
            self._update = jax.jit(_update)

    # -- rollout side -----------------------------------------------------
    def compute_actions(self, obs: np.ndarray) -> Dict[str, np.ndarray]:
        self._rng, rng = jax.random.split(self._rng)
        actions, logp, v = self._act(self.params, rng,
                                     jnp.asarray(obs, jnp.float32))
        return {ACTIONS: np.asarray(actions), ACTION_LOGP: np.asarray(logp),
                VF_PREDS: np.asarray(v)}

    def compute_values(self, obs: np.ndarray) -> np.ndarray:
        _, v = ac_forward(self.params, jnp.asarray(obs, jnp.float32))
        return np.asarray(v)

    # -- learner side -----------------------------------------------------
    def learn_on_batch(self, batch: SampleBatch) -> Dict[str, float]:
        adv = np.asarray(batch[ADVANTAGES], np.float32)
        batch = dict(batch)
        batch[ADVANTAGES] = (adv - adv.mean()) / (adv.std() + 1e-8)
        if self._n_learn > 1:
            from ray_tpu.rllib.learner import trim_batch
            batch = trim_batch(batch, self._n_learn)
        device_batch = {
            k: jnp.asarray(np.asarray(v, np.float32 if k != ACTIONS
                                      else None))
            for k, v in batch.items()}
        self._rng, rng = jax.random.split(self._rng)
        self.params, self.opt_state, stats = self._update(
            self.params, self.opt_state, rng, device_batch)
        return {k: float(v) for k, v in stats.items()}

    def get_weights(self):
        return jax.tree.map(np.asarray, self.params)

    def set_weights(self, weights):
        self.params = jax.tree.map(jnp.asarray, weights)
