"""RolloutWorker: owns a vector env + policy copy, produces SampleBatches.

Design analog: reference ``rllib/evaluation/rollout_worker.py:165`` (env
loop, ``sample():875``) with postprocessing (GAE) applied worker-side as in
``rllib/evaluation/postprocessing.py``.  TPU-first shape: rollout workers
are host-CPU actors feeding a device learner (Podracer/Anakin split) — the
env batch steps vectorized in numpy, action selection is one jitted call
per step.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

import numpy as np

from ray_tpu.rllib.env import make_vector_env
from ray_tpu.rllib.policy import PPOPolicy, compute_gae
from ray_tpu.rllib.sample_batch import (
    ACTIONS, ACTION_LOGP, ADVANTAGES, DONES, NEXT_OBS, OBS, REWARDS,
    SampleBatch, VALUE_TARGETS, VF_PREDS)


def _resolve_policy_class(name: str):
    """Policy registry keyed by config['policy'] — resolved lazily so
    remote workers (fresh processes) don't need the algo module imported
    up front (reference: ModelCatalog/policy mapping by name)."""
    if name == "ppo":
        return PPOPolicy
    if name == "dqn":
        from ray_tpu.rllib.dqn import DQNPolicy
        return DQNPolicy
    if name == "impala":
        from ray_tpu.rllib.impala import ImpalaPolicy
        return ImpalaPolicy
    if name == "appo":
        from ray_tpu.rllib.appo import APPOPolicy
        return APPOPolicy
    if name == "sac":
        from ray_tpu.rllib.sac import SACPolicy
        return SACPolicy
    if name == "recurrent_ppo":
        from ray_tpu.rllib.recurrent import RecurrentPPOPolicy
        return RecurrentPPOPolicy
    if name == "attention_ppo":
        from ray_tpu.rllib.catalog import AttentionPPOPolicy
        return AttentionPPOPolicy
    if name == "bc":
        from ray_tpu.rllib.offline import BCPolicy
        return BCPolicy
    if name == "marwil":
        from ray_tpu.rllib.offline import MARWILPolicy
        return MARWILPolicy
    if name == "a2c":
        from ray_tpu.rllib.a2c import A2CPolicy
        return A2CPolicy
    if name == "td3":
        from ray_tpu.rllib.td3 import TD3Policy
        return TD3Policy
    if name == "ddpg":
        from ray_tpu.rllib.ddpg import DDPGPolicy
        return DDPGPolicy
    raise ValueError(f"unknown policy {name!r}")


class RolloutWorker:
    """One sampling unit: ``sample()`` returns a postprocessed SampleBatch
    of ``rollout_fragment_length * num_envs`` steps."""

    def __init__(self, config: Dict[str, Any], worker_index: int = 0):
        self.config = config
        self.worker_index = worker_index
        seed = config.get("seed", 0) * 1000 + worker_index
        self.env = make_vector_env(
            config["env"], config.get("num_envs_per_worker", 1), seed=seed,
            **config.get("env_config", {}))
        obs_dim = int(np.prod(self.env.observation_space.shape))
        # model={"use_lstm"/"use_attention": True} routes through the
        # catalog, like the reference's ModelCatalog wrapper selection.
        from ray_tpu.rllib.catalog import ModelCatalog
        self.policy = _resolve_policy_class(ModelCatalog.policy_for(config))(
            obs_dim, self.env.action_space, config, seed=seed)
        self._obs = self.env.vector_reset(seed=seed)
        n = self.env.num_envs
        self._episode_rewards = np.zeros((n,), np.float64)
        self._episode_lens = np.zeros((n,), np.int64)
        self._completed_rewards: List[float] = []
        self._completed_lens: List[int] = []

    def _record_step_metrics(self, reward: np.ndarray, done: np.ndarray):
        """Per-step episode bookkeeping shared by all sampling modes."""
        self._episode_rewards += reward
        self._episode_lens += 1
        if done.any():
            idx = np.nonzero(done)[0]
            self._completed_rewards.extend(
                self._episode_rewards[idx].tolist())
            self._completed_lens.extend(self._episode_lens[idx].tolist())
            self._episode_rewards[idx] = 0.0
            self._episode_lens[idx] = 0

    # -- sampling ---------------------------------------------------------
    def sample(self) -> SampleBatch:
        if getattr(self.policy, "replay_style", False):
            return self._sample_transitions()
        if getattr(self.policy, "sequence_style", False):
            return self._sample_sequences()
        if getattr(self.policy, "recurrent", False):
            return self._sample_recurrent()
        return self._sample_onpolicy()

    def _sample_recurrent(self) -> SampleBatch:
        """Time-major [T, n] fragments for LSTM policies: snapshots the
        fragment-start hidden state and records per-step reset masks so
        the learner replays episode boundaries inside its scan
        (reference: sequence handling in rllib sample collectors)."""
        from ray_tpu.rllib.recurrent import RESETS, STATE_IN
        T = self.config.get("rollout_fragment_length", 128)
        n = self.env.num_envs
        gamma = self.config.get("gamma", 0.99)
        lam = self.config.get("lambda", 0.95)
        self.policy._ensure_state(n)
        state_in = self.policy.state_snapshot()

        obs_buf = np.empty((T, n) + self._obs.shape[1:], np.float32)
        act_buf: Optional[np.ndarray] = None
        logp_buf = np.empty((T, n), np.float32)
        vf_buf = np.empty((T, n), np.float32)
        rew_buf = np.empty((T, n), np.float32)
        done_buf = np.empty((T, n), bool)
        resets = np.zeros((T, n), np.float32)
        prev_done = np.zeros((n,), bool)

        for t in range(T):
            resets[t] = prev_done     # env finished at t-1 -> zero state
            out = self.policy.compute_actions(self._obs)
            actions = out[ACTIONS]
            if act_buf is None:
                act_buf = np.empty((T,) + actions.shape, actions.dtype)
            obs_buf[t] = self._obs
            act_buf[t] = actions
            logp_buf[t] = out[ACTION_LOGP]
            vf_buf[t] = out[VF_PREDS]
            next_obs, reward, done, info = self.env.vector_step(actions)
            rew_buf[t] = reward
            done_buf[t] = done
            self.policy.notify_dones(done)
            prev_done = done
            self._record_step_metrics(reward, done)
            self._obs = next_obs

        last_values = self.policy.compute_values(self._obs)
        adv, targets = compute_gae(rew_buf, vf_buf, done_buf, last_values,
                                   gamma, lam)
        return SampleBatch({
            OBS: obs_buf, ACTIONS: act_buf, ACTION_LOGP: logp_buf,
            VF_PREDS: vf_buf, REWARDS: rew_buf, DONES: done_buf,
            ADVANTAGES: adv, VALUE_TARGETS: targets,
            STATE_IN: state_in, RESETS: resets})

    def _sample_sequences(self) -> SampleBatch:
        """Batch-major [n, T, ...] trajectory fragments with behavior logp
        and a bootstrap obs — the learner applies its own off-policy
        correction (V-trace for IMPALA; no worker-side GAE)."""
        T = self.config.get("rollout_fragment_length", 128)
        n = self.env.num_envs
        obs_buf = np.empty((T, n) + self._obs.shape[1:], np.float32)
        act_buf = None
        logp_buf = np.empty((T, n), np.float32)
        rew_buf = np.empty((T, n), np.float32)
        done_buf = np.empty((T, n), bool)
        for t in range(T):
            out = self.policy.compute_actions(self._obs)
            actions = out[ACTIONS]
            if act_buf is None:
                act_buf = np.empty((T,) + actions.shape, actions.dtype)
            obs_buf[t] = self._obs
            act_buf[t] = actions
            logp_buf[t] = out[ACTION_LOGP]
            next_obs, reward, done, info = self.env.vector_step(actions)
            rew_buf[t] = reward
            # Truncations count as done for V-trace: the post-reset obs at
            # t+1 belongs to a NEW episode, so bootstrapping through it
            # would leak value across the boundary (standard IMPALA treats
            # every episode end as terminal; the small bias at time-limit
            # cuts beats cross-episode leakage).
            done_buf[t] = done
            self._record_step_metrics(reward, done)
            self._obs = next_obs

        def bt(a):  # time-major -> batch-major
            return np.swapaxes(a, 0, 1)
        return SampleBatch({
            OBS: bt(obs_buf), ACTIONS: bt(act_buf),
            ACTION_LOGP: bt(logp_buf), REWARDS: bt(rew_buf),
            DONES: bt(done_buf),
            "bootstrap_obs": self._obs.astype(np.float32)})

    def _sample_transitions(self) -> SampleBatch:
        """Raw (s, a, r, s', done) fragments for replay-based algorithms
        (DQN family); no GAE postprocessing."""
        T = self.config.get("rollout_fragment_length", 128)
        n = self.env.num_envs
        obs_buf = np.empty((T, n) + self._obs.shape[1:], np.float32)
        next_buf = np.empty_like(obs_buf)
        act_buf = None
        rew_buf = np.empty((T, n), np.float32)
        done_buf = np.empty((T, n), bool)
        for t in range(T):
            out = self.policy.compute_actions(self._obs)
            actions = out[ACTIONS]
            if act_buf is None:
                act_buf = np.empty((T,) + actions.shape, actions.dtype)
            obs_buf[t] = self._obs
            act_buf[t] = actions
            next_obs, reward, done, info = self.env.vector_step(actions)
            # Terminal next-obs: envs auto-reset, so `next_obs` is the new
            # episode's first obs; the true terminal obs rides in info.
            step_next = next_obs
            term = info.get("terminal_obs")
            if term is not None and done.any():
                mask = done.reshape((n,) + (1,) * (next_obs.ndim - 1))
                step_next = np.where(mask, term, next_obs)
            next_buf[t] = step_next
            # Truncations bootstrap: treat truncated as NOT done for the
            # Bellman target (value continues past the horizon).
            truncated = info.get("truncated")
            eff_done = done if truncated is None else (done & ~truncated)
            rew_buf[t] = reward
            done_buf[t] = eff_done
            self._record_step_metrics(reward, done)
            self._obs = next_obs

        def flat(a):
            return a.reshape((T * n,) + a.shape[2:])
        return SampleBatch({
            OBS: flat(obs_buf), ACTIONS: flat(act_buf),
            REWARDS: flat(rew_buf), DONES: flat(done_buf),
            NEXT_OBS: flat(next_buf)})

    def _sample_onpolicy(self) -> SampleBatch:
        T = self.config.get("rollout_fragment_length", 128)
        n = self.env.num_envs
        gamma = self.config.get("gamma", 0.99)
        lam = self.config.get("lambda", 0.95)

        obs_buf = np.empty((T, n) + self._obs.shape[1:], np.float32)
        act_buf: Optional[np.ndarray] = None
        logp_buf = np.empty((T, n), np.float32)
        vf_buf = np.empty((T, n), np.float32)
        rew_buf = np.empty((T, n), np.float32)
        done_buf = np.empty((T, n), bool)
        # value bootstrap for envs truncated (not terminated) at step t
        trunc_bootstrap = np.zeros((T, n), np.float32)

        for t in range(T):
            out = self.policy.compute_actions(self._obs)
            actions = out[ACTIONS]
            if act_buf is None:
                act_buf = np.empty((T,) + actions.shape, actions.dtype)
            obs_buf[t] = self._obs
            act_buf[t] = actions
            logp_buf[t] = out[ACTION_LOGP]
            vf_buf[t] = out[VF_PREDS]
            next_obs, reward, done, info = self.env.vector_step(actions)
            rew_buf[t] = reward
            done_buf[t] = done
            # Truncated episodes still have value beyond the horizon:
            # bootstrap their reward with V(terminal_obs)
            # (reference postprocessing.py does the same for TimeLimit).
            truncated = info.get("truncated")
            if truncated is not None and truncated.any():
                term_v = self.policy.compute_values(info["terminal_obs"])
                trunc_bootstrap[t] = np.where(truncated, term_v, 0.0)
            self._record_step_metrics(reward, done)
            self._obs = next_obs

        rew_buf = rew_buf + gamma * trunc_bootstrap
        last_values = self.policy.compute_values(self._obs)
        adv, targets = compute_gae(rew_buf, vf_buf, done_buf, last_values,
                                   gamma, lam)

        def flat(a):
            return a.reshape((T * n,) + a.shape[2:])
        return SampleBatch({
            OBS: flat(obs_buf), ACTIONS: flat(act_buf),
            ACTION_LOGP: flat(logp_buf), VF_PREDS: flat(vf_buf),
            REWARDS: flat(rew_buf), DONES: flat(done_buf),
            ADVANTAGES: flat(adv), VALUE_TARGETS: flat(targets)})

    # -- weights / metrics / health --------------------------------------
    def get_weights(self):
        return self.policy.get_weights()

    def set_weights(self, weights) -> None:
        self.policy.set_weights(weights)

    def get_metrics(self) -> Dict[str, Any]:
        """Drain completed-episode stats since the last call."""
        out = {"episode_rewards": self._completed_rewards,
               "episode_lens": self._completed_lens}
        self._completed_rewards = []
        self._completed_lens = []
        return out

    def ping(self) -> str:
        return "ok"

    def apply(self, fn, *args):
        """Run an arbitrary function on this worker (reference
        rollout_worker.apply) — used by tests and custom algorithms."""
        return fn(self, *args)
