"""SAC: soft actor-critic for continuous control.

Design analog: reference ``rllib/algorithms/sac/sac.py`` +
``sac_torch_policy.py`` (squashed-Gaussian actor, twin soft Q critics,
auto-tuned entropy temperature, polyak-averaged targets).  TPU-first: the
entire update — actor, both critics, alpha, and the polyak target move —
is ONE jitted program; action sampling is a second jitted function driven
by an explicit PRNG key.
"""

from __future__ import annotations

from typing import Any, Dict

import numpy as np

import jax
import jax.numpy as jnp

from ray_tpu.rllib.algorithm import Algorithm, AlgorithmConfig
from ray_tpu.rllib.policy import Policy
from ray_tpu.rllib.replay_buffer import ReplayBuffer
from ray_tpu.rllib.sample_batch import (ACTIONS, DONES, NEXT_OBS, OBS,
                                        REWARDS, SampleBatch)

_LOG_STD_MIN, _LOG_STD_MAX = -20.0, 2.0


class SACConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__(SAC)
        self._config.update({
            "policy": "sac",
            "hiddens": (64, 64),
            "actor_lr": 3e-4,
            "critic_lr": 3e-4,
            "alpha_lr": 3e-4,
            "initial_alpha": 0.1,
            "tau": 0.005,                    # polyak rate
            "train_batch_size": 256,
            "buffer_size": 100_000,
            "learning_starts": 1500,
            "num_train_iters": 8,
            "rollout_fragment_length": 8,
            "num_envs_per_worker": 8,
            "gamma": 0.99,
        })


def _mlp_init(key, sizes):
    params = []
    for i in range(len(sizes) - 1):
        key, k = jax.random.split(key)
        lim = 1.0 / np.sqrt(sizes[i])
        params.append({
            "w": jax.random.uniform(k, (sizes[i], sizes[i + 1]),
                                    minval=-lim, maxval=lim),
            "b": jnp.zeros((sizes[i + 1],))})
    return params


def _mlp(params, x):
    for i, layer in enumerate(params):
        x = x @ layer["w"] + layer["b"]
        if i < len(params) - 1:
            x = jnp.tanh(x)
    return x


def _actor_out(actor, obs, act_dim):
    out = _mlp(actor, obs)
    mu, log_std = out[:, :act_dim], out[:, act_dim:]
    log_std = jnp.clip(log_std, _LOG_STD_MIN, _LOG_STD_MAX)
    return mu, log_std


def _sample_action(actor, obs, key, act_dim, scale):
    """Squashed-Gaussian sample + its log prob (with tanh correction)."""
    mu, log_std = _actor_out(actor, obs, act_dim)
    std = jnp.exp(log_std)
    eps = jax.random.normal(key, mu.shape)
    pre = mu + std * eps
    a = jnp.tanh(pre)
    # log N(pre; mu, std) - sum log(1 - tanh^2) (change of variables);
    # the numerically-stable tanh-correction form from the SAC paper.
    logp = jnp.sum(
        -0.5 * (eps ** 2 + 2 * log_std + jnp.log(2 * jnp.pi))
        - 2.0 * (jnp.log(2.0) - pre - jax.nn.softplus(-2.0 * pre)),
        axis=-1)
    return a * scale, logp


def _q_forward(critic, obs, act):
    return _mlp(critic, jnp.concatenate([obs, act], axis=-1))[:, 0]


class SACPolicy(Policy):
    replay_style = True

    def __init__(self, obs_dim: int, action_space, config: Dict[str, Any],
                 seed: int = 0):
        if action_space.kind != "box":
            raise ValueError("SAC requires a continuous (box) action space")
        self.config = config
        act_dim = int(np.prod(action_space.shape)) or 1
        self.act_dim = act_dim
        self.act_scale = float(action_space.high)
        hid = tuple(config.get("hiddens", (64, 64)))
        key = jax.random.PRNGKey(seed)
        ka, k1, k2 = jax.random.split(key, 3)
        actor = _mlp_init(ka, (obs_dim,) + hid + (2 * act_dim,))
        q1 = _mlp_init(k1, (obs_dim + act_dim,) + hid + (1,))
        q2 = _mlp_init(k2, (obs_dim + act_dim,) + hid + (1,))
        log_alpha = jnp.log(jnp.asarray(config.get("initial_alpha", 0.1)))
        self.params = {"actor": actor, "q1": q1, "q2": q2,
                       "log_alpha": log_alpha}
        self.target = {"q1": jax.tree.map(jnp.copy, q1),
                       "q2": jax.tree.map(jnp.copy, q2)}

        import optax
        self._tx = {
            "actor": optax.adam(config.get("actor_lr", 3e-4)),
            "critic": optax.adam(config.get("critic_lr", 3e-4)),
            "alpha": optax.adam(config.get("alpha_lr", 3e-4)),
        }
        self.opt_state = {
            "actor": self._tx["actor"].init(actor),
            "critic": self._tx["critic"].init({"q1": q1, "q2": q2}),
            "alpha": self._tx["alpha"].init(log_alpha),
        }
        self._key = jax.random.PRNGKey(seed + 7)
        gamma = config.get("gamma", 0.99)
        tau = config.get("tau", 0.005)
        scale = self.act_scale
        target_entropy = -float(act_dim)

        @jax.jit
        def _act(actor, obs, key, deterministic):
            mu, _ = _actor_out(actor, obs, act_dim)
            a, _ = _sample_action(actor, obs, key, act_dim, scale)
            return jnp.where(deterministic, jnp.tanh(mu) * scale, a)

        self._act = _act

        @jax.jit
        def _update(params, target, opt_state, batch, key):
            k1, k2 = jax.random.split(key)
            alpha = jnp.exp(params["log_alpha"])
            # -- critic update (soft Bellman backup on twin mins)
            a_next, logp_next = _sample_action(
                params["actor"], batch[NEXT_OBS], k1, act_dim, scale)
            qn = jnp.minimum(
                _q_forward(target["q1"], batch[NEXT_OBS], a_next),
                _q_forward(target["q2"], batch[NEXT_OBS], a_next))
            backup = batch[REWARDS] + gamma * (
                1.0 - batch[DONES].astype(jnp.float32)) * (
                qn - alpha * logp_next)
            backup = jax.lax.stop_gradient(backup)

            def critic_loss(qs):
                l1 = jnp.mean((_q_forward(qs["q1"], batch[OBS],
                                          batch[ACTIONS]) - backup) ** 2)
                l2 = jnp.mean((_q_forward(qs["q2"], batch[OBS],
                                          batch[ACTIONS]) - backup) ** 2)
                return l1 + l2

            qs = {"q1": params["q1"], "q2": params["q2"]}
            closs, cgrads = jax.value_and_grad(critic_loss)(qs)
            cupd, opt_c = self._tx["critic"].update(
                cgrads, opt_state["critic"])
            import optax as _ox
            qs = _ox.apply_updates(qs, cupd)

            # -- actor update (against the UPDATED critics)
            def actor_loss(actor):
                a, logp = _sample_action(actor, batch[OBS], k2, act_dim,
                                         scale)
                q = jnp.minimum(_q_forward(qs["q1"], batch[OBS], a),
                                _q_forward(qs["q2"], batch[OBS], a))
                return jnp.mean(alpha * logp - q), logp

            (aloss, logp), agrads = jax.value_and_grad(
                actor_loss, has_aux=True)(params["actor"])
            aupd, opt_a = self._tx["actor"].update(
                agrads, opt_state["actor"])
            actor = _ox.apply_updates(params["actor"], aupd)

            # -- temperature update (match target entropy)
            def alpha_loss(log_alpha):
                return -jnp.mean(jnp.exp(log_alpha) * jax.lax.stop_gradient(
                    logp + target_entropy))

            lloss, lgrad = jax.value_and_grad(alpha_loss)(
                params["log_alpha"])
            lupd, opt_l = self._tx["alpha"].update(
                lgrad, opt_state["alpha"])
            log_alpha = _ox.apply_updates(params["log_alpha"], lupd)

            # -- polyak target move
            target = jax.tree.map(lambda t, o: (1 - tau) * t + tau * o,
                                  target, qs)
            params = {"actor": actor, "q1": qs["q1"], "q2": qs["q2"],
                      "log_alpha": log_alpha}
            opt_state = {"actor": opt_a, "critic": opt_c, "alpha": opt_l}
            stats = {"critic_loss": closs, "actor_loss": aloss,
                     "alpha": jnp.exp(log_alpha),
                     "entropy": -jnp.mean(logp)}
            return params, target, opt_state, stats

        self._update = _update

    # -- rollout side -----------------------------------------------------

    def compute_actions(self, obs: np.ndarray) -> Dict[str, np.ndarray]:
        self._key, k = jax.random.split(self._key)
        a = self._act(self.params["actor"],
                      jnp.asarray(obs, jnp.float32), k, False)
        return {ACTIONS: np.asarray(a, np.float32)}

    # -- learner side -----------------------------------------------------

    def learn_on_batch(self, batch: SampleBatch) -> Dict[str, Any]:
        device_batch = {
            OBS: jnp.asarray(np.asarray(batch[OBS], np.float32)),
            NEXT_OBS: jnp.asarray(np.asarray(batch[NEXT_OBS], np.float32)),
            ACTIONS: jnp.asarray(
                np.asarray(batch[ACTIONS], np.float32).reshape(
                    batch.count, self.act_dim)),
            REWARDS: jnp.asarray(np.asarray(batch[REWARDS], np.float32)),
            DONES: jnp.asarray(np.asarray(batch[DONES])),
        }
        self._key, k = jax.random.split(self._key)
        self.params, self.target, self.opt_state, stats = self._update(
            self.params, self.target, self.opt_state, device_batch, k)
        return {k2: float(v) for k2, v in stats.items()}

    def update_target(self):
        pass  # polyak-averaged inside every update

    def get_weights(self):
        return jax.tree.map(np.asarray, self.params)

    def set_weights(self, weights):
        self.params = jax.tree.map(jnp.asarray, weights)


class SAC(Algorithm):
    def setup(self, config: Dict[str, Any]) -> None:
        config = dict(config)
        config.setdefault("policy", "sac")
        super().setup(config)
        self.replay = ReplayBuffer(config.get("buffer_size", 100_000),
                                   seed=config.get("seed", 0))

    def training_step(self) -> Dict[str, Any]:
        c = self.config
        batch = self.workers.synchronous_sample()
        self._timesteps_total += batch.count
        self.replay.add(batch)
        stats: Dict[str, Any] = {}
        policy = self.workers.local_worker.policy
        if len(self.replay) >= c.get("learning_starts", 1500):
            for _ in range(c.get("num_train_iters", 8)):
                train = self.replay.sample(c.get("train_batch_size", 256))
                stats = policy.learn_on_batch(train)
            self.workers.sync_weights()
        # Same result schema as DQN/IMPALA/MultiAgentPPO: learner stats
        # nest under info.learner (flat copies kept for convenience).
        return {"info": {"learner": stats}, **stats}
