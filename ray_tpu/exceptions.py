"""User-facing exceptions.

Design analog: reference ``python/ray/exceptions.py`` (RayTaskError,
RayActorError, GetTimeoutError, ObjectLostError, ...).
"""

from __future__ import annotations


class RayTpuError(Exception):
    """Base class for framework errors."""


class TaskError(RayTpuError):
    """A task raised an exception during execution.

    The original exception is chained as ``cause``; the remote traceback is
    preserved as text (reference: RayTaskError pickles cause + traceback str).
    """

    def __init__(self, cause: BaseException, traceback_str: str = "",
                 task_repr: str = ""):
        self.cause = cause
        self.traceback_str = traceback_str
        self.task_repr = task_repr
        super().__init__(
            f"task {task_repr} failed: {type(cause).__name__}: {cause}\n"
            f"--- remote traceback ---\n{traceback_str}"
        )


class WorkerCrashedError(RayTpuError):
    """The worker process executing a task died unexpectedly."""


class ActorError(RayTpuError):
    pass


class ActorDiedError(ActorError):
    """A method was called on an actor that is dead and will not restart."""


class ActorUnavailableError(ActorError):
    """The actor is temporarily unreachable (e.g. restarting)."""


class GetTimeoutError(RayTpuError, TimeoutError):
    """ray_tpu.get() timed out."""


class ObjectLostError(RayTpuError):
    """An object's value was lost from every node and cannot be recovered."""


class ObjectStoreFullError(RayTpuError):
    pass


class RuntimeEnvSetupError(RayTpuError):
    pass


class SchedulingError(RayTpuError):
    """No node can satisfy the request's scheduling constraints
    (reference: TaskUnschedulableError)."""


class PlacementGroupUnavailableError(RayTpuError):
    pass


class TaskCancelledError(RayTpuError):
    """The task producing this object was cancelled via ray_tpu.cancel()
    (reference: ray.exceptions.TaskCancelledError)."""
