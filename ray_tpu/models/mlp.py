"""Minimal MLP classifier — the "MNIST milestone" model (SURVEY §7 step 4)
and the workhorse for fast train/tune tests (reference analogue: the torch
linear models in `train/tests/test_data_parallel_trainer.py`)."""

from __future__ import annotations

from typing import Dict, List

import jax
import jax.numpy as jnp


def mlp_init(rng: jax.Array, sizes: List[int]) -> Dict:
    keys = jax.random.split(rng, len(sizes) - 1)
    return {
        f"layer{i}": {
            "w": jax.random.normal(keys[i], (sizes[i], sizes[i + 1]),
                                   jnp.float32) / jnp.sqrt(sizes[i]),
            "b": jnp.zeros((sizes[i + 1],), jnp.float32),
        }
        for i in range(len(sizes) - 1)
    }


def mlp_forward(params: Dict, x: jax.Array) -> jax.Array:
    n = len(params)
    for i in range(n):
        p = params[f"layer{i}"]
        x = x @ p["w"] + p["b"]
        if i < n - 1:
            x = jax.nn.relu(x)
    return x


def mlp_loss(params: Dict, batch: Dict[str, jax.Array]) -> jax.Array:
    logits = mlp_forward(params, batch["x"])
    logp = jax.nn.log_softmax(logits)
    ll = jnp.take_along_axis(logp, batch["y"][:, None], axis=-1)[:, 0]
    return -jnp.mean(ll)
