"""ray_tpu.models: flagship model definitions (pure-functional JAX).

Models are (init_params, apply) pairs over plain pytrees with a parallel
pytree of logical-axis annotations, so any model shards under any
`ray_tpu.parallel.MeshSpec` without wrapper classes (contrast the reference,
which wraps torch modules in DDP/FSDP at `train/torch/train_loop_utils.py:70`).
"""

from ray_tpu.models.gpt import (  # noqa: F401
    GPTConfig,
    gpt_forward,
    gpt_init,
    gpt_loss,
    gpt_param_axes,
    make_train_step,
    make_train_state,
)
from ray_tpu.models.llama import (  # noqa: F401
    LlamaConfig,
    llama_forward,
    llama_init,
    llama_loss,
    llama_param_axes,
)
from ray_tpu.models.mlp import (  # noqa: F401
    mlp_forward,
    mlp_init,
    mlp_loss,
)
