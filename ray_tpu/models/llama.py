"""LLaMA-family decoder-only transformer, TPU-first.

Second flagship model family beside GPT-2 (``models/gpt.py``): RMSNorm
pre-norm, rotary position embeddings (no learned positions), SwiGLU MLP,
untied LM head, and grouped-query attention (kv_heads <= heads).  Same
TPU-first construction as GPT: bf16 compute / f32 params, layers stacked
on a scanned [L, ...] dim (single XLA while-loop; the dim doubles as the
pp shard axis), logical-axis annotations on every param so one definition
runs dp/fsdp/tp/sp via the ``ray_tpu.parallel`` rule tables, per-layer
``jax.checkpoint`` with the same policy menu as GPT, and the same
pluggable attention body (dense / Pallas flash).

The reference has no model zoo of its own (its flagship benchmarks wrap
torchvision/HF models); this family exists so Train/Tune/Serve have a
modern-architecture model to exercise, matching
``release/air_tests/air_benchmarks``' role.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Any, Callable, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from jax.ad_checkpoint import checkpoint_name as _checkpoint_name

from ray_tpu.models.gpt import token_loglikes
from ray_tpu.parallel.sharding import (LogicalAxisRules,
                                       with_logical_constraint)


@dataclasses.dataclass(frozen=True)
class LlamaConfig:
    vocab_size: int = 32000
    max_seq_len: int = 2048
    num_layers: int = 12
    num_heads: int = 12
    num_kv_heads: int = 4            # GQA: kv_heads < heads shares K/V
    embed_dim: int = 768
    mlp_dim: int = 2048              # SwiGLU hidden (~8/3 * embed, /128 pad)
    rope_theta: float = 10000.0
    rms_eps: float = 1e-5
    dtype: Any = jnp.bfloat16
    remat: bool = True
    remat_policy: str = "full"   # same menu as GPTConfig
    attention: str = "auto"          # "auto" | "dense" | "flash"
    ce_block: int = 0                # blocked-CE chunk (see GPTConfig)

    @property
    def head_dim(self) -> int:
        return self.embed_dim // self.num_heads

    @staticmethod
    def llama_125m() -> "LlamaConfig":
        return LlamaConfig()

    @staticmethod
    def tiny(vocab: int = 256, seq: int = 128) -> "LlamaConfig":
        return LlamaConfig(vocab_size=vocab, max_seq_len=seq, num_layers=2,
                           num_heads=4, num_kv_heads=2, embed_dim=64,
                           mlp_dim=192)


def llama_init(rng: jax.Array, cfg: LlamaConfig) -> Dict[str, Any]:
    """Params with per-layer weights stacked on a leading [L] dim."""
    if cfg.num_heads % cfg.num_kv_heads:
        raise ValueError(f"num_heads={cfg.num_heads} must be divisible by "
                         f"num_kv_heads={cfg.num_kv_heads}")
    k = jax.random.split(rng, 8)
    D, H, M, L, V = (cfg.embed_dim, cfg.head_dim, cfg.mlp_dim,
                     cfg.num_layers, cfg.vocab_size)
    nh, nkv = cfg.num_heads, cfg.num_kv_heads
    scale = 0.02
    rscale = scale / np.sqrt(2 * L)
    return {
        "wte": scale * jax.random.normal(k[0], (V, D), jnp.float32),
        "layers": {
            "ln1": {"scale": jnp.ones((L, D), jnp.float32)},
            "attn": {
                "wq": scale * jax.random.normal(k[1], (L, D, nh, H),
                                                jnp.float32),
                "wkv": scale * jax.random.normal(k[2], (L, D, 2, nkv, H),
                                                 jnp.float32),
                "wo": rscale * jax.random.normal(k[3], (L, nh, H, D),
                                                 jnp.float32),
            },
            "ln2": {"scale": jnp.ones((L, D), jnp.float32)},
            "mlp": {
                # SwiGLU: gate and up projections fused on a leading 2-dim.
                "wgu": scale * jax.random.normal(k[4], (L, 2, D, M),
                                                 jnp.float32),
                "wd": rscale * jax.random.normal(k[5], (L, M, D),
                                                 jnp.float32),
            },
        },
        "ln_f": {"scale": jnp.ones((D,), jnp.float32)},
        "lm_head": scale * jax.random.normal(k[6], (D, V), jnp.float32),
    }


def llama_param_axes(cfg: LlamaConfig) -> Dict[str, Any]:
    """Logical-axis annotations matching ``llama_init`` (same rule table
    as GPT: heads/mlp -> tp, embed -> fsdp, layers -> pp)."""
    return {
        "wte": (None, "embed"),
        "layers": {
            "ln1": {"scale": ("layers", "norm")},
            "attn": {
                "wq": ("layers", "embed", "heads", "kv"),
                "wkv": ("layers", "embed", None, "heads", "kv"),
                "wo": ("layers", "heads", "kv", "embed"),
            },
            "ln2": {"scale": ("layers", "norm")},
            "mlp": {
                "wgu": ("layers", None, "embed", "mlp"),
                "wd": ("layers", "mlp", "embed"),
            },
        },
        "ln_f": {"scale": ("norm",)},
        "lm_head": ("embed", None),
    }


def _rms_norm(x, scale, eps):
    x32 = x.astype(jnp.float32)
    y = x32 * jax.lax.rsqrt(jnp.mean(x32 * x32, axis=-1, keepdims=True)
                            + eps)
    return (y * scale).astype(x.dtype)


def rope_tables(S: int, H: int, theta: float) -> tuple:
    """(cos, sin) [S, H/2] f32 tables for rotary embeddings."""
    inv_freq = 1.0 / theta ** (np.arange(0, H, 2, dtype=np.float32) / H)
    t = np.arange(S, dtype=np.float32)
    freqs = np.outer(t, inv_freq)
    return jnp.asarray(np.cos(freqs)), jnp.asarray(np.sin(freqs))


def apply_rope(x, cos, sin):
    """Rotate [..., S, H] pairs (x split halves convention, like LLaMA's
    reshape-free implementations).  cos/sin broadcast over leading dims."""
    H = x.shape[-1]
    x1, x2 = x[..., : H // 2], x[..., H // 2:]
    return jnp.concatenate(
        [x1 * cos - x2 * sin, x2 * cos + x1 * sin],
        axis=-1).astype(x.dtype)


def _dense_causal_attention_gqa(q, k, v, rep: int):
    """Head-major grouped-query dense attention: q [B, N, S, H] with
    N = G*rep query heads sharing k/v [B, G, S, H].  Scores/output keep
    the (group, rep) split so K/V never replicate in memory."""
    import numpy as _np
    B, N, S, H = q.shape
    G = N // rep
    qg = q.reshape(B, G, rep, S, H)
    scores = jnp.einsum("bgrqh,bgkh->bgrqk", qg, k) / _np.sqrt(H)
    mask = jnp.tril(jnp.ones((S, S), bool))
    scores = jnp.where(mask[None, None, None],
                       scores.astype(jnp.float32), -1e30)
    probs = jax.nn.softmax(scores, axis=-1).astype(q.dtype)
    o = jnp.einsum("bgrqk,bgkh->bgrqh", probs, v)
    return o.reshape(B, N, S, H)


def _block(cfg: LlamaConfig, rules: Optional[LogicalAxisRules],
           attn_fn: Callable, cos, sin, x, p):
    lc = (lambda a, ax: with_logical_constraint(a, rules, ax)) if rules \
        else (lambda a, ax: a)
    dt = cfg.dtype
    rep = cfg.num_heads // cfg.num_kv_heads

    h = _rms_norm(x, p["ln1"]["scale"], cfg.rms_eps)
    # Head-major [B, N, S, H] throughout: native layout for the flash
    # kernels, picked in the projection epilogue for free.
    q = jnp.einsum("bsd,dnh->bnsh", h, p["attn"]["wq"].astype(dt))
    kv = jnp.einsum("bsd,dcnh->bcnsh", h, p["attn"]["wkv"].astype(dt))
    k, v = kv[:, 0], kv[:, 1]
    q = apply_rope(q, cos, sin)
    k = apply_rope(k, cos, sin)
    if rep > 1 and getattr(attn_fn, "_gqa_native", False):
        # Grouped dense path: fold the share-group dim into the einsum —
        # K/V stay at kv_heads width (no jnp.repeat materializing rep
        # copies of the KV tensors in HBM).
        o = _checkpoint_name(
            _dense_causal_attention_gqa(q, k, v, rep), "attn_out")
    else:
        if rep > 1:   # flash kernel expects equal head counts
            k = jnp.repeat(k, rep, axis=1)
            v = jnp.repeat(v, rep, axis=1)
        q = lc(q, ("batch", "heads", "seq", "kv"))
        k = lc(k, ("batch", "heads", "seq", "kv"))
        v = lc(v, ("batch", "heads", "seq", "kv"))
        o = _checkpoint_name(attn_fn(q, k, v), "attn_out")
    x = x + jnp.einsum("bnsh,nhd->bsd", o, p["attn"]["wo"].astype(dt))
    x = lc(x, ("batch", "seq", "embed"))

    h = _rms_norm(x, p["ln2"]["scale"], cfg.rms_eps)
    gu = jnp.einsum("bsd,cdm->cbsm", h, p["mlp"]["wgu"].astype(dt))
    h = jax.nn.silu(gu[0]) * gu[1]
    h = lc(h, ("batch", "seq", "mlp"))
    x = x + jnp.einsum("bsm,md->bsd", h, p["mlp"]["wd"].astype(dt))
    return lc(x, ("batch", "seq", "embed"))


def llama_hidden(params: Dict[str, Any], tokens: jax.Array,
                 cfg: LlamaConfig,
                 rules: Optional[LogicalAxisRules] = None,
                 mesh=None) -> jax.Array:
    """tokens [B, S] int32 -> final hidden [B, S, D] after rms_norm (compute
    dtype) — the trunk without the LM head (see gpt_hidden)."""
    dt = cfg.dtype
    B, S = tokens.shape
    attention = cfg.attention
    if attention == "auto":
        from ray_tpu.models.gpt import _auto_attention_variant
        attention = _auto_attention_variant(B, S, cfg)
    if attention == "flash":
        from ray_tpu.ops.flash_attention import flash_attention

        def attn_fn(q, k, v):
            return flash_attention(q, k, v, True, None, None, None, None,
                                   "bnsh")
    else:
        from ray_tpu.models.gpt import _dense_causal_attention_bnsh

        def attn_fn(q, k, v):
            return _dense_causal_attention_bnsh(q, k, v)
        attn_fn._gqa_native = True

    cos, sin = rope_tables(S, cfg.head_dim, cfg.rope_theta)
    x = params["wte"].astype(dt)[tokens]
    if rules is not None:
        x = with_logical_constraint(x, rules, ("batch", "seq", "embed"))

    block = functools.partial(_block, cfg, rules, attn_fn, cos, sin)
    if cfg.remat:
        cp = jax.checkpoint_policies
        policy = {
            "dots": cp.dots_with_no_batch_dims_saveable,
            "attn": cp.save_only_these_names("attn_out"),
            "attn_dots": cp.save_from_both_policies(
                cp.dots_with_no_batch_dims_saveable,
                cp.save_only_these_names("attn_out")),
        }.get(cfg.remat_policy)
        block = jax.checkpoint(block, policy=policy)

    x, _ = jax.lax.scan(lambda c, lp: (block(c, lp), None), x,
                        params["layers"])
    return _rms_norm(x, params["ln_f"]["scale"], cfg.rms_eps)


def llama_forward(params: Dict[str, Any], tokens: jax.Array,
                  cfg: LlamaConfig,
                  rules: Optional[LogicalAxisRules] = None,
                  mesh=None) -> jax.Array:
    """tokens [B, S] int32 -> logits [B, S, V] (compute dtype; the fused
    loss upcasts inside its reductions, same contract as gpt_forward)."""
    x = llama_hidden(params, tokens, cfg, rules, mesh)
    return jnp.einsum("bsd,dv->bsv", x, params["lm_head"].astype(cfg.dtype))


# ---------------------------------------------------------------------------
# Paged KV-cache decode (serving path) — LLaMA variant of gpt.py's
# init_paged_cache/gpt_prefill/gpt_decode_step.  GQA makes the pools
# NKV-head-major (kv_heads, not heads), rope is applied at each token's
# absolute position before the K is scattered (the pools hold POST-rope
# keys, so decode attention is a plain dot against the cache), and the
# math mirrors _block's grouped dense branch exactly — with
# cfg.dtype=float32 paged greedy decode reproduces llama_forward's
# token-by-token argmax, which the CPU equivalence tests assert.


def llama_init_paged_cache(cfg: LlamaConfig, num_pages: int,
                           page_size: int, dtype: Any = None):
    """Zeroed per-layer K/V page pools, [L, NKV, P, page, H].  Page 0 is
    the scratch sink for padded/inactive writes — allocators must never
    hand it out."""
    dt = dtype or cfg.dtype
    shape = (cfg.num_layers, cfg.num_kv_heads, num_pages, page_size,
             cfg.head_dim)
    return jnp.zeros(shape, dt), jnp.zeros(shape, dt)


def llama_prefill(params: Dict[str, Any], cfg: LlamaConfig,
                  tokens: jax.Array, length: jax.Array,
                  k_pages: jax.Array, v_pages: jax.Array,
                  page_table: jax.Array):
    """Prefill ONE padded sequence (see gpt_prefill): dense trunk,
    per-layer post-rope K/V scattered into the sequence's pages, f32
    next-token logits at position length-1.  ``tokens`` [1, S] with S a
    multiple of the page size; ``page_table`` [1, maxp];
    ``k_pages``/``v_pages`` [L, NKV, P, page, H]."""
    from ray_tpu.ops.paged_attention import prefill_kv
    dt = cfg.dtype
    rep = cfg.num_heads // cfg.num_kv_heads
    S = tokens.shape[1]
    cos, sin = rope_tables(S, cfg.head_dim, cfg.rope_theta)
    x = params["wte"].astype(dt)[tokens]

    def body(x, inp):
        p, kp, vp = inp
        h = _rms_norm(x, p["ln1"]["scale"], cfg.rms_eps)
        q = jnp.einsum("bsd,dnh->bnsh", h, p["attn"]["wq"].astype(dt))
        kv = jnp.einsum("bsd,dcnh->bcnsh", h, p["attn"]["wkv"].astype(dt))
        k, v = kv[:, 0], kv[:, 1]                        # [B, NKV, S, H]
        q = apply_rope(q, cos, sin)
        k = apply_rope(k, cos, sin)
        kp, vp = prefill_kv(kp, vp, k[0], v[0], length, page_table[0])
        o = _dense_causal_attention_gqa(q, k, v, rep)
        x = x + jnp.einsum("bnsh,nhd->bsd", o, p["attn"]["wo"].astype(dt))
        h = _rms_norm(x, p["ln2"]["scale"], cfg.rms_eps)
        gu = jnp.einsum("bsd,cdm->cbsm", h, p["mlp"]["wgu"].astype(dt))
        h = jax.nn.silu(gu[0]) * gu[1]
        return x + jnp.einsum("bsm,md->bsd", h,
                              p["mlp"]["wd"].astype(dt)), (kp, vp)

    x, (k_pages, v_pages) = jax.lax.scan(
        body, x, (params["layers"], k_pages, v_pages))
    x = _rms_norm(x, params["ln_f"]["scale"], cfg.rms_eps)
    last = x[0, length - 1]                              # [D]
    logits = jnp.einsum("d,dv->v", last,
                        params["lm_head"].astype(dt)).astype(jnp.float32)
    return logits[None], k_pages, v_pages


def llama_decode_step(params: Dict[str, Any], cfg: LlamaConfig,
                      token: jax.Array, pos: jax.Array,
                      k_pages: jax.Array, v_pages: jax.Array,
                      page_table: jax.Array):
    """One decode step for a BATCH of sequences (see gpt_decode_step).
    ``token``/``pos`` [B]; rope rotates q and the new K at each
    sequence's absolute position; the paged attention's GQA grouping
    keeps K/V at kv_heads width.  Inactive slots (pos 0, all-zero
    page-table row) harmlessly churn scratch page 0."""
    from ray_tpu.ops.paged_attention import append_kv, paged_attention
    dt = cfg.dtype
    cos_t, sin_t = rope_tables(cfg.max_seq_len, cfg.head_dim,
                               cfg.rope_theta)
    cos, sin = cos_t[pos][:, None], sin_t[pos][:, None]  # [B, 1, H/2]
    x = params["wte"].astype(dt)[token]

    def body(x, inp):
        p, kp, vp = inp
        h = _rms_norm(x, p["ln1"]["scale"], cfg.rms_eps)
        q = jnp.einsum("bd,dnh->bnh", h, p["attn"]["wq"].astype(dt))
        kv = jnp.einsum("bd,dcnh->bcnh", h, p["attn"]["wkv"].astype(dt))
        k_new, v_new = kv[:, 0], kv[:, 1]                # [B, NKV, H]
        q = apply_rope(q, cos, sin)
        k_new = apply_rope(k_new, cos, sin)
        kp, vp = append_kv(kp, vp, k_new, v_new, pos, page_table)
        o = paged_attention(q, kp, vp, pos + 1, page_table)
        x = x + jnp.einsum("bnh,nhd->bd", o, p["attn"]["wo"].astype(dt))
        h = _rms_norm(x, p["ln2"]["scale"], cfg.rms_eps)
        gu = jnp.einsum("bd,cdm->cbm", h, p["mlp"]["wgu"].astype(dt))
        h = jax.nn.silu(gu[0]) * gu[1]
        return x + jnp.einsum("bm,md->bd", h,
                              p["mlp"]["wd"].astype(dt)), (kp, vp)

    x, (k_pages, v_pages) = jax.lax.scan(
        body, x, (params["layers"], k_pages, v_pages))
    x = _rms_norm(x, params["ln_f"]["scale"], cfg.rms_eps)
    logits = jnp.einsum("bd,dv->bv", x,
                        params["lm_head"].astype(dt)).astype(jnp.float32)
    return logits, k_pages, v_pages


def llama_loss(params, batch: Dict[str, jax.Array], cfg: LlamaConfig,
               rules: Optional[LogicalAxisRules] = None,
               mesh=None) -> jax.Array:
    """Next-token CE over {"tokens": [B, S+1]} — shares the fused
    ``token_loglikes`` core (and the blocked-CE head via ``cfg.ce_block``)
    with GPT."""
    toks = batch["tokens"]
    if cfg.ce_block:
        from ray_tpu.models.gpt import blocked_ce_loglike_sum
        x = llama_hidden(params, toks[:, :-1], cfg, rules, mesh)
        return -blocked_ce_loglike_sum(
            x, params["lm_head"].astype(cfg.dtype), toks[:, 1:],
            cfg.ce_block, "dv") / toks[:, 1:].size
    logits = llama_forward(params, toks[:, :-1], cfg, rules, mesh)
    return -jnp.mean(token_loglikes(logits, toks[:, 1:]))


def make_train_step(cfg: LlamaConfig, tx,
                    rules: Optional[LogicalAxisRules] = None,
                    mesh=None, donate: bool = True):
    """Jitted (params, opt_state, batch) -> (params, opt_state, metrics);
    delegates to the GPT train-step plumbing with this family's loss."""
    from ray_tpu.models import gpt as _gpt
    return _gpt.make_train_step(
        cfg, tx, rules, mesh, donate=donate,
        loss_fn=lambda p, b: llama_loss(p, b, cfg, rules, mesh))
