"""GPT-2-class decoder-only transformer, TPU-first.

The reference's north-star training benchmark is GPT-2 DDP under Ray Train
(`release/air_tests/air_benchmarks/`); this is the equivalent flagship model,
but designed for the MXU rather than ported: bf16 compute / f32 params & o
ptimizer state, layers stacked into one scanned [L, ...] pytree (single XLA
while-loop, constant compile time in depth, and the layer dim doubles as the
pipeline-parallel shard axis), logical-axis annotations on every param so the
same definition runs dp/fsdp/tp/pp/sp via `ray_tpu.parallel` rule tables,
`jax.checkpoint` rematerialization per layer, and a pluggable attention body
(dense causal or ring attention from `ray_tpu.ops`).
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ray_tpu.parallel.sharding import LogicalAxisRules, with_logical_constraint
from ray_tpu.util import jax_compat

jax_compat.install()


@dataclasses.dataclass(frozen=True)
class GPTConfig:
    vocab_size: int = 50304          # GPT-2 padded to a multiple of 128
    max_seq_len: int = 1024
    num_layers: int = 12
    num_heads: int = 12
    embed_dim: int = 768
    mlp_ratio: int = 4
    dtype: Any = jnp.bfloat16        # compute dtype (params stay f32)
    remat: bool = True
    # "full" recomputes the whole block in bwd (min memory); "dots" saves
    # matmul outputs and recomputes only elementwise ops; "attn" saves
    # only attention outputs (never re-runs the flash kernel in bwd);
    # "attn_dots" saves both (fastest when it fits HBM).
    remat_policy: str = "full"   # "full" | "dots" | "attn" | "attn_dots"
    # "auto" picks flash at S>=1024 (the measured v5e crossover), dense
    # below; explicit values pin the implementation.
    attention: str = "auto"  # "auto"|"dense"|"flash"|"ring" (ring: sp>1)
    # Sequence-block size for the blocked cross-entropy head (0 = apply the
    # head over the full sequence).  With a block, head matmul + CE run per
    # chunk under jax.checkpoint, so no [B, S, V] logits tensor is ever
    # live — peak head memory drops V/block-fold for one extra head-matmul
    # recompute in backward.
    ce_block: int = 0
    # MoE (0 = dense FFN).  Experts shard over the ep mesh axis; routing is
    # GShard/Switch-style capacity-bounded dispatch (ray_tpu/ops/moe.py).
    num_experts: int = 0
    expert_top_k: int = 2
    capacity_factor: float = 1.25
    moe_aux_coef: float = 0.01       # load-balance loss weight

    @property
    def head_dim(self) -> int:
        return self.embed_dim // self.num_heads

    @property
    def mlp_dim(self) -> int:
        return self.mlp_ratio * self.embed_dim

    @staticmethod
    def gpt2_small() -> "GPTConfig":
        return GPTConfig()

    @staticmethod
    def tiny(vocab: int = 256, seq: int = 128) -> "GPTConfig":
        return GPTConfig(vocab_size=vocab, max_seq_len=seq, num_layers=2,
                         num_heads=4, embed_dim=64)


def gpt_init(rng: jax.Array, cfg: GPTConfig) -> Dict[str, Any]:
    """Initialize params. Per-layer weights are stacked on a leading [L] dim."""
    k = jax.random.split(rng, 8)
    D, H, M, L, V = (cfg.embed_dim, cfg.head_dim, cfg.mlp_dim,
                     cfg.num_layers, cfg.vocab_size)
    nh = cfg.num_heads
    scale = 0.02
    # residual-branch projections get the GPT-2 depth-scaled init
    rscale = scale / np.sqrt(2 * L)

    def norm(shape):
        return {"scale": jnp.ones(shape, jnp.float32),
                "bias": jnp.zeros(shape, jnp.float32)}

    if cfg.num_experts:
        E = cfg.num_experts
        ek = jax.random.split(k[6], 3)
        mlp = {
            "router": scale * jax.random.normal(ek[0], (L, D, E),
                                                jnp.float32),
            "wi": scale * jax.random.normal(ek[1], (L, E, D, M), jnp.float32),
            "bi": jnp.zeros((L, E, M), jnp.float32),
            "wo": rscale * jax.random.normal(ek[2], (L, E, M, D),
                                             jnp.float32),
            "bo": jnp.zeros((L, E, D), jnp.float32),
        }
    else:
        mlp = {
            "wi": scale * jax.random.normal(k[4], (L, D, M), jnp.float32),
            "bi": jnp.zeros((L, M), jnp.float32),
            "wo": rscale * jax.random.normal(k[5], (L, M, D), jnp.float32),
            "bo": jnp.zeros((L, D), jnp.float32),
        }

    return {
        "wte": scale * jax.random.normal(k[0], (V, D), jnp.float32),
        "wpe": scale * jax.random.normal(k[1], (cfg.max_seq_len, D),
                                         jnp.float32),
        "layers": {
            "ln1": norm((L, D)),
            "attn": {
                "wqkv": scale * jax.random.normal(
                    k[2], (L, D, 3, nh, H), jnp.float32),
                "wo": rscale * jax.random.normal(
                    k[3], (L, nh, H, D), jnp.float32),
                "bo": jnp.zeros((L, D), jnp.float32),
            },
            "ln2": norm((L, D)),
            "mlp": mlp,
        },
        "ln_f": norm((D,)),
    }


def gpt_param_axes(cfg: GPTConfig) -> Dict[str, Any]:
    """Logical-axis annotation pytree matching `gpt_init`'s output."""
    if cfg.num_experts:
        # Router stays expert-replicated (every token scores every expert);
        # expert weights shard on the leading E dim -> ep mesh axis.
        mlp = {
            "router": ("layers", "embed", None),
            "wi": ("layers", "expert", "embed", "mlp"),
            "bi": ("layers", "expert", "mlp"),
            "wo": ("layers", "expert", "mlp", "embed"),
            "bo": ("layers", "expert", "embed"),
        }
    else:
        mlp = {
            "wi": ("layers", "embed", "mlp"),
            "bi": ("layers", "mlp"),
            "wo": ("layers", "mlp", "embed"),
            "bo": ("layers", "norm"),
        }
    return {
        # wte sharded on embed (not vocab): token lookup is a gather, and a
        # vocab-sharded gather forces SPMD full rematerialization; the tied
        # LM head contracts over embed so fsdp-sharding it is free (psum).
        "wte": (None, "embed"),
        "wpe": (None, "embed"),
        "layers": {
            "ln1": {"scale": ("layers", "norm"), "bias": ("layers", "norm")},
            "attn": {
                "wqkv": ("layers", "embed", None, "heads", "kv"),
                "wo": ("layers", "heads", "kv", "embed"),
                "bo": ("layers", "norm"),
            },
            "ln2": {"scale": ("layers", "norm"), "bias": ("layers", "norm")},
            "mlp": mlp,
        },
        "ln_f": {"scale": ("norm",), "bias": ("norm",)},
    }


from jax.ad_checkpoint import checkpoint_name as _checkpoint_name


def _layer_norm(x, scale, bias, eps=1e-5):
    x32 = x.astype(jnp.float32)
    mu = jnp.mean(x32, axis=-1, keepdims=True)
    var = jnp.var(x32, axis=-1, keepdims=True)
    y = (x32 - mu) * jax.lax.rsqrt(var + eps)
    return (y * scale + bias).astype(x.dtype)


def _flash_profitable(S: int) -> bool:
    """attention="auto" crossover: the Pallas flash kernels win from
    S>=1024 on v5e (20.9 vs 28.8 ms fwd+bwd at 1024; ~2x at 4096) while
    XLA dense wins below — short sequences can't amortize the grid/DMA
    overhead (VERDICT r3 weak #7: per-shape dispatch).  Mosaic also
    rejects sub-8 blocks, which very short or odd S would hit."""
    if S < 1024 or S % 128:
        return False
    try:    # flash only pays off on real TPU; CPU/interpret is dense's
        import jax as _jax
        return _jax.default_backend() not in ("cpu",)
    except Exception:
        return False


def _auto_attention_variant(B: int, S: int, cfg) -> str:
    """attention="auto" resolution: a measured crossover record from the
    autotune cache (ray_tpu.autotune) wins when one exists for this
    shape/backend; a cold cache inherits the static _flash_profitable
    heuristic unchanged (RT_AUTOTUNE_ON_MISS=inline tunes instead).
    Only flash/dense are selectable here — ring requires an explicit
    mesh topology commitment (cfg.attention="ring")."""
    try:
        from ray_tpu.autotune.dispatch import choose
        v, rec = choose(B, S, cfg.num_heads,
                        cfg.embed_dim // cfg.num_heads, cfg.dtype,
                        causal=True, allowed=("flash", "dense"))
        if rec is not None:
            return v
    except Exception:
        pass
    return "flash" if _flash_profitable(S) else "dense"


def _dense_causal_attention(q, k, v):
    """[B,S,N,H] bf16 attention with causal mask; softmax in f32."""
    S = q.shape[1]
    scores = jnp.einsum("bqnh,bknh->bnqk", q, k) / np.sqrt(q.shape[-1])
    mask = jnp.tril(jnp.ones((S, S), bool))
    scores = jnp.where(mask[None, None], scores.astype(jnp.float32), -1e30)
    probs = jax.nn.softmax(scores, axis=-1).astype(q.dtype)
    return jnp.einsum("bnqk,bknh->bqnh", probs, v)


def _dense_causal_attention_bnsh(q, k, v):
    """[B,N,S,H] (head-major) dense attention; same math, no relayouts."""
    S = q.shape[2]
    scores = jnp.einsum("bnqh,bnkh->bnqk", q, k) / np.sqrt(q.shape[-1])
    mask = jnp.tril(jnp.ones((S, S), bool))
    scores = jnp.where(mask[None, None], scores.astype(jnp.float32), -1e30)
    probs = jax.nn.softmax(scores, axis=-1).astype(q.dtype)
    return jnp.einsum("bnqk,bnkh->bnqh", probs, v)


_dense_causal_attention_bnsh._layout = "bnsh"


def _block(cfg: GPTConfig, rules: Optional[LogicalAxisRules],
           attn_fn: Callable, x, layer_params, moe_ep_axis=None):
    """One transformer block. `layer_params` has the [L] dim already sliced.

    Returns (x, aux) — aux is the MoE load-balance loss for this layer
    (0.0 for a dense FFN) so the scan over layers can accumulate it.
    ``moe_ep_axis`` switches the MoE to its shard_map expert-parallel mode
    (weights pre-sharded on the expert dim; see ops/moe.py).
    """
    lc = (lambda a, ax: with_logical_constraint(a, rules, ax)) if rules \
        else (lambda a, ax: a)
    p = layer_params
    dt = cfg.dtype

    h = _layer_norm(x, p["ln1"]["scale"], p["ln1"]["bias"])
    if getattr(attn_fn, "_layout", "bsnh") == "bnsh":
        # Head-major attention path: the qkv projection WRITES [B,N,S,H]
        # (layout picked in the matmul epilogue, nearly free) so the flash
        # kernels get their native view with zero standalone relayouts.
        qkv = jnp.einsum("bsd,dcnh->bcnsh", h, p["attn"]["wqkv"].astype(dt))
        q, k, v = qkv[:, 0], qkv[:, 1], qkv[:, 2]
        q = lc(q, ("batch", "heads", "seq", "kv"))
        k = lc(k, ("batch", "heads", "seq", "kv"))
        v = lc(v, ("batch", "heads", "seq", "kv"))
        o = _checkpoint_name(attn_fn(q, k, v), "attn_out")
        o = jnp.einsum("bnsh,nhd->bsd", o, p["attn"]["wo"].astype(dt))
    else:
        qkv = jnp.einsum("bsd,dcnh->bscnh", h, p["attn"]["wqkv"].astype(dt))
        q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
        q = lc(q, ("batch", "seq", "heads", "kv"))
        k = lc(k, ("batch", "seq", "heads", "kv"))
        v = lc(v, ("batch", "seq", "heads", "kv"))
        o = _checkpoint_name(attn_fn(q, k, v), "attn_out")
        o = jnp.einsum("bsnh,nhd->bsd", o, p["attn"]["wo"].astype(dt))
    x = x + o + p["attn"]["bo"].astype(dt)
    x = lc(x, ("batch", "seq", "embed"))

    h = _layer_norm(x, p["ln2"]["scale"], p["ln2"]["bias"])
    if cfg.num_experts:
        from ray_tpu.ops.moe import moe_mlp
        h, aux = moe_mlp(h, p["mlp"], top_k=cfg.expert_top_k,
                         capacity_factor=cfg.capacity_factor, lc=lc,
                         ep_axis=moe_ep_axis)
    else:
        aux = jnp.zeros((), jnp.float32)
        h = jnp.einsum("bsd,dm->bsm", h, p["mlp"]["wi"].astype(dt)) \
            + p["mlp"]["bi"].astype(dt)
        h = lc(h, ("batch", "seq", "mlp"))
        h = jax.nn.gelu(h)
        h = jnp.einsum("bsm,md->bsd", h, p["mlp"]["wo"].astype(dt)) \
            + p["mlp"]["bo"].astype(dt)
    x = x + h
    return lc(x, ("batch", "seq", "embed")), aux


def gpt_hidden(params: Dict[str, Any], tokens: jax.Array,
               cfg: GPTConfig,
               rules: Optional[LogicalAxisRules] = None,
               mesh=None) -> Tuple[jax.Array, jax.Array]:
    """tokens [B, S] int32 -> (final hidden [B, S, D] after ln_f in compute
    dtype, moe_aux_loss scalar) — the trunk without the LM head, so the
    blocked-CE loss can apply head+loss per sequence chunk.

    Layers run under one `lax.scan` over the stacked [L] params — XLA sees a
    single while-loop body (fast compiles, and the [L] dim shards over pp).
    With ``cfg.attention == "ring"`` and a mesh, attention runs as ring
    attention shard_mapped over the `sp` axis (KV rotating via ppermute).
    """
    dt = cfg.dtype
    B, S = tokens.shape
    attention = cfg.attention
    if attention == "auto":
        attention = _auto_attention_variant(B, S, cfg)
    if attention == "ring" and mesh is not None:
        from jax.sharding import PartitionSpec as P
        from ray_tpu.ops.ring_attention import ring_attention_sharded
        spec = P(("dp", "fsdp"), "sp", "tp", None)
        attn_fn = jax.shard_map(
            functools.partial(ring_attention_sharded, axis_name="sp"),
            mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
            check_vma=False)
    elif attention == "flash":
        from ray_tpu.ops.flash_attention import flash_attention

        def attn_fn(q, k, v):
            return flash_attention(q, k, v, True, None, None, None, None,
                                   "bnsh")
        attn_fn._layout = "bnsh"
    else:
        attn_fn = _dense_causal_attention_bnsh

    x = params["wte"].astype(dt)[tokens] \
        + params["wpe"].astype(dt)[:S][None]
    if rules is not None:
        x = with_logical_constraint(x, rules, ("batch", "seq", "embed"))

    block = functools.partial(_block, cfg, rules, attn_fn)
    if cfg.remat:
        cp = jax.checkpoint_policies
        if cfg.remat_policy == "dots":
            policy = cp.dots_with_no_batch_dims_saveable
        elif cfg.remat_policy == "attn":
            # Save the attention outputs (tagged via checkpoint_name in
            # _block): the backward pass recomputes the cheap projections
            # and MLP but never re-runs the attention kernel — the single
            # most expensive recompute under "full"/"dots" when attention
            # is the Pallas flash kernel.
            policy = cp.save_only_these_names("attn_out")
        elif cfg.remat_policy == "attn_dots":
            policy = cp.save_from_both_policies(
                cp.dots_with_no_batch_dims_saveable,
                cp.save_only_these_names("attn_out"))
        else:
            policy = None
        block = jax.checkpoint(block, policy=policy)

    def scan_body(carry, layer_params):
        return block(carry, layer_params)

    x, aux = jax.lax.scan(scan_body, x, params["layers"])
    x = _layer_norm(x, params["ln_f"]["scale"], params["ln_f"]["bias"])
    return x, jnp.sum(aux)


def gpt_forward_with_aux(params: Dict[str, Any], tokens: jax.Array,
                         cfg: GPTConfig,
                         rules: Optional[LogicalAxisRules] = None,
                         mesh=None,
                         keep_dtype: bool = False
                         ) -> Tuple[jax.Array, jax.Array]:
    """tokens [B, S] int32 -> (logits [B, S, V] f32, moe_aux_loss scalar)."""
    x, aux = gpt_hidden(params, tokens, cfg, rules, mesh)
    logits = jnp.einsum("bsd,vd->bsv", x, params["wte"].astype(cfg.dtype))
    # keep_dtype avoids materializing [B,S,V] in f32 (6.6GB of HBM traffic
    # at bench scale) — the fused loss upcasts inside its reductions.
    if not keep_dtype:
        logits = logits.astype(jnp.float32)
    return logits, aux


def gpt_forward(params: Dict[str, Any], tokens: jax.Array, cfg: GPTConfig,
                rules: Optional[LogicalAxisRules] = None,
                mesh=None) -> jax.Array:
    """tokens [B, S] int32 -> logits [B, S, V] (f32); see
    `gpt_forward_with_aux` for the MoE aux-loss variant."""
    logits, _ = gpt_forward_with_aux(params, tokens, cfg, rules, mesh)
    return logits


# --------------------------------------------------------- paged decode
#
# Serving path (ray_tpu.serve.engine): decode reads K/V from the paged
# pools of ops/paged_attention.py instead of re-running the prefix, so
# one replica steps MANY sequences per forward at O(1) compute per
# token.  The math mirrors _block's head-major branch exactly — with
# cfg.dtype=float32 the paged greedy decode reproduces gpt_forward's
# token-by-token argmax bit-for-bit, which the CPU equivalence tests
# assert.


def init_paged_cache(cfg: GPTConfig, num_pages: int, page_size: int,
                     dtype: Any = None) -> Tuple[jax.Array, jax.Array]:
    """Zeroed per-layer K/V page pools, [L, N, P, page, H] (KV-head-major
    within each layer, matching ops.paged_attention's layouts).  Page 0
    is the scratch sink for padded/inactive writes — allocators must
    never hand it out."""
    dt = dtype or cfg.dtype
    shape = (cfg.num_layers, cfg.num_heads, num_pages, page_size,
             cfg.head_dim)
    return jnp.zeros(shape, dt), jnp.zeros(shape, dt)


def gpt_prefill(params: Dict[str, Any], cfg: GPTConfig, tokens: jax.Array,
                length: jax.Array, k_pages: jax.Array, v_pages: jax.Array,
                page_table: jax.Array
                ) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Prefill ONE padded sequence: run the trunk densely, scatter every
    layer's K/V into the sequence's pages, and return the next-token
    logits at the last real position.

    ``tokens`` [1, S] (S a multiple of the page size, S <= max_seq_len),
    ``length`` scalar int32 true length, ``page_table`` [1, maxp];
    ``k_pages``/``v_pages`` [L, N, P, page, H].  Padding positions write
    to scratch page 0 (see ops.paged_attention.prefill_kv) and, being
    causal, never influence positions < length.  Returns
    (logits [1, V] f32, k_pages, v_pages)."""
    from ray_tpu.ops.paged_attention import prefill_kv
    dt = cfg.dtype
    B, S = tokens.shape
    x = params["wte"].astype(dt)[tokens] \
        + params["wpe"].astype(dt)[:S][None]

    def body(x, inp):
        p, kp, vp = inp
        h = _layer_norm(x, p["ln1"]["scale"], p["ln1"]["bias"])
        qkv = jnp.einsum("bsd,dcnh->bcnsh", h, p["attn"]["wqkv"].astype(dt))
        q, k, v = qkv[:, 0], qkv[:, 1], qkv[:, 2]        # [B, N, S, H]
        kp, vp = prefill_kv(kp, vp, k[0], v[0], length, page_table[0])
        o = _dense_causal_attention_bnsh(q, k, v)
        o = jnp.einsum("bnsh,nhd->bsd", o, p["attn"]["wo"].astype(dt))
        x = x + o + p["attn"]["bo"].astype(dt)
        h = _layer_norm(x, p["ln2"]["scale"], p["ln2"]["bias"])
        h = jnp.einsum("bsd,dm->bsm", h, p["mlp"]["wi"].astype(dt)) \
            + p["mlp"]["bi"].astype(dt)
        h = jax.nn.gelu(h)
        h = jnp.einsum("bsm,md->bsd", h, p["mlp"]["wo"].astype(dt)) \
            + p["mlp"]["bo"].astype(dt)
        return x + h, (kp, vp)

    x, (k_pages, v_pages) = jax.lax.scan(
        body, x, (params["layers"], k_pages, v_pages))
    x = _layer_norm(x, params["ln_f"]["scale"], params["ln_f"]["bias"])
    last = x[0, length - 1]                              # [D]
    logits = jnp.einsum("d,vd->v", last,
                        params["wte"].astype(dt)).astype(jnp.float32)
    return logits[None], k_pages, v_pages


def gpt_decode_step(params: Dict[str, Any], cfg: GPTConfig,
                    token: jax.Array, pos: jax.Array, k_pages: jax.Array,
                    v_pages: jax.Array, page_table: jax.Array
                    ) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """One decode step for a BATCH of sequences against the paged cache.

    ``token`` [B] int32 current tokens, ``pos`` [B] their positions,
    ``page_table`` [B, maxp].  Writes each token's K/V at ``pos`` then
    attends positions [0, pos] through the page tables — sequences of
    different lengths batch freely, and inactive slots (pos 0, all-zero
    page-table row) harmlessly churn scratch page 0.  Returns
    (next-token logits [B, V] f32, k_pages, v_pages)."""
    from ray_tpu.ops.paged_attention import append_kv, paged_attention
    dt = cfg.dtype
    x = params["wte"].astype(dt)[token] + params["wpe"].astype(dt)[pos]

    def body(x, inp):
        p, kp, vp = inp
        h = _layer_norm(x, p["ln1"]["scale"], p["ln1"]["bias"])
        qkv = jnp.einsum("bd,dcnh->bcnh", h, p["attn"]["wqkv"].astype(dt))
        q, k_new, v_new = qkv[:, 0], qkv[:, 1], qkv[:, 2]   # [B, N, H]
        kp, vp = append_kv(kp, vp, k_new, v_new, pos, page_table)
        o = paged_attention(q, kp, vp, pos + 1, page_table)
        o = jnp.einsum("bnh,nhd->bd", o, p["attn"]["wo"].astype(dt))
        x = x + o + p["attn"]["bo"].astype(dt)
        h = _layer_norm(x, p["ln2"]["scale"], p["ln2"]["bias"])
        h = jnp.einsum("bd,dm->bm", h, p["mlp"]["wi"].astype(dt)) \
            + p["mlp"]["bi"].astype(dt)
        h = jax.nn.gelu(h)
        h = jnp.einsum("bm,md->bd", h, p["mlp"]["wo"].astype(dt)) \
            + p["mlp"]["bo"].astype(dt)
        return x + h, (kp, vp)

    x, (k_pages, v_pages) = jax.lax.scan(
        body, x, (params["layers"], k_pages, v_pages))
    x = _layer_norm(x, params["ln_f"]["scale"], params["ln_f"]["bias"])
    logits = jnp.einsum("bd,vd->bv", x,
                        params["wte"].astype(dt)).astype(jnp.float32)
    return logits, k_pages, v_pages


def gpt_loss(params, batch: Dict[str, jax.Array], cfg: GPTConfig,
             rules: Optional[LogicalAxisRules] = None, mesh=None,
             forward_fn: Optional[Callable] = None) -> jax.Array:
    """Next-token cross-entropy. batch: {"tokens": [B, S+1] int32}.

    `forward_fn(params, tokens) -> logits` overrides the forward pass (the
    pipelined variant in `ray_tpu.parallel.pipeline` plugs in here).  The
    blocked head (``cfg.ce_block``) applies only to the default forward —
    the pipelined path has its own per-microbatch drain that already bounds
    logits memory to one microbatch."""
    toks = batch["tokens"]
    targets = toks[:, 1:]
    aux = jnp.zeros((), jnp.float32)
    if forward_fn is not None:
        logits = forward_fn(params, toks[:, :-1])
    elif cfg.ce_block:
        x, aux = gpt_hidden(params, toks[:, :-1], cfg, rules, mesh)
        ll = blocked_ce_loglike_sum(x, params["wte"].astype(cfg.dtype),
                                    targets, cfg.ce_block, "vd")
        return -ll / targets.size + cfg.moe_aux_coef * aux
    else:
        logits, aux = gpt_forward_with_aux(params, toks[:, :-1], cfg, rules,
                                           mesh, keep_dtype=True)
    return -jnp.mean(token_loglikes(logits, targets)) \
        + cfg.moe_aux_coef * aux


def blocked_ce_loglike_sum(x: jax.Array, head: jax.Array,
                           targets: jax.Array, block: int,
                           head_layout: str = "vd") -> jax.Array:
    """Sum of next-token loglikes with head matmul + CE fused per sequence
    chunk: a `lax.scan` over S/block chunks whose body (chunk logits ->
    chunk loglike sum) runs under `jax.checkpoint`, so neither forward nor
    backward ever holds a [B, S, V] tensor — the live set is one
    [B, block, V] chunk.  Backward recomputes each chunk's logits (one
    extra head matmul, ~+8% head FLOPs) and accumulates d(head) across
    chunks via the scan-constant gradient path.

    Design analog: the reference materializes full logits and calls
    torch F.cross_entropy (python/ray/train examples); on TPU the fused
    blocked head converts ~6.6 GB of [B,S,V] HBM traffic into MXU-resident
    chunks.  ``head_layout``: "vd" ([V, D], tied GPT embedding) or "dv".
    """
    B, S, D = x.shape
    if S % block or S == block:
        # Non-dividing block: one full-sequence chunk under checkpoint
        # would cost the recompute with zero memory benefit — use the
        # plain fused loss instead.  That silently materializes the full
        # [B, S, V] logits the caller configured ce_block to avoid, so
        # say it loudly (this branch runs at trace time, once per shape)
        # — or refuse outright under RT_STRICT_CE_BLOCK=1.
        import os
        msg = (f"ce_block={block} does not evenly split sequence length "
               f"S={S} into multiple chunks; falling back to full "
               f"[B={B}, S={S}, V] logits — the blocked head's memory "
               f"win is LOST. Pick ce_block so that S % ce_block == 0 "
               f"and ce_block < S.")
        if os.environ.get("RT_STRICT_CE_BLOCK") == "1":
            raise ValueError(msg)
        import warnings
        warnings.warn(msg, RuntimeWarning, stacklevel=2)
        full_eq = "bsd,vd->bsv" if head_layout == "vd" else "bsd,dv->bsv"
        return jnp.sum(token_loglikes(jnp.einsum(full_eq, x, head),
                                      targets))
    nb = S // block
    eq = "bcd,vd->bcv" if head_layout == "vd" else "bcd,dv->bcv"

    @jax.checkpoint
    def chunk_ll(xc, tc):
        logits = jnp.einsum(eq, xc, head)
        return jnp.sum(token_loglikes(logits, tc))

    xb = jnp.moveaxis(x.reshape(B, nb, block, D), 1, 0)
    tb = jnp.moveaxis(targets.reshape(B, nb, block), 1, 0)
    total, _ = jax.lax.scan(
        lambda acc, args: (acc + chunk_ll(*args), None),
        jnp.zeros((), jnp.float32), (xb, tb))
    return total


def token_loglikes(logits, targets) -> jax.Array:
    """Fused cross-entropy core: ll_i = logit[target_i] - logsumexp_i.

    Written so XLA fuses the f32 upcast into the reductions and never
    materializes an f32 [..., V] tensor; shared by the standard and the
    pipelined (per-microbatch drain) loss paths.  Returns f32 [...]."""
    m = jax.lax.stop_gradient(jnp.max(logits, axis=-1, keepdims=True))
    z = (logits - m).astype(jnp.float32)
    lse = jnp.log(jnp.sum(jnp.exp(z), axis=-1)) + m[..., 0].astype(
        jnp.float32)
    tgt = jnp.take_along_axis(logits, targets[..., None], axis=-1)[..., 0]
    return tgt.astype(jnp.float32) - lse


# ---------------------------------------------------------------- train step

def make_train_state(rng, cfg: GPTConfig, learning_rate: float = 3e-4,
                     weight_decay: float = 0.1):
    """(params, opt_state, optimizer) with AdamW."""
    import optax
    params = gpt_init(rng, cfg)
    tx = optax.adamw(learning_rate, b1=0.9, b2=0.95,
                     weight_decay=weight_decay)
    return params, tx.init(params), tx


def make_train_step(cfg: GPTConfig, tx,
                    rules: Optional[LogicalAxisRules] = None,
                    mesh=None, donate: bool = True,
                    forward_fn: Optional[Callable] = None,
                    loss_fn: Optional[Callable] = None):
    """Returns jittable (params, opt_state, batch) -> (params, opt_state,
    metrics).  Under a Mesh + sharded inputs, XLA emits all collectives
    (gradient reduction across dp/fsdp, tp/sp activation collectives) — the
    TPU equivalent of the reference's DDP allreduce hook.

    ``loss_fn(params, batch) -> scalar`` overrides the whole loss (the
    pipelined trainer plugs its fused-epilogue loss in here), so the
    optimizer/metric plumbing lives in exactly one place."""
    if loss_fn is None:
        def loss_fn(params, batch):
            return gpt_loss(params, batch, cfg, rules, mesh, forward_fn)

    def step(params, opt_state, batch):
        loss, grads = jax.value_and_grad(loss_fn)(params, batch)
        updates, opt_state = tx.update(grads, opt_state, params)
        import optax
        params = optax.apply_updates(params, updates)
        gnorm = optax.global_norm(grads)
        return params, opt_state, {"loss": loss, "grad_norm": gnorm}

    return jax.jit(step, donate_argnums=(0, 1) if donate else ())
