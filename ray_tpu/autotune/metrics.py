"""Autotune observability counters.

Two sinks from one ``bump()``:

* a plain in-process dict (``stats()``) — the raylet folds it into its
  node-stats report, so raylet-side tuning (rare but possible) is visible
  per node, and tests can assert on it without a cluster;
* lazily-created ``ray_tpu.util.metrics`` Counters — worker processes
  (where tuning actually happens: benches, trainers, serve replicas)
  flush these to the GCS, which aggregates them across processes into
  ``/api/metrics`` as ``ray_tpu_autotune_*`` series.

Counters are created on first bump, not at import, so importing the
autotune subsystem never starts the metrics flusher thread in processes
that never tune.
"""

from __future__ import annotations

import threading
from typing import Dict

COUNTER_NAMES = ("autotune_cache_hits", "autotune_cache_misses",
                 "autotune_tune_ms")

_lock = threading.Lock()
_stats: Dict[str, float] = {k: 0.0 for k in COUNTER_NAMES}
_user_counters = None     # name -> util.metrics.Counter, created lazily


def _counters():
    global _user_counters
    if _user_counters is None:
        try:
            from ray_tpu.util.metrics import Counter
            _user_counters = {
                "autotune_cache_hits": Counter(
                    "autotune_cache_hits",
                    "kernel-autotune cache lookups that hit"),
                "autotune_cache_misses": Counter(
                    "autotune_cache_misses",
                    "kernel-autotune cache lookups that missed"),
                "autotune_tune_ms": Counter(
                    "autotune_tune_ms",
                    "wall-clock ms spent tuning kernels (cold-cache cost)"),
            }
        except Exception:
            _user_counters = {}
    return _user_counters


def bump(name: str, value: float = 1.0) -> None:
    with _lock:
        _stats[name] = _stats.get(name, 0.0) + value
    c = _counters().get(name)
    if c is not None:
        try:
            c.inc(value)
        except Exception:
            pass


def stats() -> Dict[str, float]:
    """Snapshot of this process's autotune counters (ints where whole)."""
    with _lock:
        return {k: (int(v) if float(v).is_integer() else round(v, 3))
                for k, v in _stats.items()}


def reset() -> None:
    """Test hook."""
    with _lock:
        for k in list(_stats):
            _stats[k] = 0.0
