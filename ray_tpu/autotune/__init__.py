"""Kernel autotune subsystem: block-config search, persistent cache,
and measured kernel-variant dispatch.

Three modules (see ARCHITECTURE.md "Kernel autotuning & dispatch"):

* ``cache``    — JSON-lines persistent cache, keyed by (op, backend
  fingerprint, canonical shape key); survives restarts, shared across
  processes.
* ``search``   — timing harness + pruned block sweeps per registered op
  (flash block_q/block_k, splash fwd/dkv/dq blocks), interpret-aware so
  the same code runs on CPU CI and for real on TPU.
* ``dispatch`` — ``attention(q, k, v, ...)``: picks flash / ring /
  dense / splash per shape from measured crossover records.

Importing this package must stay cheap and jax-free: the raylet reads
``metrics.stats()`` for node stats, and benches import the cache before
deciding whether to touch a TPU.  ``search`` and ``dispatch`` import jax
lazily inside their functions; they are NOT imported here — import them
explicitly (``from ray_tpu.autotune import dispatch``).
"""

from ray_tpu.autotune import metrics  # noqa: F401  (jax-free)
from ray_tpu.autotune.cache import (AutotuneCache, attention_key,  # noqa
                                    backend_fingerprint, cache_path,
                                    canon_dtype, get_cache, norm_batch)

__all__ = ["AutotuneCache", "attention_key", "backend_fingerprint",
           "cache_path", "canon_dtype", "get_cache", "norm_batch",
           "metrics"]
