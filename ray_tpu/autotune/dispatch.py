"""Measured kernel-variant dispatch for attention.

``attention(q, k, v, ...)`` picks flash vs ring vs dense (vs splash when
the shape and jax build admit it) per shape from MEASURED timings, not
heuristics: ``tune_attention`` times every applicable variant (each with
its own tuned config) and persists the winner as an ``attention_variant``
record in the autotune cache; ``attention`` consults that record — via a
process-local L1 memo so the cache is touched once per shape — and runs
the winning kernel.

On a cache miss the behavior is configurable (``RT_AUTOTUNE_ON_MISS``):

* ``default`` (the default): fall back to the static heuristic the
  models used before the subsystem existed (flash when profitable,
  dense otherwise) — zero added latency, the miss is counted so the
  operator sees the cold cache in /api/metrics;
* ``inline``: tune on first use, under a budget
  (``RT_AUTOTUNE_BUDGET_S``, default 30 s per shape), then persist —
  the second process to hit the shape reads the first one's answer;
* offline: run ``scripts/autotune_sweep.py`` once per fleet and ship
  the cache file.
"""

from __future__ import annotations

import os
import threading
from typing import Any, Dict, List, Optional, Tuple

from ray_tpu.autotune import metrics as _am
from ray_tpu.autotune.cache import (attention_key, backend_fingerprint,
                                    canon_dtype, get_cache)

VARIANT_OP = "attention_variant"

# Variant op-name in the cache, per selectable variant.
_VARIANT_OPS = {"flash": "flash_attention", "dense": "dense_attention",
                "ring": "ring_attention", "splash": "splash_attention"}

# L1 memo: (backend, key, allowed) -> chosen variant str or None (miss).
_MEMO: Dict[Tuple[str, str, tuple], Optional[str]] = {}
_memo_lock = threading.Lock()


def on_miss_mode() -> str:
    return os.environ.get("RT_AUTOTUNE_ON_MISS", "default").strip().lower()


def _budget_s() -> float:
    try:
        return float(os.environ.get("RT_AUTOTUNE_BUDGET_S", "30"))
    except ValueError:
        return 30.0


def clear_memo() -> None:
    """Test hook: drop the process-local variant memo."""
    with _memo_lock:
        _MEMO.clear()


# -------------------------------------------------------- applicability

def _flash_ok(S: int, interpret: bool) -> bool:
    from ray_tpu.autotune.search import valid_blocks
    if interpret:
        return S >= 2
    return bool(valid_blocks(S) or valid_blocks(S, (8, 16, 32, 64)))


def applicable_variants(kd: dict, interpret: bool,
                        mesh=None) -> List[str]:
    """Which variants can legally run at this shape/runtime.  Order is
    the tie-break preference (earlier wins on equal timings)."""
    from ray_tpu.autotune.search import splash_supported
    out = ["dense"]
    if _flash_ok(kd["S"], interpret):
        out.insert(0, "flash")
    if splash_supported(kd):
        out.insert(0, "splash")
    if mesh is not None and kd.get("causal", True) and _ring_ok(kd, mesh):
        out.append("ring")
    return out


def _ring_ok(kd: dict, mesh) -> bool:
    try:
        sp = dict(zip(mesh.axis_names, mesh.devices.shape)).get("sp", 1)
    except Exception:
        return False
    return sp > 1 and kd["S"] % sp == 0


# --------------------------------------------------------------- choice

def choose_variant_from_timings(timings: Dict[str, Optional[float]],
                                allowed: Optional[Tuple[str, ...]] = None
                                ) -> Optional[str]:
    """Pure crossover policy: cheapest measured variant wins; variants
    that failed to run (None/inf) never win; ``allowed`` filters.  Used
    directly by tests with synthetic timings."""
    best, best_ms = None, float("inf")
    for v, ms in timings.items():
        if allowed is not None and v not in allowed:
            continue
        if ms is None or ms != ms or ms == float("inf"):
            continue
        if ms < best_ms:
            best, best_ms = v, ms
    return best


def _heuristic_variant(S: int, allowed: Tuple[str, ...]) -> str:
    """The pre-autotune static policy (mirrors models' _flash_profitable):
    flash once the sequence is long and lane-aligned, else dense."""
    import jax
    if ("flash" in allowed and S >= 1024 and S % 128 == 0
            and jax.default_backend() != "cpu"):
        return "flash"
    return "dense" if "dense" in allowed else allowed[0]


def choose(B: int, S: int, N: int, H: int, dtype: Any, causal: bool = True,
           allowed: Optional[Tuple[str, ...]] = None, mesh=None,
           interpret: Optional[bool] = None) -> Tuple[str, Optional[dict]]:
    """Pick the attention variant for a shape.

    Returns (variant, variant_record_or_None).  Consults the L1 memo,
    then the persistent cache's ``attention_variant`` record, then the
    on-miss policy."""
    import jax
    interp = (jax.default_backend() != "tpu") if interpret is None \
        else interpret
    kd = {"B": B, "S": S, "N": N, "H": H,
          "dtype": canon_dtype(dtype), "causal": bool(causal)}
    avail = applicable_variants(kd, interp, mesh=mesh)
    if allowed is not None:
        avail = [v for v in avail if v in allowed]
    if not avail:
        return "dense", None
    allowed_t = tuple(avail)
    key = attention_key(B, S, N, H, dtype, causal)
    backend = backend_fingerprint()
    memo_key = (backend, key, allowed_t)
    with _memo_lock:
        hit = _MEMO.get(memo_key, _MEMO)       # sentinel: _MEMO itself
    cache = get_cache()
    if hit is not _MEMO:
        if hit is not None:
            return hit, cache.lookup(VARIANT_OP, key, count=False)
    else:
        rec = cache.lookup(VARIANT_OP, key)
        variant = None
        if rec is not None:
            v = (rec.get("config") or {}).get("variant")
            if v in allowed_t:
                variant = v
        if variant is None and on_miss_mode() == "inline":
            rec = tune_attention(B, S, N, H, dtype, causal,
                                 variants=allowed_t, mesh=mesh,
                                 interpret=interp,
                                 budget_s=_budget_s())
            if rec is not None:
                v = (rec.get("config") or {}).get("variant")
                if v in allowed_t:
                    variant = v
        with _memo_lock:
            _MEMO[memo_key] = variant
        if variant is not None:
            return variant, rec
    # Miss (or memoized miss): inherit the pre-subsystem heuristic.
    return _heuristic_variant(S, allowed_t), None


def auto_variant(B: int, S: int, N: int, H: int, dtype: Any,
                 causal: bool = True,
                 allowed: Tuple[str, ...] = ("flash", "dense"),
                 mesh=None) -> str:
    """Model-facing entry point for attention="auto": never raises,
    never tunes unless RT_AUTOTUNE_ON_MISS=inline, returns a variant
    name from ``allowed``."""
    try:
        v, _ = choose(B, S, N, H, dtype, causal, allowed=allowed,
                      mesh=mesh)
        return v if v in allowed else allowed[-1]
    except Exception:
        return allowed[-1]


# --------------------------------------------------------------- tuning

def tune_attention(B: int, S: int, N: int, H: int, dtype: Any,
                   causal: bool = True,
                   variants: Optional[Tuple[str, ...]] = None,
                   mesh=None, interpret: Optional[bool] = None,
                   budget_s: Optional[float] = None,
                   force: bool = False) -> Optional[dict]:
    """Time every applicable variant (tuning each variant's own config
    first) and persist the crossover winner as an ``attention_variant``
    record.  Returns the record, or None when nothing ran."""
    import time as _time

    from ray_tpu.autotune import search as _search
    import jax
    interp = (jax.default_backend() != "tpu") if interpret is None \
        else interpret
    key = attention_key(B, S, N, H, dtype, causal)
    kd = _search.parse_key(key)
    cache = get_cache()
    if not force:
        rec = cache.lookup(VARIANT_OP, key, count=False)
        if rec is not None:
            return rec
    avail = applicable_variants(kd, interp, mesh=mesh)
    if variants is not None:
        avail = [v for v in avail if v in variants]
    t0 = _time.perf_counter()
    timings: Dict[str, Optional[float]] = {}
    per_budget = None
    if budget_s is not None and avail:
        per_budget = budget_s / len(avail)
    context = {"mesh": mesh} if mesh is not None else None
    for v in avail:
        rec = _search.tune(_VARIANT_OPS[v], key, interpret=interp,
                           budget_s=per_budget, context=context,
                           force=force)
        timings[v] = rec.get("ms") if rec else None
    _am.bump("autotune_tune_ms", (_time.perf_counter() - t0) * 1e3)
    winner = choose_variant_from_timings(timings)
    if winner is None:
        return None
    return cache.put(VARIANT_OP, key, {"variant": winner},
                     timings[winner], meta={"timings": timings})


# ------------------------------------------------------------ execution

def make_splash_kernel(N: int, S: int, cfg: Optional[dict],
                       interpret: bool):
    """Build a causal splash-MHA callable over [N, S, H] (vmap it over
    batch; caller pre-scales q).  cfg carries the block knobs from the
    autotune sweep; None uses 128s (the minimum this jax build accepts)."""
    from jax.experimental.pallas.ops.tpu import splash_attention as spl
    cfg = cfg or {}
    fwd = int(cfg.get("block_q", 128))
    fkv = int(cfg.get("block_kv", fwd))
    bq = int(cfg.get("block_q_bwd", fwd))
    bkv = int(cfg.get("block_kv_bwd", fkv))
    sizes = spl.BlockSizes(
        block_q=fwd, block_kv=fkv, block_kv_compute=fkv,
        block_q_dkv=bq, block_kv_dkv=bkv, block_kv_dkv_compute=bkv,
        block_q_dq=bq, block_kv_dq=bkv)
    mask = spl.MultiHeadMask(
        [spl.CausalMask((S, S)) for _ in range(N)])
    return spl.make_splash_mha(mask, head_shards=1, q_seq_shards=1,
                               block_sizes=sizes, interpret=interpret)


def _run_variant(variant: str, q, k, v, causal: bool, sm_scale, interp:
                 bool, layout: str, mesh, config: Optional[dict]):
    import jax
    import jax.numpy as jnp
    if variant == "flash":
        from ray_tpu.ops.flash_attention import flash_attention
        cfg = config or {}
        return flash_attention(q, k, v, causal,
                               cfg.get("block_q"), cfg.get("block_k"),
                               sm_scale, interp, layout)
    if variant == "ring":
        from ray_tpu.ops.ring_attention import ring_attention
        if layout == "bnsh":
            q, k, v = (x.swapaxes(1, 2) for x in (q, k, v))
        o = ring_attention(q, k, v, mesh)
        return o.swapaxes(1, 2) if layout == "bnsh" else o
    if variant == "splash":
        if layout != "bnsh":
            q, k, v = (x.swapaxes(1, 2) for x in (q, k, v))
        N, S, H = q.shape[1], q.shape[2], q.shape[3]
        scale = sm_scale if sm_scale is not None else H ** -0.5
        kern = make_splash_kernel(N, S, config, interp)
        o = jax.vmap(lambda q, k, v: kern(q * scale, k, v))(q, k, v)
        o = o.astype(q.dtype)
        return o if layout == "bnsh" else o.swapaxes(1, 2)
    from ray_tpu.ops.flash_attention import _dense_reference
    if layout == "bnsh":
        q, k, v = (x.swapaxes(1, 2) for x in (q, k, v))
    o = _dense_reference(q, k, v, causal, sm_scale)
    return o.swapaxes(1, 2) if layout == "bnsh" else o


def attention(q, k, v, causal: bool = True, sm_scale=None,
              variant: Optional[str] = None, mesh=None,
              interpret: Optional[bool] = None, layout: str = "bsnh"):
    """Dispatched multi-head attention.

    q, k, v: [B, S, N, H] ("bsnh", default) or [B, N, S, H] ("bnsh").
    ``variant`` forces a kernel ("flash"/"dense"/"ring"/"splash");
    None consults the autotune cache (measured crossover) with the
    on-miss policy.  ``mesh`` enables the ring variant (sequence
    sharded over its "sp" axis)."""
    import jax
    interp = (jax.default_backend() != "tpu") if interpret is None \
        else interpret
    if layout == "bnsh":
        B, N, S, H = q.shape
    else:
        B, S, N, H = q.shape
    if variant is None:
        variant, _rec = choose(B, S, N, H, q.dtype, causal, mesh=mesh,
                               interpret=interp)
    cfg = None
    if variant in ("flash", "splash"):
        rec = get_cache().lookup(_VARIANT_OPS[variant],
                                 attention_key(B, S, N, H, q.dtype,
                                               causal), count=False)
        cfg = rec.get("config") if rec else None
    return _run_variant(variant, q, k, v, causal, sm_scale, interp,
                        layout, mesh, cfg)


__all__ = ["attention", "choose", "auto_variant", "tune_attention",
           "choose_variant_from_timings", "applicable_variants",
           "make_splash_kernel", "clear_memo", "on_miss_mode",
           "VARIANT_OP"]
