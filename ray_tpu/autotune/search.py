"""Block-config search engine: benchmark candidate configs for a
registered op under a warmup + best-of-N timing harness.

Ops register a candidate generator and a builder; the builder returns a
zero-arg callable that runs ONE timed step (fwd+bwd for training kernels)
and synchronizes before returning — syncing by pulling one scalar, the
only reliable completion barrier through the tunneled axon backend
(see bench.py).  The harness is interpret-mode-aware: on CPU the Pallas
kernels run interpreted, so candidate sets shrink to tiny blocks and one
repeat, which keeps the end-to-end tune testable in CI seconds while the
same code path sweeps the real grid on TPU.

Candidate pruning encodes the Mosaic tiling rules the kernels live
under: blocks divide S, blocks >= 8 sublanes (the TPU compiler rejects
sub-tile blocks), and the f32 probability tile block_q x block_k must
fit VMEM (~16 MB/core; we cap the tile at 8 MB to leave room for the
operand tiles and accumulators).
"""

from __future__ import annotations

import time
from typing import Any, Callable, Dict, List, Optional, Tuple

from ray_tpu.autotune import metrics as _am
from ray_tpu.autotune.cache import (attention_key, backend_fingerprint,
                                    canon_dtype, get_cache, norm_batch)

# f32 probability-tile VMEM budget for a (block_q, block_k) pair.
_VMEM_TILE_BYTES = 8 * 1024 * 1024

# Sublane minimum: Mosaic rejects blocks under 8 rows on real TPU.
_MIN_BLOCK = 8


class OpSpec:
    def __init__(self, name: str,
                 candidates: Callable[[dict, bool], List[dict]],
                 build: Callable[..., Callable[[], Any]]):
        self.name = name
        self.candidates = candidates
        self.build = build


_OPS: Dict[str, OpSpec] = {}


def register_op(name: str, candidates, build) -> OpSpec:
    spec = OpSpec(name, candidates, build)
    _OPS[name] = spec
    return spec


def get_op(name: str) -> OpSpec:
    return _OPS[name]


def parse_key(key: str) -> dict:
    """Inverse of cache.attention_key: "B=2|S=4096|..." -> typed dict."""
    out: dict = {}
    for part in key.split("|"):
        k, v = part.split("=", 1)
        out[k] = v if k == "dtype" else int(v)
    out["causal"] = bool(out.get("causal", 1))
    return out


# ------------------------------------------------------------------ timing

def time_fn(fn: Callable[[], Any], iters: int = 3, repeats: int = 2,
            warmup: int = 1) -> float:
    """Best-of-``repeats`` mean wall-clock ms per call.  ``warmup`` calls
    absorb compilation; ``fn`` must synchronize internally."""
    for _ in range(max(1, warmup)):
        fn()
    best = float("inf")
    for _ in range(max(1, repeats)):
        t0 = time.perf_counter()
        for _ in range(max(1, iters)):
            fn()
        best = min(best, (time.perf_counter() - t0) / max(1, iters))
    return best * 1e3


def _is_interpret(interpret: Optional[bool]) -> bool:
    if interpret is not None:
        return interpret
    import jax
    return jax.default_backend() != "tpu"


def search_op(op: str, key: str, candidates: Optional[List[dict]] = None,
              interpret: Optional[bool] = None, budget_s: Optional[float]
              = None, iters: Optional[int] = None,
              context: Optional[dict] = None
              ) -> Tuple[Optional[dict], float, List[Tuple[dict, float]]]:
    """Benchmark every candidate config for ``op`` at ``key``.

    Returns (best_config, best_ms, [(config, ms), ...]).  A candidate
    that fails to build or run (compile rejection, OOM) costs itself,
    not the sweep.  ``budget_s`` stops the sweep once exceeded, provided
    at least one candidate finished."""
    spec = get_op(op)
    interp = _is_interpret(interpret)
    kd = parse_key(key)
    cands = candidates if candidates is not None else spec.candidates(
        kd, interp)
    if iters is None:
        iters = 1 if interp else 3
    results: List[Tuple[dict, float]] = []
    t_start = time.perf_counter()
    for cfg in cands:
        if (budget_s is not None and results
                and time.perf_counter() - t_start > budget_s):
            break
        try:
            fn = spec.build(kd, cfg, interpret=interp,
                            context=context or {})
            ms = time_fn(fn, iters=iters, repeats=1 if interp else 2)
        except Exception:
            continue
        results.append((cfg, ms))
    if not results:
        return None, float("inf"), results
    best_cfg, best_ms = min(results, key=lambda r: r[1])
    return best_cfg, best_ms, results


def tune(op: str, key: str, force: bool = False, **search_kw
         ) -> Optional[dict]:
    """Cache-aware tune: return the cached record for (op, backend, key)
    or run the sweep, persist the winner, and return the new record.
    Returns None when no candidate survived (op unsupported at this
    shape/backend)."""
    cache = get_cache()
    if not force:
        rec = cache.lookup(op, key)
        if rec is not None:
            return rec
    else:
        _am.bump("autotune_cache_misses")
    t0 = time.perf_counter()
    best_cfg, best_ms, results = search_op(op, key, **search_kw)
    _am.bump("autotune_tune_ms", (time.perf_counter() - t0) * 1e3)
    if best_cfg is None:
        return None
    meta = {"swept": len(results),
            "results": [[c, round(ms, 4)] for c, ms in results[:32]]}
    return cache.put(op, key, best_cfg, best_ms, meta=meta)


# --------------------------------------------------------- block helpers

def valid_blocks(S: int, values=(128, 256, 512, 1024)) -> List[int]:
    return [v for v in values if v <= S and S % v == 0 and v >= _MIN_BLOCK]


def suggest_blocks(S: int) -> Tuple[int, int, int]:
    """For an S no TPU-legal block divides, suggest the nearest padded
    sequence length and a block pair for it: (padded_S, block_q,
    block_k).  Used by the strict-mode divisibility error path."""
    pad = 128 if S > 16 else 8
    S_pad = ((int(S) + pad - 1) // pad) * pad
    cands = valid_blocks(S_pad) or [pad]
    b = max(cands)
    return S_pad, b, b


def flash_candidates(kd: dict, interpret: bool) -> List[dict]:
    """Pruned (block_q, block_k) sweep under the Mosaic rules."""
    S = kd["S"]
    if interpret:
        vals = [v for v in (8, 16, 32, 64, 128) if v <= S and S % v == 0]
        vals = vals[-2:] or [S]        # tiny CI shapes: 2 candidates max
    else:
        vals = valid_blocks(S)
        if not vals:
            vals = valid_blocks(S, (8, 16, 32, 64)) or [S]
    out = []
    for bq in vals:
        for bk in vals:
            if bq * bk * 4 > _VMEM_TILE_BYTES:
                continue
            out.append({"block_q": bq, "block_k": bk})
    return out


def _qkv_for(kd: dict, layout: str = "bsnh"):
    import jax.numpy as jnp
    import numpy as np
    B, S, N, H = kd["B"], kd["S"], kd["N"], kd["H"]
    dtype = jnp.dtype(kd["dtype"])
    shape = (B, N, S, H) if layout == "bnsh" else (B, S, N, H)
    rng = np.random.default_rng(0)
    return tuple(jnp.asarray(rng.standard_normal(shape), dtype)
                 for _ in range(3))


def _sync_scalar(r):
    import jax.numpy as jnp
    float(jnp.asarray(r).reshape(-1)[0])


def _fwdbwd_timed(loss_fn, q, k, v):
    """Jitted grad-of-loss wrapped as a self-syncing zero-arg callable."""
    import jax
    f = jax.jit(jax.grad(loss_fn, argnums=(0, 1, 2)))

    def run():
        r = f(q, k, v)
        _sync_scalar(r[0])
        return r
    return run


def flash_build(kd: dict, cfg: dict, interpret: bool, context: dict):
    import jax.numpy as jnp
    from ray_tpu.ops.flash_attention import flash_attention
    q, k, v = _qkv_for(kd)
    bq, bk = int(cfg["block_q"]), int(cfg["block_k"])
    causal = kd["causal"]

    def loss(q, k, v):
        return flash_attention(q, k, v, causal, bq, bk, None,
                               interpret).astype(jnp.float32).sum()
    return _fwdbwd_timed(loss, q, k, v)


def dense_build(kd: dict, cfg: dict, interpret: bool, context: dict):
    import jax.numpy as jnp
    from ray_tpu.ops.flash_attention import _dense_reference
    q, k, v = _qkv_for(kd)
    causal = kd["causal"]

    def loss(q, k, v):
        return _dense_reference(q, k, v, causal,
                                None).astype(jnp.float32).sum()
    return _fwdbwd_timed(loss, q, k, v)


def ring_build(kd: dict, cfg: dict, interpret: bool, context: dict):
    """Ring attention needs a mesh with an sp axis — supplied via
    ``context={"mesh": mesh}`` (mesh topology is runtime state, not part
    of the shape key; the backend fingerprint carries device count)."""
    import jax.numpy as jnp
    from ray_tpu.ops.ring_attention import ring_attention
    mesh = context.get("mesh")
    if mesh is None:
        raise ValueError("ring_attention tuning requires context['mesh']")
    if not kd["causal"]:
        raise ValueError("ring_attention is causal-only")
    q, k, v = _qkv_for(kd)

    def loss(q, k, v):
        return ring_attention(q, k, v, mesh).astype(jnp.float32).sum()
    return _fwdbwd_timed(loss, q, k, v)


def splash_supported(kd: dict) -> bool:
    """jax's splash kernels require head_dim and seq multiples of 128
    (this jax version), and blocks of 128."""
    try:
        from jax.experimental.pallas.ops.tpu import splash_attention  # noqa
    except Exception:
        return False
    return (kd["H"] % 128 == 0 and kd["S"] % 128 == 0
            and kd.get("causal", True))


def splash_candidates(kd: dict, interpret: bool) -> List[dict]:
    """The splash BlockSizes surface: eight knobs (fwd q/kv/kv_compute,
    dkv q/kv/kv_compute, dq q/kv), all multiples of 128.  Pruned: compute
    blocks ride their parent kv block, dkv/dq sweep jointly — the
    remaining grid is fwd x bwd block sizes."""
    if not splash_supported(kd):
        return []
    S = kd["S"]
    vals = [v for v in (128, 256, 512) if v <= S and S % v == 0]
    if interpret:
        vals = vals[:1]
    out = []
    for fwd in vals:
        for bwd in vals:
            out.append({"block_q": fwd, "block_kv": fwd,
                        "block_q_bwd": bwd, "block_kv_bwd": bwd})
    return out


def splash_build(kd: dict, cfg: dict, interpret: bool, context: dict):
    import jax
    import jax.numpy as jnp
    import numpy as np
    from ray_tpu.autotune.dispatch import make_splash_kernel
    kern = make_splash_kernel(kd["N"], kd["S"], cfg, interpret)
    q, k, v = _qkv_for(kd, layout="bnsh")
    scale = 1.0 / np.sqrt(kd["H"])

    def loss(q, k, v):
        out = jax.vmap(lambda q, k, v: kern(q * scale, k, v))(q, k, v)
        return out.astype(jnp.float32).sum()
    return _fwdbwd_timed(loss, q, k, v)


register_op("flash_attention", flash_candidates, flash_build)
register_op("dense_attention", lambda kd, interp: [{}], dense_build)
register_op("ring_attention", lambda kd, interp: [{}], ring_build)
register_op("splash_attention", splash_candidates, splash_build)


def tune_flash(B: int, S: int, N: int, H: int, dtype: Any = "bfloat16",
               causal: bool = True, candidates: Optional[List[dict]] = None,
               interpret: Optional[bool] = None, force: bool = False,
               budget_s: Optional[float] = None) -> Optional[dict]:
    """Convenience wrapper: tune flash block sizes for one shape and
    persist the winner.  Returns the cache record."""
    key = attention_key(B, S, N, H, canon_dtype(dtype), causal)
    return tune("flash_attention", key, force=force, candidates=candidates,
                interpret=interpret, budget_s=budget_s)


__all__ = ["register_op", "get_op", "search_op", "tune", "tune_flash",
           "time_fn", "suggest_blocks", "valid_blocks", "flash_candidates",
           "splash_candidates", "splash_supported", "parse_key",
           "attention_key", "backend_fingerprint", "norm_batch"]
