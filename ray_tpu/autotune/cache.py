"""Persistent kernel-autotune cache: JSON-lines, keyed per shape, shared.

One record per line, schema-versioned:

    {"v": 1, "op": "flash_attention", "backend": "tpu:tpuv5litepod",
     "key": "B=2|S=4096|N=12|H=64|dtype=bfloat16|causal=1",
     "config": {"block_q": 1024, "block_k": 1024}, "ms": 56.9,
     "meta": {...}, "ts": 1754380000.0}

Records are keyed by ``(op, backend fingerprint, canonical shape key)``;
for the same full key, the LAST line wins, so a re-tune is a plain append.
Durability rules (same discipline as the spill files / BENCH_LASTGOOD):

* **append** is a single ``write()`` to an ``O_APPEND`` fd — concurrent
  processes interleave whole lines, never bytes;
* **rewrite** (compaction) goes through tmp + fsync + ``os.replace`` so a
  kill mid-compact can never destroy the only copy;
* **load** skips lines that fail to parse (the torn tail of a crashed
  append) and records with a foreign schema version — a corrupt cache
  degrades to a cold cache, it never raises into the kernel call path.

The file lives at ``$RT_AUTOTUNE_CACHE`` (default
``~/.cache/ray_tpu/autotune.jsonl``) and is shared across processes:
``lookup`` re-stats the file (throttled) and reloads when another process
appended, so a sweep in one process is visible to trainers in another
without restarts.

This module imports neither jax nor the cluster runtime at module level —
the raylet reads counters from it and must stay light.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Any, Dict, Optional, Tuple

from ray_tpu.autotune import metrics as _am

SCHEMA_VERSION = 1
DEFAULT_PATH = os.path.join("~", ".cache", "ray_tpu", "autotune.jsonl")

# How often lookup() is willing to re-stat the backing file for changes
# made by OTHER processes.  The stat is cheap but the kernel call path is
# hot, so it is throttled rather than per-call.
RELOAD_THROTTLE_S = 0.5


def cache_path() -> str:
    return os.path.expanduser(
        os.environ.get("RT_AUTOTUNE_CACHE") or DEFAULT_PATH)


def canon_dtype(dtype: Any) -> str:
    """Canonical dtype string ("bfloat16", "float32", ...) for key
    normalization — accepts strings, numpy/jax dtypes, and jnp scalar
    types, without importing jax."""
    try:
        import numpy as np
        return str(np.dtype(dtype))
    except Exception:
        return str(dtype)


def norm_batch(B: int) -> int:
    """Batch is bucketed to the next power of two: timings are much more
    sensitive to (S, N, H, dtype) than to small batch deltas, and the
    bucket keeps one sweep reusable across nearby batches."""
    B = max(1, int(B))
    return 1 << (B - 1).bit_length()


def attention_key(B: int, S: int, N: int, H: int, dtype: Any,
                  causal: bool = True) -> str:
    """Canonical shape key shared by every attention-family op (flash,
    splash, ring, dense, and the variant-crossover records)."""
    return (f"B={norm_batch(B)}|S={int(S)}|N={int(N)}|H={int(H)}"
            f"|dtype={canon_dtype(dtype)}|causal={int(bool(causal))}")


def backend_fingerprint() -> str:
    """Identity of the measuring backend.  CPU is always interpret mode
    (one fingerprint regardless of host), real backends carry the device
    kind and count — a cache tuned on v5e must not drive a v4 pod.
    Imports jax lazily; falls back to a degenerate fingerprint when no
    backend is importable (cache tests without jax)."""
    try:
        import jax
        b = jax.default_backend()
        if b == "cpu":
            return "cpu:interpret"
        devs = jax.devices()
        kind = str(getattr(devs[0], "device_kind", "") or b)
        return f"{b}:{kind.lower().replace(' ', '')}x{len(devs)}"
    except Exception:
        return "unknown"


class AutotuneCache:
    """In-memory view over one JSON-lines cache file (see module doc)."""

    def __init__(self, path: Optional[str] = None):
        self.path = os.path.expanduser(path) if path else cache_path()
        self._lock = threading.RLock()
        self._records: Dict[Tuple[str, str, str], dict] = {}
        self._stat: Optional[Tuple[int, int]] = None
        self._last_stat_t = 0.0
        self.corrupt_lines = 0
        self._load()

    # ------------------------------------------------------------- load

    def _file_stat(self):
        try:
            st = os.stat(self.path)
            return (st.st_size, st.st_mtime_ns)
        except OSError:
            return None

    def _load(self) -> None:
        with self._lock:
            self._records.clear()
            self.corrupt_lines = 0
            self._stat = self._file_stat()
            self._last_stat_t = time.monotonic()
            if self._stat is None:
                return
            try:
                with open(self.path, "r", encoding="utf-8") as f:
                    data = f.read()
            except OSError:
                return
            for line in data.splitlines():
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                    if not isinstance(rec, dict):
                        raise ValueError("not a record")
                except Exception:
                    # Torn tail of a crashed append, or garbage: a corrupt
                    # line costs itself, not the cache.
                    self.corrupt_lines += 1
                    continue
                if rec.get("v") != SCHEMA_VERSION:
                    continue
                try:
                    k = (str(rec["op"]), str(rec["backend"]),
                         str(rec["key"]))
                except KeyError:
                    self.corrupt_lines += 1
                    continue
                self._records[k] = rec        # last line wins

    def maybe_reload(self) -> None:
        """Pick up appends from other processes (throttled stat)."""
        with self._lock:
            now = time.monotonic()
            if now - self._last_stat_t < RELOAD_THROTTLE_S:
                return
            self._last_stat_t = now
            if self._file_stat() != self._stat:
                self._load()

    # ------------------------------------------------------------ query

    def lookup(self, op: str, key: str, backend: Optional[str] = None,
               count: bool = True) -> Optional[dict]:
        """Best record for (op, backend, key) or None.  ``count=False``
        suppresses the hit/miss counters for repeat consultations the
        caller already memoized once."""
        backend = backend or backend_fingerprint()
        self.maybe_reload()
        with self._lock:
            rec = self._records.get((op, backend, key))
        if count:
            _am.bump("autotune_cache_hits" if rec is not None
                     else "autotune_cache_misses")
        return rec

    def records(self):
        with self._lock:
            return list(self._records.values())

    def __len__(self):
        with self._lock:
            return len(self._records)

    # ------------------------------------------------------------ write

    def put(self, op: str, key: str, config: dict, ms: float,
            meta: Optional[dict] = None,
            backend: Optional[str] = None) -> dict:
        """Append one record (atomic whole-line append) and adopt it
        in-memory."""
        backend = backend or backend_fingerprint()
        rec = {"v": SCHEMA_VERSION, "op": op, "backend": backend,
               "key": key, "config": config,
               "ms": round(float(ms), 4) if ms is not None else None,
               "ts": round(time.time(), 3)}
        if meta:
            rec["meta"] = meta
        line = json.dumps(rec, sort_keys=True) + "\n"
        with self._lock:
            os.makedirs(os.path.dirname(self.path) or ".", exist_ok=True)
            # O_APPEND + one write(): concurrent appenders interleave
            # whole lines.  (A torn line from a crash mid-write is
            # tolerated by _load.)
            fd = os.open(self.path, os.O_WRONLY | os.O_CREAT | os.O_APPEND,
                         0o644)
            try:
                os.write(fd, line.encode("utf-8"))
            finally:
                os.close(fd)
            self._records[(op, backend, key)] = rec
            self._stat = self._file_stat()
        return rec

    def rewrite(self) -> int:
        """Compact the file to one line per key (drops superseded
        records, corrupt lines, and foreign schema versions).  tmp +
        fsync + rename: a kill mid-compact leaves the old file intact.
        Returns the number of records written."""
        with self._lock:
            self._load()                      # fold in foreign appends
            tmp = self.path + ".tmp"
            os.makedirs(os.path.dirname(self.path) or ".", exist_ok=True)
            with open(tmp, "w", encoding="utf-8") as f:
                for rec in self._records.values():
                    f.write(json.dumps(rec, sort_keys=True) + "\n")
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, self.path)
            self.corrupt_lines = 0
            self._stat = self._file_stat()
            return len(self._records)


_CACHES: Dict[str, AutotuneCache] = {}
_caches_lock = threading.Lock()


def get_cache(path: Optional[str] = None) -> AutotuneCache:
    """Process-wide cache singleton per resolved path (the env var may
    legitimately change between tests)."""
    p = os.path.expanduser(path) if path else cache_path()
    with _caches_lock:
        c = _CACHES.get(p)
        if c is None:
            c = _CACHES[p] = AutotuneCache(p)
        return c
