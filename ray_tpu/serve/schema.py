"""Declarative Serve config: schema + deploy-from-file.

Design analog: reference ``python/ray/serve/schema.py``
(ServeApplicationSchema: pydantic models consumed by ``serve deploy`` /
the REST API) and ``serve/scripts.py`` (the serve CLI).  TPU-first
simplification: plain dataclasses validated by hand (no pydantic in the
image), YAML or JSON on disk, deployments referenced by
``import_path = "module:attribute"`` exactly like the reference.
"""

from __future__ import annotations

import dataclasses
import importlib
from typing import Any, Dict, List, Optional

_ALLOWED_OPTIONS = ("num_replicas", "max_concurrent_queries",
                    "autoscaling_config", "user_config")


@dataclasses.dataclass
class DeploymentSchema:
    """One deployment entry of an application config."""
    name: str
    import_path: str                      # "pkg.module:deployment_obj"
    num_replicas: Optional[int] = None
    max_concurrent_queries: Optional[int] = None
    autoscaling_config: Optional[Dict[str, Any]] = None
    user_config: Optional[Dict[str, Any]] = None
    init_args: tuple = ()
    init_kwargs: Optional[Dict[str, Any]] = None

    @staticmethod
    def from_dict(d: Dict[str, Any]) -> "DeploymentSchema":
        unknown = set(d) - {f.name for f in
                            dataclasses.fields(DeploymentSchema)}
        if unknown:
            raise ValueError(f"unknown deployment config keys: "
                             f"{sorted(unknown)}")
        if "name" not in d or "import_path" not in d:
            raise ValueError("deployment config needs 'name' and "
                             "'import_path'")
        d = dict(d)
        d["init_args"] = tuple(d.get("init_args") or ())
        return DeploymentSchema(**d)


@dataclasses.dataclass
class ServeApplicationSchema:
    """Whole-application config (reference ServeApplicationSchema)."""
    deployments: List[DeploymentSchema]
    http_host: Optional[str] = None
    http_port: int = 0

    @staticmethod
    def from_dict(d: Dict[str, Any]) -> "ServeApplicationSchema":
        deps = [DeploymentSchema.from_dict(x)
                for x in d.get("deployments", [])]
        if not deps:
            raise ValueError("config has no deployments")
        names = [x.name for x in deps]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate deployment names in config: "
                             f"{names}")
        return ServeApplicationSchema(
            deployments=deps, http_host=d.get("http_host"),
            http_port=int(d.get("http_port", 0)))

    @staticmethod
    def from_file(path: str) -> "ServeApplicationSchema":
        import json
        with open(path) as f:
            text = f.read()
        if path.endswith((".yaml", ".yml")):
            import yaml
            return ServeApplicationSchema.from_dict(yaml.safe_load(text))
        return ServeApplicationSchema.from_dict(json.loads(text))


def _import_target(import_path: str):
    if ":" not in import_path:
        raise ValueError(
            f"import_path {import_path!r} must be 'module:attribute'")
    mod_name, attr = import_path.split(":", 1)
    obj = importlib.import_module(mod_name)
    for part in attr.split("."):
        obj = getattr(obj, part)
    return obj


def deploy_application(schema: ServeApplicationSchema) -> Dict[str, Any]:
    """Deploy every entry of a declarative config (reference
    ``serve deploy``).  Returns the application's status dict."""
    from ray_tpu import serve
    from ray_tpu.serve import Deployment

    for entry in schema.deployments:
        target = _import_target(entry.import_path)
        if not isinstance(target, Deployment):
            raise TypeError(
                f"{entry.import_path} resolved to {type(target).__name__}, "
                f"expected a @serve.deployment")
        opts = {k: getattr(entry, k) for k in _ALLOWED_OPTIONS
                if getattr(entry, k) is not None}
        target = target.options(name=entry.name, **opts)
        if entry.init_args or entry.init_kwargs:
            target = target.bind(*entry.init_args,
                                 **(entry.init_kwargs or {}))
        serve.run(target)
    if schema.http_host:
        serve.start_http(schema.http_host, schema.http_port)
    return serve.status()
