"""Paged KV-cache bookkeeping: page allocator + per-sequence tables.

Reference analog: vLLM's BlockAllocator/BlockTable (vllm/core/
block_manager.py) — the host-side half of PagedAttention.  The device
half (the pools and the gather/scatter ops) lives in
``ray_tpu.ops.paged_attention``; this module owns WHICH pages a sequence
may touch.  Page 0 is reserved as the scratch sink the device ops route
padded/inactive writes to, so the free list starts at page 1 and a
sequence's table row is padded with zeros past its reserved pages.

Allocation is all-or-nothing at admission time (the engine reserves the
worst case ``ceil((prompt + max_new) / page)`` up front), which makes
mid-decode OOM structurally impossible — a sequence that fits at
admission always finishes.  That trades utilization for the property the
continuous-batching loop leans on: retire is the only page-freeing
event, so the loop never has to preempt.
"""

from __future__ import annotations

from typing import List

import numpy as np


class PageAllocator:
    """Free-list allocator over pages ``1..num_pages-1`` (page 0 is the
    scratch sink and is never handed out)."""

    def __init__(self, num_pages: int):
        if num_pages < 2:
            raise ValueError("need at least 2 pages (page 0 is reserved)")
        self.num_pages = num_pages
        # LIFO free list: recently-freed pages are reused first, keeping
        # the hot working set small.
        self._free: List[int] = list(range(num_pages - 1, 0, -1))

    @property
    def free_pages(self) -> int:
        return len(self._free)

    def can_alloc(self, n: int) -> bool:
        return n <= len(self._free)

    def alloc(self, n: int) -> List[int]:
        """Pop ``n`` pages or raise — callers gate on ``can_alloc`` so a
        raise here is an accounting bug, not backpressure."""
        if n > len(self._free):
            raise MemoryError(
                f"KV cache exhausted: need {n} pages, {len(self._free)} free")
        pages, self._free[-n:] = self._free[-n:], []
        return pages

    def free(self, pages: List[int]) -> None:
        for p in pages:
            if not 0 < p < self.num_pages:
                raise ValueError(f"freeing invalid page id {p}")
        if set(pages) & set(self._free):
            raise ValueError("double free in KV page allocator")
        self._free.extend(pages)


def table_row(pages: List[int], maxp: int) -> np.ndarray:
    """A sequence's fixed-width page-table row: its reserved pages padded
    with 0 (the scratch page) out to ``maxp`` — positions never reach the
    padding, and if they somehow did, the write lands in scratch instead
    of another sequence's cache."""
    if len(pages) > maxp:
        raise ValueError(f"{len(pages)} pages exceed table width {maxp}")
    row = np.zeros((maxp,), np.int32)
    row[: len(pages)] = pages
    return row
