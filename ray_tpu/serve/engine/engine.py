"""Continuous-batching inference engine over the paged KV cache.

Reference analogs: vLLM's LLMEngine/Scheduler (continuous batching,
paged KV) and the reference repo's serve replicas; the model side is
``models/gpt.py``/``models/llama.py``'s ``*_prefill``/``*_decode_step``
paged entry points.

The core loop is **iteration-level scheduling**: instead of batching
whole requests (every sequence waits for the slowest), the engine admits
and retires sequences *per decode step* — a new request joins the live
batch at the next step boundary, a finished one frees its slot and pages
immediately.  One replica therefore decodes up to ``max_batch``
sequences per forward dispatch, each at its own position, with per-token
results streamed to callers through per-sequence asyncio queues (the
transport half — serve's ``handle_stream`` + ``num_returns="streaming"``
— rides on those queues).

Admission reserves the worst case ``ceil((prompt + max_new) / page)``
pages up front (see kv_cache.py), so a sequence admitted is a sequence
that finishes: the loop never preempts and never OOMs mid-decode.
Prefill runs one sequence per dispatch (B=1, fixed padded shape);
decode runs the whole batch (fixed shape [max_batch]) with inactive
slots parked on scratch page 0.  Both are jitted once; dispatches run on
a single-thread executor so the actor's event loop keeps serving
admissions and cancellations while XLA computes.
"""

from __future__ import annotations

import asyncio
import collections
import concurrent.futures
import dataclasses
import logging
import time
from typing import Any, AsyncIterator, Dict, List, Optional, Sequence

import numpy as np

from ray_tpu.serve import resilience
from ray_tpu.serve.engine.kv_cache import PageAllocator, table_row

logger = logging.getLogger(__name__)

_DONE = object()


@dataclasses.dataclass
class EngineConfig:
    model: str = "gpt"                 # "gpt" | "llama"
    model_config: Any = None           # GPTConfig/LlamaConfig; tiny default
    page_size: int = 8
    num_pages: int = 128               # pool size; page 0 is scratch
    max_batch: int = 8                 # decode slots per step
    max_prompt_len: int = 64           # multiple of page_size
    max_new_tokens: int = 32           # per-request cap
    eos_token: Optional[int] = None
    dtype: Any = None                  # KV pool dtype (default: model's)


class _Sequence:
    __slots__ = ("prompt", "max_new", "pages", "row", "queue", "generated",
                 "pos", "last_token", "cancelled", "slot", "prefilled",
                 "deadline")

    def __init__(self, prompt: List[int], max_new: int,
                 deadline: Optional[float] = None):
        self.prompt = prompt
        self.max_new = max_new
        self.deadline = deadline       # absolute epoch seconds, or None
        self.pages: List[int] = []
        self.row: Optional[np.ndarray] = None
        self.queue: asyncio.Queue = asyncio.Queue()
        self.generated = 0
        self.pos = len(prompt)         # next KV write position
        self.last_token: Optional[int] = None
        self.cancelled = False
        self.slot: Optional[int] = None
        self.prefilled = False


class InferenceEngine:
    """Paged continuous-batching engine; see module docstring."""

    def __init__(self, config: EngineConfig, params: Any = None,
                 rng_seed: int = 0):
        import jax

        cfg = config
        if cfg.max_prompt_len % cfg.page_size:
            raise ValueError("max_prompt_len must be a multiple of "
                             f"page_size ({cfg.page_size})")
        if cfg.model == "gpt":
            from ray_tpu.models.gpt import (GPTConfig, gpt_decode_step,
                                            gpt_init, gpt_prefill,
                                            init_paged_cache)
            mc = cfg.model_config or GPTConfig.tiny(
                seq=cfg.max_prompt_len + cfg.max_new_tokens)
            init_fn, prefill_fn, decode_fn = \
                gpt_init, gpt_prefill, gpt_decode_step
            cache_fn = lambda: init_paged_cache(   # noqa: E731
                mc, cfg.num_pages, cfg.page_size, cfg.dtype)
        elif cfg.model == "llama":
            from ray_tpu.models.llama import (LlamaConfig,
                                              llama_decode_step,
                                              llama_init,
                                              llama_init_paged_cache,
                                              llama_prefill)
            mc = cfg.model_config or LlamaConfig.tiny(
                seq=cfg.max_prompt_len + cfg.max_new_tokens)
            init_fn, prefill_fn, decode_fn = \
                llama_init, llama_prefill, llama_decode_step
            cache_fn = lambda: llama_init_paged_cache(   # noqa: E731
                mc, cfg.num_pages, cfg.page_size, cfg.dtype)
        else:
            raise ValueError(f"unknown engine model '{cfg.model}'")
        if mc.max_seq_len < cfg.max_prompt_len + cfg.max_new_tokens:
            raise ValueError(
                f"model max_seq_len {mc.max_seq_len} < max_prompt_len + "
                f"max_new_tokens ({cfg.max_prompt_len + cfg.max_new_tokens})")

        self.config = cfg
        self.model_config = mc
        self._params = params if params is not None else \
            init_fn(jax.random.PRNGKey(rng_seed), mc)
        self._k_pages, self._v_pages = cache_fn()
        self._alloc = PageAllocator(cfg.num_pages)
        self._maxp = -(-(cfg.max_prompt_len + cfg.max_new_tokens)
                       // cfg.page_size)

        # Jit with params/config closed over: one compile per entry
        # point, shapes fixed ([1, max_prompt_len] prefill,
        # [max_batch] decode), so the steady-state loop never re-traces.
        def _prefill(tokens, length, kp, vp, pt):
            return prefill_fn(self._params, mc, tokens, length, kp, vp, pt)

        def _decode(token, pos, kp, vp, pt):
            return decode_fn(self._params, mc, token, pos, kp, vp, pt)

        self._prefill = jax.jit(_prefill)
        self._decode = jax.jit(_decode)

        self._waiting: collections.deque = collections.deque()
        self._active: Dict[int, _Sequence] = {}   # slot -> sequence
        self._free_slots: List[int] = list(range(cfg.max_batch - 1, -1, -1))
        self._wake = asyncio.Event()
        self._loop_task: Optional[asyncio.Task] = None
        self._steps = 0
        # Single lane for XLA dispatches: the device serializes anyway,
        # and one lane keeps (k_pages, v_pages) updates ordered.
        self._exec = concurrent.futures.ThreadPoolExecutor(
            1, thread_name_prefix="rt-engine")

    # ------------------------------------------------------------- public

    async def generate(self, tokens: Sequence[int],
                       max_new_tokens: Optional[int] = None,
                       deadline: Optional[float] = None
                       ) -> AsyncIterator[int]:
        """Admit one sequence; yields generated token ids as they decode.
        Closing the iterator early (client disconnect) cancels the
        sequence and frees its pages at the next step boundary.  An
        absolute ``deadline`` (epoch seconds) bounds the whole request:
        expiry raises DeadlineExceeded to the consumer AND retires the
        sequence inside the batch loop — its slot and KV pages free at
        the next step boundary instead of decoding tokens nobody reads."""
        tokens = [int(t) for t in tokens]
        if not tokens:
            raise ValueError("empty prompt")
        if len(tokens) > self.config.max_prompt_len:
            raise ValueError(f"prompt length {len(tokens)} exceeds "
                             f"max_prompt_len {self.config.max_prompt_len}")
        max_new = min(max_new_tokens or self.config.max_new_tokens,
                      self.config.max_new_tokens)
        self._ensure_loop()
        seq = _Sequence(tokens, max_new, deadline)
        self._waiting.append(seq)
        self._wake.set()
        try:
            while True:
                if seq.deadline is None:
                    item = await seq.queue.get()
                else:
                    rem = seq.deadline - time.time()
                    if rem <= 0:
                        raise resilience.DeadlineExceeded(
                            "deadline expired while decoding")
                    try:
                        item = await asyncio.wait_for(seq.queue.get(), rem)
                    except asyncio.TimeoutError:
                        raise resilience.DeadlineExceeded(
                            "deadline expired while decoding") from None
                if item is _DONE:
                    return
                if isinstance(item, BaseException):
                    raise item
                yield item
        finally:
            seq.cancelled = True
            self._wake.set()

    def stats(self) -> Dict[str, int]:
        return {"active": len(self._active), "waiting": len(self._waiting),
                "free_pages": self._alloc.free_pages, "steps": self._steps}

    def close(self):
        if self._loop_task is not None:
            self._loop_task.cancel()
            self._loop_task = None
        self._exec.shutdown(wait=False)

    # ----------------------------------------------------------- internals

    def _ensure_loop(self):
        if self._loop_task is None or self._loop_task.done():
            self._loop_task = asyncio.get_running_loop().create_task(
                self._run_loop())

    def _pages_needed(self, seq: _Sequence) -> int:
        return -(-(len(seq.prompt) + seq.max_new) // self.config.page_size)

    @staticmethod
    def _deadline_expired(seq: _Sequence) -> bool:
        return seq.deadline is not None and time.time() > seq.deadline

    def _admit(self):
        while self._waiting and self._free_slots:
            seq = self._waiting[0]
            if seq.cancelled:
                self._waiting.popleft()
                continue
            if self._deadline_expired(seq):
                # Expired while queued: reject instead of spending pages
                # and decode steps on a request nobody is waiting for.
                self._waiting.popleft()
                seq.queue.put_nowait(resilience.DeadlineExceeded(
                    "deadline expired while waiting for admission"))
                continue
            need = self._pages_needed(seq)
            if not self._alloc.can_alloc(need):
                if not self._active:
                    # Nothing will ever free up: the request exceeds the
                    # whole pool.  Fail it instead of parking forever.
                    self._waiting.popleft()
                    seq.queue.put_nowait(MemoryError(
                        f"request needs {need} KV pages, pool has "
                        f"{self._alloc.free_pages} free and 0 active"))
                    continue
                break   # head-of-line waits for a retire
            self._waiting.popleft()
            seq.pages = self._alloc.alloc(need)
            seq.row = table_row(seq.pages, self._maxp)
            seq.slot = self._free_slots.pop()
            self._active[seq.slot] = seq

    def _retire(self, seq: _Sequence, done: bool = True):
        self._active.pop(seq.slot, None)
        self._free_slots.append(seq.slot)
        seq.slot = None
        if seq.pages:
            self._alloc.free(seq.pages)
            seq.pages = []
        if done and not seq.cancelled:
            seq.queue.put_nowait(_DONE)

    def _push(self, seq: _Sequence, token: int) -> bool:
        """Deliver one token; returns True when the sequence is finished
        (EOS or max_new reached)."""
        seq.generated += 1
        seq.last_token = token
        if not seq.cancelled:
            seq.queue.put_nowait(token)
        eos = self.config.eos_token
        return seq.generated >= seq.max_new or \
            (eos is not None and token == eos)

    async def _run_loop(self):
        import jax.numpy as jnp
        loop = asyncio.get_running_loop()
        cfg = self.config
        S = cfg.max_prompt_len
        while True:
            try:
                for seq in [s for s in self._active.values() if s.cancelled]:
                    self._retire(seq, done=False)
                # Deadline sweep: an expired sequence stops decoding NOW —
                # its slot and KV pages free for live requests and the
                # rest of the batch keeps stepping unharmed.
                for seq in [s for s in self._active.values()
                            if self._deadline_expired(s)]:
                    self._retire(seq, done=False)
                    if not seq.cancelled:
                        seq.queue.put_nowait(resilience.DeadlineExceeded(
                            "deadline expired while decoding"))
                self._admit()
                if not self._active:
                    if self._waiting:
                        continue   # admission makes progress every pass
                    self._wake.clear()
                    # Re-check: generate() may have appended between the
                    # test above and the clear.
                    if not self._waiting:
                        await self._wake.wait()
                    continue

                # Prefill new admissions one at a time (B=1, one shape).
                for seq in [s for s in self._active.values()
                            if not s.prefilled]:
                    toks = np.zeros((1, S), np.int32)
                    toks[0, : len(seq.prompt)] = seq.prompt
                    def _run(seq=seq, toks=toks):
                        logits, kp, vp = self._prefill(
                            toks, np.int32(len(seq.prompt)),
                            self._k_pages, self._v_pages, seq.row[None])
                        return int(jnp.argmax(logits[0])), kp, vp
                    tok, self._k_pages, self._v_pages = \
                        await loop.run_in_executor(self._exec, _run)
                    seq.prefilled = True
                    if self._push(seq, tok) or seq.cancelled:
                        self._retire(seq, done=not seq.cancelled)

                if not self._active:
                    continue
                # Chaos hook: a stalled decode (wedged device, stuck
                # dispatch) is indistinguishable from a dead replica to
                # the client — the ingress's stall detector must fail the
                # stream over.  The hook injects exactly that.
                from ray_tpu.util import fault_injection
                stall = fault_injection.stall_replica_decode_s()
                if stall:
                    await asyncio.sleep(stall)
                # One batched decode step over every live slot.  Inactive
                # slots run token 0 at pos 0 against an all-zero table
                # row — their writes land in scratch page 0.
                token = np.zeros((cfg.max_batch,), np.int32)
                pos = np.zeros((cfg.max_batch,), np.int32)
                tables = np.zeros((cfg.max_batch, self._maxp), np.int32)
                for slot, seq in self._active.items():
                    token[slot] = seq.last_token
                    pos[slot] = seq.pos
                    tables[slot] = seq.row
                def _step():
                    logits, kp, vp = self._decode(
                        token, pos, self._k_pages, self._v_pages, tables)
                    return np.asarray(jnp.argmax(logits, axis=-1)), kp, vp
                nxt, self._k_pages, self._v_pages = \
                    await loop.run_in_executor(self._exec, _step)
                self._steps += 1
                for slot, seq in list(self._active.items()):
                    seq.pos += 1
                    if self._push(seq, int(nxt[slot])) or seq.cancelled:
                        self._retire(seq, done=not seq.cancelled)
            except asyncio.CancelledError:
                raise
            except Exception as e:   # noqa: BLE001
                logger.exception("inference engine step failed")
                for seq in list(self._active.values()):
                    self._retire(seq, done=False)
                    seq.queue.put_nowait(e)
                while self._waiting:
                    self._waiting.popleft().queue.put_nowait(e)


class LLMServer:
    """Ready-made serve deployment body around an InferenceEngine.

    ``serve.deployment(LLMServer).bind(EngineConfig(...))`` gives an HTTP
    +handle-callable token streamer: payloads are
    ``{"tokens": [...], "max_new_tokens": N}``; the response is the
    stream of generated token ids (a list for unary callers, per-token
    SSE events through the streaming ingress)."""

    def __init__(self, config: Optional[EngineConfig] = None,
                 params: Any = None, **config_kwargs):
        self._engine = InferenceEngine(config or EngineConfig(
            **config_kwargs), params=params)

    async def __call__(self, payload):
        if not isinstance(payload, dict) or "tokens" not in payload:
            raise ValueError(
                'expected {"tokens": [...], "max_new_tokens": N}')
        # The replica publishes the request's end-to-end deadline via
        # contextvar (see serve/resilience.py); handing it to the engine
        # lets an expired request free its KV pages mid-batch.
        async for tok in self._engine.generate(
                payload["tokens"], payload.get("max_new_tokens"),
                deadline=resilience.current_deadline()):
            yield tok

    def stats(self) -> Dict[str, int]:
        return self._engine.stats()
