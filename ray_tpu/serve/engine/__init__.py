"""ray_tpu.serve.engine — streaming LLM inference engine.

Continuous batching over a paged KV cache (vLLM-style iteration-level
scheduling), streaming per-token results through serve's
``num_returns="streaming"`` transport.  See engine.py for the loop and
kv_cache.py for the page accounting.
"""

from ray_tpu.serve.engine.engine import (EngineConfig,  # noqa: F401
                                         InferenceEngine, LLMServer)
from ray_tpu.serve.engine.kv_cache import (PageAllocator,  # noqa: F401
                                           table_row)
