"""Serve controller: reconciles declared deployments to replica actors.

Reference analogs: ServeController (serve/controller.py:64),
DeploymentState/DeploymentStateManager replica lifecycle
(_private/deployment_state.py:959,1769), BasicAutoscalingPolicy on queue
metrics (_private/autoscaling_policy.py:93).

The controller is a detached async actor.  A reconcile loop drives each
deployment's replica set toward its target count, probes replica health,
replaces dead replicas, and (when autoscaling is configured) adjusts the
target from the replicas' reported queue depths — scale-up when the mean
outstanding queue exceeds the target, scale-down when it falls well below.
"""

from __future__ import annotations

import asyncio
import dataclasses
import logging
import os
import time
from typing import Any, Dict, List, Optional

logger = logging.getLogger(__name__)

CONTROLLER_NAME = "_serve_controller"
RECONCILE_PERIOD_S = 0.5


def _env_f(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, default))
    except ValueError:
        return default


@dataclasses.dataclass
class DeploymentSpec:
    name: str
    callable_blob: bytes          # cloudpickle (cls_or_fn, args, kwargs)
    num_replicas: int = 1
    max_concurrent_queries: int = 8
    route_prefix: str = ""
    resources: Optional[Dict[str, float]] = None
    num_cpus: float = 1.0
    autoscaling: Optional[Dict[str, Any]] = None  # min/max_replicas,
    #                                              target_queue_len
    # Arbitrary config pushed to live replicas via reconfigure() without a
    # restart (reference: deployment user_config + replica reconfigure).
    user_config: Optional[Dict[str, Any]] = None
    # Per-replica runtime env (reference: ray_actor_options.runtime_env);
    # e.g. env_vars pinning one deployment's workers to the TPU platform
    # while the cluster default keeps workers on CPU.
    runtime_env: Optional[Dict[str, Any]] = None


class Replica:
    """Actor body hosting one deployment replica."""

    def __init__(self, callable_blob: bytes, max_concurrent_queries: int = 8,
                 user_config: Optional[Dict[str, Any]] = None):
        import cloudpickle
        target, args, kwargs = cloudpickle.loads(callable_blob)
        if isinstance(target, type):
            self._fn = target(*args, **kwargs)
        else:
            self._fn = target
        if user_config is not None:
            self.reconfigure(user_config)
        self._outstanding = 0
        # Concurrency is bounded HERE, not by the actor's max_concurrency:
        # requests waiting on an actor-level semaphore would be invisible to
        # queue_len, capping the autoscaler's signal at the concurrency
        # limit no matter how deep the real backlog is.
        self._sem = asyncio.Semaphore(max_concurrent_queries)

    @staticmethod
    def _resolve(fn):
        import inspect
        # Resolve a class instance to its bound __call__ so coroutine /
        # generator detection sees the real function.
        if (not inspect.isfunction(fn) and not inspect.ismethod(fn)
                and callable(fn) and hasattr(fn, "__call__")):
            fn = fn.__call__
        return fn

    async def handle_request(self, args, kwargs,
                             method: Optional[str] = None,
                             deadline: Optional[float] = None):
        import functools

        from ray_tpu.serve import resilience

        async def _invoke():
            fn = self._resolve(
                self._fn if method is None else getattr(self._fn, method))
            if asyncio.iscoroutinefunction(fn):
                result = await fn(*args, **kwargs)
            else:
                # Sync handlers must not block the replica's event loop:
                # run them on threads; self._sem bounds the fan-out.
                result = \
                    await asyncio.get_running_loop().run_in_executor(
                        None, functools.partial(fn, *args, **kwargs))
                if asyncio.iscoroutine(result):
                    result = await result
            # A generator-handler called through the unary path drains
            # to a list — the raw generator object is replica-local
            # and would fail to pickle into the reply.
            if hasattr(result, "__anext__"):
                return [item async for item in result]
            if hasattr(result, "__next__") and hasattr(result, "send"):
                return await asyncio.get_running_loop().run_in_executor(
                    None, list, result)
            return result

        self._outstanding += 1
        # Publish the end-to-end deadline to the handler body (the
        # inference engine reads it to bound decode); the wait_for below
        # is the backstop for handlers that never look.
        token = resilience.set_deadline(deadline)
        try:
            rem = resilience.deadline_remaining(deadline)
            if rem is not None and rem <= 0:
                raise resilience.DeadlineExceeded(
                    "deadline expired before the replica started")
            async with self._sem:
                rem = resilience.deadline_remaining(deadline)
                if rem is None:
                    return await _invoke()
                if rem <= 0:
                    raise resilience.DeadlineExceeded(
                        "deadline expired while queued on the replica")
                try:
                    return await asyncio.wait_for(_invoke(), rem)
                except asyncio.TimeoutError:
                    raise resilience.DeadlineExceeded(
                        "deadline expired during the request") from None
        finally:
            resilience.reset_deadline(token)
            self._outstanding -= 1

    async def handle_stream(self, args, kwargs,
                            method: Optional[str] = None,
                            deadline: Optional[float] = None):
        """Streaming twin of handle_request: an async generator the owner
        consumes per-item via ``num_returns="streaming"`` — the caller
        sees each yield while the handler is still running.  Sync
        generators are stepped on threads so they can block; plain
        (non-generator) results degrade to a single-item stream.
        ``_outstanding``/the semaphore span the WHOLE stream life, so
        queue_len (the autoscaler signal) counts live streams, not just
        call setup.  The request ``deadline`` is published through
        ``resilience.set_deadline`` for the handler (the engine bounds
        decode with it) and re-checked here at every yield."""
        import functools

        from ray_tpu.serve import resilience
        from ray_tpu.util import fault_injection

        def _check_deadline():
            rem = resilience.deadline_remaining(deadline)
            if rem is not None and rem <= 0:
                raise resilience.DeadlineExceeded(
                    "deadline expired mid-stream")

        self._outstanding += 1
        token = resilience.set_deadline(deadline)
        try:
            _check_deadline()
            async with self._sem:
                _check_deadline()
                fn = self._resolve(
                    self._fn if method is None else getattr(self._fn, method))
                loop = asyncio.get_running_loop()
                if asyncio.iscoroutinefunction(fn):
                    result = await fn(*args, **kwargs)
                else:
                    result = fn(*args, **kwargs)
                    if asyncio.iscoroutine(result):
                        result = await result
                if hasattr(result, "__anext__"):
                    async for item in result:
                        stall = fault_injection.stall_stream_s()
                        if stall:
                            await asyncio.sleep(stall)
                        _check_deadline()
                        yield item
                elif hasattr(result, "__next__") and hasattr(result, "send"):
                    sentinel = object()
                    _next = functools.partial(next, result, sentinel)
                    try:
                        while True:
                            item = await loop.run_in_executor(None, _next)
                            if item is sentinel:
                                break
                            stall = fault_injection.stall_stream_s()
                            if stall:
                                await asyncio.sleep(stall)
                            _check_deadline()
                            yield item
                    finally:
                        close = getattr(result, "close", None)
                        if close is not None:
                            await loop.run_in_executor(None, close)
                else:
                    yield result
        finally:
            resilience.reset_deadline(token)
            self._outstanding -= 1

    def reconfigure(self, user_config: Dict[str, Any]) -> bool:
        """Apply a user_config update in place (reference: the replica
        calls the user class's reconfigure(user_config) on deploy-time
        config changes — no restart)."""
        hook = getattr(self._fn, "reconfigure", None)
        if hook is None:
            raise ValueError(
                "deployment has user_config but its class defines no "
                "reconfigure(user_config) method")
        hook(user_config)
        return True

    def queue_len(self) -> int:
        return self._outstanding

    def ping(self) -> bool:
        return True


class ServeController:
    def __init__(self):
        self.deployments: Dict[str, DeploymentSpec] = {}
        self.replicas: Dict[str, List] = {}        # name -> actor handles
        self.targets: Dict[str, int] = {}          # name -> target count
        self._replica_seq = 0
        self._shutdown = False
        self._loop_task = None
        self._metrics: Dict[str, List[float]] = {}  # queue-len history
        # Health-probe grace for initializing replicas (reference:
        # initial health-check period in deployment_state): a replica
        # whose __init__ is still compiling a jitted model must not be
        # killed for missing a 10s ping.  actor_id -> created monotonic;
        # ids that have answered once graduate to the normal probe.
        self._replica_created: Dict[str, float] = {}
        self._replica_seen_healthy: set = set()
        # deploy() and the background loop both reconcile; without this
        # lock a concurrent `reps[:] = alive` clobbers (and orphans)
        # replicas the other invocation just created.
        self._reconcile_lock = asyncio.Lock()
        # Long-poll state (reference serve/_private/long_poll.py
        # LongPollHost): per-deployment replica-set version + waiter event.
        self._versions: Dict[str, int] = {}
        self._change_events: Dict[str, asyncio.Event] = {}
        self._restored = False

    async def _maybe_restore(self):
        """Crash recovery (reference: the controller checkpoints its
        state and recovers on restart): a GCS-restarted controller
        re-adopts its deployments AND the still-live replica actors from
        the KV snapshot written each reconcile — replicas keep serving
        through the crash; reconcile then replaces any that died."""
        if self._restored:
            return
        self._restored = True
        try:
            import cloudpickle
            from ray_tpu._private.worker import get_core
            from ray_tpu.actor import ActorHandle
            raw = await get_core().gcs.request(
                {"type": "kv_get", "ns": "serve", "key": b"state"})
            if not raw:
                return
            state = cloudpickle.loads(raw)
            self._replica_seq = state.get("replica_seq", 0)
            for name, (spec, target, replica_ids) in \
                    state.get("deployments", {}).items():
                self.deployments[name] = spec
                self.targets[name] = target
                self.replicas[name] = [ActorHandle(a, "Replica")
                                       for a in replica_ids]
                self._bump_version(name)   # routers refresh handles
            if self.deployments:
                logger.info("serve controller restored %d deployments "
                            "from KV", len(self.deployments))
        except Exception:
            logger.exception("serve controller state restore failed")

    def _bump_version(self, name: str):
        self._versions[name] = self._versions.get(name, 0) + 1
        ev = self._change_events.pop(name, None)
        if ev is not None:
            ev.set()

    async def listen_for_change(self, name: str, last_version: int,
                                timeout: float = 30.0) -> Dict[str, Any]:
        await self._maybe_restore()
        await self._ensure_loop()
        """Long-poll: parks until the deployment's replica set differs from
        ``last_version`` (or timeout), then returns the current snapshot.
        Routers learn about scale events push-style instead of waiting out
        a TTL (reference long_poll.py:listen_for_change)."""
        cur = self._versions.get(name, 0)
        if cur == last_version:
            ev = self._change_events.setdefault(name, asyncio.Event())
            try:
                await asyncio.wait_for(ev.wait(), timeout)
            except asyncio.TimeoutError:
                pass
            cur = self._versions.get(name, 0)
        return {"version": cur,
                "replicas": list(self.replicas.get(name, []))}

    async def _ensure_loop(self):
        if self._loop_task is None:
            self._loop_task = asyncio.get_running_loop().create_task(
                self._reconcile_loop())

    async def deploy(self, spec: DeploymentSpec) -> bool:
        """Create or update a deployment (idempotent goal-state write).

        A changed callable/config replaces every existing replica — old
        replicas would otherwise keep serving the old code forever (the
        reference rolls replicas on version change,
        deployment_state.py:959)."""
        await self._ensure_loop()
        await self._maybe_restore()
        old = self.deployments.get(spec.name)
        code_changed = old is not None and (
            old.callable_blob != spec.callable_blob or
            old.max_concurrent_queries != spec.max_concurrent_queries or
            old.num_cpus != spec.num_cpus or
            old.resources != spec.resources or
            old.runtime_env != spec.runtime_env)
        config_changed = (old is not None and not code_changed
                          and old.user_config != spec.user_config)
        self.deployments[spec.name] = spec
        self.targets[spec.name] = spec.num_replicas
        if spec.autoscaling:
            lo = spec.autoscaling.get("min_replicas", 1)
            hi = spec.autoscaling.get("max_replicas", spec.num_replicas)
            self.targets[spec.name] = min(max(spec.num_replicas, lo), hi)
        self.replicas.setdefault(spec.name, [])
        if code_changed:
            async with self._reconcile_lock:
                for r in self.replicas.get(spec.name, []):
                    await self._kill_replica(r)
                self.replicas[spec.name] = []
        elif config_changed:
            # Lightweight path: push the new user_config into live
            # replicas in place — no restart, in-flight requests unharmed.
            async with self._reconcile_lock:
                for r in self.replicas.get(spec.name, []):
                    await asyncio.wait_for(
                        r.reconfigure.remote(spec.user_config), timeout=30)
        await self._reconcile_once()
        return True

    async def _kill_replica(self, handle,
                            drain_s: Optional[float] = None):
        """Drain then kill (reference: replica graceful shutdown —
        deployment_state waits for in-flight requests before stopping).
        Bounded by ``RT_SERVE_DRAIN_S`` (poll cadence
        ``RT_SERVE_DRAIN_POLL_S``): a wedged request must not block
        scale-down forever.  Streams still live at the deadline are
        killed with the replica and complete through the ingress's
        mid-stream failover — counted as ``drain_handoffs`` and logged
        as a drain_timeout so operators can tell graceful drains from
        forced ones.  Async kill: the blocking ray_tpu.kill would
        deadlock the actor loop this controller runs on."""
        if drain_s is None:
            drain_s = _env_f("RT_SERVE_DRAIN_S", 10.0)
        poll_s = max(0.01, _env_f("RT_SERVE_DRAIN_POLL_S", 0.1))
        deadline = time.monotonic() + drain_s
        leftover = 0
        while True:
            try:
                leftover = await asyncio.wait_for(
                    handle.queue_len.remote(), timeout=2)
            except Exception:
                leftover = 0
                break   # dead/unreachable: nothing to drain
            if leftover == 0 or time.monotonic() >= deadline:
                break
            await asyncio.sleep(poll_s)
        if leftover:
            logger.warning(
                "serve: drain_timeout — replica %s still had %d in-flight "
                "request(s) after %.1fs; force-failing them over",
                handle._actor_id[:8], leftover, drain_s)
            from ray_tpu.serve import metrics as serve_metrics
            serve_metrics.bump("drain_handoffs", leftover)
        from ray_tpu._private.worker import get_core
        try:
            await get_core().gcs.request({"type": "kill_actor",
                                          "actor_id": handle._actor_id,
                                          "no_restart": True})
        except Exception:
            pass
        # keep the health-grace bookkeeping bounded under replica churn
        self._replica_created.pop(handle._actor_id, None)
        self._replica_seen_healthy.discard(handle._actor_id)

    async def rolling_restart(self, name: str) -> Dict[str, Any]:
        """Replace every replica of ``name`` one at a time with zero
        dropped streams (reference: deployment_state's rolling update,
        one-at-a-time flavor).  Per replica: (1) surge-create the
        replacement and wait until it answers a ping, so serving capacity
        never dips below target; (2) under the reconcile lock, swap it
        into the routing set and bump the long-poll version — routers and
        ingresses stop sending to the victim push-style BEFORE it stops;
        (3) outside the lock, drain the victim (RT_SERVE_DRAIN_S) and
        kill it — streams still live at the drain deadline complete
        through the ingress's mid-stream failover (drain_handoffs)."""
        await self._maybe_restore()
        await self._ensure_loop()
        spec = self.deployments.get(name)
        if spec is None:
            raise ValueError(f"no deployment named {name!r}")
        old_ids = [r._actor_id for r in self.replicas.get(name, [])]
        replaced = 0
        skipped = 0
        for aid in old_ids:
            async with self._reconcile_lock:
                reps = self.replicas.setdefault(name, [])
                victim = next(
                    (r for r in reps if r._actor_id == aid), None)
                if victim is None:
                    skipped += 1   # died and was replaced mid-rollout
                    continue
                fresh = await self._create_replica(name, spec)
                try:
                    await asyncio.wait_for(fresh.ping.remote(),
                                           timeout=120)
                    self._replica_seen_healthy.add(fresh._actor_id)
                except Exception:
                    await self._kill_replica(fresh, drain_s=0)
                    raise RuntimeError(
                        f"rolling_restart({name!r}): replacement replica "
                        "failed to become ready; aborting rollout")
                reps.remove(victim)
                reps.append(fresh)
                # Stop-routing-first: the version bump reaches routers
                # and ingresses (long-poll push) before the victim is
                # touched, so no NEW request lands on it while draining.
                self._bump_version(name)
            await self._kill_replica(victim)
            replaced += 1
        logger.info("serve: rolling restart of %s replaced %d replica(s)"
                    " (%d already gone)", name, replaced, skipped)
        return {"deployment": name, "replaced": replaced,
                "skipped": skipped}

    async def delete_deployment(self, name: str) -> bool:
        # Under the reconcile lock: an in-flight reconcile that already
        # snapshotted this deployment would otherwise recreate (and orphan)
        # replicas right after we kill them.
        await self._maybe_restore()
        async with self._reconcile_lock:
            self.deployments.pop(name, None)
            self.targets.pop(name, None)
            victims = self.replicas.pop(name, [])
            # Routers stop sending FIRST (long-poll push), then drain:
            # draining a replica that still receives traffic never ends.
            self._bump_version(name)
            for r in victims:
                await self._kill_replica(r)
        return True

    async def status(self) -> Dict[str, Any]:
        await self._maybe_restore()
        return {
            name: {
                "target": self.targets.get(name, 0),
                "running": len(self.replicas.get(name, [])),
                "route_prefix": spec.route_prefix,
            }
            for name, spec in self.deployments.items()
        }

    async def get_replicas(self, name: str) -> List:
        """Replica handles for the router (cached client-side)."""
        await self._maybe_restore()
        await self._ensure_loop()   # a restarted controller reconciles
        return list(self.replicas.get(name, []))

    async def routes(self) -> Dict[str, str]:
        """route_prefix -> deployment name (for the HTTP ingress)."""
        return {spec.route_prefix: name
                for name, spec in self.deployments.items()
                if spec.route_prefix}

    async def shutdown(self) -> bool:
        self._shutdown = True
        for name in list(self.deployments):
            await self.delete_deployment(name)
        return True

    # ------------------------------------------------------------ internals

    async def _reconcile_loop(self):
        await self._maybe_restore()
        while not self._shutdown:
            try:
                await self._reconcile_once()
                await self._autoscale()
                await self._publish_status()
            except Exception:
                logger.exception("serve reconcile failed")
            await asyncio.sleep(RECONCILE_PERIOD_S)

    async def _publish_status(self):
        """Push app status into GCS KV so the dashboard (which lives in
        the GCS process, not a worker) can serve /api/serve without a
        cluster client (reference: dashboard/modules/serve/ reads the
        controller through ray calls; here KV is the decoupling).  Uses
        the async GCS channel directly — this coroutine runs ON the core
        IO loop, where the blocking kv_put wrapper would deadlock."""
        import json as _json

        from ray_tpu._private.worker import get_core
        status = {
            name: {
                "target": self.targets.get(name, 0),
                "running": len(self.replicas.get(name, [])),
                "route_prefix": spec.route_prefix,
            }
            for name, spec in self.deployments.items()
        }
        await get_core().gcs.request({
            "type": "kv_put", "ns": "serve", "key": b"status",
            "value": _json.dumps({"deployments": status,
                                  "updated_at": time.time()}).encode(),
            "overwrite": True})
        import cloudpickle
        state = {
            "replica_seq": self._replica_seq,
            "deployments": {
                name: (spec, self.targets.get(name, 0),
                       [r._actor_id for r in self.replicas.get(name, [])])
                for name, spec in self.deployments.items()
            },
        }
        await get_core().gcs.request({
            "type": "kv_put", "ns": "serve", "key": b"state",
            "value": cloudpickle.dumps(state), "overwrite": True})

    async def _create_replica(self, name: str, spec: DeploymentSpec):
        """Create one replica actor for ``name`` and return its handle.
        Callers must hold ``_reconcile_lock`` (or be the reconcile loop
        itself) — creation mutates the shared replica bookkeeping."""
        from ray_tpu._private.worker import get_core
        from ray_tpu.actor import ActorHandle
        self._replica_seq += 1
        resources = {"CPU": spec.num_cpus, **(spec.resources or {})}
        # max_concurrency has headroom over the request bound: requests
        # queue inside the replica (visible to queue_len) instead of at
        # the actor layer.
        scheduling = None
        if spec.runtime_env:
            from ray_tpu.remote_function import _build_scheduling
            scheduling = _build_scheduling(
                {"runtime_env": spec.runtime_env})
        actor_id = await get_core().create_actor_async(
            Replica,
            (spec.callable_blob, spec.max_concurrent_queries,
             spec.user_config),
            {},
            resources=resources,
            scheduling=scheduling,
            max_concurrency=4 * spec.max_concurrent_queries + 8,
            name=f"_serve:{name}:{self._replica_seq}")
        self._replica_created[actor_id] = time.monotonic()
        return ActorHandle(actor_id, "Replica")

    async def _reconcile_once(self):
        async def probe(r):
            aid = r._actor_id
            fresh = aid not in self._replica_seen_healthy
            if fresh and time.monotonic() - self._replica_created.get(
                    aid, 0.0) < 120.0:
                # Init grace: give a replica still constructing (model
                # load / jit compile) the full window before the 10s
                # liveness bar applies.
                try:
                    await asyncio.wait_for(r.ping.remote(), timeout=1.0)
                    self._replica_seen_healthy.add(aid)
                except Exception:
                    pass
                return True
            try:
                # ObjectRef is awaitable; wait_for wraps it.
                await asyncio.wait_for(r.ping.remote(), timeout=10)
                self._replica_seen_healthy.add(aid)
                return True
            except Exception:
                return False

        async with self._reconcile_lock:
            for name, spec in list(self.deployments.items()):
                reps = self.replicas.setdefault(name, [])
                before = [r._actor_id for r in reps]
                target = self.targets.get(name, spec.num_replicas)
                # Probe health in parallel; kill-and-replace failures (a
                # merely dropped replica would keep running and leak its
                # resource reservation).
                oks = await asyncio.gather(*[probe(r) for r in reps])
                for r, ok in zip(list(reps), oks):
                    if not ok:
                        logger.warning("serve: replica of %s unhealthy, "
                                       "replacing", name)
                        await self._kill_replica(r)
                reps[:] = [r for r, ok in zip(reps, oks) if ok]
                while len(reps) < target:
                    reps.append(await self._create_replica(name, spec))
                victims = []
                while len(reps) > target:
                    victims.append(reps.pop())
                if [r._actor_id for r in reps] != before:
                    self._bump_version(name)   # before draining victims
                for v in victims:
                    await self._kill_replica(v)

    async def _autoscale(self):
        """Queue-depth autoscaling (reference: autoscaling_policy.py:93)."""
        for name, spec in list(self.deployments.items()):
            cfg = spec.autoscaling
            reps = self.replicas.get(name, [])
            if not cfg or not reps:
                continue
            try:
                qs = await asyncio.gather(
                    *[asyncio.wait_for(r.queue_len.remote(), timeout=10)
                      for r in reps])
            except Exception:
                continue
            mean_q = sum(qs) / len(qs)
            hist = self._metrics.setdefault(name, [])
            hist.append(mean_q)
            del hist[:-5]
            target_q = cfg.get("target_queue_len", 2.0)
            lo = cfg.get("min_replicas", 1)
            hi = cfg.get("max_replicas", spec.num_replicas)
            cur = self.targets.get(name, len(reps))
            smoothed = sum(hist) / len(hist)
            if smoothed > target_q and cur < hi:
                self.targets[name] = min(hi, cur + 1)
                logger.info("serve: scaling %s up to %d (queue %.1f)",
                            name, self.targets[name], smoothed)
            elif smoothed < 0.5 * target_q and cur > lo and len(hist) >= 5:
                self.targets[name] = max(lo, cur - 1)
                logger.info("serve: scaling %s down to %d (queue %.1f)",
                            name, self.targets[name], smoothed)
