"""HTTP ingress actor: asyncio HTTP/1.1 server routing to replicas,
with token-streaming responses.

Reference analog: HTTPProxyActor + LongestPrefixRouter
(_private/http_proxy.py:387,143).  No aiohttp/starlette in this image, so
the request loop is a small hand-rolled HTTP/1.1 parser: request line +
headers + Content-Length body, JSON in/out.

Everything here is async-on-the-actor-loop; sync ray_tpu calls (which block
on the same loop) are never used — the controller is resolved through an
async GCS lookup and replicas are called by awaiting their ObjectRefs.

POST /<route_prefix>  body=JSON  ->  result of deployment(body)
GET  /-/routes                   ->  route table
GET  /-/healthz                  ->  "ok"

**Streaming.**  A request with ``"stream": true`` in its JSON body (or
``Accept: text/event-stream``) is routed through the replica's streaming
path (``handle_stream`` + ``num_returns="streaming"``): the response is
``Transfer-Encoding: chunked`` Server-Sent Events, one ``data:`` event
per yielded item, flushed as produced — the client reads the first token
while the replica is still generating.  The stream ends with an
``event: end`` record and the chunked terminator; the connection stays
keep-alive.  A client that disconnects (or stops reading past the write
timeout) cancels the replica-side stream, which frees the engine's KV
pages.

**Self-protection.**  Connection storms are load-shed at accept time
(429 + Retry-After once ``max_connections`` are live); malformed or
oversized requests get clean 400/413s instead of a hung reader; every
socket read and write is bounded by a timeout, with the slow-client
fault hook (``util.fault_injection``) injected inside the drain so
chaos tests can trip the write path deterministically.
"""

from __future__ import annotations

import asyncio

from ray_tpu._private.async_utils import spawn
import itertools
import json
import logging
import os
from typing import Dict, Optional, Tuple

logger = logging.getLogger(__name__)

_MAX_HEADERS = 64


async def _materialize(item):
    from ray_tpu._private.object_ref import ObjectRef
    if isinstance(item, ObjectRef):
        return await item
    return item


def _env_f(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, default))
    except ValueError:
        return default


class _BadRequest(Exception):
    def __init__(self, code: int, message: str):
        super().__init__(message)
        self.code = code


class HTTPIngress:
    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 namespace: str = "default", *,
                 max_connections: Optional[int] = None,
                 max_body_bytes: Optional[int] = None,
                 read_timeout_s: Optional[float] = None,
                 write_timeout_s: Optional[float] = None,
                 stream_idle_timeout_s: Optional[float] = None):
        self._host, self._port = host, port
        self._namespace = namespace
        self._server = None
        self._routes: Dict[str, str] = {}
        self._replicas: Dict[str, list] = {}
        self._rr = itertools.count()
        self._ctrl = None
        self._nconn = 0
        self._shed = 0          # connections 429'd (observability)
        self._max_conn = int(max_connections if max_connections is not None
                             else _env_f("RT_SERVE_MAX_CONNECTIONS", 256))
        self._max_body = int(max_body_bytes if max_body_bytes is not None
                             else _env_f("RT_SERVE_MAX_BODY_BYTES",
                                         10 * 1024 * 1024))
        self._read_timeout = (read_timeout_s if read_timeout_s is not None
                              else _env_f("RT_SERVE_READ_TIMEOUT_S", 120.0))
        self._write_timeout = (write_timeout_s
                               if write_timeout_s is not None
                               else _env_f("RT_SERVE_WRITE_TIMEOUT_S", 30.0))
        self._stream_idle = (stream_idle_timeout_s
                             if stream_idle_timeout_s is not None
                             else _env_f("RT_SERVE_STREAM_IDLE_S", 120.0))

    async def _ensure_started(self):
        if self._server is not None:
            return
        self._server = await asyncio.start_server(
            self._serve_conn, self._host, self._port)
        self._port = self._server.sockets[0].getsockname()[1]
        self._route_refresh_task = spawn(
            self._route_refresh_loop(), name="ingress-route-refresh")

    async def address(self) -> Tuple[str, int]:
        await self._ensure_started()
        return (self._host, self._port)

    async def stats(self) -> Dict[str, int]:
        return {"connections": self._nconn, "shed": self._shed,
                "max_connections": self._max_conn}

    async def _controller(self):
        if self._ctrl is None:
            from ray_tpu._private.worker import get_core
            from ray_tpu.actor import ActorHandle
            from ray_tpu.serve.controller import CONTROLLER_NAME
            info = await get_core().gcs.request(
                {"type": "get_named_actor", "name": CONTROLLER_NAME,
                 "namespace": self._namespace})
            if info is None:
                raise RuntimeError("serve controller not running")
            self._ctrl = ActorHandle(info["actor_id"], "ServeController")
        return self._ctrl

    async def _route_refresh_loop(self):
        while True:
            try:
                ctrl = await self._controller()
                self._routes = await ctrl.routes.remote()
                for name in set(self._routes.values()):
                    self._replicas[name] = \
                        await ctrl.get_replicas.remote(name)
            except Exception:
                self._ctrl = None  # controller restarted; re-resolve
            await asyncio.sleep(1.0)

    def _match_route(self, path: str) -> Optional[str]:
        # Longest matching route prefix wins, on path-segment boundaries
        # (http_proxy.py:143 LongestPrefixRouter): /echo matches /echo and
        # /echo/x but not /echoes.
        target: Optional[str] = None
        best = -1
        for prefix, name in self._routes.items():
            p = prefix.rstrip("/")
            if (path == p or path.startswith(p + "/")) and len(p) > best:
                target, best = name, len(p)
        return target

    async def _pick_replica(self, name: str):
        reps = self._replicas.get(name)
        if not reps:
            ctrl = await self._controller()
            reps = self._replicas[name] = \
                await ctrl.get_replicas.remote(name)
        if not reps:
            raise RuntimeError(f"deployment {name} has no replicas")
        return reps[next(self._rr) % len(reps)]

    async def _call(self, name: str, payload):
        replica = await self._pick_replica(name)
        return await replica.handle_request.remote([payload], {}, None)

    async def _call_stream(self, name: str, payload):
        """StreamingObjectRefGenerator of the replica handler's yields."""
        replica = await self._pick_replica(name)
        return replica.handle_stream.options(
            num_returns="streaming").remote([payload], {}, None)

    # --------------------------------------------------------- connection

    async def _serve_conn(self, reader: asyncio.StreamReader,
                          writer: asyncio.StreamWriter):
        if self._nconn >= self._max_conn:
            # Load shedding: a storm of connections must not starve the
            # live ones (or the event loop).  Shed at accept with an
            # explicit retry hint; /-/healthz stays responsive because
            # established connections still serve.
            self._shed += 1
            try:
                await self._respond(writer, 429,
                                    {"error": "too many connections"},
                                    extra_headers={"Retry-After": "1"},
                                    close=True)
            except Exception:
                pass
            finally:
                writer.close()
            return
        self._nconn += 1
        try:
            while True:
                try:
                    line = await asyncio.wait_for(
                        reader.readline(), self._read_timeout)
                except (asyncio.TimeoutError, ValueError):
                    return   # idle keep-alive or oversized request line
                if not line or line in (b"\r\n", b"\n"):
                    return
                try:
                    method, path, _ = line.decode().split(" ", 2)
                except ValueError:
                    return await self._respond(
                        writer, 400, {"error": "bad request"}, close=True)
                try:
                    headers, body = await self._read_request(reader)
                except _BadRequest as e:
                    # The body was not (fully) read: the connection can't
                    # be reused safely, so answer and close.
                    return await self._respond(
                        writer, e.code, {"error": str(e)}, close=True)
                except (asyncio.TimeoutError, ValueError,
                        asyncio.IncompleteReadError):
                    return   # client stopped mid-request: nothing to say
                keep = headers.get("connection", "").lower() != "close"
                await self._dispatch(writer, method, path, headers, body)
                if not keep:
                    return
        except (asyncio.IncompleteReadError, ConnectionResetError,
                BrokenPipeError, asyncio.TimeoutError):
            pass
        finally:
            self._nconn -= 1
            try:
                writer.close()
            except Exception:
                pass

    async def _read_request(self, reader) -> Tuple[Dict[str, str], bytes]:
        headers: Dict[str, str] = {}
        for _ in range(_MAX_HEADERS):
            h = await asyncio.wait_for(reader.readline(),
                                       self._read_timeout)
            if h in (b"\r\n", b"\n", b""):
                break
            k, sep, v = h.decode("latin-1").partition(":")
            if sep:
                headers[k.strip().lower()] = v.strip()
        else:
            raise _BadRequest(400, "too many headers")
        raw_n = headers.get("content-length", "0") or "0"
        try:
            n = int(raw_n)
            if n < 0:
                raise ValueError
        except ValueError:
            # A reader that trusted this value would hang waiting for a
            # body that never comes (or worse, int("1e9")-style garbage).
            raise _BadRequest(400,
                              f"malformed content-length {raw_n!r}") from None
        if n > self._max_body:
            raise _BadRequest(413, f"body of {n} bytes exceeds limit "
                                   f"{self._max_body}")
        body = b""
        if n:
            body = await asyncio.wait_for(reader.readexactly(n),
                                          self._read_timeout)
        return headers, body

    # ----------------------------------------------------------- dispatch

    async def _dispatch(self, writer, method: str, path: str,
                        headers: Dict[str, str], body: bytes):
        path = path.split("?", 1)[0]  # health checks may append queries
        if path == "/-/healthz":
            return await self._respond(writer, 200, "ok")
        if path == "/-/routes":
            return await self._respond(writer, 200, self._routes)
        target = self._match_route(path)
        if target is None:
            # Route-table miss: the background refresh runs on a 1s
            # cadence, so a request racing a fresh serve.run (or a fresh
            # ingress) would 404 spuriously.  Pull the table once,
            # synchronously, before giving up.
            try:
                ctrl = await self._controller()
                self._routes = await ctrl.routes.remote()
            except Exception:
                self._ctrl = None
            target = self._match_route(path)
        if target is None:
            return await self._respond(writer, 404,
                                       {"error": f"no route for {path}"})
        try:
            payload = json.loads(body) if body else None
        except json.JSONDecodeError:
            payload = body.decode("utf-8", "replace")
        streaming = ("text/event-stream" in headers.get("accept", "")
                     or (isinstance(payload, dict)
                         and payload.get("stream") is True))
        if streaming:
            return await self._dispatch_stream(writer, target, payload)
        try:
            result = await self._call(target, payload)
            await self._respond(writer, 200, {"result": result})
        except Exception as e:  # noqa: BLE001
            logger.exception("serve http: request to %s failed", target)
            await self._respond(writer, 500, {"error": repr(e)})

    async def _dispatch_stream(self, writer, target: str, payload):
        """SSE token stream: chunked transfer, one data event per yield,
        flushed as produced.  Client disconnect / write timeout / idle
        stream all cancel the replica-side generator."""
        try:
            gen = await self._call_stream(target, payload)
        except Exception as e:   # noqa: BLE001
            logger.exception("serve http: stream to %s failed to start",
                             target)
            return await self._respond(writer, 500, {"error": repr(e)})
        await self._write(
            writer,
            b"HTTP/1.1 200 OK\r\n"
            b"Content-Type: text/event-stream\r\n"
            b"Cache-Control: no-cache\r\n"
            b"Transfer-Encoding: chunked\r\n\r\n")
        try:
            while True:
                try:
                    # Each stream item is a per-yield ObjectRef (the
                    # generator owner side of num_returns="streaming");
                    # awaiting the ref materializes the token.
                    item = await asyncio.wait_for(gen.__anext__(),
                                                  self._stream_idle)
                    item = await asyncio.wait_for(
                        _materialize(item), self._stream_idle)
                except StopAsyncIteration:
                    await self._write_event(writer, "end", {})
                    break
                except asyncio.TimeoutError:
                    gen.cancel()
                    await self._write_event(
                        writer, "error",
                        {"error": f"stream idle for {self._stream_idle}s"})
                    break
                except Exception as e:   # noqa: BLE001 handler raised
                    await self._write_event(writer, "error",
                                            {"error": repr(e)})
                    break
                await self._write_event(writer, None, item)
            await self._write(writer, b"0\r\n\r\n")   # chunk terminator
        except (ConnectionResetError, BrokenPipeError,
                asyncio.TimeoutError):
            # Client gone (or reading too slowly): tear down the
            # replica-side stream so the engine frees its KV pages.
            gen.cancel()
            raise

    async def _write_event(self, writer, event: Optional[str], data):
        payload = (f"event: {event}\n" if event else "") + \
            "data: " + json.dumps(data, default=repr) + "\n\n"
        raw = payload.encode()
        await self._write(writer,
                          f"{len(raw):x}\r\n".encode() + raw + b"\r\n")

    async def _drain(self, writer):
        from ray_tpu.util import fault_injection
        delay = fault_injection.slow_client_delay_s()
        if delay:
            await asyncio.sleep(delay)
        await writer.drain()

    async def _write(self, writer, data: bytes):
        """All socket writes funnel here: a client that stops reading
        (full TCP window) parks drain(); the timeout converts that into
        an abort instead of an ingress slot leaked forever."""
        writer.write(data)
        await asyncio.wait_for(self._drain(writer), self._write_timeout)

    async def _respond(self, writer, code: int, payload,
                       extra_headers: Optional[Dict[str, str]] = None,
                       close: bool = False):
        if isinstance(payload, str):
            data = payload.encode()
            ctype = "text/plain"
        else:
            data = json.dumps(payload, default=repr).encode()
            ctype = "application/json"
        reason = {200: "OK", 400: "Bad Request", 404: "Not Found",
                  413: "Payload Too Large", 429: "Too Many Requests",
                  500: "Internal Server Error"}.get(code, "ERR")
        extra = "".join(f"{k}: {v}\r\n"
                        for k, v in (extra_headers or {}).items())
        if close:
            extra += "Connection: close\r\n"
        await self._write(
            writer,
            f"HTTP/1.1 {code} {reason}\r\n"
            f"Content-Type: {ctype}\r\n"
            f"Content-Length: {len(data)}\r\n{extra}\r\n".encode() + data)
        if close:
            writer.close()
