"""HTTP ingress actor: minimal asyncio HTTP/1.1 server routing to replicas.

Reference analog: HTTPProxyActor + LongestPrefixRouter
(_private/http_proxy.py:387,143).  No aiohttp/starlette in this image, so
the request loop is a small hand-rolled HTTP/1.1 parser: request line +
headers + Content-Length body, JSON in/out.

Everything here is async-on-the-actor-loop; sync ray_tpu calls (which block
on the same loop) are never used — the controller is resolved through an
async GCS lookup and replicas are called by awaiting their ObjectRefs.

POST /<route_prefix>  body=JSON  ->  result of deployment(body)
GET  /-/routes                   ->  route table
GET  /-/healthz                  ->  "ok"
"""

from __future__ import annotations

import asyncio
import itertools
import json
import logging
from typing import Dict, Optional, Tuple

logger = logging.getLogger(__name__)


class HTTPIngress:
    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 namespace: str = "default"):
        self._host, self._port = host, port
        self._namespace = namespace
        self._server = None
        self._routes: Dict[str, str] = {}
        self._replicas: Dict[str, list] = {}
        self._rr = itertools.count()
        self._ctrl = None

    async def _ensure_started(self):
        if self._server is not None:
            return
        self._server = await asyncio.start_server(
            self._serve_conn, self._host, self._port)
        self._port = self._server.sockets[0].getsockname()[1]
        asyncio.get_running_loop().create_task(self._route_refresh_loop())

    async def address(self) -> Tuple[str, int]:
        await self._ensure_started()
        return (self._host, self._port)

    async def _controller(self):
        if self._ctrl is None:
            from ray_tpu._private.worker import get_core
            from ray_tpu.actor import ActorHandle
            from ray_tpu.serve.controller import CONTROLLER_NAME
            info = await get_core().gcs.request(
                {"type": "get_named_actor", "name": CONTROLLER_NAME,
                 "namespace": self._namespace})
            if info is None:
                raise RuntimeError("serve controller not running")
            self._ctrl = ActorHandle(info["actor_id"], "ServeController")
        return self._ctrl

    async def _route_refresh_loop(self):
        while True:
            try:
                ctrl = await self._controller()
                self._routes = await ctrl.routes.remote()
                for name in set(self._routes.values()):
                    self._replicas[name] = \
                        await ctrl.get_replicas.remote(name)
            except Exception:
                self._ctrl = None  # controller restarted; re-resolve
            await asyncio.sleep(1.0)

    async def _call(self, name: str, payload):
        reps = self._replicas.get(name)
        if not reps:
            ctrl = await self._controller()
            reps = self._replicas[name] = \
                await ctrl.get_replicas.remote(name)
        if not reps:
            raise RuntimeError(f"deployment {name} has no replicas")
        replica = reps[next(self._rr) % len(reps)]
        return await replica.handle_request.remote([payload], {}, None)

    async def _serve_conn(self, reader: asyncio.StreamReader,
                          writer: asyncio.StreamWriter):
        try:
            while True:
                line = await reader.readline()
                if not line or line in (b"\r\n", b"\n"):
                    return
                try:
                    method, path, _ = line.decode().split(" ", 2)
                except ValueError:
                    return await self._respond(writer, 400,
                                               {"error": "bad request"})
                headers = {}
                while True:
                    h = await reader.readline()
                    if h in (b"\r\n", b"\n", b""):
                        break
                    k, _, v = h.decode().partition(":")
                    headers[k.strip().lower()] = v.strip()
                body = b""
                n = int(headers.get("content-length", 0) or 0)
                if n:
                    body = await reader.readexactly(n)
                keep = headers.get("connection", "").lower() != "close"
                await self._dispatch(writer, method, path, body)
                if not keep:
                    return
        except (asyncio.IncompleteReadError, ConnectionResetError):
            pass
        finally:
            try:
                writer.close()
            except Exception:
                pass

    async def _dispatch(self, writer, method: str, path: str, body: bytes):
        path = path.split("?", 1)[0]  # health checks may append queries
        if path == "/-/healthz":
            return await self._respond(writer, 200, "ok")
        if path == "/-/routes":
            return await self._respond(writer, 200, self._routes)
        # Longest matching route prefix wins, on path-segment boundaries
        # (http_proxy.py:143 LongestPrefixRouter): /echo matches /echo and
        # /echo/x but not /echoes.
        target: Optional[str] = None
        best = -1
        for prefix, name in self._routes.items():
            p = prefix.rstrip("/")
            if (path == p or path.startswith(p + "/")) and len(p) > best:
                target, best = name, len(p)
        if target is None:
            return await self._respond(writer, 404,
                                       {"error": f"no route for {path}"})
        try:
            payload = json.loads(body) if body else None
        except json.JSONDecodeError:
            payload = body.decode("utf-8", "replace")
        try:
            result = await self._call(target, payload)
            await self._respond(writer, 200, {"result": result})
        except Exception as e:  # noqa: BLE001
            logger.exception("serve http: request to %s failed", target)
            await self._respond(writer, 500, {"error": repr(e)})

    async def _respond(self, writer, code: int, payload):
        if isinstance(payload, str):
            data = payload.encode()
            ctype = "text/plain"
        else:
            data = json.dumps(payload, default=repr).encode()
            ctype = "application/json"
        writer.write(
            f"HTTP/1.1 {code} {'OK' if code == 200 else 'ERR'}\r\n"
            f"Content-Type: {ctype}\r\n"
            f"Content-Length: {len(data)}\r\n\r\n".encode() + data)
        await writer.drain()
