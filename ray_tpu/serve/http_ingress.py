"""HTTP ingress actor: asyncio HTTP/1.1 server routing to replicas,
with token-streaming responses and zero-loss failover.

Reference analog: HTTPProxyActor + LongestPrefixRouter
(_private/http_proxy.py:387,143).  No aiohttp/starlette in this image, so
the request loop is a small hand-rolled HTTP/1.1 parser: request line +
headers + Content-Length body, JSON in/out.

Everything here is async-on-the-actor-loop; sync ray_tpu calls (which block
on the same loop) are never used — the controller is resolved through an
async GCS lookup and replicas are called by awaiting their ObjectRefs.

POST /<route_prefix>  body=JSON  ->  result of deployment(body)
GET  /-/routes                   ->  route table
GET  /-/healthz                  ->  "ok"

**Streaming.**  A request with ``"stream": true`` in its JSON body (or
``Accept: text/event-stream``) is routed through the replica's streaming
path (``handle_stream`` + ``num_returns="streaming"``): the response is
``Transfer-Encoding: chunked`` Server-Sent Events, one ``data:`` event
per yielded item, flushed as produced — the client reads the first token
while the replica is still generating.  The stream ends with an
``event: end`` record and the chunked terminator; the connection stays
keep-alive.  A client that disconnects (or stops reading past the write
timeout) cancels the replica-side stream, which frees the engine's KV
pages.

**Resilience** (see ``serve/resilience.py`` for the state machines):

* *Mid-stream failover.*  The ingress records each live stream's request
  payload and the items already delivered to the client.  When the
  serving replica dies (ActorDiedError from the stream) or stalls past
  ``RT_SERVE_STALL_S``, the ingress cancels the broken stream, picks a
  healthy replica, and resumes: for token-generation payloads
  (``{"tokens": [...], "max_new_tokens": N}``) it re-prefills
  ``prompt + delivered`` with the remaining token budget — under greedy
  decoding the resumed tail is bit-identical to an uninterrupted run —
  and for opaque payloads it replays the request and skips the items
  already delivered.  The client's SSE stream never breaks; a resumed
  stream bumps the ``streams_resumed`` counter.

* *Circuit breaking + bounded retry.*  Per-replica consecutive-failure
  breakers (``RT_SERVE_CB_THRESHOLD``/``RT_SERVE_CB_COOLDOWN_S``) eject
  failing replicas from routing with half-open probe re-admission; every
  request carries a retry budget (``RT_SERVE_RETRY_BUDGET``) spent on
  exponential-backoff-with-jitter re-sends (``router_retries`` counter).
  Budget exhausted or no routable replica → 503.

* *Deadlines.*  ``x-request-deadline-s`` header (or ``deadline_s`` in
  the JSON body) sets an absolute end-to-end deadline propagated to the
  replica and engine; expiry → 504, with replica-side decode cancelled
  and its KV pages freed.

* *Push-based replica discovery.*  A long-poll listener per routed
  deployment (controller ``listen_for_change``) replaces the 1s replica
  poll: stop-routing decisions (rolling restart, scale-down) reach the
  ingress the moment the controller bumps the version, not a poll period
  later.  Controller loss falls back to exponential-backoff re-resolve
  (``ctrl_reresolves`` in ``stats()``) instead of a tight retry loop.

**Self-protection.**  Connection storms are load-shed at accept time
(429 + Retry-After once ``max_connections`` are live); malformed or
oversized requests get clean 400/413s instead of a hung reader; every
socket read and write is bounded by a timeout, with the slow-client
fault hook (``util.fault_injection``) injected inside the drain so
chaos tests can trip the write path deterministically.
"""

from __future__ import annotations

import asyncio

from ray_tpu._private.async_utils import spawn
import itertools
import json
import logging
import os
import time
from typing import Dict, Optional, Tuple

from ray_tpu.serve import metrics as serve_metrics
from ray_tpu.serve import resilience

logger = logging.getLogger(__name__)

_MAX_HEADERS = 64


async def _materialize(item):
    from ray_tpu._private.object_ref import ObjectRef
    if isinstance(item, ObjectRef):
        return await item
    return item


def _env_f(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, default))
    except ValueError:
        return default


class _BadRequest(Exception):
    def __init__(self, code: int, message: str):
        super().__init__(message)
        self.code = code


class _Unavailable(Exception):
    """No routable replica within the retry budget (HTTP 503)."""


class HTTPIngress:
    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 namespace: str = "default", *,
                 max_connections: Optional[int] = None,
                 max_body_bytes: Optional[int] = None,
                 read_timeout_s: Optional[float] = None,
                 write_timeout_s: Optional[float] = None,
                 stream_idle_timeout_s: Optional[float] = None,
                 stall_timeout_s: Optional[float] = None):
        self._host, self._port = host, port
        self._namespace = namespace
        self._server = None
        self._routes: Dict[str, str] = {}
        self._replicas: Dict[str, list] = {}
        self._rr = itertools.count()
        self._ctrl = None
        self._nconn = 0
        self._shed = 0          # connections 429'd (observability)
        self._cb = resilience.CircuitBreaker(
            on_open=lambda rid: serve_metrics.bump("circuit_open"))
        self._listen_tasks: Dict[str, asyncio.Task] = {}
        # Controller re-resolve backoff: repeated failures (controller
        # restarting, GCS briefly away) grow the retry interval instead of
        # hammering the GCS with a lookup per request per second.
        self._ctrl_failures = 0
        self._ctrl_retry_at = 0.0         # monotonic gate
        self._ctrl_reresolves = 0         # successful re-resolves (stats)
        self._max_conn = int(max_connections if max_connections is not None
                             else _env_f("RT_SERVE_MAX_CONNECTIONS", 256))
        self._max_body = int(max_body_bytes if max_body_bytes is not None
                             else _env_f("RT_SERVE_MAX_BODY_BYTES",
                                         10 * 1024 * 1024))
        self._read_timeout = (read_timeout_s if read_timeout_s is not None
                              else _env_f("RT_SERVE_READ_TIMEOUT_S", 120.0))
        self._write_timeout = (write_timeout_s
                               if write_timeout_s is not None
                               else _env_f("RT_SERVE_WRITE_TIMEOUT_S", 30.0))
        self._stream_idle = (stream_idle_timeout_s
                             if stream_idle_timeout_s is not None
                             else _env_f("RT_SERVE_STREAM_IDLE_S", 120.0))
        # A stream quiet past this long is treated as a stalled replica
        # and failed over (vs. _stream_idle, which is the terminal bound).
        self._stall_s = (stall_timeout_s if stall_timeout_s is not None
                         else _env_f("RT_SERVE_STALL_S", 30.0))

    async def _ensure_started(self):
        if self._server is not None:
            return
        self._server = await asyncio.start_server(
            self._serve_conn, self._host, self._port)
        self._port = self._server.sockets[0].getsockname()[1]
        self._route_refresh_task = spawn(
            self._route_refresh_loop(), name="ingress-route-refresh")

    async def address(self) -> Tuple[str, int]:
        await self._ensure_started()
        return (self._host, self._port)

    async def stats(self) -> Dict[str, int]:
        return {"connections": self._nconn, "shed": self._shed,
                "max_connections": self._max_conn,
                "ctrl_reresolves": self._ctrl_reresolves,
                **serve_metrics.stats()}

    # ------------------------------------------------- controller discovery

    async def _controller(self):
        if self._ctrl is None:
            if time.monotonic() < self._ctrl_retry_at:
                raise RuntimeError("serve controller unavailable "
                                   "(re-resolve backing off)")
            from ray_tpu._private.worker import get_core
            from ray_tpu.actor import ActorHandle
            from ray_tpu.serve.controller import CONTROLLER_NAME
            try:
                info = await get_core().gcs.request(
                    {"type": "get_named_actor", "name": CONTROLLER_NAME,
                     "namespace": self._namespace})
            except Exception:
                self._ctrl_backoff()
                raise
            if info is None:
                self._ctrl_backoff()
                raise RuntimeError("serve controller not running")
            self._ctrl = ActorHandle(info["actor_id"], "ServeController")
            if self._ctrl_failures:
                # Dual-sink: the local attribute feeds this ingress's
                # stats(); the registry counter survives the node-stats ->
                # GCS-fold -> /api/metrics chain (the attribute alone was
                # invisible off-process).
                self._ctrl_reresolves += 1
                serve_metrics.bump("ctrl_reresolves")
            self._ctrl_failures = 0
        return self._ctrl

    def _ctrl_backoff(self):
        self._ctrl_failures += 1
        delay = min(8.0, 0.25 * (2 ** min(self._ctrl_failures, 6)))
        self._ctrl_retry_at = time.monotonic() + delay

    def _ctrl_lost(self):
        """A call through the cached handle failed: drop it so the next
        _controller() re-resolves (through the backoff gate)."""
        self._ctrl = None
        self._ctrl_backoff()

    async def _route_refresh_loop(self):
        while True:
            try:
                ctrl = await self._controller()
                self._routes = await ctrl.routes.remote()
                names = set(self._routes.values())
                for name in names:
                    t = self._listen_tasks.get(name)
                    if t is None or t.done():
                        self._listen_tasks[name] = spawn(
                            self._listen_replicas(name),
                            name=f"ingress-listen-{name}")
                for name in list(self._listen_tasks):
                    if name not in names:
                        self._listen_tasks.pop(name).cancel()
                        self._replicas.pop(name, None)
            except Exception:
                self._ctrl_lost()  # controller restarted; re-resolve
            await asyncio.sleep(1.0)

    async def _listen_replicas(self, name: str):
        """Long-poll the controller for replica-set changes (push, not
        poll): a rolling restart's stop-routing version bump lands here
        the moment it happens, so no new stream targets a draining
        replica."""
        version = -1
        while True:
            try:
                ctrl = await self._controller()
                upd = await asyncio.wait_for(
                    ctrl.listen_for_change.remote(name, version, 25.0),
                    timeout=40.0)
                version = upd["version"]
                self._replicas[name] = upd["replicas"]
                live = {r._actor_id
                        for reps in self._replicas.values() for r in reps}
                self._cb.forget_missing(live)
            except asyncio.CancelledError:
                raise
            except Exception:
                self._ctrl_lost()
                await asyncio.sleep(
                    min(8.0, 0.25 * (2 ** min(self._ctrl_failures, 6))))

    # ------------------------------------------------------------- routing

    def _match_route(self, path: str) -> Optional[str]:
        # Longest matching route prefix wins, on path-segment boundaries
        # (http_proxy.py:143 LongestPrefixRouter): /echo matches /echo and
        # /echo/x but not /echoes.
        target: Optional[str] = None
        best = -1
        for prefix, name in self._routes.items():
            p = prefix.rstrip("/")
            if (path == p or path.startswith(p + "/")) and len(p) > best:
                target, best = name, len(p)
        return target

    async def _pick_replica(self, name: str,
                            exclude: Optional[set] = None):
        reps = self._replicas.get(name)
        if not reps:
            ctrl = await self._controller()
            reps = self._replicas[name] = \
                await ctrl.get_replicas.remote(name)
        if not reps:
            raise _Unavailable(f"deployment {name} has no replicas")
        picked = self._cb.select(reps, next(self._rr), exclude=exclude)
        if picked is None:
            # Everything routable is ejected or excluded: maybe the
            # controller already replaced the dead replicas — refresh the
            # set once before giving up.
            try:
                ctrl = await self._controller()
                reps = self._replicas[name] = \
                    await ctrl.get_replicas.remote(name)
            except Exception:
                reps = []
            picked = self._cb.select(reps, next(self._rr), exclude=exclude)
        if picked is None:
            raise _Unavailable(
                f"deployment {name} has no routable replica "
                "(all ejected or excluded)")
        return picked

    def _expired(self, deadline: Optional[float]) -> bool:
        rem = resilience.deadline_remaining(deadline)
        return rem is not None and rem <= 0

    async def _call(self, name: str, payload,
                    deadline: Optional[float] = None):
        """Unary call with circuit breaking + bounded backoff retry."""
        policy = resilience.RetryPolicy()
        exclude: set = set()
        while True:
            if self._expired(deadline):
                raise resilience.DeadlineExceeded(
                    "request deadline expired before completion")
            replica = await self._pick_replica(name, exclude)
            rid = replica._actor_id
            try:
                result = await replica.handle_request.remote(
                    [payload], {}, None, deadline)
            except Exception as e:   # noqa: BLE001
                if not resilience.is_retryable_error(e):
                    raise
                self._cb.record_failure(rid)
                exclude.add(rid)
                self._replicas.pop(name, None)   # force a refresh
                if not policy.can_retry():
                    raise _Unavailable(
                        f"retry budget exhausted for {name}: {e!r}") from e
                serve_metrics.bump("router_retries")
                await asyncio.sleep(policy.next_backoff_s(deadline))
                continue
            self._cb.record_success(rid)
            return result

    async def _call_stream(self, name: str, payload,
                           deadline: Optional[float] = None,
                           exclude: Optional[set] = None):
        """StreamingObjectRefGenerator of the replica handler's yields;
        returns (generator, replica_actor_id)."""
        replica = await self._pick_replica(name, exclude)
        gen = replica.handle_stream.options(
            num_returns="streaming").remote([payload], {}, None, deadline)
        return gen, replica._actor_id

    # --------------------------------------------------------- connection

    async def _serve_conn(self, reader: asyncio.StreamReader,
                          writer: asyncio.StreamWriter):
        if self._nconn >= self._max_conn:
            # Load shedding: a storm of connections must not starve the
            # live ones (or the event loop).  Shed at accept with an
            # explicit retry hint; /-/healthz stays responsive because
            # established connections still serve.
            self._shed += 1
            try:
                await self._respond(writer, 429,
                                    {"error": "too many connections"},
                                    extra_headers={"Retry-After": "1"},
                                    close=True)
            except Exception:
                pass
            finally:
                writer.close()
            return
        self._nconn += 1
        try:
            while True:
                try:
                    line = await asyncio.wait_for(
                        reader.readline(), self._read_timeout)
                except (asyncio.TimeoutError, ValueError):
                    return   # idle keep-alive or oversized request line
                if not line or line in (b"\r\n", b"\n"):
                    return
                try:
                    method, path, _ = line.decode().split(" ", 2)
                except ValueError:
                    return await self._respond(
                        writer, 400, {"error": "bad request"}, close=True)
                try:
                    headers, body = await self._read_request(reader)
                except _BadRequest as e:
                    # The body was not (fully) read: the connection can't
                    # be reused safely, so answer and close.
                    return await self._respond(
                        writer, e.code, {"error": str(e)}, close=True)
                except (asyncio.TimeoutError, ValueError,
                        asyncio.IncompleteReadError):
                    return   # client stopped mid-request: nothing to say
                keep = headers.get("connection", "").lower() != "close"
                await self._dispatch(writer, method, path, headers, body)
                if not keep:
                    return
        except (asyncio.IncompleteReadError, ConnectionResetError,
                BrokenPipeError, asyncio.TimeoutError):
            pass
        finally:
            self._nconn -= 1
            try:
                writer.close()
            except Exception:
                pass

    async def _read_request(self, reader) -> Tuple[Dict[str, str], bytes]:
        headers: Dict[str, str] = {}
        for _ in range(_MAX_HEADERS):
            h = await asyncio.wait_for(reader.readline(),
                                       self._read_timeout)
            if h in (b"\r\n", b"\n", b""):
                break
            k, sep, v = h.decode("latin-1").partition(":")
            if sep:
                headers[k.strip().lower()] = v.strip()
        else:
            raise _BadRequest(400, "too many headers")
        raw_n = headers.get("content-length", "0") or "0"
        try:
            n = int(raw_n)
            if n < 0:
                raise ValueError
        except ValueError:
            # A reader that trusted this value would hang waiting for a
            # body that never comes (or worse, int("1e9")-style garbage).
            raise _BadRequest(400,
                              f"malformed content-length {raw_n!r}") from None
        if n > self._max_body:
            raise _BadRequest(413, f"body of {n} bytes exceeds limit "
                                   f"{self._max_body}")
        body = b""
        if n:
            body = await asyncio.wait_for(reader.readexactly(n),
                                          self._read_timeout)
        return headers, body

    # ----------------------------------------------------------- dispatch

    @staticmethod
    def _parse_deadline(headers: Dict[str, str], payload) -> Optional[float]:
        """Relative deadline (seconds) from the `x-request-deadline-s`
        header or a `deadline_s` body field, as an absolute epoch time."""
        v = headers.get("x-request-deadline-s")
        if v is None and isinstance(payload, dict):
            v = payload.get("deadline_s")
        if v is None:
            return None
        try:
            return time.time() + float(v)
        except (TypeError, ValueError):
            return None

    async def _dispatch(self, writer, method: str, path: str,
                        headers: Dict[str, str], body: bytes):
        path = path.split("?", 1)[0]  # health checks may append queries
        if path == "/-/healthz":
            return await self._respond(writer, 200, "ok")
        if path == "/-/routes":
            return await self._respond(writer, 200, self._routes)
        target = self._match_route(path)
        if target is None:
            # Route-table miss: the background refresh runs on a 1s
            # cadence, so a request racing a fresh serve.run (or a fresh
            # ingress) would 404 spuriously.  Pull the table once,
            # synchronously, before giving up.
            try:
                ctrl = await self._controller()
                self._routes = await ctrl.routes.remote()
            except Exception:
                self._ctrl_lost()
            target = self._match_route(path)
        if target is None:
            return await self._respond(writer, 404,
                                       {"error": f"no route for {path}"})
        try:
            payload = json.loads(body) if body else None
        except json.JSONDecodeError:
            payload = body.decode("utf-8", "replace")
        deadline = self._parse_deadline(headers, payload)
        streaming = ("text/event-stream" in headers.get("accept", "")
                     or (isinstance(payload, dict)
                         and payload.get("stream") is True))
        if streaming:
            return await self._dispatch_stream(writer, target, payload,
                                               deadline)
        try:
            result = await self._call(target, payload, deadline)
            await self._respond(writer, 200, {"result": result})
        except Exception as e:  # noqa: BLE001
            code = self._error_code(e)
            if code == 500:
                logger.exception("serve http: request to %s failed", target)
            await self._respond(writer, code, {"error": repr(e)})

    @staticmethod
    def _error_code(e: BaseException) -> int:
        if resilience.is_deadline_error(e):
            return 504
        if isinstance(e, _Unavailable):
            return 503
        return 500

    # ---------------------------------------------------------- streaming

    @staticmethod
    def _resume_payload(payload, delivered) -> Tuple[object, int]:
        """(payload-for-retry, items-to-skip).  Token-generation payloads
        resume by re-prefill: ``prompt + delivered`` with the remaining
        budget — under greedy decoding the new replica recomputes the
        exact KV state and continues bit-identically.  Anything else
        replays the original request and skips what the client already
        has (correct for any deterministic stream)."""
        if (isinstance(payload, dict)
                and isinstance(payload.get("tokens"), list)
                and isinstance(payload.get("max_new_tokens"), int)
                and delivered
                and all(isinstance(t, int) for t in delivered)):
            return ({**payload,
                     "tokens": list(payload["tokens"]) + list(delivered),
                     "max_new_tokens":
                         payload["max_new_tokens"] - len(delivered)},
                    0)
        return payload, len(delivered)

    async def _dispatch_stream(self, writer, target: str, payload,
                               deadline: Optional[float] = None):
        """SSE token stream with mid-stream failover: chunked transfer,
        one data event per yield, flushed as produced.  Replica death or
        decode stall hands the stream to a healthy replica (see
        _resume_payload); client disconnect / write timeout / terminal
        idle cancel the replica-side generator."""
        policy = resilience.RetryPolicy()
        exclude: set = set()
        delivered: list = []
        headers_sent = False
        per_item_timeout = min(self._stall_s, self._stream_idle)

        async def fail(code: int, message: str):
            if headers_sent:
                await self._write_event(writer, "error",
                                        {"error": message, "code": code})
                await self._write(writer, b"0\r\n\r\n")
            else:
                await self._respond(writer, code, {"error": message})

        while True:
            if self._expired(deadline):
                return await fail(504, "request deadline expired")
            attempt_payload, skip = (payload, 0) if not delivered \
                else self._resume_payload(payload, delivered)
            if (isinstance(attempt_payload, dict)
                    and isinstance(
                        attempt_payload.get("max_new_tokens"), int)
                    and attempt_payload["max_new_tokens"] <= 0):
                # The dead replica had already generated every requested
                # token; nothing left to resume — just finish the stream.
                await self._write_event(writer, "end", {})
                await self._write(writer, b"0\r\n\r\n")
                return
            try:
                gen, rid = await self._call_stream(
                    target, attempt_payload, deadline, exclude)
            except _Unavailable as e:
                if policy.can_retry() and not self._expired(deadline):
                    serve_metrics.bump("router_retries")
                    await asyncio.sleep(policy.next_backoff_s(deadline))
                    continue
                return await fail(503, repr(e))
            except Exception as e:   # noqa: BLE001
                logger.exception("serve http: stream to %s failed to start",
                                 target)
                return await fail(self._error_code(e), repr(e))
            if not headers_sent:
                await self._write(
                    writer,
                    b"HTTP/1.1 200 OK\r\n"
                    b"Content-Type: text/event-stream\r\n"
                    b"Cache-Control: no-cache\r\n"
                    b"Transfer-Encoding: chunked\r\n\r\n")
                headers_sent = True
            resumed = bool(delivered)
            got_any = False
            try:
                while True:
                    rem = resilience.deadline_remaining(deadline)
                    wait = per_item_timeout if rem is None \
                        else min(per_item_timeout, max(rem, 0.0))
                    try:
                        # Each stream item is a per-yield ObjectRef (the
                        # generator owner side of num_returns="streaming");
                        # awaiting the ref materializes the token.
                        item = await asyncio.wait_for(gen.__anext__(), wait)
                        item = await asyncio.wait_for(
                            _materialize(item), wait)
                    except StopAsyncIteration:
                        self._cb.record_success(rid)
                        await self._write_event(writer, "end", {})
                        await self._write(writer, b"0\r\n\r\n")
                        return
                    except asyncio.TimeoutError:
                        if self._expired(deadline):
                            gen.cancel()
                            return await fail(
                                504, "request deadline expired mid-stream")
                        # Stalled replica: treat like a death and fail
                        # the stream over.
                        raise resilience.DecodeStalled(
                            f"no token for {wait:.1f}s")
                    if resumed and not got_any:
                        serve_metrics.bump("streams_resumed")
                    got_any = True
                    if skip > 0:
                        # Replay path: the client already has this item.
                        skip -= 1
                        delivered.append(item)
                        continue
                    await self._write_event(writer, None, item)
                    delivered.append(item)
            except (ConnectionResetError, BrokenPipeError):
                # Client gone: tear down the replica-side stream so the
                # engine frees its KV pages.
                gen.cancel()
                raise
            except asyncio.TimeoutError:
                # _write timed out (client reading too slowly): same as
                # a disconnect.
                gen.cancel()
                raise
            except Exception as e:   # noqa: BLE001
                gen.cancel()
                if resilience.is_deadline_error(e):
                    return await fail(504, "request deadline expired")
                if not (resilience.is_retryable_error(e)
                        or isinstance(e, resilience.DecodeStalled)):
                    # Handler exception: deterministic, don't retry.
                    return await fail(500, repr(e))
                self._cb.record_failure(rid)
                exclude.add(rid)
                self._replicas.pop(target, None)   # force a refresh
                if not policy.can_retry():
                    return await fail(
                        503, f"retry budget exhausted: {e!r}")
                serve_metrics.bump("router_retries")
                logger.warning(
                    "serve http: stream to %s replica %s broke (%r); "
                    "failing over with %d tokens delivered",
                    target, rid[:8], e, len(delivered))
                await asyncio.sleep(policy.next_backoff_s(deadline))
                continue

    async def _write_event(self, writer, event: Optional[str], data):
        payload = (f"event: {event}\n" if event else "") + \
            "data: " + json.dumps(data, default=repr) + "\n\n"
        raw = payload.encode()
        await self._write(writer,
                          f"{len(raw):x}\r\n".encode() + raw + b"\r\n")

    async def _drain(self, writer):
        from ray_tpu.util import fault_injection
        delay = fault_injection.slow_client_delay_s()
        if delay:
            await asyncio.sleep(delay)
        await writer.drain()

    async def _write(self, writer, data: bytes):
        """All socket writes funnel here: a client that stops reading
        (full TCP window) parks drain(); the timeout converts that into
        an abort instead of an ingress slot leaked forever."""
        writer.write(data)
        await asyncio.wait_for(self._drain(writer), self._write_timeout)

    async def _respond(self, writer, code: int, payload,
                       extra_headers: Optional[Dict[str, str]] = None,
                       close: bool = False):
        if isinstance(payload, str):
            data = payload.encode()
            ctype = "text/plain"
        else:
            data = json.dumps(payload, default=repr).encode()
            ctype = "application/json"
        reason = {200: "OK", 400: "Bad Request", 404: "Not Found",
                  413: "Payload Too Large", 429: "Too Many Requests",
                  500: "Internal Server Error",
                  503: "Service Unavailable",
                  504: "Gateway Timeout"}.get(code, "ERR")
        extra = "".join(f"{k}: {v}\r\n"
                        for k, v in (extra_headers or {}).items())
        if close:
            extra += "Connection: close\r\n"
        await self._write(
            writer,
            f"HTTP/1.1 {code} {reason}\r\n"
            f"Content-Type: {ctype}\r\n"
            f"Content-Length: {len(data)}\r\n{extra}\r\n".encode() + data)
        if close:
            writer.close()
