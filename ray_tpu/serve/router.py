"""Client-side router: queue-aware replica choice.

Reference analog: Router/ReplicaSet (_private/router.py:261,62) — requests
are assigned client-side to the replica with the fewest locally-tracked
outstanding requests among two random candidates (power-of-two-choices),
with the replica set cached and refreshed from the controller.
"""

from __future__ import annotations

import random
import threading
import time
from typing import Any, Dict, List

import ray_tpu

REFRESH_PERIOD_S = 1.0


class DeploymentHandle:
    """Callable handle to a deployment: ``handle.remote(*args)``."""

    def __init__(self, name: str, controller):
        self._name = name
        self._controller = controller
        self._replicas: List = []
        self._outstanding: Dict[str, int] = {}
        self._last_refresh = 0.0
        self._lock = threading.Lock()

    def __reduce__(self):
        # Handles travel into replicas for deployment graphs (a deployment
        # bound with another deployment calls it through its handle); the
        # lock and cached replica view rebuild fresh in the destination.
        return (DeploymentHandle, (self._name, self._controller))

    def _refresh(self, force: bool = False):
        now = time.monotonic()
        if not force and now - self._last_refresh < REFRESH_PERIOD_S:
            return
        reps = ray_tpu.get(
            self._controller.get_replicas.remote(self._name))
        with self._lock:
            self._replicas = reps
            self._last_refresh = now
            # Counters reset each refresh window: they only need to skew
            # the power-of-two choice within the window, and resetting
            # makes lost decrements self-healing.
            self._outstanding = {}

    def _pick(self):
        with self._lock:
            reps = list(self._replicas)
        if not reps:
            raise RuntimeError(
                f"deployment {self._name} has no running replicas")
        if len(reps) == 1:
            return reps[0]
        a, b = random.sample(reps, 2)
        na = self._outstanding.get(a._actor_id, 0)
        nb = self._outstanding.get(b._actor_id, 0)
        return a if na <= nb else b

    def remote(self, *args, _method: str = None, **kwargs):
        """Route one request; returns an ObjectRef of the result."""
        self._refresh()
        replica = self._pick()
        aid = replica._actor_id
        with self._lock:
            # In-flight estimate; reset wholesale on each refresh rather
            # than tracking completions (which would cost a deserialization
            # per reply just to decrement a heuristic counter).
            self._outstanding[aid] = self._outstanding.get(aid, 0) + 1
        return replica.handle_request.remote(list(args), kwargs, _method)

    def method(self, name: str):
        """handle.method("encode").remote(...) calls a named method."""
        h = self
        class _M:  # noqa: N801 - tiny adapter
            def remote(self, *a, **k):
                return h.remote(*a, _method=name, **k)
        return _M()

