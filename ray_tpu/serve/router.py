"""Client-side router: queue-aware replica choice, push-updated.

Reference analog: Router/ReplicaSet (_private/router.py:261,62) — requests
are assigned client-side to the replica with the fewest locally-tracked
outstanding requests among two random candidates (power-of-two-choices).
The replica set is kept fresh by a long-poll listener thread against the
controller (reference serve/_private/long_poll.py LongPollClient): scale
events become visible push-style, typically within one RPC round-trip.
The TTL refresh remains only as a safety net (listener thread died, or
the controller was replaced).
"""

from __future__ import annotations

import random
import threading
import time
import weakref
from typing import Any, Dict, List

import ray_tpu

# Fallback only — the long-poll listener delivers changes immediately.
REFRESH_PERIOD_S = 30.0
# In-flight counters are a within-window heuristic; they must keep the old
# 1s reset cadence now that refreshes are rare.
COUNTER_RESET_PERIOD_S = 1.0
_LISTEN_TIMEOUT_S = 30.0


class DeploymentHandle:
    """Callable handle to a deployment: ``handle.remote(*args)``."""

    def __init__(self, name: str, controller):
        self._name = name
        self._controller = controller
        self._replicas: List = []
        self._outstanding: Dict[str, int] = {}
        self._last_refresh = 0.0
        self._lock = threading.Lock()
        self._version = 0
        self._listener: threading.Thread = None
        self._counters_reset_at = 0.0

    def __reduce__(self):
        # Handles travel into replicas for deployment graphs (a deployment
        # bound with another deployment calls it through its handle); the
        # lock and cached replica view rebuild fresh in the destination.
        return (DeploymentHandle, (self._name, self._controller))

    def _refresh(self, force: bool = False):
        self._ensure_listener()
        now = time.monotonic()
        if not force and now - self._last_refresh < REFRESH_PERIOD_S:
            return
        reps = ray_tpu.get(
            self._controller.get_replicas.remote(self._name))
        with self._lock:
            self._replicas = reps
            self._last_refresh = now
            # Counters reset each refresh window: they only need to skew
            # the power-of-two choice within the window, and resetting
            # makes lost decrements self-healing.
            self._outstanding = {}

    def _ensure_listener(self):
        # Unlocked pre-check keeps the steady-state remote() path to one
        # lock acquisition; the locked re-check below handles the benign
        # startup race.
        t = self._listener
        if t is not None and t.is_alive():
            return
        with self._lock:
            t = self._listener
            if t is not None and t.is_alive():
                return
            t = threading.Thread(target=_listen_loop,
                                 args=(weakref.ref(self),),
                                 name=f"serve-longpoll-{self._name}",
                                 daemon=True)
            self._listener = t
            # start() inside the lock: a not-yet-started thread reports
            # is_alive()==False, which would let a concurrent caller spawn
            # a duplicate listener.
            t.start()

    def _pick(self):
        with self._lock:
            reps = list(self._replicas)
        if not reps:
            raise RuntimeError(
                f"deployment {self._name} has no running replicas")
        if len(reps) == 1:
            return reps[0]
        a, b = random.sample(reps, 2)
        na = self._outstanding.get(a._actor_id, 0)
        nb = self._outstanding.get(b._actor_id, 0)
        return a if na <= nb else b

    def remote(self, *args, _method: str = None, **kwargs):
        """Route one request; returns an ObjectRef of the result."""
        self._refresh()
        replica = self._pick()
        aid = replica._actor_id
        now = time.monotonic()
        with self._lock:
            # In-flight estimate; reset wholesale on a short cadence rather
            # than tracking completions (which would cost a deserialization
            # per reply just to decrement a heuristic counter).  Decoupled
            # from the refresh TTL: with push updates, refreshes are rare.
            if now - self._counters_reset_at > COUNTER_RESET_PERIOD_S:
                self._outstanding = {}
                self._counters_reset_at = now
            self._outstanding[aid] = self._outstanding.get(aid, 0) + 1
        return replica.handle_request.remote(list(args), kwargs, _method)

    def remote_stream(self, *args, _method: str = None, **kwargs):
        """Route one STREAMING request: returns a
        ``StreamingObjectRefGenerator`` whose items are the handler's
        yields, consumable while the replica is still generating
        (``async for`` it, or ``next()`` off-loop).  Dropping the
        generator early cancels the replica-side stream."""
        self._refresh()
        replica = self._pick()
        aid = replica._actor_id
        now = time.monotonic()
        with self._lock:
            if now - self._counters_reset_at > COUNTER_RESET_PERIOD_S:
                self._outstanding = {}
                self._counters_reset_at = now
            self._outstanding[aid] = self._outstanding.get(aid, 0) + 1
        return replica.handle_stream.options(
            num_returns="streaming").remote(list(args), kwargs, _method)

    def method(self, name: str):
        """handle.method("encode").remote(...) calls a named method."""
        h = self
        class _M:  # noqa: N801 - tiny adapter
            def remote(self, *a, **k):
                return h.remote(*a, _method=name, **k)
            def remote_stream(self, *a, **k):
                return h.remote_stream(*a, _method=name, **k)
        return _M()



def _listen_loop(handle_ref):
    """Long-poll listener: parks on controller.listen_for_change and applies
    replica-set updates the moment they land.  Holds only a weakref to the
    handle so a dropped handle lets both the handle and this thread die.
    Backs off exponentially on failure and exits after ~10 consecutive
    errors (controller gone, e.g. serve.shutdown with live handles) — a
    later remote() restarts it via _ensure_listener."""
    failures = 0
    while True:
        h = handle_ref()
        if h is None:
            return
        if h._listener is not threading.current_thread():
            return  # superseded by a newer listener
        name, controller, ver = h._name, h._controller, h._version
        del h
        try:
            res = ray_tpu.get(
                controller.listen_for_change.remote(
                    name, ver, _LISTEN_TIMEOUT_S),
                timeout=_LISTEN_TIMEOUT_S + 30)
            failures = 0
        except Exception:
            failures += 1
            if failures >= 10:
                return
            time.sleep(min(1.0 * 2 ** (failures - 1), 30.0))
            continue
        h = handle_ref()
        if h is None:
            return
        if res["version"] != ver:
            with h._lock:
                h._replicas = res["replicas"]
                h._version = res["version"]
                h._outstanding = {}
                h._last_refresh = time.monotonic()
        del h
