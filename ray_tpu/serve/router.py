"""Client-side router: queue-aware replica choice, push-updated.

Reference analog: Router/ReplicaSet (_private/router.py:261,62) — requests
are assigned client-side to the replica with the fewest locally-tracked
outstanding requests among two random candidates (power-of-two-choices).
The replica set is kept fresh by a long-poll listener thread against the
controller (reference serve/_private/long_poll.py LongPollClient): scale
events become visible push-style, typically within one RPC round-trip.
The TTL refresh remains only as a safety net (listener thread died, or
the controller was replaced).

**Hardening** (serve/resilience.py): replica choice runs through a
per-replica circuit breaker — callers report outcomes via
``report_failure``/``report_success`` (or use ``remote_retrying``, which
does it automatically plus bounded backoff retry), and ejected replicas
drop out of the power-of-two candidate set until their half-open probe
passes.  ``_deadline_s`` on ``remote``/``remote_stream`` propagates an
end-to-end deadline to the replica (and through it, the engine).
"""

from __future__ import annotations

import random
import threading
import time
import weakref
from typing import Any, Dict, List, Optional

import ray_tpu
from ray_tpu.serve import resilience

# Fallback only — the long-poll listener delivers changes immediately.
REFRESH_PERIOD_S = 30.0
# In-flight counters are a within-window heuristic; they must keep the old
# 1s reset cadence now that refreshes are rare.
COUNTER_RESET_PERIOD_S = 1.0
_LISTEN_TIMEOUT_S = 30.0


class DeploymentHandle:
    """Callable handle to a deployment: ``handle.remote(*args)``."""

    def __init__(self, name: str, controller):
        self._name = name
        self._controller = controller
        self._replicas: List = []
        self._outstanding: Dict[str, int] = {}
        self._last_refresh = 0.0
        self._lock = threading.Lock()
        self._version = 0
        self._listener: threading.Thread = None
        self._counters_reset_at = 0.0
        self._cb = resilience.CircuitBreaker(on_open=self._on_cb_open)

    @staticmethod
    def _on_cb_open(replica_id: str):
        from ray_tpu.serve import metrics as serve_metrics
        serve_metrics.bump("circuit_open")

    def report_failure(self, replica_id: str):
        """Feed the circuit breaker: call with the replica's actor id
        when a request sent through this handle failed with a system
        error (replica death, lost connection)."""
        with self._lock:
            self._cb.record_failure(replica_id)

    def report_success(self, replica_id: str):
        with self._lock:
            self._cb.record_success(replica_id)

    def __reduce__(self):
        # Handles travel into replicas for deployment graphs (a deployment
        # bound with another deployment calls it through its handle); the
        # lock and cached replica view rebuild fresh in the destination.
        return (DeploymentHandle, (self._name, self._controller))

    def _refresh(self, force: bool = False):
        self._ensure_listener()
        now = time.monotonic()
        if not force and now - self._last_refresh < REFRESH_PERIOD_S:
            return
        reps = ray_tpu.get(
            self._controller.get_replicas.remote(self._name))
        with self._lock:
            self._replicas = reps
            self._last_refresh = now
            # Counters reset each refresh window: they only need to skew
            # the power-of-two choice within the window, and resetting
            # makes lost decrements self-healing.
            self._outstanding = {}

    def _ensure_listener(self):
        # Unlocked pre-check keeps the steady-state remote() path to one
        # lock acquisition; the locked re-check below handles the benign
        # startup race.
        t = self._listener
        if t is not None and t.is_alive():
            return
        with self._lock:
            t = self._listener
            if t is not None and t.is_alive():
                return
            t = threading.Thread(target=_listen_loop,
                                 args=(weakref.ref(self),),
                                 name=f"serve-longpoll-{self._name}",
                                 daemon=True)
            self._listener = t
            # start() inside the lock: a not-yet-started thread reports
            # is_alive()==False, which would let a concurrent caller spawn
            # a duplicate listener.
            t.start()

    def _pick(self, exclude: Optional[set] = None):
        with self._lock:
            reps = list(self._replicas)
            if reps:
                # Breaker-filtered candidate set: ejected replicas sit out
                # until their half-open probe; if EVERYTHING is ejected,
                # fall back to the raw set (a request that might succeed
                # beats a guaranteed routing error).
                avail = self._cb.filter(reps, exclude=exclude)
                reps = avail or reps
        if not reps:
            raise RuntimeError(
                f"deployment {self._name} has no running replicas")
        if len(reps) == 1:
            return reps[0]
        a, b = random.sample(reps, 2)
        na = self._outstanding.get(a._actor_id, 0)
        nb = self._outstanding.get(b._actor_id, 0)
        return a if na <= nb else b

    @staticmethod
    def _deadline(deadline_s: Optional[float]) -> Optional[float]:
        return None if deadline_s is None else time.time() + deadline_s

    def _count(self, aid: str):
        now = time.monotonic()
        with self._lock:
            # In-flight estimate; reset wholesale on a short cadence rather
            # than tracking completions (which would cost a deserialization
            # per reply just to decrement a heuristic counter).  Decoupled
            # from the refresh TTL: with push updates, refreshes are rare.
            if now - self._counters_reset_at > COUNTER_RESET_PERIOD_S:
                self._outstanding = {}
                self._counters_reset_at = now
            self._outstanding[aid] = self._outstanding.get(aid, 0) + 1

    def remote(self, *args, _method: str = None,
               _deadline_s: Optional[float] = None, **kwargs):
        """Route one request; returns an ObjectRef of the result.
        ``_deadline_s`` (relative seconds) rides to the replica as an
        absolute end-to-end deadline — expiry raises DeadlineExceeded
        from the ref instead of computing a result nobody will read."""
        self._refresh()
        replica = self._pick()
        self._count(replica._actor_id)
        return replica.handle_request.remote(
            list(args), kwargs, _method, self._deadline(_deadline_s))

    async def remote_retrying(self, *args, _method: str = None,
                              _deadline_s: Optional[float] = None,
                              **kwargs):
        """Awaitable hardened call: routes like ``remote`` but awaits the
        result, feeds the circuit breaker with the outcome, and retries
        retryable system failures (replica death, lost connections) on a
        different replica with exponential backoff + jitter, bounded by
        the RT_SERVE_RETRY_BUDGET and the deadline.  Returns the result
        directly (not an ObjectRef)."""
        import asyncio
        deadline = self._deadline(_deadline_s)
        policy = resilience.RetryPolicy()
        exclude: set = set()
        while True:
            rem = resilience.deadline_remaining(deadline)
            if rem is not None and rem <= 0:
                raise resilience.DeadlineExceeded(
                    "request deadline expired before completion")
            self._refresh()
            replica = self._pick(exclude)
            aid = replica._actor_id
            self._count(aid)
            try:
                result = await replica.handle_request.remote(
                    list(args), kwargs, _method, deadline)
            except Exception as e:   # noqa: BLE001
                if not resilience.is_retryable_error(e):
                    raise
                self.report_failure(aid)
                exclude.add(aid)
                if not policy.can_retry():
                    raise
                from ray_tpu.serve import metrics as serve_metrics
                serve_metrics.bump("router_retries")
                self._refresh(force=True)
                await asyncio.sleep(policy.next_backoff_s(deadline))
                continue
            self.report_success(aid)
            return result

    def remote_stream(self, *args, _method: str = None,
                      _deadline_s: Optional[float] = None, **kwargs):
        """Route one STREAMING request: returns a
        ``StreamingObjectRefGenerator`` whose items are the handler's
        yields, consumable while the replica is still generating
        (``async for`` it, or ``next()`` off-loop).  Dropping the
        generator early cancels the replica-side stream."""
        self._refresh()
        replica = self._pick()
        self._count(replica._actor_id)
        return replica.handle_stream.options(
            num_returns="streaming").remote(
                list(args), kwargs, _method, self._deadline(_deadline_s))

    def method(self, name: str):
        """handle.method("encode").remote(...) calls a named method."""
        h = self
        class _M:  # noqa: N801 - tiny adapter
            def remote(self, *a, **k):
                return h.remote(*a, _method=name, **k)
            def remote_stream(self, *a, **k):
                return h.remote_stream(*a, _method=name, **k)
        return _M()



def _listen_loop(handle_ref):
    """Long-poll listener: parks on controller.listen_for_change and applies
    replica-set updates the moment they land.  Holds only a weakref to the
    handle so a dropped handle lets both the handle and this thread die.
    Backs off exponentially on failure and exits after ~10 consecutive
    errors (controller gone, e.g. serve.shutdown with live handles) — a
    later remote() restarts it via _ensure_listener."""
    failures = 0
    while True:
        h = handle_ref()
        if h is None:
            return
        if h._listener is not threading.current_thread():
            return  # superseded by a newer listener
        name, controller, ver = h._name, h._controller, h._version
        del h
        try:
            res = ray_tpu.get(
                controller.listen_for_change.remote(
                    name, ver, _LISTEN_TIMEOUT_S),
                timeout=_LISTEN_TIMEOUT_S + 30)
            failures = 0
        except Exception:
            failures += 1
            if failures >= 10:
                return
            time.sleep(min(1.0 * 2 ** (failures - 1), 30.0))
            continue
        h = handle_ref()
        if h is None:
            return
        if res["version"] != ver:
            with h._lock:
                h._replicas = res["replicas"]
                h._version = res["version"]
                h._outstanding = {}
                h._last_refresh = time.monotonic()
        del h
