"""Serve-layer resilience primitives: circuit breaking, bounded retry,
and end-to-end request deadlines.

Reference analogs: the Ray paper's fault-tolerance story applied to the
serving path (PAPERS.md "Ray: A Distributed Framework for Emerging AI
Applications"), Ray Serve's replica health gating, and classic
router-side hardening (Finagle/Envoy-style consecutive-failure circuit
breakers with half-open probes, capped exponential backoff with jitter).

Three independent pieces, shared by the HTTP ingress and the
``DeploymentHandle`` router:

* **CircuitBreaker** — per-replica failure accounting.  ``threshold``
  consecutive failures eject a replica (state OPEN: the router stops
  selecting it); after ``cooldown_s`` the breaker admits exactly one
  probe request (HALF_OPEN) — a success re-closes the circuit, a failure
  re-opens it for another cooldown.  Ejection is routing-local and
  optimistic by design: the controller's health probe is the authority
  that actually replaces dead replicas; the breaker only keeps live
  traffic away from them in the seconds between death and replacement.

* **RetryPolicy** — bounded retry with exponential backoff + full
  jitter and a per-request attempt budget.  The budget covers the WHOLE
  request (initial attempt + unary retries + mid-stream failovers), so
  a flapping fleet degrades to an error instead of an infinite retry
  storm.  Backoff sleeps never exceed the request's remaining deadline.

* **Deadlines** — an absolute ``time.time()`` deadline propagated
  ingress → handle → replica → engine.  The replica publishes it
  through a contextvar (``current_deadline()``) so handler bodies (the
  inference engine, most importantly) can cancel decode and free KV
  pages instead of computing tokens nobody will read.  An expired
  deadline surfaces as ``DeadlineExceeded`` (504 at the ingress).

Everything here is import-light and event-loop-free: pure state
machines the async callers drive.
"""

from __future__ import annotations

import contextvars
import os
import random
import time
from typing import Dict, List, Optional, Sequence

__all__ = [
    "DeadlineExceeded", "DecodeStalled", "CircuitBreaker", "RetryPolicy",
    "current_deadline", "deadline_remaining", "set_deadline",
    "is_deadline_error", "is_retryable_error",
]


class DeadlineExceeded(Exception):
    """A request's end-to-end deadline expired before completion.

    Raised replica-side (and engine-side) so decode stops and KV pages
    free; mapped to HTTP 504 at the ingress.  Deliberately a plain
    Exception: it crosses the wire pickled inside TaskError like any
    handler exception."""


class DecodeStalled(Exception):
    """A live stream produced no item within the stall window
    (RT_SERVE_STALL_S).  Ingress-local: raised to route the stream into
    the failover path — the replica may be wedged even though its actor
    is nominally alive."""


def _env_f(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, default))
    except ValueError:
        return default


# --------------------------------------------------------------- deadlines

_REQUEST_DEADLINE: contextvars.ContextVar[Optional[float]] = \
    contextvars.ContextVar("rt_serve_request_deadline", default=None)


def set_deadline(deadline: Optional[float]):
    """Publish the absolute request deadline (epoch seconds) to handler
    code below this frame; returns the reset token."""
    return _REQUEST_DEADLINE.set(deadline)


def reset_deadline(token) -> None:
    _REQUEST_DEADLINE.reset(token)


def current_deadline() -> Optional[float]:
    """The active request's absolute deadline, or None when unbounded."""
    return _REQUEST_DEADLINE.get()


def deadline_remaining(deadline: Optional[float] = None) -> Optional[float]:
    """Seconds until ``deadline`` (defaults to the contextvar); None when
    unbounded.  May be <= 0 — callers treat that as expired."""
    if deadline is None:
        deadline = current_deadline()
    if deadline is None:
        return None
    return deadline - time.time()


def is_deadline_error(exc: BaseException) -> bool:
    """True when ``exc`` is a DeadlineExceeded, directly or as the cause
    inside a TaskError that crossed the wire."""
    if isinstance(exc, DeadlineExceeded):
        return True
    cause = getattr(exc, "cause", None)
    return cause is not None and (
        isinstance(cause, DeadlineExceeded)
        or type(cause).__name__ == "DeadlineExceeded")


def is_retryable_error(exc: BaseException) -> bool:
    """True for SYSTEM failures a different replica can absorb (replica
    death, lost connections, crashed workers).  Handler exceptions
    (TaskError around user code) are NOT retryable — they would recur
    deterministically on every replica — and neither are deadline
    expirations (retrying cannot un-expire a deadline).

    The ``cause`` of a TaskError is inspected too: a call that races the
    GCS's death record dials the dead worker's old address and comes back
    as ``TaskError(ConnectionRefusedError)`` rather than ActorDiedError —
    same failure, different wrapper."""
    from ray_tpu import exceptions as rex

    def _system(e: BaseException) -> bool:
        if isinstance(e, DecodeStalled):
            return True
        if isinstance(e, (rex.ActorDiedError, rex.ActorUnavailableError,
                          rex.WorkerCrashedError)):
            return True
        if isinstance(e, (ConnectionError, EOFError)):
            return True
        # protocol.ConnectionLost (by name: this module stays import-light).
        return type(e).__name__ == "ConnectionLost"

    if is_deadline_error(exc):
        return False
    if _system(exc):
        return True
    cause = getattr(exc, "cause", None)
    return cause is not None and _system(cause)


# --------------------------------------------------------- circuit breaker

CB_CLOSED = "closed"
CB_OPEN = "open"
CB_HALF_OPEN = "half_open"


class _Breaker:
    __slots__ = ("state", "failures", "opened_at", "probe_in_flight",
                 "probe_at")

    def __init__(self):
        self.state = CB_CLOSED
        self.failures = 0
        self.opened_at = 0.0
        self.probe_in_flight = False
        self.probe_at = 0.0


class CircuitBreaker:
    """Per-replica consecutive-failure circuit breaker with half-open
    probe re-admission.  Keys are replica actor ids; unknown keys are
    implicitly CLOSED.  Not thread-safe by itself — the ingress drives it
    from one event loop; ``DeploymentHandle`` wraps calls in its own
    lock."""

    def __init__(self, threshold: Optional[int] = None,
                 cooldown_s: Optional[float] = None,
                 on_open=None):
        self.threshold = int(threshold if threshold is not None
                             else _env_f("RT_SERVE_CB_THRESHOLD", 3))
        self.cooldown_s = (cooldown_s if cooldown_s is not None
                           else _env_f("RT_SERVE_CB_COOLDOWN_S", 5.0))
        self._breakers: Dict[str, _Breaker] = {}
        self._on_open = on_open          # callback(replica_id) on ejection

    # -- state transitions ------------------------------------------------

    def record_success(self, replica_id: str) -> None:
        b = self._breakers.get(replica_id)
        if b is None:
            return
        # Any success fully heals: half-open probe passed, or a straggler
        # success raced the ejection.
        self._breakers.pop(replica_id, None)

    def record_failure(self, replica_id: str) -> None:
        b = self._breakers.setdefault(replica_id, _Breaker())
        b.failures += 1
        if b.state == CB_HALF_OPEN:
            # The probe failed: re-open for another full cooldown.
            b.state = CB_OPEN
            b.opened_at = time.monotonic()
            b.probe_in_flight = False
            return
        if b.state == CB_CLOSED and b.failures >= self.threshold:
            b.state = CB_OPEN
            b.opened_at = time.monotonic()
            if self._on_open is not None:
                try:
                    self._on_open(replica_id)
                except Exception:
                    pass

    # -- selection --------------------------------------------------------

    def try_admit(self, replica_id: str) -> bool:
        """True when the replica may receive a request right now.  An OPEN
        breaker past its cooldown transitions to HALF_OPEN and admits ONE
        probe; further requests are refused until the probe resolves.  A
        probe slot reserved but never resolved (the caller admitted a
        replica it didn't end up sending to, or the send's outcome was
        lost) expires after another cooldown so the breaker can't wedge
        shut."""
        b = self._breakers.get(replica_id)
        if b is None or b.state == CB_CLOSED:
            return True
        if b.state == CB_OPEN:
            if time.monotonic() - b.opened_at < self.cooldown_s:
                return False
            b.state = CB_HALF_OPEN
            b.probe_in_flight = False
        if b.state == CB_HALF_OPEN:
            if b.probe_in_flight and \
                    time.monotonic() - b.probe_at < self.cooldown_s:
                return False
            b.probe_in_flight = True
            b.probe_at = time.monotonic()
            return True
        return True

    def state(self, replica_id: str) -> str:
        b = self._breakers.get(replica_id)
        if b is None:
            return CB_CLOSED
        if b.state == CB_OPEN and \
                time.monotonic() - b.opened_at >= self.cooldown_s:
            return CB_HALF_OPEN
        return b.state

    def filter(self, replicas: Sequence, *,
               exclude: Optional[set] = None) -> List:
        """Replicas currently routable, minus ``exclude`` (actor ids).
        CLOSED replicas are preferred: half-open probe slots are only
        spent when NO closed replica remains, so a healthy fleet never
        burns probes on cooled-down breakers while good targets exist."""
        pool = [r for r in replicas
                if not (exclude and r._actor_id in exclude)]
        closed = [r for r in pool
                  if self.state(r._actor_id) == CB_CLOSED]
        if closed:
            return closed
        return [r for r in pool if self.try_admit(r._actor_id)]

    def select(self, replicas: Sequence, index: int = 0, *,
               exclude: Optional[set] = None):
        """One routable replica (round-robin by ``index`` over the
        filtered set), or None when every candidate is ejected and still
        cooling."""
        avail = self.filter(replicas, exclude=exclude)
        if not avail:
            return None
        return avail[index % len(avail)]

    def forget_missing(self, live_ids) -> None:
        """Drop breaker state for replicas no longer in the set (replaced
        by the controller) so the map stays bounded under churn."""
        live = set(live_ids)
        for rid in list(self._breakers):
            if rid not in live:
                del self._breakers[rid]

    def snapshot(self) -> Dict[str, str]:
        return {rid: self.state(rid) for rid in list(self._breakers)}


# ------------------------------------------------------------------ retry

class RetryPolicy:
    """Bounded retry budget with capped exponential backoff + full
    jitter.  One instance per REQUEST (the budget is per-request state);
    construction is cheap."""

    def __init__(self, budget: Optional[int] = None,
                 base_s: Optional[float] = None,
                 cap_s: Optional[float] = None):
        self.budget = int(budget if budget is not None
                          else _env_f("RT_SERVE_RETRY_BUDGET", 3))
        self.base_s = (base_s if base_s is not None
                       else _env_f("RT_SERVE_RETRY_BASE_S", 0.05))
        self.cap_s = (cap_s if cap_s is not None
                      else _env_f("RT_SERVE_RETRY_CAP_S", 2.0))
        self.attempts = 0

    def can_retry(self) -> bool:
        return self.attempts < self.budget

    def next_backoff_s(self, deadline: Optional[float] = None) -> float:
        """Consume one budget unit; returns the sleep before the retry
        (full jitter over an exponentially growing window, clamped to the
        request's remaining deadline)."""
        self.attempts += 1
        window = min(self.cap_s, self.base_s * (2 ** (self.attempts - 1)))
        sleep = random.uniform(0.0, window)
        rem = deadline_remaining(deadline)
        if rem is not None:
            sleep = max(0.0, min(sleep, rem))
        return sleep
