"""Micro-batching for replica methods (reference: serve/batching.py).

``@serve.batch`` turns ``async def f(self, items: list)`` into a per-call
API: concurrent callers are queued, and when either ``max_batch_size``
requests are waiting or ``batch_wait_timeout_s`` elapses, the underlying
function runs once on the batch and each caller gets its own element.

On TPU replicas this is the fill-the-MXU lever: a jitted forward with a
fixed batch dim amortizes dispatch across concurrent requests.
"""

from __future__ import annotations

import asyncio

from ray_tpu._private.async_utils import spawn
import functools
from typing import Any, Callable, List, Optional


def batch(fn: Optional[Callable] = None, *, max_batch_size: int = 8,
          batch_wait_timeout_s: float = 0.01):
    def wrap(func):
        attr = f"__serve_batch_queue_{func.__name__}"

        @functools.wraps(func)
        async def caller(self, item):
            # The queue lives on the instance (not a closure dict keyed by
            # id(self)): it dies with the instance and can't be handed to a
            # different object on CPython id reuse.
            q = getattr(self, attr, None)
            if q is None:
                q = _BatchQueue(lambda items: func(self, items),
                                max_batch_size, batch_wait_timeout_s)
                setattr(self, attr, q)
            return await q.submit(item)

        caller._is_serve_batch = True
        return caller

    if fn is not None:
        return wrap(fn)
    return wrap


class _BatchQueue:
    def __init__(self, fn, max_size: int, wait_s: float):
        self._fn = fn
        self._max = max_size
        self._wait = wait_s
        self._pending: List = []   # (item, future)
        self._flusher: Optional[asyncio.TimerHandle] = None

    async def submit(self, item) -> Any:
        fut = asyncio.get_running_loop().create_future()
        self._pending.append((item, fut))
        if len(self._pending) >= self._max:
            self._flush()
        elif self._flusher is None:
            self._flusher = asyncio.get_running_loop().call_later(
                self._wait, self._flush)
        return await fut

    def _flush(self):
        if self._flusher is not None:
            self._flusher.cancel()
            self._flusher = None
        batch_, self._pending = self._pending, []
        if not batch_:
            return
        items = [x for x, _ in batch_]
        futs = [f for _, f in batch_]

        async def run():
            try:
                results = await self._fn(items)
                if len(results) != len(items):
                    raise ValueError(
                        f"@serve.batch function returned {len(results)} "
                        f"results for {len(items)} inputs")
                for f, r in zip(futs, results):
                    if not f.done():
                        f.set_result(r)
            except Exception as e:
                for f in futs:
                    if not f.done():
                        f.set_exception(e)

        spawn(run(), name="serve-batch-run")
