"""Serve resilience observability counters.

Same dual-sink shape as ``ray_tpu.autotune.metrics`` — one ``bump()``
feeds:

* a plain in-process dict (``stats()``) — the raylet folds it into its
  node-stats report so head-side consumers (``state.serve_totals()``,
  the dashboard) see per-node values, and unit tests can assert on it
  without a cluster;
* lazily-created ``ray_tpu.util.metrics`` Counters — the processes
  where routing actually happens (ingress actors, handle-holding
  workers) flush these to the GCS, which aggregates them across
  processes into ``/api/metrics`` as ``ray_tpu_<name>`` series.

Counters are created on first bump, not at import, so importing the
serve package never starts the metrics flusher thread in processes that
never route requests.

The four counters tell the resilience story end to end:

* ``router_retries``  — attempts re-sent to a different replica after a
  retryable system failure (unary retries + backoff loops);
* ``circuit_open``    — CLOSED→OPEN breaker transitions (replica
  ejections from routing);
* ``streams_resumed`` — SSE streams failed over mid-decode and resumed
  on a healthy replica (the zero-dropped-streams invariant, countable);
* ``drain_handoffs``  — in-flight streams a drain deadline force-handed
  to failover during replica replacement (each one is a drain that did
  not complete gracefully);
* ``ctrl_reresolves`` — ingress re-resolutions of the serve controller
  after failures (each one is a controller restart/outage the ingress
  rode out; a climbing count means the control plane is flapping).
"""

from __future__ import annotations

import threading
from typing import Dict

COUNTER_NAMES = ("router_retries", "circuit_open", "streams_resumed",
                 "drain_handoffs", "ctrl_reresolves")

_lock = threading.Lock()
_stats: Dict[str, float] = {k: 0.0 for k in COUNTER_NAMES}
_user_counters = None     # name -> util.metrics.Counter, created lazily


def _counters():
    global _user_counters
    if _user_counters is None:
        try:
            from ray_tpu.util.metrics import Counter
            _user_counters = {
                "router_retries": Counter(
                    "router_retries",
                    "serve requests re-sent to another replica after a "
                    "retryable failure"),
                "circuit_open": Counter(
                    "circuit_open",
                    "replica circuit-breaker CLOSED->OPEN transitions "
                    "(routing ejections)"),
                "streams_resumed": Counter(
                    "streams_resumed",
                    "SSE streams failed over mid-decode and resumed on a "
                    "healthy replica"),
                "drain_handoffs": Counter(
                    "drain_handoffs",
                    "in-flight streams force-failed-over when a replica "
                    "drain hit its deadline"),
                "ctrl_reresolves": Counter(
                    "ctrl_reresolves",
                    "ingress re-resolutions of the serve controller after "
                    "failures (controller restarts ridden out)"),
            }
        except Exception:
            _user_counters = {}
    return _user_counters


def bump(name: str, value: float = 1.0) -> None:
    with _lock:
        _stats[name] = _stats.get(name, 0.0) + value
    c = _counters().get(name)
    if c is not None:
        try:
            c.inc(value)
        except Exception:
            pass


def stats() -> Dict[str, float]:
    """Snapshot of this process's serve counters (ints where whole)."""
    with _lock:
        return {k: (int(v) if float(v).is_integer() else round(v, 3))
                for k, v in _stats.items()}


def reset() -> None:
    """Test hook."""
    with _lock:
        for k in list(_stats):
            _stats[k] = 0.0
