"""ray_tpu.serve — online model serving.

Reference analogs: ``python/ray/serve/`` — ``serve.run`` (api.py:455),
``@serve.deployment`` (deployment.py), ServeController reconciliation
(controller.py:64, _private/deployment_state.py:1769), queue-aware router
(_private/router.py:261), micro-batching (serve/batching.py), HTTP proxy
(_private/http_proxy.py:387).

TPU-first shape: replicas are actors whose handlers typically close over a
jitted forward function — one replica per chip (or per slice via placement
groups).  The controller reconciles declared deployments to replica actors;
routing is client-side least-outstanding over the replica set with a cached
view refreshed from the controller.
"""

from __future__ import annotations

import time
from typing import Any, Callable, Dict, List, Optional

import ray_tpu
from ray_tpu.serve.batching import batch  # noqa: F401
from ray_tpu.serve.controller import (CONTROLLER_NAME, ServeController,
                                      DeploymentSpec)
from ray_tpu.serve.router import DeploymentHandle

__all__ = ["deployment", "run", "get_handle", "delete", "shutdown",
           "batch", "status", "start_http", "rolling_restart"]


class Deployment:
    """Declarative deployment wrapper produced by @serve.deployment."""

    def __init__(self, cls_or_fn, name, config):
        self._callable = cls_or_fn
        self.name = name
        self.config = config
        self._init_args: tuple = ()
        self._init_kwargs: dict = {}

    def options(self, **kw) -> "Deployment":
        d = Deployment(self._callable, kw.pop("name", self.name),
                       {**self.config, **kw})
        d._init_args, d._init_kwargs = self._init_args, self._init_kwargs
        return d

    def bind(self, *args, **kwargs) -> "Deployment":
        d = Deployment(self._callable, self.name, dict(self.config))
        d._init_args, d._init_kwargs = args, kwargs
        return d

    def _spec(self) -> DeploymentSpec:
        import cloudpickle
        return DeploymentSpec(
            name=self.name,
            callable_blob=cloudpickle.dumps(
                (self._callable, self._init_args, self._init_kwargs)),
            num_replicas=self.config.get("num_replicas", 1),
            max_concurrent_queries=self.config.get(
                "max_concurrent_queries", 8),
            route_prefix=self.config.get("route_prefix",
                                         f"/{self.name}"),
            resources=self.config.get("ray_actor_options", {}).get(
                "resources"),
            num_cpus=self.config.get("ray_actor_options", {}).get(
                "num_cpus", 1.0),
            autoscaling=self.config.get("autoscaling_config"),
            user_config=self.config.get("user_config"),
            runtime_env=self.config.get("ray_actor_options", {}).get(
                "runtime_env"),
        )


def deployment(cls_or_fn=None, *, name: Optional[str] = None, **config):
    """Decorator declaring a deployment (reference: serve/deployment.py)."""
    def wrap(target):
        return Deployment(target, name or target.__name__, config)
    if cls_or_fn is not None:
        return wrap(cls_or_fn)
    return wrap


def _controller() -> "ray_tpu.actor.ActorHandle":
    try:
        return ray_tpu.get_actor(CONTROLLER_NAME)
    except Exception:
        actor_cls = ray_tpu.remote(ServeController)
        # Generous concurrency: every live DeploymentHandle keeps one
        # listen_for_change long-poll PARKED in a slot (reference
        # LongPollHost is slot-free only because Serve's controller is
        # asyncio-unbounded); parked polls cost memory, not CPU.
        return actor_cls.options(name=CONTROLLER_NAME, lifetime="detached",
                                 get_if_exists=True, num_cpus=0.1,
                                 max_restarts=-1,
                                 max_concurrency=512).remote()


def run(target: Deployment, *, _blocking: bool = True) -> DeploymentHandle:
    """Deploy (create or update) and return a handle.

    Deployment graphs (reference: serve/dag.py + deployment_graph_build):
    a Deployment bound as another deployment's init arg is deployed first
    and replaced by its DeploymentHandle, so composed models call each
    other through the router (`self.upstream.remote(x)`)."""
    import copy

    def _has_dep(v) -> bool:
        if isinstance(v, Deployment):
            return True
        if isinstance(v, (list, tuple)):
            return any(_has_dep(x) for x in v)
        if isinstance(v, dict):
            return any(_has_dep(x) for x in v.values())
        return False

    def _materialize(v):
        # Recurse through containers: a Deployment nested in a list/dict
        # init arg must still be deployed and replaced by its handle —
        # silently pickling the raw Deployment into the replica would only
        # fail at first request time.  Containers WITHOUT a nested
        # Deployment pass through untouched (rebuilding would break tuple
        # subclasses and drop dict-subclass state like default factories).
        if isinstance(v, Deployment):
            return run(v, _blocking=_blocking)
        if not _has_dep(v):
            return v
        if isinstance(v, tuple):
            items = [_materialize(x) for x in v]
            return (v._replace(**dict(zip(v._fields, items)))
                    if hasattr(v, "_fields") else tuple(items))
        if isinstance(v, (list, dict)):
            c = copy.copy(v)   # preserves subclass + its extra state
            if isinstance(c, list):
                for i, x in enumerate(c):
                    c[i] = _materialize(x)
            else:
                for k in list(c):
                    c[k] = _materialize(c[k])
            return c
        return v

    if any(_has_dep(v) for v in (*target._init_args,
                                 *target._init_kwargs.values())):
        target = target.bind(
            *[_materialize(a) for a in target._init_args],
            **{k: _materialize(v)
               for k, v in target._init_kwargs.items()})
    ctrl = _controller()
    ray_tpu.get(ctrl.deploy.remote(target._spec()))
    if _blocking:
        deadline = time.monotonic() + 120
        while time.monotonic() < deadline:
            st = ray_tpu.get(ctrl.status.remote())
            d = st.get(target.name)
            if d and d["running"] >= d["target"]:
                break
            time.sleep(0.2)
        else:
            raise TimeoutError(
                f"deployment {target.name} did not become ready")
    return get_handle(target.name)


def get_handle(name: str) -> DeploymentHandle:
    return DeploymentHandle(name, _controller())


def status() -> Dict[str, Any]:
    return ray_tpu.get(_controller().status.remote())


def delete(name: str):
    ray_tpu.get(_controller().delete_deployment.remote(name))


def rolling_restart(name: str) -> Dict[str, Any]:
    """Replace every replica of ``name`` one at a time with zero dropped
    streams: surge-create the replacement, stop routing to the victim
    (long-poll push), drain it (RT_SERVE_DRAIN_S), then kill it —
    stragglers complete via the ingress's mid-stream failover.  Returns
    ``{"deployment", "replaced", "skipped"}``."""
    return ray_tpu.get(_controller().rolling_restart.remote(name),
                       timeout=600)


def start_http(host: str = "127.0.0.1", port: int = 0,
               per_node: bool = False) -> str:
    """Start the HTTP ingress; returns the first ingress's base URL.

    Reference: one ``HTTPProxyActor`` per node (http_proxy.py:387) so no
    single actor is a serving bottleneck or SPOF.  ``per_node=True``
    starts one ingress pinned to every alive node (named
    ``_serve_http:<node12>``); ``http_addresses()`` lists them all.  Each
    ingress keeps its own long-poll-refreshed route table, so any of them
    can serve any route."""
    urls = _start_ingresses(host, port, per_node)
    return urls[0]


def http_addresses() -> List[str]:
    """Base URLs of every running ingress actor (reference:
    serve.status() proxy listing)."""
    from ray_tpu._private.worker import get_core
    urls = []
    named = get_core().gcs_request({"type": "list_named_actors"})
    for rec in named:
        name = rec["name"]
        if name == "_serve_http" or name.startswith("_serve_http:"):
            try:
                a = ray_tpu.get_actor(name)
                h, p = ray_tpu.get(a.address.remote(), timeout=30)
                urls.append(f"http://{h}:{p}")
            except Exception:
                pass
    return sorted(urls)


def _wait_name_free(name: str, core, timeout: float = 30.0) -> bool:
    """Block until a detached-actor name is free in the GCS.

    ``get_named_actor`` already filters DEAD actors, so the name is free
    as soon as the kill lands.  Returns False on timeout (callers proceed
    anyway — the retry then fails loudly instead of silently hanging)."""
    from ray_tpu._private.worker import global_worker
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        try:
            rec = core.gcs_request({"type": "get_named_actor",
                                    "name": name,
                                    "namespace": global_worker.namespace})
        except Exception:
            return True     # GCS gone — nothing to conflict with
        if rec is None:
            return True
        time.sleep(0.1)
    return False


def _start_ingresses(host: str, port: int, per_node: bool) -> List[str]:
    from ray_tpu._private.worker import get_core, global_worker
    from ray_tpu.serve.http_ingress import HTTPIngress
    _controller()  # make sure the controller exists for route refresh
    ingress_cls = ray_tpu.remote(HTTPIngress)
    targets: List[tuple] = [("_serve_http", None)]
    if per_node:
        from ray_tpu.util.scheduling_strategies import (
            NodeAffinitySchedulingStrategy)
        nodes = get_core().gcs_request({"type": "get_nodes"})
        targets = [(f"_serve_http:{n['node_id'][:12]}",
                    NodeAffinitySchedulingStrategy(n["node_id"]))
                   for n in nodes if n["alive"]]
    urls = []
    for name, strategy in targets:
        # Every node's ingress tries the requested port (on real
        # multi-host clusters the binds are on distinct hosts).  Only on
        # an actual bind conflict — simulated clusters share one host —
        # does that node's ingress fall back to an ephemeral port.
        addr = None
        last_err: Optional[Exception] = None
        for node_port in ((port,) if port == 0 else (port, 0)):
            ingress = ingress_cls.options(
                name=name, lifetime="detached", get_if_exists=True,
                num_cpus=0, max_concurrency=64,
                scheduling_strategy=strategy).remote(
                host, node_port, global_worker.namespace)
            try:
                addr = ray_tpu.get(ingress.address.remote(), timeout=60)
                break
            except Exception as e:
                # a bind conflict surfaces as a wrapped TaskError(OSError)
                # — retry once on an ephemeral port; anything that also
                # fails the retry propagates below
                last_err = e
                ray_tpu.kill(ingress)
                # kill() is async on the GCS side: until the DEAD state
                # lands, get_if_exists on the retry would hand back the
                # DYING actor and the ephemeral-port attempt would time
                # out against it.  Wait for the name to actually free.
                _wait_name_free(name, get_core(), timeout=30)
        if addr is None:
            raise RuntimeError(
                f"serve ingress {name} failed to start") from last_err
        urls.append(f"http://{addr[0]}:{addr[1]}")
    return urls


def shutdown():
    """Tear down all deployments, the controller, and the ingress."""
    from ray_tpu._private.worker import get_core
    fleet = []
    try:
        fleet = [r["name"] for r in
                 get_core().gcs_request({"type": "list_named_actors"})
                 if r["name"].startswith("_serve_http:")]
    except Exception:
        pass
    for actor_name in (*fleet, "_serve_http", CONTROLLER_NAME):
        try:
            a = ray_tpu.get_actor(actor_name)
            if actor_name == CONTROLLER_NAME:
                try:
                    ray_tpu.get(a.shutdown.remote(), timeout=30)
                except Exception:
                    pass
            ray_tpu.kill(a)
        except Exception:
            pass

from ray_tpu._private.usage_stats import record_library_usage as _rlu
_rlu("serve")
del _rlu
