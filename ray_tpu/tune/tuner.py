"""Tuner: the user-facing tuning entry point.

Design analog: reference ``python/ray/tune/tuner.py`` (Tuner.fit:249 ->
tune.run -> TrialRunner loop) plus ``Tuner.restore`` for experiment resume.
Accepts a function, a Trainable subclass, or a train.BaseTrainer (wrapped
via as_trainable, mirroring base_trainer.py:500).
"""

from __future__ import annotations

import os
from typing import Any, Callable, Dict, Optional, Type, Union

from ray_tpu.air.config import RunConfig
from ray_tpu.air.result import Result
from ray_tpu.air.storage import is_uri
from ray_tpu.tune.execution.trial_runner import TrialRunner
from ray_tpu.tune.experiment.trial import Trial
from ray_tpu.tune.result_grid import ResultGrid
from ray_tpu.tune.search.basic_variant import BasicVariantGenerator
from ray_tpu.tune.search.searcher import Searcher
from ray_tpu.tune.trainable import Trainable, wrap_function
from ray_tpu.tune.tune_config import TuneConfig


def _to_trainable_cls(trainable) -> Type[Trainable]:
    from ray_tpu.train.base_trainer import BaseTrainer
    if isinstance(trainable, BaseTrainer):
        return trainable.as_trainable()
    if isinstance(trainable, type) and issubclass(trainable, Trainable):
        return trainable
    if callable(trainable):
        return wrap_function(trainable)
    raise TypeError(f"cannot tune {type(trainable)}")


def _mirror_dir(uri: str, fresh: bool = False) -> str:
    """Local mirror for a synced experiment URI.

    Keyed by (uri, pid) so concurrent same-URI runs on one machine don't
    interleave writes; ``fresh=True`` wipes any leftover state first (a new
    run must not inherit a previous experiment's files)."""
    import atexit
    import hashlib
    import shutil
    import tempfile
    h = hashlib.sha1(uri.encode()).hexdigest()[:12]
    d = os.path.join(tempfile.gettempdir(),
                     f"rt_tune_mirror_{h}_{os.getpid()}")
    if fresh:
        shutil.rmtree(d, ignore_errors=True)
    os.makedirs(d, exist_ok=True)
    # The mirror is a full experiment copy; reap it at interpreter exit so
    # repeated URI-storage runs don't accumulate copies in /tmp (same
    # pattern as Checkpoint.from_uri's download dirs).
    atexit.register(shutil.rmtree, d, ignore_errors=True)
    return d


class Tuner:
    def __init__(self,
                 trainable: Union[Callable, Type[Trainable], Any],
                 *,
                 param_space: Optional[Dict[str, Any]] = None,
                 tune_config: Optional[TuneConfig] = None,
                 run_config: Optional[RunConfig] = None,
                 _restore_path: Optional[str] = None):
        self._trainable_cls = _to_trainable_cls(trainable)
        self._param_space = param_space or {}
        self._tune_config = tune_config or TuneConfig()
        self._run_config = run_config or RunConfig()
        self._restore_path = _restore_path

    @classmethod
    def restore(cls, path: str, trainable,
                *, tune_config: Optional[TuneConfig] = None,
                run_config: Optional[RunConfig] = None) -> "Tuner":
        """Resume an interrupted experiment from its storage directory or
        URI (file://, gs://, ... — the experiment is downloaded first, so
        no surviving node needs a local copy).  Pass the original
        tune_config/run_config so stop criteria and schedulers apply to
        the resumed trials as well."""
        return cls(trainable, tune_config=tune_config,
                   run_config=run_config, _restore_path=path)

    def fit(self) -> ResultGrid:
        tc = self._tune_config
        searcher = tc.search_alg
        if searcher is None:
            searcher = BasicVariantGenerator(
                self._param_space, num_samples=tc.num_samples, seed=tc.seed)
        elif isinstance(searcher, Searcher):
            searcher.set_search_properties(tc.metric, tc.mode or "max",
                                           self._param_space)
            # Open-ended searchers (TPE/GP) honor num_samples like the
            # reference: cap total suggestions.
            if tc.num_samples and searcher.total_suggestions is None:
                from ray_tpu.tune.search.searcher import BudgetedSearcher
                searcher = BudgetedSearcher(searcher, tc.num_samples)

        name = self._run_config.name or "tune_experiment"
        storage = self._run_config.storage_path
        restore_path = self._restore_path
        sync_uri = None
        if storage:
            storage = (storage.rstrip("/") + "/" + name
                       if is_uri(storage) else os.path.join(storage, name))
        elif restore_path:
            # Resumed experiments keep checkpointing where they left off.
            storage = restore_path
        if storage and is_uri(storage):
            # URI storage: run against a local mirror, sync every
            # experiment-state save (reference tune/syncer.py).  A resume
            # from a URI first pulls the experiment down — the local mirror
            # may live on a node that never saw the original run.
            sync_uri = storage
            storage = _mirror_dir(sync_uri, fresh=True)
            if restore_path:
                from ray_tpu.air.storage import get_provider
                get_provider(sync_uri).download_dir(sync_uri, storage)
                restore_path = storage
        elif restore_path and is_uri(restore_path):
            # URI restore combined with local (or absent) storage_path:
            # still download the experiment before reading state from it.
            from ray_tpu.air.storage import get_provider
            local = _mirror_dir(restore_path, fresh=True)
            get_provider(restore_path).download_dir(restore_path, local)
            restore_path = local
            if not storage:
                storage = local

        runner = TrialRunner(
            self._trainable_cls,
            searcher=searcher,
            scheduler=tc.scheduler,
            metric=tc.metric,
            mode=tc.mode or "max",
            max_concurrent=tc.max_concurrent_trials,
            stop=self._run_config.stop,
            max_failures=self._run_config.failure_config.max_failures,
            experiment_name=name,
            storage_path=storage,
            reuse_actors=tc.reuse_actors,
            sync_uri=sync_uri,
        )
        if restore_path:
            runner.restore_experiment_state(restore_path)
        runner.run_until_done()
        return ResultGrid(
            [self._trial_to_result(t) for t in runner.trials],
            metric=tc.metric, mode=tc.mode or "max")

    @staticmethod
    def _trial_to_result(trial: Trial) -> Result:
        return Result(
            metrics=trial.last_result or None,
            checkpoint=trial.checkpoint,
            error=RuntimeError(trial.error) if trial.error else None,
            metrics_history=trial.metrics_history,
        )
