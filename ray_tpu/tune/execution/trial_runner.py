"""TrialRunner: the Tune experiment event loop.

Design analog: reference ``python/ray/tune/execution/trial_runner.py:327``
(step:969 -- start pending trials, collect one ready result, feed searcher +
scheduler, apply decisions) and ``ray_trial_executor.py:191`` (trial actors).
Experiment state snapshots every ``checkpoint_period`` steps mirror
_ExperimentCheckpointManager (trial_runner.py:136) for Tuner.restore.
"""

from __future__ import annotations

import json
import logging
import os
import time
from typing import Any, Dict, List, Optional, Tuple

import cloudpickle

import ray_tpu
from ray_tpu.air.checkpoint import Checkpoint
from ray_tpu.tune.experiment.trial import (
    ERROR, PENDING, RUNNING, TERMINATED, Trial)
from ray_tpu.tune.schedulers.trial_scheduler import (
    FIFOScheduler, TrialScheduler)
from ray_tpu.tune.search.searcher import Searcher

logger = logging.getLogger(__name__)


class _TrialActor:
    """Actor body hosting one Trainable instance."""

    def __init__(self, trainable_blob: bytes, config: Dict[str, Any],
                 trial_id: str, trial_name: str):
        cls = cloudpickle.loads(trainable_blob)
        self._t = cls(config, trial_id=trial_id, trial_name=trial_name)

    def train(self) -> Dict[str, Any]:
        return self._t.train()

    def save(self):
        return self._t.save()

    def restore(self, ckpt):
        self._t.restore(ckpt)

    def reset(self, new_config: Dict[str, Any]) -> bool:
        ok = self._t.reset_config(new_config)
        if ok:
            self._t.config = new_config
        return ok

    def request_stop(self):
        self._t.stop()
        return True


class TrialRunner:
    def __init__(self,
                 trainable_cls,
                 searcher: Searcher,
                 scheduler: Optional[TrialScheduler] = None,
                 metric: Optional[str] = None,
                 mode: str = "max",
                 max_concurrent: Optional[int] = None,
                 stop: Optional[Dict[str, Any]] = None,
                 max_failures: int = 0,
                 experiment_name: str = "exp",
                 storage_path: Optional[str] = None,
                 checkpoint_period: int = 10,
                 reuse_actors: bool = False,
                 sync_uri: Optional[str] = None,
                 sync_period_s: float = 5.0):
        self._trainable_cls = trainable_cls
        self._trainable_blob = cloudpickle.dumps(trainable_cls)
        self._searcher = searcher
        self._scheduler = scheduler or FIFOScheduler()
        self._scheduler.set_search_properties(metric, mode)
        self._metric = metric
        self._mode = mode
        self._max_concurrent = max_concurrent or 8
        self._stop = stop or {}
        self._max_failures = max_failures
        self._experiment_name = experiment_name
        self._storage_path = storage_path
        self._checkpoint_period = checkpoint_period
        self._reuse_actors = reuse_actors
        # Experiment-dir sync to URI storage (reference tune/syncer.py):
        # every experiment-state save is mirrored to sync_uri, debounced to
        # one upload per sync_period_s, with a forced final sync.
        self._sync_uri = sync_uri
        self._sync_period_s = sync_period_s
        self._last_sync = 0.0
        self.trials: List[Trial] = []
        self._exploit_requests: List[Tuple[Trial, Trial, Dict]] = []
        self._searcher_exhausted = False
        self._steps = 0
        self._resources = trainable_cls.default_resource_request({})

    # -- scheduler callback surface --------------------------------------
    def live_trials(self) -> List[Trial]:
        return [t for t in self.trials if t.status == RUNNING]

    def request_exploit(self, victim: Trial, donor: Trial,
                        new_config: Dict[str, Any]):
        self._exploit_requests.append((victim, donor, new_config))

    # -- main loop --------------------------------------------------------
    def step(self):
        self._maybe_add_trials()
        self._start_pending()
        self._process_one_result()
        self._apply_exploits()
        self._steps += 1
        if self._storage_path and \
                self._steps % self._checkpoint_period == 0:
            self.save_experiment_state()
            self._maybe_sync()

    def is_finished(self) -> bool:
        return (self._searcher_exhausted
                and all(t.is_finished() for t in self.trials))

    def run_until_done(self):
        while not self.is_finished():
            self.step()
        if self._storage_path:
            self.save_experiment_state()
            self._maybe_sync(force=True)

    def _maybe_sync(self, force: bool = False):
        if not self._sync_uri or not self._storage_path:
            return
        now = time.time()
        if not force and now - self._last_sync < self._sync_period_s:
            return
        self._last_sync = now
        from ray_tpu.air.storage import get_provider
        try:
            get_provider(self._sync_uri).upload_dir(self._storage_path,
                                                    self._sync_uri)
        except Exception:
            logger.warning("experiment sync to %s failed", self._sync_uri,
                           exc_info=True)

    # -- internals --------------------------------------------------------
    def _maybe_add_trials(self):
        if self._searcher_exhausted:
            return
        while len([t for t in self.trials if not t.is_finished()]) < \
                self._max_concurrent:
            tid = f"{len(self.trials):05d}"
            cfg = self._searcher.suggest(tid)
            if cfg is None:
                # Exhausted vs. backpressured: a searcher with a known
                # budget is done once it's met; any searcher is done when
                # it returns None with no trials still in flight (custom
                # Searchers need not implement total_suggestions).
                total = self._searcher.total_suggestions
                if (total is not None and len(self.trials) >= total) or \
                        all(t.is_finished() for t in self.trials):
                    self._searcher_exhausted = True
                break
            trial = Trial(cfg, trial_id=tid,
                          experiment_name=self._experiment_name)
            self.trials.append(trial)
            self._scheduler.on_trial_add(self, trial)

    def _start_pending(self):
        running = len(self.live_trials())
        for trial in self.trials:
            if running >= self._max_concurrent:
                break
            if trial.status != PENDING:
                continue
            self._start_trial(trial)
            running += 1

    def _start_trial(self, trial: Trial,
                     restore_from: Optional[Checkpoint] = None):
        actor_cls = ray_tpu.remote(_TrialActor)
        opts = {"num_cpus": self._resources.get("CPU", 1.0),
                "max_concurrency": 2}
        if self._resources.get("TPU"):
            opts["num_tpus"] = self._resources["TPU"]
        trial.actor = actor_cls.options(**opts).remote(
            self._trainable_blob, trial.config, trial.trial_id,
            trial.trial_name)
        ckpt = restore_from or trial.checkpoint
        if ckpt is not None:
            ray_tpu.get(trial.actor.restore.remote(ckpt))
        trial.status = RUNNING
        trial.pending_ref = trial.actor.train.remote()

    def _process_one_result(self):
        refs = [t.pending_ref for t in self.trials
                if t.status == RUNNING and t.pending_ref is not None]
        if not refs:
            return
        # Process every ready result this step: draining a single trial's
        # queue would starve the others and break population-relative
        # schedulers (PBT/median) that compare concurrent progress.
        ready, _ = ray_tpu.wait(refs, num_returns=1, timeout=10.0)
        if not ready:
            return
        ready_set, _ = ray_tpu.wait(refs, num_returns=len(refs),
                                    timeout=0.05)
        batch = ready_set or ready
        # Rotate processing order each step: lockstep trials otherwise hit
        # every ASHA rung in trial order, and the first arrival at an empty
        # rung always survives -- rotation restores the asynchrony the
        # schedulers assume.
        rot = self._steps % len(batch)
        for ref in batch[rot:] + batch[:rot]:
            self._handle_result_ref(ref)

    def _handle_result_ref(self, ref):
        trial = next((t for t in self.trials if t.pending_ref == ref), None)
        if trial is None:
            return
        try:
            result = ray_tpu.get(ref)
        except Exception as e:  # actor died or train raised
            self._on_trial_error(trial, e)
            return
        trial.pending_ref = None
        result.setdefault("trial_id", trial.trial_id)
        result["config"] = trial.config

        if result.get("done"):
            # A bare terminal signal keeps the last reported metrics
            # (reference merges the final result into last_result).
            merged = dict(trial.last_result)
            merged.update(result)
            trial.last_result = merged
            self._complete_trial(trial, merged)
            return
        trial.last_result = result
        trial.metrics_history.append(result)
        self._searcher.on_trial_result(trial.trial_id, result)
        decision = self._scheduler.on_trial_result(self, trial, result)
        if self._should_stop(result):
            decision = TrialScheduler.STOP
        if decision == TrialScheduler.STOP:
            self._checkpoint_trial(trial)
            self._complete_trial(trial, result)
        else:
            trial.pending_ref = trial.actor.train.remote()

    def _should_stop(self, result: Dict[str, Any]) -> bool:
        # Reference semantics (tune/stopper MaximumIterationStopper et al.):
        # stop when result[key] >= threshold, regardless of optimization
        # mode -- thresholds are ceilings on monotone counters/metrics.
        return any(key in result and result[key] >= threshold
                   for key, threshold in self._stop.items())

    def _checkpoint_trial(self, trial: Trial):
        try:
            trial.checkpoint = ray_tpu.get(trial.actor.save.remote())
        except Exception:
            pass

    def _complete_trial(self, trial: Trial, result: Dict[str, Any]):
        self._checkpoint_trial(trial)
        self._searcher.on_trial_complete(trial.trial_id, result)
        self._scheduler.on_trial_complete(self, trial, result)
        self._stop_actor(trial)
        trial.status = TERMINATED

    def _on_trial_error(self, trial: Trial, error: Exception):
        trial.num_failures += 1
        self._stop_actor(trial)
        if trial.num_failures <= self._max_failures:
            logger.warning("trial %s failed (%d/%d), restarting",
                           trial.trial_id, trial.num_failures,
                           self._max_failures)
            trial.status = PENDING
            return
        trial.error = str(error)
        trial.status = ERROR
        self._searcher.on_trial_complete(trial.trial_id, error=True)
        self._scheduler.on_trial_error(self, trial)

    def _stop_actor(self, trial: Trial):
        if trial.actor is not None:
            try:
                ray_tpu.get(trial.actor.request_stop.remote(), timeout=5.0)
            except Exception:
                pass
            try:
                ray_tpu.kill(trial.actor)
            except Exception:
                pass
            trial.actor = None
        trial.pending_ref = None

    def _apply_exploits(self):
        reqs, self._exploit_requests = self._exploit_requests, []
        for victim, donor, new_config in reqs:
            if victim.status != RUNNING or donor.status != RUNNING:
                continue
            try:
                donor_ckpt = ray_tpu.get(donor.actor.save.remote())
            except Exception:
                continue
            logger.info("PBT exploit: %s <- %s", victim.trial_id,
                        donor.trial_id)
            # Drain the victim's in-flight step, then replace it.
            try:
                if victim.pending_ref is not None:
                    ray_tpu.get(victim.pending_ref)
            except Exception:
                pass
            victim.pending_ref = None
            victim.config = new_config
            if self._reuse_actors and victim.actor is not None:
                # In-place exploit: reset_config + restore on the live
                # actor (reference reuse_actors fast path).
                try:
                    if ray_tpu.get(
                            victim.actor.reset.remote(new_config)):
                        ray_tpu.get(
                            victim.actor.restore.remote(donor_ckpt))
                        victim.pending_ref = victim.actor.train.remote()
                        continue
                except Exception:
                    pass
            self._stop_actor(victim)
            victim.status = PENDING
            self._start_trial(victim, restore_from=donor_ckpt)

    # -- experiment checkpointing -----------------------------------------
    def save_experiment_state(self):
        os.makedirs(self._storage_path, exist_ok=True)
        state = {
            "experiment_name": self._experiment_name,
            "timestamp": time.time(),
            "trials": [t.state_dict() for t in self.trials],
        }
        tmp = os.path.join(self._storage_path, ".experiment_state.tmp")
        with open(tmp, "wb") as f:
            f.write(cloudpickle.dumps(state))
        os.replace(tmp, os.path.join(self._storage_path,
                                     "experiment_state.pkl"))
        with open(os.path.join(self._storage_path,
                               "experiment_state.json"), "w") as f:
            json.dump({"experiment_name": self._experiment_name,
                       "trials": [
                           {k: v for k, v in t.state_dict().items()
                            if k != "checkpoint"}
                           for t in self.trials]}, f, indent=2, default=str)

    def restore_experiment_state(self, path: str):
        with open(os.path.join(path, "experiment_state.pkl"), "rb") as f:
            state = cloudpickle.loads(f.read())
        self.trials = [Trial.from_state(s, state["experiment_name"])
                       for s in state["trials"]]
        # Unfinished trials restart (from their last checkpoint if any).
        for t in self.trials:
            if not t.is_finished():
                t.status = PENDING
        self._searcher_exhausted = True  # configs already materialized
