"""The Trainable contract and its function-API adapter.

Design analog: reference ``python/ray/tune/trainable/trainable.py:66``
(setup/step/save_checkpoint/load_checkpoint/stop driven by the trial
executor) and ``trainable/function_trainable.py`` (user fn in a thread,
results pulled through a queue -- same mechanism our train worker uses).
"""

from __future__ import annotations

import queue
import threading
import traceback
from typing import Any, Callable, Dict, Optional, Type

from ray_tpu.air import session as air_session
from ray_tpu.air.checkpoint import Checkpoint


class Trainable:
    """Subclass API: override setup/step/save_checkpoint/load_checkpoint."""

    def __init__(self, config: Optional[Dict[str, Any]] = None,
                 trial_id: str = "", trial_name: str = ""):
        self.config = config or {}
        self.trial_id = trial_id
        self.trial_name = trial_name
        self.iteration = 0
        self.setup(self.config)

    # -- subclass hooks ---------------------------------------------------
    def setup(self, config: Dict[str, Any]) -> None:
        pass

    def step(self) -> Dict[str, Any]:
        raise NotImplementedError

    def save_checkpoint(self) -> Optional[Dict[str, Any]]:
        return None

    def load_checkpoint(self, checkpoint: Optional[Dict[str, Any]]) -> None:
        pass

    def reset_config(self, new_config: Dict[str, Any]) -> bool:
        """Reuse this instance for a new config (PBT exploit without actor
        restart).  Return False to force a fresh actor."""
        return False

    def cleanup(self) -> None:
        pass

    # -- driver-side driver ----------------------------------------------
    def train(self) -> Dict[str, Any]:
        result = self.step()
        self.iteration += 1
        result = dict(result or {})
        result.setdefault("training_iteration", self.iteration)
        result.setdefault("done", False)
        return result

    def save(self) -> Checkpoint:
        state = self.save_checkpoint() or {}
        return Checkpoint.from_dict(
            {"trainable_state": state, "iteration": self.iteration})

    def restore(self, checkpoint: Checkpoint) -> None:
        data = checkpoint.to_dict()
        self.iteration = data.get("iteration", 0)
        self.load_checkpoint(data.get("trainable_state"))

    def stop(self) -> None:
        self.cleanup()

    @classmethod
    def default_resource_request(cls, config: Dict[str, Any]
                                 ) -> Dict[str, float]:
        return {"CPU": 1.0}


class FunctionTrainable(Trainable):
    """Wraps ``fn(config)`` (which calls tune.report) into step() pulls."""

    _fn: Callable = None  # set by subclass factory

    def setup(self, config):
        # maxsize=1: session.report blocks until the driver consumes the
        # result (the reference's report handshake).  Besides backpressure,
        # this is what makes reset_config safe: an orphaned fn thread parks
        # on a discarded queue's put() instead of free-running.
        self._queue: "queue.Queue" = queue.Queue(maxsize=1)
        self._started = False
        self._restore_checkpoint: Optional[Checkpoint] = None
        self._error: Optional[str] = None

    def reset_config(self, new_config):
        """In-place PBT exploit: orphan the running fn thread (daemonic; it
        parks on its now-discarded bounded queue) and arm a fresh start.
        Avoids a full actor restart per exploit — on the reference this is
        the reuse_actors fast path."""
        self._queue = queue.Queue(maxsize=1)
        self._started = False
        self._restore_checkpoint = None
        self._latest_fn_checkpoint = None
        return True

    def _start(self):
        fn = type(self)._fn
        q = self._queue
        restore_ckpt = self._restore_checkpoint
        config = dict(self.config)
        trial_id, trial_name = self.trial_id, self.trial_name

        class _FnSession(air_session._SessionBase):
            def __init__(self):
                self.trial_id = trial_id
                self.trial_name = trial_name

            def report(self, metrics, checkpoint=None):
                q.put(("report", metrics, checkpoint))

            def get_checkpoint(self):
                return restore_ckpt

        def _run():
            air_session._set_session(_FnSession())
            try:
                import inspect
                if inspect.signature(fn).parameters:
                    fn(config)
                else:
                    fn()
                q.put(("done", None, None))
            except BaseException as e:  # noqa: BLE001
                q.put(("error", repr(e), traceback.format_exc()))
            finally:
                air_session._set_session(None)

        self._thread = threading.Thread(target=_run, daemon=True)
        self._thread.start()
        self._started = True

    def step(self):
        if not self._started:
            self._start()
        kind, payload, extra = self._queue.get()
        if kind == "error":
            raise RuntimeError(
                f"tune function failed: {payload}\n{extra}")
        if kind == "done":
            return {"done": True}
        metrics, ckpt = payload, extra
        if ckpt is not None:
            self._latest_fn_checkpoint = ckpt
        return dict(metrics)

    def save_checkpoint(self):
        ckpt = getattr(self, "_latest_fn_checkpoint", None)
        return {"fn_checkpoint": ckpt.to_dict()} if ckpt else None

    def load_checkpoint(self, state):
        if state and state.get("fn_checkpoint") is not None:
            self._restore_checkpoint = Checkpoint.from_dict(
                state["fn_checkpoint"])


def wrap_function(fn: Callable) -> Type[FunctionTrainable]:
    return type(f"fn_{getattr(fn, '__name__', 'trainable')}",
                (FunctionTrainable,), {"_fn": staticmethod(fn)})


def wrap_trainer_as_trainable(trainer) -> Type[Trainable]:
    """Adapt a train.BaseTrainer into a Trainable (reference
    base_trainer.py:500 as_trainable).  Each step() drains one report from
    the trainer's training loop, run on a background thread."""
    import copy

    def _fn(config):
        t = copy.deepcopy(trainer)
        if config:
            # Tune param_space keys override train_loop_config entries
            # (reference: train_loop_config nested under param_space).
            loop_cfg = dict(getattr(t, "_train_loop_config", None) or {})
            loop_cfg.update(config.get("train_loop_config", config))
            if hasattr(t, "_train_loop_config"):
                t._train_loop_config = loop_cfg
        t.setup()
        t.training_loop()

    cls = wrap_function(_fn)

    def _resources(cls_, config):
        # The trial actor itself is lightweight; the nested trainer
        # gang-reserves num_workers x bundle() via its own placement group
        # when training starts (reference: Tune allocates the whole PG up
        # front; deferring to the trainer keeps trial startup cheap and
        # lets the PG wait queue do admission control).
        return {"CPU": 0.1}

    cls.default_resource_request = classmethod(_resources)
    return cls


def with_parameters(trainable, **kwargs):
    """Bind large constant objects into a trainable (reference
    tune/trainable/util.py with_parameters) -- values ship by object ref."""
    import ray_tpu
    refs = {k: ray_tpu.put(v) for k, v in kwargs.items()}

    if isinstance(trainable, type) and issubclass(trainable, Trainable):
        base = trainable

        class _WithParams(base):  # type: ignore[valid-type]
            def setup(self, config):
                import ray_tpu as _rt
                bound = {k: _rt.get(r) for k, r in refs.items()}
                merged = dict(config)
                merged.update(bound)
                super().setup(merged)

        _WithParams.__name__ = base.__name__
        return _WithParams

    fn = trainable

    def _wrapped(config):
        import ray_tpu as _rt
        bound = {k: _rt.get(r) for k, r in refs.items()}
        return fn(config, **bound)

    _wrapped.__name__ = getattr(fn, "__name__", "with_parameters")
    return _wrapped


def with_resources(trainable, resources: Dict[str, float]):
    """Attach a resource request (reference tune/trainable/util.py)."""
    if isinstance(trainable, type) and issubclass(trainable, Trainable):
        cls = trainable
    else:
        cls = wrap_function(trainable)

    res = dict(resources)

    class _WithResources(cls):  # type: ignore[valid-type]
        @classmethod
        def default_resource_request(cls_, config):
            return dict(res)

    _WithResources.__name__ = cls.__name__
    return _WithResources
