"""TuneConfig.

Design analog: reference ``python/ray/tune/tune_config.py`` (TuneConfig
dataclass: metric/mode/search_alg/scheduler/num_samples/
max_concurrent_trials).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional


@dataclass
class TuneConfig:
    metric: Optional[str] = None
    mode: Optional[str] = None
    num_samples: int = 1
    max_concurrent_trials: Optional[int] = None
    search_alg: Optional[object] = None
    scheduler: Optional[object] = None
    # Default True: trainables opt in via reset_config (FunctionTrainable
    # does); class trainables returning False still get a fresh actor.
    # Avoids a worker-process restart per PBT exploit.
    reuse_actors: bool = True
    seed: Optional[int] = None

    def __post_init__(self):
        if self.mode is not None and self.mode not in ("min", "max"):
            raise ValueError("mode must be 'min' or 'max'")
