"""Searcher contract + ConcurrencyLimiter.

Design analog: reference ``python/ray/tune/search/searcher.py`` (Searcher
with suggest/on_trial_complete) and ``search/concurrency_limiter.py``.
"""

from __future__ import annotations

from typing import Any, Dict, Optional


class Searcher:
    def __init__(self, metric: Optional[str] = None, mode: str = "max"):
        self.metric = metric
        self.mode = mode

    def set_search_properties(self, metric: Optional[str], mode: str,
                              config: Dict[str, Any]) -> bool:
        if metric:
            self.metric = metric
        if mode:
            self.mode = mode
        return True

    def suggest(self, trial_id: str) -> Optional[Dict[str, Any]]:
        """Next config, None when exhausted/backpressured."""
        raise NotImplementedError

    def on_trial_result(self, trial_id: str, result: Dict[str, Any]):
        pass

    def on_trial_complete(self, trial_id: str,
                          result: Optional[Dict[str, Any]] = None,
                          error: bool = False):
        pass

    @property
    def total_suggestions(self) -> Optional[int]:
        """How many configs this searcher will emit, if known."""
        return None


class ConcurrencyLimiter(Searcher):
    def __init__(self, searcher: Searcher, max_concurrent: int):
        super().__init__(searcher.metric, searcher.mode)
        self.searcher = searcher
        self.max_concurrent = max_concurrent
        self._live = set()

    def set_search_properties(self, metric, mode, config):
        return self.searcher.set_search_properties(metric, mode, config)

    def suggest(self, trial_id):
        if len(self._live) >= self.max_concurrent:
            return None
        cfg = self.searcher.suggest(trial_id)
        if cfg is not None:
            self._live.add(trial_id)
        return cfg

    def on_trial_result(self, trial_id, result):
        self.searcher.on_trial_result(trial_id, result)

    def on_trial_complete(self, trial_id, result=None, error=False):
        self._live.discard(trial_id)
        self.searcher.on_trial_complete(trial_id, result, error)

    @property
    def total_suggestions(self):
        return self.searcher.total_suggestions


class BudgetedSearcher(Searcher):
    """Caps an open-ended searcher (TPE/GP suggest forever) at
    ``num_samples`` trials — the reference applies num_samples to any
    search_alg the same way (tune.run num_samples semantics)."""

    def __init__(self, searcher: Searcher, max_trials: int):
        super().__init__(searcher.metric, searcher.mode)
        self.searcher = searcher
        self.max_trials = max_trials
        self._issued = 0

    def set_search_properties(self, metric, mode, config):
        return self.searcher.set_search_properties(metric, mode, config)

    def suggest(self, trial_id):
        if self._issued >= self.max_trials:
            return None
        cfg = self.searcher.suggest(trial_id)
        if cfg is not None:
            self._issued += 1
        return cfg

    def on_trial_result(self, trial_id, result):
        self.searcher.on_trial_result(trial_id, result)

    def on_trial_complete(self, trial_id, result=None, error=False):
        self.searcher.on_trial_complete(trial_id, result, error)

    @property
    def total_suggestions(self):
        return self.max_trials
