"""Gaussian-process Bayesian optimization search (native, numpy-only).

Design analog: reference ``python/ray/tune/search/bayesopt/`` (wraps the
external `bayesian-optimization` package) — implemented directly here: an
RBF-kernel GP posterior over the normalized continuous dims with an
Expected Improvement acquisition maximized by random multistart.
Categorical dims fall back to the TPE-style frequency model; pure-random
until n_startup_trials observations exist.
"""

from __future__ import annotations

import math
import random
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ray_tpu.tune.search.sample import (Categorical, Domain, Float, Integer,
                                        is_grid)
from ray_tpu.tune.search.searcher import Searcher
from ray_tpu.tune.search.tpe import _flatten, _unflatten


class BayesOptSearcher(Searcher):
    def __init__(self, space: Optional[Dict[str, Any]] = None,
                 metric: Optional[str] = None, mode: str = "max",
                 n_startup_trials: int = 6, n_candidates: int = 256,
                 length_scale: float = 0.2, noise: float = 1e-4,
                 xi: float = 0.01, seed: Optional[int] = None):
        super().__init__(metric, mode)
        self._space = _flatten(space) if space else {}
        self._n_startup = n_startup_trials
        self._n_candidates = n_candidates
        self._ls = length_scale
        self._noise = noise
        self._xi = xi
        self._rng = random.Random(seed)
        self._np_rng = np.random.RandomState(seed)
        self._pending: Dict[str, Dict[tuple, Any]] = {}
        self._done: List[Tuple[Dict[tuple, Any], float]] = []

    def set_search_properties(self, metric, mode, config):
        super().set_search_properties(metric, mode, config)
        if config and not self._space:
            self._space = _flatten(config)
        return True

    def _numeric_dims(self):
        return [(p, d) for p, d in self._space.items()
                if isinstance(d, (Float, Integer))]

    # -------------------------------------------------------------- encode

    def _to_unit(self, dom, v: float) -> float:
        lo, hi = float(dom.lower), float(dom.upper)
        if getattr(dom, "log", False):
            return (math.log(v) - math.log(lo)) / \
                (math.log(hi) - math.log(lo))
        return (v - lo) / (hi - lo)

    def _from_unit(self, dom, u: float):
        lo, hi = float(dom.lower), float(dom.upper)
        if getattr(dom, "log", False):
            v = math.exp(math.log(lo) + u * (math.log(hi) - math.log(lo)))
        else:
            v = lo + u * (hi - lo)
        if isinstance(dom, Integer):
            v = max(dom.lower, min(dom.upper - 1, int(round(v))))
        return v

    # ------------------------------------------------------------- suggest

    def suggest(self, trial_id: str) -> Optional[Dict[str, Any]]:
        dims = self._numeric_dims()
        flat: Dict[tuple, Any] = {}
        for path, dom in self._space.items():
            if not isinstance(dom, Domain):
                flat[path] = dom
            elif not isinstance(dom, (Float, Integer)):
                flat[path] = dom.sample(self._rng)
        if len(self._done) < self._n_startup or not dims:
            for path, dom in dims:
                flat[path] = dom.sample(self._rng)
        else:
            x_best = self._maximize_ei(dims)
            for (path, dom), u in zip(dims, x_best):
                flat[path] = self._from_unit(dom, float(u))
        self._pending[trial_id] = flat
        return _unflatten(flat)

    def on_trial_complete(self, trial_id, result=None, error=False):
        flat = self._pending.pop(trial_id, None)
        if flat is None or error or not result or self.metric not in result:
            return
        v = float(result[self.metric])
        self._done.append((flat, v if self.mode == "max" else -v))

    # ------------------------------------------------------------------ GP

    def _maximize_ei(self, dims) -> np.ndarray:
        X = np.array([[self._to_unit(dom, float(cfg[path]))
                       for path, dom in dims]
                      for cfg, _ in self._done if
                      all(path in cfg for path, _ in dims)])
        y = np.array([v for cfg, v in self._done
                      if all(path in cfg for path, _ in dims)])
        ymu, ysd = y.mean(), y.std() + 1e-12
        yn = (y - ymu) / ysd

        def k(A, B):
            d2 = ((A[:, None, :] - B[None, :, :]) ** 2).sum(-1)
            return np.exp(-0.5 * d2 / (self._ls ** 2))

        K = k(X, X) + self._noise * np.eye(len(X))
        L = np.linalg.cholesky(K)
        alpha = np.linalg.solve(L.T, np.linalg.solve(L, yn))

        cand = self._np_rng.rand(self._n_candidates, len(dims))
        # Exploit around the incumbent too (local refinement candidates).
        best_x = X[int(np.argmax(yn))]
        local = np.clip(best_x[None, :] + 0.1 *
                        self._np_rng.randn(self._n_candidates // 4,
                                           len(dims)), 0.0, 1.0)
        cand = np.vstack([cand, local])

        Ks = k(cand, X)
        mu = Ks @ alpha
        v = np.linalg.solve(L, Ks.T)
        var = np.clip(1.0 - (v ** 2).sum(axis=0), 1e-12, None)
        sd = np.sqrt(var)
        fbest = yn.max()
        z = (mu - fbest - self._xi) / sd
        # Standard-normal pdf/cdf without scipy.
        pdf = np.exp(-0.5 * z * z) / math.sqrt(2 * math.pi)
        cdf = 0.5 * (1.0 + np.vectorize(math.erf)(z / math.sqrt(2)))
        ei = (mu - fbest - self._xi) * cdf + sd * pdf
        return cand[int(np.argmax(ei))]
