"""Search-space primitives.

Design analog: reference ``python/ray/tune/search/sample.py`` (Domain /
Float / Integer / Categorical with samplers) and
``tune/search/variant_generator.py`` (grid expansion).
"""

from __future__ import annotations

import random
from typing import Any, Callable, Dict, List, Sequence


class Domain:
    def sample(self, rng: random.Random) -> Any:
        raise NotImplementedError


class Float(Domain):
    def __init__(self, lower: float, upper: float, log: bool = False,
                 q: float = 0.0):
        if log and lower <= 0:
            raise ValueError("loguniform requires lower > 0")
        self.lower, self.upper, self.log, self.q = lower, upper, log, q

    def sample(self, rng):
        import math
        if self.log:
            v = math.exp(rng.uniform(math.log(self.lower),
                                     math.log(self.upper)))
        else:
            v = rng.uniform(self.lower, self.upper)
        if self.q:
            v = round(v / self.q) * self.q
        return v


class Integer(Domain):
    def __init__(self, lower: int, upper: int, log: bool = False,
                 q: int = 1):
        self.lower, self.upper, self.log, self.q = lower, upper, log, q

    def sample(self, rng):
        import math
        if self.log:
            v = int(math.exp(rng.uniform(math.log(self.lower),
                                         math.log(self.upper))))
        else:
            v = rng.randint(self.lower, self.upper - 1)
        v = max(self.lower, min(self.upper - 1, (v // self.q) * self.q))
        return v


class Categorical(Domain):
    def __init__(self, categories: Sequence[Any]):
        self.categories = list(categories)

    def sample(self, rng):
        return rng.choice(self.categories)


class Normal(Domain):
    def __init__(self, mean: float = 0.0, sd: float = 1.0):
        self.mean, self.sd = mean, sd

    def sample(self, rng):
        return rng.gauss(self.mean, self.sd)


class Function(Domain):
    def __init__(self, fn: Callable):
        self.fn = fn

    def sample(self, rng):
        return self.fn(None) if self.fn.__code__.co_argcount else self.fn()


def uniform(lower: float, upper: float) -> Float:
    return Float(lower, upper)


def quniform(lower: float, upper: float, q: float) -> Float:
    return Float(lower, upper, q=q)


def loguniform(lower: float, upper: float) -> Float:
    return Float(lower, upper, log=True)


def randint(lower: int, upper: int) -> Integer:
    return Integer(lower, upper)


def qrandint(lower: int, upper: int, q: int) -> Integer:
    return Integer(lower, upper, q=q)


def lograndint(lower: int, upper: int) -> Integer:
    return Integer(lower, upper, log=True)


def randn(mean: float = 0.0, sd: float = 1.0) -> Normal:
    return Normal(mean, sd)


def choice(categories: Sequence[Any]) -> Categorical:
    return Categorical(categories)


def sample_from(fn: Callable) -> Function:
    return Function(fn)


def grid_search(values: List[Any]) -> Dict[str, List[Any]]:
    return {"grid_search": list(values)}


def is_grid(spec: Any) -> bool:
    return isinstance(spec, dict) and set(spec.keys()) == {"grid_search"}
