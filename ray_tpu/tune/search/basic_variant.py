"""Grid + random search over a param space.

Design analog: reference ``python/ray/tune/search/basic_variant.py``
(BasicVariantGenerator) + ``variant_generator.py`` grid expansion: the
cross-product of every ``grid_search`` key, times ``num_samples`` random
draws of the Domain keys.
"""

from __future__ import annotations

import itertools
import random
from typing import Any, Dict, List, Optional

from ray_tpu.tune.search.sample import Domain, is_grid
from ray_tpu.tune.search.searcher import Searcher


def _grid_paths(space: Dict[str, Any], prefix=()) -> List[tuple]:
    """Collect (path, values) for every grid_search at any nesting depth."""
    out = []
    for k, v in space.items():
        if is_grid(v):
            out.append((prefix + (k,), v["grid_search"]))
        elif isinstance(v, dict):
            out.extend(_grid_paths(v, prefix + (k,)))
    return out


def _deep_copy_dicts(space: Dict[str, Any]) -> Dict[str, Any]:
    return {k: _deep_copy_dicts(v) if isinstance(v, dict) else v
            for k, v in space.items()}


def _set_path(cfg: Dict[str, Any], path: tuple, value: Any):
    for k in path[:-1]:
        cfg = cfg[k]
    cfg[path[-1]] = value


def _expand_grid(space: Dict[str, Any]) -> List[Dict[str, Any]]:
    grids = _grid_paths(space)
    if not grids:
        return [_deep_copy_dicts(space)]
    axes = [values for _, values in grids]
    out = []
    for combo in itertools.product(*axes):
        cfg = _deep_copy_dicts(space)
        for (path, _), v in zip(grids, combo):
            _set_path(cfg, path, v)
        out.append(cfg)
    return out


def _resolve(cfg: Dict[str, Any], rng: random.Random) -> Dict[str, Any]:
    out = {}
    for k, v in cfg.items():
        if isinstance(v, Domain):
            out[k] = v.sample(rng)
        elif isinstance(v, dict) and not is_grid(v):
            out[k] = _resolve(v, rng)
        else:
            out[k] = v
    return out


class BasicVariantGenerator(Searcher):
    def __init__(self, space: Optional[Dict[str, Any]] = None,
                 num_samples: int = 1, seed: Optional[int] = None):
        super().__init__()
        self._space = space or {}
        self._num_samples = num_samples
        self._rng = random.Random(seed)
        self._variants: Optional[List[Dict[str, Any]]] = None
        self._idx = 0

    def set_search_properties(self, metric, mode, config):
        if config:
            self._space = config
        self._variants = None
        self._idx = 0
        return super().set_search_properties(metric, mode, config)

    def _materialize(self):
        grids = _expand_grid(self._space)
        self._variants = []
        for _ in range(self._num_samples):
            for g in grids:
                self._variants.append(_resolve(g, self._rng))

    def suggest(self, trial_id: str) -> Optional[Dict[str, Any]]:
        if self._variants is None:
            self._materialize()
        if self._idx >= len(self._variants):
            return None
        cfg = self._variants[self._idx]
        self._idx += 1
        return cfg

    @property
    def total_suggestions(self) -> int:
        if self._variants is None:
            self._materialize()
        return len(self._variants)
