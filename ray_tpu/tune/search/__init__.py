from ray_tpu.tune.search.searcher import ConcurrencyLimiter, Searcher
from ray_tpu.tune.search.basic_variant import BasicVariantGenerator
from ray_tpu.tune.search.bayesopt import BayesOptSearcher
from ray_tpu.tune.search.tpe import TPESearcher

__all__ = ["Searcher", "ConcurrencyLimiter", "BasicVariantGenerator",
           "BayesOptSearcher", "TPESearcher"]
