from ray_tpu.tune.search.searcher import ConcurrencyLimiter, Searcher
from ray_tpu.tune.search.basic_variant import BasicVariantGenerator

__all__ = ["Searcher", "ConcurrencyLimiter", "BasicVariantGenerator"]
