"""Tree-structured Parzen Estimator search (native, numpy-only).

Design analog: reference ``python/ray/tune/search/hyperopt/`` and
``search/optuna/`` — both wrap external TPE implementations; here TPE is
implemented directly (the classic Bergstra et al. 2011 factorized form):
split observations at the gamma-quantile into good/bad sets, model each
dimension with kernel density estimates l(x) (good) and g(x) (bad), and
suggest the candidate maximizing l(x)/g(x).
"""

from __future__ import annotations

import math
import random
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ray_tpu.tune.search.sample import (Categorical, Domain, Float, Integer,
                                        is_grid)
from ray_tpu.tune.search.searcher import Searcher


def _flatten(space: Dict[str, Any], prefix=()) -> Dict[tuple, Any]:
    out = {}
    for k, v in space.items():
        if isinstance(v, dict) and not is_grid(v):
            out.update(_flatten(v, prefix + (k,)))
        else:
            out[prefix + (k,)] = v
    return out


def _unflatten(flat: Dict[tuple, Any]) -> Dict[str, Any]:
    out: Dict[str, Any] = {}
    for path, v in flat.items():
        cur = out
        for k in path[:-1]:
            cur = cur.setdefault(k, {})
        cur[path[-1]] = v
    return out


class TPESearcher(Searcher):
    def __init__(self, space: Optional[Dict[str, Any]] = None,
                 metric: Optional[str] = None, mode: str = "max",
                 n_startup_trials: int = 8, n_candidates: int = 32,
                 gamma: float = 0.25, seed: Optional[int] = None):
        super().__init__(metric, mode)
        self._space = _flatten(space) if space else {}
        self._n_startup = n_startup_trials
        self._n_candidates = n_candidates
        self._gamma = gamma
        self._rng = random.Random(seed)
        self._np_rng = np.random.RandomState(seed)
        # trial_id -> flat config; completed: (flat config, signed metric)
        self._pending: Dict[str, Dict[tuple, Any]] = {}
        self._done: List[Tuple[Dict[tuple, Any], float]] = []

    def set_search_properties(self, metric, mode, config):
        super().set_search_properties(metric, mode, config)
        if config and not self._space:
            self._space = _flatten(config)
        return True

    # ------------------------------------------------------------- suggest

    def suggest(self, trial_id: str) -> Optional[Dict[str, Any]]:
        flat = {}
        use_model = len(self._done) >= self._n_startup
        for path, dom in self._space.items():
            if not isinstance(dom, Domain):
                flat[path] = dom                      # constant
            elif use_model and isinstance(dom, (Float, Integer, Categorical)):
                flat[path] = self._suggest_dim(path, dom)
            else:
                flat[path] = dom.sample(self._rng)
        self._pending[trial_id] = flat
        return _unflatten(flat)

    def on_trial_complete(self, trial_id, result=None, error=False):
        flat = self._pending.pop(trial_id, None)
        if flat is None or error or not result or self.metric not in result:
            return
        v = float(result[self.metric])
        self._done.append((flat, v if self.mode == "max" else -v))

    # ---------------------------------------------------------- TPE per dim

    def _split(self):
        """good/bad observation split at the gamma quantile (signed metric,
        larger is better)."""
        ranked = sorted(self._done, key=lambda cv: -cv[1])
        n_good = max(1, int(math.ceil(self._gamma * len(ranked))))
        return ranked[:n_good], ranked[n_good:]

    def _suggest_dim(self, path, dom):
        good, bad = self._split()
        gvals = [c[path] for c, _ in good if path in c]
        bvals = [c[path] for c, _ in bad if path in c]
        if not gvals:
            return dom.sample(self._rng)
        if isinstance(dom, Categorical):
            return self._categorical_choice(dom, gvals, bvals)
        return self._numeric_choice(dom, gvals, bvals)

    def _categorical_choice(self, dom, gvals, bvals):
        cats = dom.categories
        # Laplace-smoothed frequency ratio l(c)/g(c).
        lw = np.array([1.0 + sum(1 for v in gvals if v == c) for c in cats])
        gw = np.array([1.0 + sum(1 for v in bvals if v == c) for c in cats])
        score = (lw / lw.sum()) / (gw / gw.sum())
        return cats[int(np.argmax(score))]

    def _numeric_choice(self, dom, gvals, bvals):
        lo, hi = float(dom.lower), float(dom.upper)
        log = getattr(dom, "log", False)
        tf = math.log if log else (lambda x: x)
        inv = math.exp if log else (lambda x: x)
        a, b = tf(lo), tf(hi)
        g = np.array([tf(float(v)) for v in gvals])
        bb = np.array([tf(float(v)) for v in bvals]) if bvals else None
        span = b - a
        bw_g = max(span / max(math.sqrt(len(g)), 1.0), 1e-8 * span + 1e-12)

        # Sample candidates from the good-set mixture, clipped to bounds.
        centers = g[self._np_rng.randint(len(g), size=self._n_candidates)]
        cand = np.clip(centers + self._np_rng.randn(self._n_candidates) *
                       bw_g, a, b)

        def kde(x, pts, bw):
            if pts is None or len(pts) == 0:
                return np.full_like(x, 1.0 / span)
            d = (x[:, None] - pts[None, :]) / bw
            return np.exp(-0.5 * d * d).sum(axis=1) / (len(pts) * bw)

        score = kde(cand, g, bw_g) / (kde(cand, bb, bw_g) + 1e-12)
        best = inv(float(cand[int(np.argmax(score))]))
        if isinstance(dom, Integer):
            q = max(int(getattr(dom, "q", 1) or 1), 1)
            best = int(round(best / q) * q)
            best = max(dom.lower, min(dom.upper - 1, best))
        else:
            if getattr(dom, "q", 0.0):
                best = round(best / dom.q) * dom.q
            best = max(lo, min(hi, best))
        return best
