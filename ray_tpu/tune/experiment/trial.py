"""Trial state record.

Design analog: reference ``python/ray/tune/experiment/trial.py:207`` (Trial
with status lifecycle PENDING/RUNNING/PAUSED/TERMINATED/ERROR, last_result,
checkpoint manager hooks).
"""

from __future__ import annotations

import uuid
from typing import Any, Dict, List, Optional

from ray_tpu.air.checkpoint import Checkpoint

PENDING = "PENDING"
RUNNING = "RUNNING"
PAUSED = "PAUSED"
TERMINATED = "TERMINATED"
ERROR = "ERROR"


class Trial:
    def __init__(self, config: Dict[str, Any], trial_id: str = "",
                 experiment_name: str = ""):
        self.trial_id = trial_id or uuid.uuid4().hex[:8]
        self.config = config
        self.experiment_name = experiment_name
        self.status = PENDING
        self.last_result: Dict[str, Any] = {}
        self.metrics_history: List[Dict[str, Any]] = []
        self.checkpoint: Optional[Checkpoint] = None
        self.error: Optional[str] = None
        self.actor = None           # _TrialActor handle while RUNNING
        self.pending_ref = None     # in-flight train() ref
        self.num_failures = 0
        self.scratch: Dict[str, Any] = {}  # scheduler scratch space

    @property
    def trial_name(self) -> str:
        return f"{self.experiment_name}_{self.trial_id}"

    def is_finished(self) -> bool:
        return self.status in (TERMINATED, ERROR)

    def state_dict(self) -> Dict[str, Any]:
        return {
            "trial_id": self.trial_id,
            "config": self.config,
            "status": self.status,
            "last_result": self.last_result,
            "error": self.error,
            "num_failures": self.num_failures,
            "checkpoint": self.checkpoint.to_dict()
            if self.checkpoint else None,
        }

    @classmethod
    def from_state(cls, state: Dict[str, Any],
                   experiment_name: str = "") -> "Trial":
        t = cls(state["config"], trial_id=state["trial_id"],
                experiment_name=experiment_name)
        t.status = state["status"]
        t.last_result = state.get("last_result") or {}
        t.error = state.get("error")
        t.num_failures = state.get("num_failures", 0)
        if state.get("checkpoint") is not None:
            t.checkpoint = Checkpoint.from_dict(state["checkpoint"])
        return t

    def __repr__(self):
        return f"Trial({self.trial_id}, {self.status})"
