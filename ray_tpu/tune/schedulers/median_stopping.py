"""Median stopping rule.

Design analog: reference ``python/ray/tune/schedulers/median_stopping_rule.py``:
stop a trial at time t if its best result so far is worse than the median of
other trials' running averages at t.
"""

from __future__ import annotations

from collections import defaultdict
from typing import Dict, List

from ray_tpu.tune.schedulers.trial_scheduler import TrialScheduler


class MedianStoppingRule(TrialScheduler):
    def __init__(self, metric: str = None, mode: str = "max",
                 time_attr: str = "training_iteration",
                 grace_period: int = 1, min_samples_required: int = 3):
        self.metric = metric
        self.mode = mode
        self.time_attr = time_attr
        self.grace_period = grace_period
        self.min_samples = min_samples_required
        self._histories: Dict[str, List[float]] = defaultdict(list)

    def _val(self, result) -> float:
        v = float(result[self.metric])
        return v if self.mode == "max" else -v

    def on_trial_result(self, runner, trial, result) -> str:
        if self.metric not in result:
            return self.CONTINUE
        self._histories[trial.trial_id].append(self._val(result))
        t = result.get(self.time_attr, 0)
        if t < self.grace_period:
            return self.CONTINUE
        means = [sum(h) / len(h)
                 for tid, h in self._histories.items()
                 if tid != trial.trial_id and h]
        if len(means) < self.min_samples:
            return self.CONTINUE
        means.sort()
        median = means[len(means) // 2]
        best = max(self._histories[trial.trial_id])
        return self.STOP if best < median else self.CONTINUE
