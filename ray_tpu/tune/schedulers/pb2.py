"""PB2: Population Based Bandits — PBT with a GP-bandit explore step.

Design analog: reference ``python/ray/tune/schedulers/pb2.py`` (wraps GPy):
instead of PBT's random 1.2x/0.8x perturbation, fit a GP to
(hyperparameters -> reward improvement) observations from the whole
population and pick the exploring trial's new config by maximizing a UCB
acquisition.  Implemented numpy-only (same GP core idea as
search/bayesopt.py).  Falls back to PBT-style perturbation until enough
observations exist.
"""

from __future__ import annotations

import math
import random
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ray_tpu.tune.schedulers.pbt import PopulationBasedTraining
from ray_tpu.tune.search.sample import Domain, Float, Integer


class PB2(PopulationBasedTraining):
    def __init__(self, *args, ucb_kappa: float = 1.5,
                 min_observations: int = 4, n_candidates: int = 128,
                 length_scale: float = 0.3, **kwargs):
        super().__init__(*args, **kwargs)
        self._kappa = ucb_kappa
        self._min_obs = min_observations
        self._n_cand = n_candidates
        self._ls = length_scale
        self._np_rng = np.random.RandomState(kwargs.get("seed"))
        # (normalized hyperparam vector, reward delta) observations.
        self._obs: List[Tuple[np.ndarray, float]] = []
        self._last_score: Dict[str, float] = {}

    # Continuous mutation dims in a fixed order.
    def _dims(self):
        return sorted(
            (k, spec) for k, spec in self.mutations.items()
            if isinstance(spec, (Float, Integer)))

    def _encode(self, config: Dict[str, Any]) -> Optional[np.ndarray]:
        dims = self._dims()
        if not dims:
            return None
        out = []
        for k, dom in dims:
            v = config.get(k)
            if not isinstance(v, (int, float)):
                return None
            lo, hi = float(dom.lower), float(dom.upper)
            if getattr(dom, "log", False):
                u = (math.log(max(v, lo)) - math.log(lo)) / \
                    (math.log(hi) - math.log(lo))
            else:
                u = (v - lo) / (hi - lo)
            out.append(min(1.0, max(0.0, u)))
        return np.array(out)

    def _decode(self, u: np.ndarray) -> Dict[str, Any]:
        cfg = {}
        for (k, dom), x in zip(self._dims(), u):
            lo, hi = float(dom.lower), float(dom.upper)
            if getattr(dom, "log", False):
                v = math.exp(math.log(lo) + float(x) *
                             (math.log(hi) - math.log(lo)))
            else:
                v = lo + float(x) * (hi - lo)
            if isinstance(dom, Integer):
                v = max(dom.lower, min(dom.upper - 1, int(round(v))))
            cfg[k] = v
        return cfg

    def on_trial_result(self, runner, trial, result) -> str:
        # Record reward deltas as bandit observations before the base
        # class potentially wipes the score on exploit.
        if self.metric in result:
            v = self._val(result)
            prev = self._last_score.get(trial.trial_id)
            if prev is not None:
                x = self._encode(trial.config)
                if x is not None:
                    self._obs.append((x, v - prev))
                    if len(self._obs) > 500:
                        self._obs.pop(0)
            self._last_score[trial.trial_id] = v
        return super().on_trial_result(runner, trial, result)

    def explore(self, config: Dict[str, Any]) -> Dict[str, Any]:
        dims = self._dims()
        if not dims or len(self._obs) < self._min_obs:
            return super().explore(config)
        new = super().explore(config)   # non-GP keys still PBT-perturbed
        new.update(self._decode(self._ucb_argmax()))
        return new

    def _ucb_argmax(self) -> np.ndarray:
        X = np.stack([x for x, _ in self._obs])
        y = np.array([d for _, d in self._obs])
        sd = y.std() + 1e-12
        yn = (y - y.mean()) / sd

        def k(A, B):
            d2 = ((A[:, None, :] - B[None, :, :]) ** 2).sum(-1)
            return np.exp(-0.5 * d2 / (self._ls ** 2))

        K = k(X, X) + 1e-4 * np.eye(len(X))
        L = np.linalg.cholesky(K)
        alpha = np.linalg.solve(L.T, np.linalg.solve(L, yn))
        cand = self._np_rng.rand(self._n_cand, X.shape[1])
        Ks = k(cand, X)
        mu = Ks @ alpha
        v = np.linalg.solve(L, Ks.T)
        var = np.clip(1.0 - (v ** 2).sum(axis=0), 1e-12, None)
        ucb = mu + self._kappa * np.sqrt(var)
        return cand[int(np.argmax(ucb))]
