from ray_tpu.tune.schedulers.trial_scheduler import (
    FIFOScheduler, TrialScheduler)
from ray_tpu.tune.schedulers.asha import ASHAScheduler
from ray_tpu.tune.schedulers.hyperband import HyperBandScheduler
from ray_tpu.tune.schedulers.median_stopping import MedianStoppingRule
from ray_tpu.tune.schedulers.pbt import PopulationBasedTraining
from ray_tpu.tune.schedulers.pb2 import PB2

__all__ = [
    "TrialScheduler", "FIFOScheduler", "ASHAScheduler",
    "HyperBandScheduler", "MedianStoppingRule", "PopulationBasedTraining",
    "PB2",
]
