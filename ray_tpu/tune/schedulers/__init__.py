from ray_tpu.tune.schedulers.trial_scheduler import (
    FIFOScheduler, TrialScheduler)
from ray_tpu.tune.schedulers.asha import ASHAScheduler
from ray_tpu.tune.schedulers.median_stopping import MedianStoppingRule
from ray_tpu.tune.schedulers.pbt import PopulationBasedTraining

__all__ = [
    "TrialScheduler", "FIFOScheduler", "ASHAScheduler",
    "MedianStoppingRule", "PopulationBasedTraining",
]
