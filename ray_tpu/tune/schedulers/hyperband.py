"""HyperBand scheduler: bracketed synchronous successive halving.

Design analog: reference ``python/ray/tune/schedulers/hyperband.py``
(HyperBandScheduler).  Trials are assigned round-robin to brackets with
different starting budgets; within a bracket, when the whole cohort has
reported at a rung milestone, only the top 1/eta continue (the reference
pauses trials at the rung barrier; this runtime has no PAUSE, so leaders
keep running and losers are stopped when the rung resolves — same
selection, slightly more compute spent on winners, no idle waiting).
"""

from __future__ import annotations

import math
from typing import Any, Dict, List, Optional

from ray_tpu.tune.schedulers.trial_scheduler import TrialScheduler


class _Bracket:
    def __init__(self, min_t: int, max_t: int, eta: float):
        self.eta = eta
        self.milestones: List[int] = []
        t = min_t
        while t < max_t:
            self.milestones.append(int(t))
            t *= eta
        self.trials: List[str] = []            # trial ids in this bracket
        # milestone -> {trial_id: signed metric}
        self.recorded: Dict[int, Dict[str, float]] = {
            m: {} for m in self.milestones}
        self.stopped: set = set()
        self.done: set = set()                 # finished/errored trial ids

    def live_cohort(self, milestone: int) -> List[str]:
        """Trials that could still report at this milestone."""
        return [t for t in self.trials
                if t not in self.stopped and t not in self.done]

    def resolve(self, milestone: int) -> List[str]:
        """If every live cohort member has recorded at the milestone,
        return the ids to stop (bottom 1 - 1/eta); else []."""
        rec = self.recorded[milestone]
        cohort = self.live_cohort(milestone)
        if not cohort or any(t not in rec for t in cohort):
            return []
        ranked = sorted(cohort, key=lambda t: -rec[t])
        keep = max(1, int(math.floor(len(ranked) / self.eta)))
        losers = ranked[keep:]
        self.stopped.update(losers)
        return losers


class HyperBandScheduler(TrialScheduler):
    def __init__(self, metric: Optional[str] = None, mode: str = "max",
                 time_attr: str = "training_iteration",
                 max_t: int = 81, reduction_factor: float = 3.0):
        self.metric = metric
        self.mode = mode
        self.time_attr = time_attr
        self.max_t = max_t
        self.eta = reduction_factor
        # s_max+1 brackets, bracket s starts at max_t / eta^s (classic
        # HyperBand budget ladder).
        s_max = int(math.log(max_t) / math.log(reduction_factor))
        self.brackets = [
            _Bracket(max(1, int(max_t / reduction_factor ** s)), max_t,
                     reduction_factor)
            for s in range(s_max, -1, -1)]
        self._assign_idx = 0
        self._by_trial: Dict[str, _Bracket] = {}

    def on_trial_add(self, runner, trial):
        bracket = self.brackets[self._assign_idx % len(self.brackets)]
        self._assign_idx += 1
        bracket.trials.append(trial.trial_id)
        self._by_trial[trial.trial_id] = bracket

    def _signed(self, result) -> float:
        v = float(result[self.metric])
        return v if self.mode == "max" else -v

    def on_trial_result(self, runner, trial, result) -> str:
        if self.metric not in result or self.time_attr not in result:
            return self.CONTINUE
        bracket = self._by_trial.get(trial.trial_id)
        if bracket is None:
            return self.CONTINUE
        t = result[self.time_attr]
        if trial.trial_id in bracket.stopped:
            return self.STOP
        action = self.CONTINUE
        for m in bracket.milestones:
            if t >= m and trial.trial_id not in bracket.recorded[m]:
                bracket.recorded[m][trial.trial_id] = self._signed(result)
                # Rung losers are marked; each stops at its next report
                # (the runner enacts decisions per-trial, so cross-trial
                # stops are deferred one iteration).
                losers = bracket.resolve(m)
                if trial.trial_id in losers:
                    action = self.STOP
        if trial.trial_id in bracket.stopped:
            action = self.STOP
        if t >= self.max_t:
            action = self.STOP
        return action

    def on_trial_complete(self, runner, trial, result):
        b = self._by_trial.get(trial.trial_id)
        if b:
            b.done.add(trial.trial_id)
            # A finished trial can unblock pending rung barriers.
            for m in b.milestones:
                b.resolve(m)

    def on_trial_error(self, runner, trial):
        self.on_trial_complete(runner, trial, None)
