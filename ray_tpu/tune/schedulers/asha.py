"""Asynchronous Successive Halving (ASHA).

Design analog: reference ``python/ray/tune/schedulers/async_hyperband.py``
(AsyncHyperBandScheduler / ASHAScheduler): rungs at grace_period *
reduction_factor^k; a trial reaching a rung continues only if its metric is
in the top 1/reduction_factor of results recorded at that rung.
"""

from __future__ import annotations

from typing import Any, Dict, List

from ray_tpu.tune.schedulers.trial_scheduler import TrialScheduler


class _Rung:
    def __init__(self, milestone: float):
        self.milestone = milestone
        self.recorded: List[float] = []

    def cutoff(self, reduction_factor: float) -> float:
        import math
        if not self.recorded:
            return float("-inf")
        vals = sorted(self.recorded, reverse=True)
        k = max(0, int(math.ceil(len(vals) / reduction_factor)) - 1)
        return vals[k]


class ASHAScheduler(TrialScheduler):
    def __init__(self, metric: str = None, mode: str = "max",
                 time_attr: str = "training_iteration",
                 max_t: int = 100, grace_period: int = 1,
                 reduction_factor: float = 4.0, brackets: int = 1):
        self.metric = metric
        self.mode = mode
        self.time_attr = time_attr
        self.max_t = max_t
        self.grace_period = grace_period
        self.rf = reduction_factor
        rungs: List[_Rung] = []
        t = grace_period
        while t < max_t:
            rungs.append(_Rung(t))
            t = int(t * reduction_factor)
        self.rungs = rungs  # ascending milestones

    def _metric_val(self, result: Dict[str, Any]) -> float:
        v = float(result[self.metric])
        return v if self.mode == "max" else -v

    def on_trial_result(self, runner, trial, result) -> str:
        if self.metric not in result or self.time_attr not in result:
            return self.CONTINUE
        t = result[self.time_attr]
        if t >= self.max_t:
            return self.STOP
        v = self._metric_val(result)
        action = self.CONTINUE
        for rung in reversed(self.rungs):
            if t < rung.milestone:
                continue
            marker = f"_asha_rung_{rung.milestone}"
            if trial.scratch.get(marker):
                break
            trial.scratch[marker] = True
            cutoff = rung.cutoff(self.rf)
            rung.recorded.append(v)
            if v < cutoff:
                action = self.STOP
            break
        return action
