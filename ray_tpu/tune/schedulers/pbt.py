"""Population Based Training.

Design analog: reference ``python/ray/tune/schedulers/pbt.py``
(PopulationBasedTraining): every perturbation_interval, trials in the bottom
quantile exploit (clone checkpoint + config of) a top-quantile trial, then
explore (perturb hyperparams by 1.2x/0.8x or resample).  The runner applies
the exploit by restoring the victim's trainable from the donor's checkpoint
with the perturbed config.
"""

from __future__ import annotations

import random
from typing import Any, Callable, Dict, Optional

from ray_tpu.tune.search.sample import Domain
from ray_tpu.tune.schedulers.trial_scheduler import TrialScheduler


class PopulationBasedTraining(TrialScheduler):
    def __init__(self, metric: str = None, mode: str = "max",
                 time_attr: str = "training_iteration",
                 perturbation_interval: int = 5,
                 hyperparam_mutations: Optional[Dict[str, Any]] = None,
                 quantile_fraction: float = 0.25,
                 resample_probability: float = 0.25,
                 seed: Optional[int] = None):
        self.metric = metric
        self.mode = mode
        self.time_attr = time_attr
        self.interval = perturbation_interval
        self.mutations = hyperparam_mutations or {}
        self.quantile = quantile_fraction
        self.resample_prob = resample_probability
        self._rng = random.Random(seed)
        self._scores: Dict[str, float] = {}

    def _val(self, result) -> float:
        v = float(result[self.metric])
        return v if self.mode == "max" else -v

    def explore(self, config: Dict[str, Any]) -> Dict[str, Any]:
        new = dict(config)
        for k, spec in self.mutations.items():
            if self._rng.random() < self.resample_prob or k not in new:
                new[k] = self._sample(spec)
            elif isinstance(new[k], (int, float)):
                factor = 1.2 if self._rng.random() > 0.5 else 0.8
                new[k] = type(new[k])(new[k] * factor)
            else:
                new[k] = self._sample(spec)
        return new

    def _sample(self, spec):
        if isinstance(spec, Domain):
            return spec.sample(self._rng)
        if isinstance(spec, list):
            return self._rng.choice(spec)
        if isinstance(spec, Callable):
            return spec()
        return spec

    def on_trial_result(self, runner, trial, result) -> str:
        if self.metric not in result:
            return self.CONTINUE
        self._scores[trial.trial_id] = self._val(result)
        t = result.get(self.time_attr, 0)
        last = trial.scratch.get("_pbt_last_perturb", 0)
        if t - last < self.interval:
            return self.CONTINUE
        trial.scratch["_pbt_last_perturb"] = t

        live = [tr for tr in runner.live_trials() if tr.trial_id
                in self._scores]
        if len(live) < 2:
            return self.CONTINUE
        ranked = sorted(live, key=lambda tr: self._scores[tr.trial_id])
        n_q = max(1, int(len(ranked) * self.quantile))
        bottom = ranked[:n_q]
        top = ranked[-n_q:]
        if trial in bottom and trial not in top:
            donor = self._rng.choice(top)
            new_config = self.explore(donor.config)
            # Drop the victim's stale score: until it reports from the
            # donor's checkpoint it must not participate in quantile
            # ranking (otherwise two near-tied trials exploit each other
            # every report — ping-pong churn that never converges).
            self._scores.pop(trial.trial_id, None)
            # The runner performs checkpoint transfer + in-place restart.
            runner.request_exploit(trial, donor, new_config)
        return self.CONTINUE
