"""Trial scheduler contract.

Design analog: reference ``python/ray/tune/schedulers/trial_scheduler.py``
(TrialScheduler with CONTINUE/PAUSE/STOP decisions fed from
TrialRunner.step).
"""

from __future__ import annotations

from typing import Any, Dict, Optional


class TrialScheduler:
    CONTINUE = "CONTINUE"
    PAUSE = "PAUSE"
    STOP = "STOP"

    metric: Optional[str] = None
    mode: str = "max"

    def set_search_properties(self, metric: Optional[str], mode: str) -> bool:
        if metric:
            self.metric = metric
        if mode:
            self.mode = mode
        return True

    def on_trial_add(self, runner, trial):
        pass

    def on_trial_result(self, runner, trial,
                        result: Dict[str, Any]) -> str:
        return self.CONTINUE

    def on_trial_complete(self, runner, trial, result: Dict[str, Any]):
        pass

    def on_trial_error(self, runner, trial):
        pass


class FIFOScheduler(TrialScheduler):
    """Run every trial to completion (reference default)."""
