"""ResultGrid: the return value of Tuner.fit().

Design analog: reference ``python/ray/tune/result_grid.py`` (ResultGrid
with get_best_result/get_dataframe).
"""

from __future__ import annotations

from typing import List, Optional

from ray_tpu.air.result import Result


class ResultGrid:
    def __init__(self, results: List[Result], metric: Optional[str] = None,
                 mode: str = "max"):
        self._results = results
        self._metric = metric
        self._mode = mode

    def __len__(self):
        return len(self._results)

    def __getitem__(self, i) -> Result:
        return self._results[i]

    def __iter__(self):
        return iter(self._results)

    @property
    def errors(self) -> List[Exception]:
        return [r.error for r in self._results if r.error is not None]

    def get_best_result(self, metric: Optional[str] = None,
                        mode: Optional[str] = None) -> Result:
        metric = metric or self._metric
        mode = mode or self._mode
        if metric is None:
            raise ValueError("metric required (set in TuneConfig or here)")
        candidates = [r for r in self._results
                      if r.error is None and metric in (r.metrics or {})]
        if not candidates:
            raise RuntimeError("no completed trial reported "
                               f"metric '{metric}'")
        key = lambda r: r.metrics[metric]  # noqa: E731
        return (max if mode == "max" else min)(candidates, key=key)

    def get_dataframe(self):
        import pandas as pd
        return pd.DataFrame([r.metrics or {} for r in self._results])
