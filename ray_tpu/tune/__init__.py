"""Hyperparameter tuning (Ray Tune equivalent).

Design analog: reference ``python/ray/tune/`` -- Tuner.fit (tuner.py:249),
TrialRunner event loop (execution/trial_runner.py:969), Trainable contract
(trainable/trainable.py:66), search spaces (tune/search/), schedulers
(tune/schedulers/: ASHA async_hyperband.py, PBT pbt.py, median stopping).
Trials are actors gang-placed like any other workload; a trial whose
Trainable is a JaxTrainer runs a nested worker gang (SPMD program) on its
slice.
"""

from ray_tpu.tune.search.sample import (
    choice, grid_search, lograndint, loguniform, qrandint, quniform,
    randint, randn, uniform, sample_from)
from ray_tpu.tune.trainable import Trainable, with_parameters, with_resources
from ray_tpu.tune.tune_config import TuneConfig
from ray_tpu.tune.tuner import Tuner
from ray_tpu.tune.result_grid import ResultGrid
from ray_tpu.air import session as _session

# Function-API report surface (reference: ray.tune.report / air session).
report = _session.report
get_checkpoint = _session.get_checkpoint

__all__ = [
    "Trainable", "TuneConfig", "Tuner", "ResultGrid",
    "choice", "grid_search", "lograndint", "loguniform", "qrandint",
    "quniform", "randint", "randn", "uniform", "sample_from",
    "with_parameters", "with_resources", "report", "get_checkpoint",
]

from ray_tpu._private.usage_stats import record_library_usage as _rlu
_rlu("tune")
del _rlu
