"""Workflow engine: DAG steps with durable per-step checkpoints.

Design analog: reference ``python/ray/workflow/api.py`` (run:120,
resume:232, get_output:297, resume_all:468, wait_for_event:557, cancel)
+ ``workflow_storage.py``: each step's output is pickled to
``<storage>/<workflow_id>/steps/<step_id>.pkl`` before the step is
considered done; resume loads completed steps instead of re-running them
(exactly-once per step).  Step ids are deterministic positions in the DAG
topology so the same DAG resumes against its own checkpoints.

Management surface:
  * ``get_output(wf_id, block=True)`` — wait for/return a workflow's
    final value from storage, regardless of which process runs it.
  * ``resume_all()`` — restart every resumable workflow (RUNNING with a
    dead owner pid, or FAILED); the post-crash recovery entry point.
  * ``event(name)`` / ``send_event(wf_id, name, value)`` — durable
    event-gated steps: the step completes when the event lands in
    storage (and stays satisfied across resumes).
  * ``cancel(wf_id)`` — request cancellation; the executor checks at
    every step boundary (running steps finish, like the reference).
"""

from __future__ import annotations

import json
import os
import pickle
import tempfile
import time
from typing import Any, Dict, List, Optional

import ray_tpu
from ray_tpu.dag import DAGNode, FunctionNode, InputNode, MultiOutputNode

_storage_dir: Optional[str] = None


class WorkflowCancelledError(Exception):
    """The workflow was cancelled via workflow.cancel()."""


class EventNode(DAGNode):
    """A step that completes when a named external event arrives.

    Durable: ``send_event`` writes the value under the workflow's storage,
    so an event received before a crash stays satisfied after resume, and
    a workflow parked on an un-sent event can be resumed and park again
    (reference ``api.py:557`` wait_for_event + event listeners).
    """

    def __init__(self, name: str, timeout_s: Optional[float] = None,
                 poll_interval_s: float = 0.2):
        super().__init__((), {})
        self.name = name
        self.timeout_s = timeout_s
        self.poll_interval_s = poll_interval_s

    def _execute_self(self, resolved, input_args, input_kwargs):
        raise TypeError("EventNode only executes inside workflow.run() — "
                        "events need a workflow id to be delivered to")


def event(name: str, timeout_s: Optional[float] = None) -> EventNode:
    """DAG node gating on a named event (use as an upstream of .bind())."""
    return EventNode(name, timeout_s)


def send_event(workflow_id: str, name: str, value: Any = None) -> None:
    """Deliver an event to a workflow (from any process on this storage)."""
    d = os.path.join(_wf_dir(workflow_id), "events")
    os.makedirs(d, exist_ok=True)
    tmp = os.path.join(d, name + ".tmp")
    with open(tmp, "wb") as f:
        pickle.dump(value, f)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, os.path.join(d, name + ".pkl"))


def init(storage: Optional[str] = None):
    """Set the workflow storage root (reference: workflow.init storage
    URI; local directories only here)."""
    global _storage_dir
    _storage_dir = storage or os.path.join(tempfile.gettempdir(),
                                           "rt_workflows")
    os.makedirs(_storage_dir, exist_ok=True)
    return _storage_dir


def _storage() -> str:
    return _storage_dir or init()


def _wf_dir(workflow_id: str) -> str:
    return os.path.join(_storage(), workflow_id)


def _step_ids(dag: DAGNode) -> Dict[int, str]:
    """Deterministic step id per node: topo position + step name."""
    ids = {}
    for i, node in enumerate(dag.topo_order()):
        name = node.name if isinstance(node, FunctionNode) \
            else type(node).__name__
        ids[id(node)] = f"{i:04d}_{name}"
    return ids


def _meta_path(workflow_id: str) -> str:
    return os.path.join(_wf_dir(workflow_id), "meta.json")


def _write_meta(workflow_id: str, _only_if_status=None, **updates):
    """Read-modify-write of meta.json under an exclusive flock, so
    concurrent writers (executor finishing vs. cancel() from another
    process) cannot interleave.  ``_only_if_status`` makes the write
    conditional: it is dropped unless the current status is in the given
    set — cancel() must never overwrite a terminal SUCCEEDED/FAILED."""
    import fcntl
    path = _meta_path(workflow_id)
    with open(path + ".lock", "w") as lk:
        fcntl.flock(lk, fcntl.LOCK_EX)
        meta = {}
        if os.path.exists(path):
            with open(path) as f:
                meta = json.load(f)
        if _only_if_status is not None and \
                meta.get("status") not in _only_if_status:
            return meta
        meta.update(updates)
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(meta, f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
    return meta


def run(dag: DAGNode, *, workflow_id: Optional[str] = None,
        args: tuple = (), _dag_source=None) -> Any:
    """Execute the DAG durably; blocks and returns the final output."""
    workflow_id = workflow_id or f"wf_{int(time.time() * 1000):x}"
    wf = _wf_dir(workflow_id)
    os.makedirs(os.path.join(wf, "steps"), exist_ok=True)
    # Persist the DAG itself so `resume(workflow_id)` works from a fresh
    # process without the user re-supplying it.
    import cloudpickle
    dag_path = os.path.join(wf, "dag.pkl")
    if not os.path.exists(dag_path):
        # The DAG pickle is what resume() rebuilds from — make it durable
        # before meta publishes RUNNING, or a crash leaves a workflow that
        # claims to be resumable with a torn dag.pkl.
        with open(dag_path, "wb") as f:
            cloudpickle.dump((dag, args), f)
            f.flush()
            os.fsync(f.fileno())
    _write_meta(workflow_id, status="RUNNING", start_time=time.time(),
                pid=os.getpid())
    try:
        result = _execute(dag, workflow_id, args)
        _write_meta(workflow_id, status="SUCCEEDED", end_time=time.time())
        return result
    except WorkflowCancelledError:
        _write_meta(workflow_id, status="CANCELED", end_time=time.time())
        raise
    except Exception as e:
        _write_meta(workflow_id, status="FAILED", error=str(e),
                    end_time=time.time())
        raise


def run_async(dag: DAGNode, *, workflow_id: Optional[str] = None,
              args: tuple = ()):
    """Run in a daemon thread; returns (workflow_id, thread)."""
    import threading
    workflow_id = workflow_id or f"wf_{int(time.time() * 1000):x}"

    def _bg():
        try:
            run(dag, workflow_id=workflow_id, args=args)
        except Exception:
            pass   # terminal status/error is in meta; get_output surfaces it

    t = threading.Thread(target=_bg, daemon=True)
    t.start()
    return workflow_id, t


def _execute(dag: DAGNode, workflow_id: str, input_args: tuple) -> Any:
    ids = _step_ids(dag)
    steps_dir = os.path.join(_wf_dir(workflow_id), "steps")
    resolved: Dict[int, Any] = {}

    def step_path(node):
        return os.path.join(steps_dir, ids[id(node)] + ".pkl")

    for node in dag.topo_order():
        # Cancellation is honored at step boundaries: the running step
        # finishes (its checkpoint stays valid for a later resume), then
        # the workflow stops (reference: workflow cancel semantics).
        if get_status(workflow_id) == "CANCEL_REQUESTED":
            raise WorkflowCancelledError(workflow_id)
        if isinstance(node, InputNode):
            if len(input_args) != 1:
                raise TypeError("workflow input must be a single value "
                                "(pass args=(value,))")
            resolved[id(node)] = input_args[0]
            continue
        if isinstance(node, MultiOutputNode):
            resolved[id(node)] = [node._resolve(a, resolved)
                                  for a in node._bound_args]
            continue
        if isinstance(node, EventNode):
            resolved[id(node)] = _wait_event(workflow_id, node)
            continue
        path = step_path(node)
        if os.path.exists(path):
            with open(path, "rb") as f:
                resolved[id(node)] = pickle.load(f)
            continue
        # Submit with materialized parent values (durable boundary: the
        # checkpoint, not the object store, is the source of truth).
        args = [node._resolve(a, resolved) for a in node._bound_args]
        kwargs = {k: node._resolve(v, resolved)
                  for k, v in node._bound_kwargs.items()}
        value = ray_tpu.get(node._fn.remote(*args, **kwargs))
        tmp = path + ".tmp"
        with open(tmp, "wb") as f:
            pickle.dump(value, f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)   # atomic: a step is done iff its file exists
        resolved[id(node)] = value
    return resolved[id(dag)]


def _wait_event(workflow_id: str, node: EventNode) -> Any:
    """Block until the event file exists (cancel-aware); durable across
    resumes — an already-delivered event returns immediately."""
    path = os.path.join(_wf_dir(workflow_id), "events", node.name + ".pkl")
    deadline = (time.monotonic() + node.timeout_s
                if node.timeout_s is not None else None)
    while not os.path.exists(path):
        if get_status(workflow_id) == "CANCEL_REQUESTED":
            raise WorkflowCancelledError(workflow_id)
        if deadline is not None and time.monotonic() > deadline:
            raise TimeoutError(
                f"workflow {workflow_id}: event {node.name!r} not received "
                f"within {node.timeout_s}s")
        time.sleep(node.poll_interval_s)
    with open(path, "rb") as f:
        return pickle.load(f)


def cancel(workflow_id: str) -> None:
    """Request cancellation; the executor (this or any process) honors it
    at the next step boundary / event poll.  The conditional write makes
    cancel-vs-finish races safe: a workflow that reached a terminal state
    keeps it."""
    if get_status(workflow_id) is None:
        return
    _write_meta(workflow_id, _only_if_status=("RUNNING",),
                status="CANCEL_REQUESTED")


def resume_all(include_failed: bool = False) -> List[str]:
    """Resume every resumable workflow: status RUNNING whose owner pid is
    dead (driver crashed mid-run — reference api.py:468 resume_all), plus
    FAILED ones when include_failed.  Each resumes on a daemon thread;
    returns their ids (get_output(wf_id) joins them)."""
    resumed = []
    for info in list_all():
        status = info.get("status")
        pid = info.get("pid")
        dead_owner = pid is not None and not os.path.exists(f"/proc/{pid}")
        if (status == "RUNNING" and dead_owner) or \
                (include_failed and status == "FAILED"):
            wid = info["workflow_id"]
            import threading
            threading.Thread(target=_safe_resume, args=(wid,),
                             daemon=True).start()
            resumed.append(wid)
    return resumed


def _safe_resume(workflow_id: str) -> None:
    try:
        resume(workflow_id)
    except Exception:
        pass   # status lands in meta; get_output surfaces it


def _load_dag(workflow_id: str):
    import cloudpickle
    dag_path = os.path.join(_wf_dir(workflow_id), "dag.pkl")
    if not os.path.exists(dag_path):
        raise ValueError(f"no stored workflow {workflow_id!r}")
    with open(dag_path, "rb") as f:
        return cloudpickle.load(f)


def resume(workflow_id: str) -> Any:
    """Re-run a workflow from storage; completed steps load from their
    checkpoints (reference api.py:232)."""
    dag, args = _load_dag(workflow_id)
    return run(dag, workflow_id=workflow_id, args=args)


def get_status(workflow_id: str) -> Optional[str]:
    path = _meta_path(workflow_id)
    if not os.path.exists(path):
        return None
    with open(path) as f:
        return json.load(f).get("status")


def get_output(workflow_id: str, block: bool = True,
               timeout: Optional[float] = None) -> Any:
    """Final output of a workflow (reference api.py:297 get_output).

    Blocks while the workflow is RUNNING (it may be executing in another
    process — progress is observed through storage).  Raises on FAILED /
    CANCELED, GetTimeoutError on timeout, ValueError if unknown."""
    deadline = time.monotonic() + timeout if timeout is not None else None
    while True:
        status = get_status(workflow_id)
        if status is None:
            raise ValueError(f"no such workflow {workflow_id!r}")
        if status == "SUCCEEDED":
            # Every step checkpointed: re-driving the DAG is pure reads
            # (no meta rewrite — concurrent observers keep seeing
            # SUCCEEDED, unlike a full resume()).
            dag, args = _load_dag(workflow_id)
            return _execute(dag, workflow_id, args)
        if status == "CANCELED":
            raise WorkflowCancelledError(workflow_id)
        if status == "FAILED":
            with open(_meta_path(workflow_id)) as f:
                raise RuntimeError(
                    f"workflow {workflow_id} failed: "
                    f"{json.load(f).get('error')}")
        if not block:
            raise ValueError(f"workflow {workflow_id} is {status}")
        if deadline is not None and time.monotonic() > deadline:
            from ray_tpu.exceptions import GetTimeoutError
            raise GetTimeoutError(
                f"workflow {workflow_id} still {status} after {timeout}s")
        time.sleep(0.2)


def list_all() -> List[Dict[str, Any]]:
    root = _storage()
    out = []
    for wid in sorted(os.listdir(root)):
        meta = _meta_path(wid)
        if os.path.exists(meta):
            with open(meta) as f:
                out.append({"workflow_id": wid, **json.load(f)})
    return out
