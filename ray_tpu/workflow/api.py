"""Workflow engine: DAG steps with durable per-step checkpoints.

Design analog: reference ``python/ray/workflow/api.py`` (run:120,
resume:232) + ``workflow_storage.py``: each step's output is pickled to
``<storage>/<workflow_id>/steps/<step_id>.pkl`` before the step is
considered done; resume loads completed steps instead of re-running them
(exactly-once per step).  Step ids are deterministic positions in the DAG
topology so the same DAG resumes against its own checkpoints.
"""

from __future__ import annotations

import json
import os
import pickle
import tempfile
import time
from typing import Any, Dict, List, Optional

import ray_tpu
from ray_tpu.dag import DAGNode, FunctionNode, InputNode, MultiOutputNode

_storage_dir: Optional[str] = None


def init(storage: Optional[str] = None):
    """Set the workflow storage root (reference: workflow.init storage
    URI; local directories only here)."""
    global _storage_dir
    _storage_dir = storage or os.path.join(tempfile.gettempdir(),
                                           "rt_workflows")
    os.makedirs(_storage_dir, exist_ok=True)
    return _storage_dir


def _storage() -> str:
    return _storage_dir or init()


def _wf_dir(workflow_id: str) -> str:
    return os.path.join(_storage(), workflow_id)


def _step_ids(dag: DAGNode) -> Dict[int, str]:
    """Deterministic step id per node: topo position + step name."""
    ids = {}
    for i, node in enumerate(dag.topo_order()):
        name = node.name if isinstance(node, FunctionNode) \
            else type(node).__name__
        ids[id(node)] = f"{i:04d}_{name}"
    return ids


def _meta_path(workflow_id: str) -> str:
    return os.path.join(_wf_dir(workflow_id), "meta.json")


def _write_meta(workflow_id: str, **updates):
    path = _meta_path(workflow_id)
    meta = {}
    if os.path.exists(path):
        with open(path) as f:
            meta = json.load(f)
    meta.update(updates)
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(meta, f)
    os.replace(tmp, path)
    return meta


def run(dag: DAGNode, *, workflow_id: Optional[str] = None,
        args: tuple = (), _dag_source=None) -> Any:
    """Execute the DAG durably; blocks and returns the final output."""
    workflow_id = workflow_id or f"wf_{int(time.time() * 1000):x}"
    wf = _wf_dir(workflow_id)
    os.makedirs(os.path.join(wf, "steps"), exist_ok=True)
    # Persist the DAG itself so `resume(workflow_id)` works from a fresh
    # process without the user re-supplying it.
    import cloudpickle
    dag_path = os.path.join(wf, "dag.pkl")
    if not os.path.exists(dag_path):
        with open(dag_path, "wb") as f:
            cloudpickle.dump((dag, args), f)
    _write_meta(workflow_id, status="RUNNING", start_time=time.time())
    try:
        result = _execute(dag, workflow_id, args)
        _write_meta(workflow_id, status="SUCCEEDED", end_time=time.time())
        return result
    except Exception as e:
        _write_meta(workflow_id, status="FAILED", error=str(e),
                    end_time=time.time())
        raise


def run_async(dag: DAGNode, *, workflow_id: Optional[str] = None,
              args: tuple = ()):
    """Run in a daemon thread; returns (workflow_id, thread)."""
    import threading
    workflow_id = workflow_id or f"wf_{int(time.time() * 1000):x}"
    t = threading.Thread(target=run, args=(dag,),
                         kwargs={"workflow_id": workflow_id, "args": args},
                         daemon=True)
    t.start()
    return workflow_id, t


def _execute(dag: DAGNode, workflow_id: str, input_args: tuple) -> Any:
    ids = _step_ids(dag)
    steps_dir = os.path.join(_wf_dir(workflow_id), "steps")
    resolved: Dict[int, Any] = {}

    def step_path(node):
        return os.path.join(steps_dir, ids[id(node)] + ".pkl")

    for node in dag.topo_order():
        if isinstance(node, InputNode):
            if len(input_args) != 1:
                raise TypeError("workflow input must be a single value "
                                "(pass args=(value,))")
            resolved[id(node)] = input_args[0]
            continue
        if isinstance(node, MultiOutputNode):
            resolved[id(node)] = [node._resolve(a, resolved)
                                  for a in node._bound_args]
            continue
        path = step_path(node)
        if os.path.exists(path):
            with open(path, "rb") as f:
                resolved[id(node)] = pickle.load(f)
            continue
        # Submit with materialized parent values (durable boundary: the
        # checkpoint, not the object store, is the source of truth).
        args = [node._resolve(a, resolved) for a in node._bound_args]
        kwargs = {k: node._resolve(v, resolved)
                  for k, v in node._bound_kwargs.items()}
        value = ray_tpu.get(node._fn.remote(*args, **kwargs))
        tmp = path + ".tmp"
        with open(tmp, "wb") as f:
            pickle.dump(value, f)
        os.replace(tmp, path)   # atomic: a step is done iff its file exists
        resolved[id(node)] = value
    return resolved[id(dag)]


def resume(workflow_id: str) -> Any:
    """Re-run a workflow from storage; completed steps load from their
    checkpoints (reference api.py:232)."""
    import cloudpickle
    dag_path = os.path.join(_wf_dir(workflow_id), "dag.pkl")
    if not os.path.exists(dag_path):
        raise ValueError(f"no stored workflow {workflow_id!r}")
    with open(dag_path, "rb") as f:
        dag, args = cloudpickle.load(f)
    return run(dag, workflow_id=workflow_id, args=args)


def get_status(workflow_id: str) -> Optional[str]:
    path = _meta_path(workflow_id)
    if not os.path.exists(path):
        return None
    with open(path) as f:
        return json.load(f).get("status")


def get_output(workflow_id: str) -> Any:
    """Final output of a SUCCEEDED workflow (from its last step's
    checkpoint)."""
    if get_status(workflow_id) != "SUCCEEDED":
        raise ValueError(f"workflow {workflow_id} has not succeeded")
    return resume(workflow_id)   # every step cached: pure checkpoint reads


def list_all() -> List[Dict[str, Any]]:
    root = _storage()
    out = []
    for wid in sorted(os.listdir(root)):
        meta = _meta_path(wid)
        if os.path.exists(meta):
            with open(meta) as f:
                out.append({"workflow_id": wid, **json.load(f)})
    return out
