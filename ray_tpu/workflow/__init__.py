"""Durable workflow execution over DAGs.

Design analog: reference ``python/ray/workflow/`` — ``workflow.run``
(api.py:120), ``workflow.resume`` (api.py:232): run a task DAG with every
step's output checkpointed to storage, so a crashed run resumes from the
last completed step with exactly-once step execution.
"""

from ray_tpu.workflow.api import (WorkflowCancelledError, cancel, event,
                                  get_output, get_status, init, list_all,
                                  resume, resume_all, run, run_async,
                                  send_event)

__all__ = ["init", "run", "run_async", "resume", "resume_all", "cancel",
           "event", "send_event", "get_output", "get_status", "list_all",
           "WorkflowCancelledError"]

from ray_tpu._private.usage_stats import record_library_usage as _rlu
_rlu("workflow")
del _rlu
