"""Distributed training orchestration (Ray Train equivalent, TPU-first).

Design analog: reference ``python/ray/train/`` -- BaseTrainer.fit
(base_trainer.py:339), DataParallelTrainer (data_parallel_trainer.py:56),
BackendExecutor (_internal/backend_executor.py:43), WorkerGroup
(_internal/worker_group.py:92).  The framework backend is JAX: instead of
``dist.init_process_group(nccl)`` (train/torch/config.py:113) workers run
``jax.distributed.initialize`` so in-slice collectives compile into the
pjit step over ICI.
"""

from ray_tpu.train.backend import Backend, BackendConfig
from ray_tpu.train.base_trainer import BaseTrainer, TrainingFailedError
from ray_tpu.train.batch_predictor import BatchPredictor
from ray_tpu.train.data_parallel_trainer import DataParallelTrainer
from ray_tpu.train.transformers_trainer import (TransformersTrainer,
                                                load_model)
from ray_tpu.train.gbdt_trainer import (GBDTTrainer, SklearnTrainer,
                                        load_estimator)
from ray_tpu.train.jax.config import JaxConfig
from ray_tpu.train.jax.jax_trainer import JaxTrainer
from ray_tpu.train.predictor import JaxPredictor, Predictor

__all__ = [
    "Backend",
    "BackendConfig",
    "BaseTrainer",
    "TrainingFailedError",
    "BatchPredictor",
    "DataParallelTrainer",
    "GBDTTrainer", "TransformersTrainer", "load_model",
    "SklearnTrainer",
    "load_estimator",
    "JaxConfig",
    "JaxTrainer",
    "JaxPredictor",
    "Predictor",
]

from ray_tpu._private.usage_stats import record_library_usage as _rlu
_rlu("train")
del _rlu
