"""Predictors: checkpoint -> inference callable.

Design analog: reference ``python/ray/train/predictor.py`` (Predictor base:
from_checkpoint / predict with preprocessing hooks) and
``train/torch/torch_predictor.py`` — here the framework flavor is JAX: the
model apply fn is jitted once per process and batches are device_put as one
large array so the MXU sees full tiles.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional, Union

import numpy as np

from ray_tpu.air.checkpoint import Checkpoint

DataBatchType = Union[np.ndarray, Dict[str, np.ndarray]]


class Predictor:
    """Base predictor contract (reference train/predictor.py:71)."""

    @classmethod
    def from_checkpoint(cls, checkpoint: Checkpoint,
                        **kwargs) -> "Predictor":
        raise NotImplementedError

    def predict(self, data: DataBatchType, **kwargs) -> DataBatchType:
        raise NotImplementedError


class JaxPredictor(Predictor):
    """Predictor over a pure ``apply_fn(params, x)``.

    The apply fn is jitted lazily on first predict; params live on device
    for the predictor's lifetime, so per-batch cost is one host->device
    transfer of the batch (reference torch_predictor moves the model to GPU
    once in __init__)."""

    def __init__(self, apply_fn: Callable, params: Any, jit: bool = True):
        import jax
        self._apply = jax.jit(apply_fn) if jit else apply_fn
        self._params = jax.device_put(params)

    @classmethod
    def from_checkpoint(cls, checkpoint: Checkpoint, *,
                        apply_fn: Callable, params_key: str = "params",
                        jit: bool = True) -> "JaxPredictor":
        data = checkpoint.to_dict()
        if params_key not in data:
            raise ValueError(
                f"checkpoint has no {params_key!r} entry "
                f"(keys: {sorted(data)})")
        return cls(apply_fn, data[params_key], jit=jit)

    def predict(self, data: DataBatchType, **kwargs) -> np.ndarray:
        out = self._apply(self._params, data)
        return np.asarray(out)
