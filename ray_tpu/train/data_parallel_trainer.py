"""DataParallelTrainer: SPMD train loop over a worker gang.

Design analog: reference ``python/ray/train/data_parallel_trainer.py:56``
(training_loop:343 drives BackendExecutor; dataset shards via
_internal/dataset_spec.py + Dataset.split).  The train_loop_per_worker runs
once per host; on TPU each invocation is the per-process part of one SPMD
program (multi-controller JAX), with collectives compiled into the step.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional

from ray_tpu.air import session as air_session
from ray_tpu.air.checkpoint import Checkpoint
from ray_tpu.air.config import RunConfig, ScalingConfig
from ray_tpu.train.backend import BackendConfig
from ray_tpu.train.base_trainer import BaseTrainer
from ray_tpu.train._internal.backend_executor import (
    BackendExecutor, TrainingWorkerError)


class DataParallelTrainer(BaseTrainer):
    _backend_config_cls = BackendConfig

    def __init__(self,
                 train_loop_per_worker: Callable,
                 *,
                 train_loop_config: Optional[Dict[str, Any]] = None,
                 backend_config: Optional[BackendConfig] = None,
                 scaling_config: Optional[ScalingConfig] = None,
                 run_config: Optional[RunConfig] = None,
                 datasets: Optional[Dict[str, Any]] = None,
                 resume_from_checkpoint: Optional[Checkpoint] = None):
        super().__init__(scaling_config=scaling_config,
                         run_config=run_config,
                         datasets=datasets,
                         resume_from_checkpoint=resume_from_checkpoint)
        self._train_loop = train_loop_per_worker
        self._train_loop_config = train_loop_config
        self._backend_config = backend_config or self._backend_config_cls()

    def training_loop(self) -> None:
        executor = BackendExecutor(
            self._backend_config, self.scaling_config,
            max_failures=self.run_config.failure_config.max_failures)
        executor.start()
        train_fn = self._wrap_train_loop()
        config = self._train_loop_config
        try:
            executor.start_training(
                train_fn, config, checkpoint=self.resume_from_checkpoint)
            while True:
                try:
                    results = executor.get_next_results()
                except TrainingWorkerError as e:
                    # A planned preemption handoff (worker checkpointed and
                    # exited clean) restarts without burning the budget.
                    if not executor.recover(
                            train_fn, config,
                            preempted=getattr(e, "preempted", False)):
                        raise
                    continue
                if results is None:
                    break
                # Forward rank-0 metrics upward (driver session: Tune or
                # the direct runner), attaching the aggregated checkpoint.
                air_session.report(results[0],
                                   checkpoint=executor.latest_checkpoint)
        finally:
            self._final_checkpoint = executor.latest_checkpoint
            executor.shutdown()

    def _wrap_train_loop(self) -> Callable:
        """Hook for sharding datasets into the per-worker fn."""
        datasets = self.datasets
        user_fn = self._train_loop
        if not datasets:
            return user_fn

        def wrapped(config=None):
            # Late module import: this closure is shipped by value, so any
            # global it captured at pickle time would be a disconnected
            # snapshot on the worker -- resolve the real module dict here.
            from ray_tpu.air import session
            from ray_tpu.train import data_parallel_trainer as dpt
            rank = session.get_world_rank()
            world = session.get_world_size()
            shards = {}
            for name, ds in datasets.items():
                split = getattr(ds, "split", None)
                if callable(split):
                    shards[name] = ds.split(world, equal=True)[rank]
                else:
                    shards[name] = ds
            dpt._DATASET_SHARDS.update(shards)
            try:
                import inspect
                if inspect.signature(user_fn).parameters:
                    return user_fn(config if config is not None else {})
                return user_fn()
            finally:
                dpt._DATASET_SHARDS.clear()

        return wrapped


# Per-worker dataset shards exposed through session.get_dataset_shard
# (reference: air/session.py get_dataset_shard).
_DATASET_SHARDS: Dict[str, Any] = {}


def get_dataset_shard(name: str = "train"):
    if name not in _DATASET_SHARDS:
        raise KeyError(f"no dataset shard named '{name}' "
                       f"(have {list(_DATASET_SHARDS)})")
    return _DATASET_SHARDS[name]
