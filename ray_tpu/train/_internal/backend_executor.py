"""BackendExecutor: drives a training run over a WorkerGroup.

Design analog: reference ``python/ray/train/_internal/backend_executor.py:43``
-- placement-group creation (:138), rank assignment (:245), start_training
(:315), worker-failure handling (:510,571).  TPU-first deltas: ranks map to
hosts of a slice; a lost worker means the whole slice restarts from the last
checkpoint (slice is all-or-nothing, SURVEY.md §7 hard part (e)).

Gang supervision: besides surfacing RPC errors from ``get_next``, the
executor subscribes to the GCS ``"actors"`` pubsub channel and trips a
death event the moment ANY gang actor is recorded dead — ranks wedged
inside a collective waiting on the dead peer can't report an error, so
the watch (not the RPC path) is what bounds detection latency.  Recovery
tears the whole gang down, verifies the latest checkpoint's manifest +
CRCs before trusting it (falling back to the previous intact sibling),
and restarts with exponential backoff under a bounded budget
(``FailureConfig.max_failures`` or ``RT_TRAIN_MAX_RECOVERIES``).  A
planned preemption handoff (worker exits clean after a final checkpoint)
restarts the gang WITHOUT burning budget.
"""

from __future__ import annotations

import logging
import os
import threading
import time
from typing import Any, Callable, Dict, List, Optional

import ray_tpu
from ray_tpu.air.checkpoint import Checkpoint
from ray_tpu.air.config import ScalingConfig
from ray_tpu.train.backend import BackendConfig
from ray_tpu.train._internal import checkpoint_store
from ray_tpu.train._internal.worker_group import WorkerGroup
from ray_tpu.util.placement_group import (
    placement_group, remove_placement_group)

logger = logging.getLogger(__name__)


class TrainBackendError(RuntimeError):
    pass


class TrainingWorkerError(RuntimeError):
    """A worker died or the train fn raised; carries the remote traceback.
    ``preempted`` marks a planned handoff (worker exited clean after a
    preemption notice) — recovery restarts without burning budget."""

    def __init__(self, msg: str, traceback_str: str = "",
                 preempted: bool = False):
        super().__init__(msg + ("\n" + traceback_str if traceback_str else ""))
        self.traceback_str = traceback_str
        self.preempted = preempted


def _bump(name: str, value: float = 1.0) -> None:
    try:
        from ray_tpu.train import metrics as train_metrics
        train_metrics.bump(name, value)
    except Exception:
        pass


class BackendExecutor:
    def __init__(self, backend_config: BackendConfig,
                 scaling_config: ScalingConfig,
                 max_failures: int = 0):
        self._backend_config = backend_config
        self._backend = backend_config.backend_cls()()
        self._scaling = scaling_config
        self._max_failures = max_failures
        self._num_failures = 0
        self._pg = None
        self._group: Optional[WorkerGroup] = None
        self._pending: List[Any] = []
        self._finished: List[bool] = []
        self._latest_checkpoint: Optional[Checkpoint] = None
        # Gang death watch (GCS actors-channel pubsub): set the moment any
        # gang actor is recorded dead, with the dead actors' records.
        self._death_event = threading.Event()
        self._dead_actors: List[dict] = []
        self._watch_cb: Optional[Callable] = None

    # -- lifecycle --------------------------------------------------------
    def start(self):
        sc = self._scaling
        # Head bundle (trainer_resources) first, then one bundle per worker
        # (reference backend_executor.py:138).
        bundles = sc.as_placement_group_bundles()
        worker_offset = len(bundles) - sc.num_workers
        self._pg = placement_group(bundles, strategy=sc.placement_strategy)
        if not self._pg.ready(timeout=60.0):
            remove_placement_group(self._pg)
            self._pg = None
            raise TrainBackendError(
                f"placement group for {sc.num_workers} x {sc.bundle()} "
                "could not be scheduled (insufficient cluster resources)")
        self._group = WorkerGroup(sc.num_workers, sc.bundle(),
                                  placement_group=self._pg,
                                  bundle_offset=worker_offset)
        for w in self._group.workers:
            w.actor.set_context.remote(
                world_rank=w.rank,
                world_size=sc.num_workers,
                local_rank=w.local_rank,
                local_world_size=self._group.local_world_size(w.ip),
                node_rank=w.node_rank,
            )
        self._start_death_watch()
        self._backend.on_start(self._group, self._backend_config)

    def _start_death_watch(self):
        """Subscribe to GCS actor-lifecycle events for THIS gang.  The
        callback runs on the core's pubsub thread: record + set the event,
        nothing else.  Events published while a control-plane partition is
        open are not replayed, so the RPC error path below remains the
        backstop — the watch only bounds detection latency."""
        self._death_event.clear()
        self._dead_actors = []
        gang_ids = {w.actor_id for w in self._group.workers if w.actor_id}
        dead, ev = self._dead_actors, self._death_event

        def _on_actor_event(data, _ids=gang_ids):
            try:
                if data.get("event") != "dead":
                    return
                actor = data.get("actor") or {}
                if actor.get("actor_id") in _ids:
                    dead.append(actor)
                    ev.set()
            except Exception:
                pass

        try:
            from ray_tpu.util import pubsub
            pubsub.subscribe("actors", _on_actor_event)
            self._watch_cb = _on_actor_event
        except Exception:
            # No pubsub (e.g. core not fully up): RPC errors still surface
            # worker death, just without the early collective-hang escape.
            self._watch_cb = None

    def _stop_death_watch(self):
        if self._watch_cb is not None:
            try:
                from ray_tpu.util import pubsub
                pubsub.unsubscribe("actors", self._watch_cb)
            except Exception:
                pass
            self._watch_cb = None

    def start_training(self, train_fn: Callable,
                       config: Optional[Dict[str, Any]] = None,
                       checkpoint: Optional[Checkpoint] = None):
        if self._group is None:
            raise TrainBackendError("executor not started")
        self._backend.on_training_start(self._group, self._backend_config)
        if checkpoint is not None:
            self._latest_checkpoint = checkpoint
        refs = [w.actor.start_training.remote(
                    train_fn, config, self._latest_checkpoint)
                for w in self._group.workers]
        ray_tpu.get(refs)
        self._finished = [False] * len(self._group)
        self._train_fn = train_fn
        self._config = config

    # -- result pump ------------------------------------------------------
    def get_next_results(self) -> Optional[List[Dict[str, Any]]]:
        """One bundle of per-worker reports for the same iteration, or None
        when every worker's train fn returned (reference
        backend_executor.py:414: all-or-nothing consistency check)."""
        if all(self._finished):
            return None
        out: List[Optional[Dict[str, Any]]] = [None] * len(self._group)
        # Issue one get_next per live worker and collect via wait() so an
        # error raised on any rank surfaces immediately, even while other
        # ranks hang inside a collective waiting for the dead peer
        # (reference backend_executor uses ray.wait the same way).
        ref_to_rank = {}
        for i, w in enumerate(self._group.workers):
            if not self._finished[i]:
                ref_to_rank[w.actor.get_next.remote()] = i
        remaining = list(ref_to_rank)
        preempted_rank: Optional[int] = None
        while remaining:
            ready, remaining = ray_tpu.wait(
                remaining, num_returns=len(remaining), timeout=5.0)
            for ref in ready:
                i = ref_to_rank[ref]
                try:
                    kind, payload, extra = ray_tpu.get(ref)
                except Exception as e:
                    raise TrainingWorkerError(
                        f"worker rank={i} died during training: {e}") from e
                if kind == "error":
                    raise TrainingWorkerError(
                        f"train loop failed on rank={i}: {payload}",
                        extra or "")
                if kind == "preempted":
                    # Keep draining this round's ready refs (a final
                    # checkpoint-bearing report may ride in the same
                    # batch) before signalling the planned handoff.
                    preempted_rank = i
                    self._finished[i] = True
                    continue
                if kind == "done":
                    self._finished[i] = True
                    continue
                metrics, ckpt = payload, extra
                if ckpt is not None and i == 0:
                    # Rank-0 checkpoint wins (reference keeps rank-0's).
                    self._latest_checkpoint = ckpt
                out[i] = metrics
            if preempted_rank is not None:
                raise TrainingWorkerError(
                    f"worker rank={preempted_rank} exited on a preemption "
                    "notice (planned handoff)", preempted=True)
            if self._death_event.is_set() and remaining:
                # The GCS recorded a gang death; ranks still pending may be
                # wedged in a collective and will never answer.  In-flight
                # results from this round are already drained above.
                names = ", ".join(
                    (a.get("name") or a.get("actor_id", "?")[:12])
                    for a in self._dead_actors) or "?"
                raise TrainingWorkerError(
                    f"gang worker death recorded by GCS ({names}); "
                    "tearing down the group")
        if all(self._finished):
            return None
        live = [m for m in out if m is not None]
        if live and len(live) != sum(1 for f in self._finished if not f):
            raise TrainBackendError(
                "workers reported unevenly: every live worker must call "
                "session.report() the same number of times")
        return live if live else None

    # -- recovery ---------------------------------------------------------
    def _failure_budget(self) -> int:
        """Restart budget: FailureConfig.max_failures when set, else the
        RT_TRAIN_MAX_RECOVERIES env (-1 = unbounded, 0 = fail fast)."""
        if self._max_failures != 0:
            return self._max_failures
        try:
            return int(os.environ.get("RT_TRAIN_MAX_RECOVERIES", "0"))
        except ValueError:
            return 0

    def _recovery_backoff_s(self) -> float:
        """Exponential backoff before restart attempt N (base doubles per
        consecutive failure, capped) so a crash-looping gang can't hammer
        the scheduler."""
        try:
            base = float(os.environ.get("RT_TRAIN_RECOVERY_BACKOFF_S", "0.5"))
            cap = float(os.environ.get(
                "RT_TRAIN_RECOVERY_BACKOFF_MAX_S", "30"))
        except ValueError:
            base, cap = 0.5, 30.0
        if base <= 0:
            return 0.0
        return min(cap, base * (2 ** max(0, self._num_failures - 1)))

    def recover(self, train_fn: Callable,
                config: Optional[Dict[str, Any]],
                *, preempted: bool = False) -> bool:
        """Tear down and restart the gang from the latest VERIFIED
        checkpoint.  Returns False when the failure budget is exhausted.
        A planned preemption handoff restarts without burning budget."""
        if preempted:
            _bump("preemptions")
            logger.info("planned preemption handoff; restarting gang from "
                        "the latest checkpoint")
        else:
            self._num_failures += 1
            budget = self._failure_budget()
            if budget >= 0 and self._num_failures > budget:
                logger.error(
                    "train worker failure %d exceeds restart budget %d; "
                    "giving up", self._num_failures, budget)
                return False
            _bump("train_recoveries")
            backoff = self._recovery_backoff_s()
            logger.warning(
                "train worker failure %d/%s; restarting group in %.1fs",
                self._num_failures,
                budget if budget >= 0 else "inf", backoff)
            if backoff > 0:
                time.sleep(backoff)
        self._latest_checkpoint = self._verified_checkpoint(
            self._latest_checkpoint)
        self._teardown_group()
        self.start()
        self.start_training(train_fn, config, self._latest_checkpoint)
        return True

    def _verified_checkpoint(self,
                             ckpt: Optional[Checkpoint]
                             ) -> Optional[Checkpoint]:
        """Gate restarts on checkpoint integrity: a directory-form
        checkpoint in CheckpointStore layout (has MANIFEST.json) must pass
        manifest + CRC verification before the gang reuses it; on failure
        fall back to the newest intact sibling, else restart from scratch.
        Dict-form checkpoints (in-memory, can't be torn by a crash) pass
        through untouched."""
        if ckpt is None or ckpt.path is None:
            return ckpt
        path = ckpt.path
        if not os.path.exists(
                os.path.join(path, checkpoint_store.MANIFEST_NAME)):
            return ckpt   # not store-format; nothing to verify against
        try:
            checkpoint_store.verify_checkpoint_dir(path)
            return ckpt
        except checkpoint_store.CorruptCheckpointError as e:
            _bump("ckpt_corrupt_skipped")
            logger.warning(
                "latest checkpoint failed verification (%s); falling back "
                "to the previous intact one", e)
        root = os.path.dirname(os.path.abspath(path))
        try:
            store = checkpoint_store.CheckpointStore(root)
            for step in reversed(store.list_steps()):
                cand = os.path.join(root, f"ckpt-{step:012d}")
                if os.path.abspath(cand) == os.path.abspath(path):
                    continue
                try:
                    checkpoint_store.verify_checkpoint_dir(cand)
                    return Checkpoint.from_directory(cand)
                except checkpoint_store.CorruptCheckpointError:
                    _bump("ckpt_corrupt_skipped")
        except OSError:
            pass
        logger.warning(
            "no intact checkpoint found under %s; restarting from scratch",
            root)
        return None

    @property
    def latest_checkpoint(self) -> Optional[Checkpoint]:
        return self._latest_checkpoint

    @property
    def num_failures(self) -> int:
        return self._num_failures

    def _teardown_group(self):
        self._stop_death_watch()
        if self._group is not None:
            try:
                self._backend.on_shutdown(self._group, self._backend_config)
            except Exception:
                pass
            self._group.shutdown()
            self._group = None
        if self._pg is not None:
            try:
                remove_placement_group(self._pg)
            except Exception:
                pass
            self._pg = None

    def shutdown(self):
        self._teardown_group()
