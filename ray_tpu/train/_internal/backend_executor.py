"""BackendExecutor: drives a training run over a WorkerGroup.

Design analog: reference ``python/ray/train/_internal/backend_executor.py:43``
-- placement-group creation (:138), rank assignment (:245), start_training
(:315), worker-failure handling (:510,571).  TPU-first deltas: ranks map to
hosts of a slice; a lost worker means the whole slice restarts from the last
checkpoint (slice is all-or-nothing, SURVEY.md §7 hard part (e)).
"""

from __future__ import annotations

import logging
from typing import Any, Callable, Dict, List, Optional

import ray_tpu
from ray_tpu.air.checkpoint import Checkpoint
from ray_tpu.air.config import ScalingConfig
from ray_tpu.train.backend import BackendConfig
from ray_tpu.train._internal.worker_group import WorkerGroup
from ray_tpu.util.placement_group import (
    placement_group, remove_placement_group)

logger = logging.getLogger(__name__)


class TrainBackendError(RuntimeError):
    pass


class TrainingWorkerError(RuntimeError):
    """A worker died or the train fn raised; carries the remote traceback."""

    def __init__(self, msg: str, traceback_str: str = ""):
        super().__init__(msg + ("\n" + traceback_str if traceback_str else ""))
        self.traceback_str = traceback_str


class BackendExecutor:
    def __init__(self, backend_config: BackendConfig,
                 scaling_config: ScalingConfig,
                 max_failures: int = 0):
        self._backend_config = backend_config
        self._backend = backend_config.backend_cls()()
        self._scaling = scaling_config
        self._max_failures = max_failures
        self._num_failures = 0
        self._pg = None
        self._group: Optional[WorkerGroup] = None
        self._pending: List[Any] = []
        self._finished: List[bool] = []
        self._latest_checkpoint: Optional[Checkpoint] = None

    # -- lifecycle --------------------------------------------------------
    def start(self):
        sc = self._scaling
        # Head bundle (trainer_resources) first, then one bundle per worker
        # (reference backend_executor.py:138).
        bundles = sc.as_placement_group_bundles()
        worker_offset = len(bundles) - sc.num_workers
        self._pg = placement_group(bundles, strategy=sc.placement_strategy)
        if not self._pg.ready(timeout=60.0):
            remove_placement_group(self._pg)
            self._pg = None
            raise TrainBackendError(
                f"placement group for {sc.num_workers} x {sc.bundle()} "
                "could not be scheduled (insufficient cluster resources)")
        self._group = WorkerGroup(sc.num_workers, sc.bundle(),
                                  placement_group=self._pg,
                                  bundle_offset=worker_offset)
        for w in self._group.workers:
            w.actor.set_context.remote(
                world_rank=w.rank,
                world_size=sc.num_workers,
                local_rank=w.local_rank,
                local_world_size=self._group.local_world_size(w.ip),
                node_rank=w.node_rank,
            )
        self._backend.on_start(self._group, self._backend_config)

    def start_training(self, train_fn: Callable,
                       config: Optional[Dict[str, Any]] = None,
                       checkpoint: Optional[Checkpoint] = None):
        if self._group is None:
            raise TrainBackendError("executor not started")
        self._backend.on_training_start(self._group, self._backend_config)
        if checkpoint is not None:
            self._latest_checkpoint = checkpoint
        refs = [w.actor.start_training.remote(
                    train_fn, config, self._latest_checkpoint)
                for w in self._group.workers]
        ray_tpu.get(refs)
        self._finished = [False] * len(self._group)
        self._train_fn = train_fn
        self._config = config

    # -- result pump ------------------------------------------------------
    def get_next_results(self) -> Optional[List[Dict[str, Any]]]:
        """One bundle of per-worker reports for the same iteration, or None
        when every worker's train fn returned (reference
        backend_executor.py:414: all-or-nothing consistency check)."""
        if all(self._finished):
            return None
        out: List[Optional[Dict[str, Any]]] = [None] * len(self._group)
        # Issue one get_next per live worker and collect via wait() so an
        # error raised on any rank surfaces immediately, even while other
        # ranks hang inside a collective waiting for the dead peer
        # (reference backend_executor uses ray.wait the same way).
        ref_to_rank = {}
        for i, w in enumerate(self._group.workers):
            if not self._finished[i]:
                ref_to_rank[w.actor.get_next.remote()] = i
        remaining = list(ref_to_rank)
        while remaining:
            ready, remaining = ray_tpu.wait(
                remaining, num_returns=len(remaining), timeout=5.0)
            for ref in ready:
                i = ref_to_rank[ref]
                try:
                    kind, payload, extra = ray_tpu.get(ref)
                except Exception as e:
                    raise TrainingWorkerError(
                        f"worker rank={i} died during training: {e}") from e
                if kind == "error":
                    raise TrainingWorkerError(
                        f"train loop failed on rank={i}: {payload}",
                        extra or "")
                if kind == "done":
                    self._finished[i] = True
                    continue
                metrics, ckpt = payload, extra
                if ckpt is not None and i == 0:
                    # Rank-0 checkpoint wins (reference keeps rank-0's).
                    self._latest_checkpoint = ckpt
                out[i] = metrics
        if all(self._finished):
            return None
        live = [m for m in out if m is not None]
        if live and len(live) != sum(1 for f in self._finished if not f):
            raise TrainBackendError(
                "workers reported unevenly: every live worker must call "
                "session.report() the same number of times")
        return live if live else None

    def recover(self, train_fn: Callable,
                config: Optional[Dict[str, Any]]) -> bool:
        """Tear down and restart the gang from the latest checkpoint.
        Returns False when failure budget is exhausted."""
        self._num_failures += 1
        if self._max_failures >= 0 and self._num_failures > self._max_failures:
            return False
        logger.warning("train worker failure %d/%s; restarting group",
                       self._num_failures, self._max_failures)
        self._teardown_group()
        self.start()
        self.start_training(train_fn, config, self._latest_checkpoint)
        return True

    @property
    def latest_checkpoint(self) -> Optional[Checkpoint]:
        return self._latest_checkpoint

    def _teardown_group(self):
        if self._group is not None:
            try:
                self._backend.on_shutdown(self._group, self._backend_config)
            except Exception:
                pass
            self._group.shutdown()
            self._group = None
        if self._pg is not None:
            try:
                remove_placement_group(self._pg)
            except Exception:
                pass
            self._pg = None

    def shutdown(self):
        self._teardown_group()
