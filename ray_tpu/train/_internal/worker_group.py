"""WorkerGroup: a gang of train-worker actors.

Design analog: reference ``python/ray/train/_internal/worker_group.py:92``
(WorkerGroup with execute/execute_async over RayTrainWorker actors).  Each
worker is one actor == one host process; on TPU it owns every chip the
bundle granted (the jax process model), so there is no per-GPU worker
multiplexing to reproduce.
"""

from __future__ import annotations

import os
import queue
import socket
import threading
import time
import traceback
import uuid
from typing import Any, Callable, Dict, List, Optional

import ray_tpu
from ray_tpu.air import session as air_session
from ray_tpu.air.checkpoint import Checkpoint


class Preempted(BaseException):
    """Raised inside the train loop by session.report at the step boundary
    after a preemption notice, unwinding the user fn AFTER its final
    checkpoint-bearing report so the worker exits clean.  BaseException so
    a user loop's broad ``except Exception`` cannot swallow the handoff."""


class RayTrainWorker:
    """Actor body hosting the user's train loop in a background thread.

    The reference pushes results through a queue consumed by the driver
    (train/_internal/session.py:325); here `get_next` blocks on that queue
    from the driver side.
    """

    def __init__(self):
        self._thread: Optional[threading.Thread] = None
        self._queue: "queue.Queue" = queue.Queue()
        self._ctx: Dict[str, Any] = {}
        # Monotonic deadline by which this worker must be gone, set by a
        # preempt() RPC or the preempt_notice fault; None = no notice.
        self._preempt_deadline: Optional[float] = None

    # -- plumbing ---------------------------------------------------------
    def execute(self, fn: Callable, *args, **kwargs):
        """Run an arbitrary function in the worker process (setup hooks)."""
        return fn(*args, **kwargs)

    def node_ip(self) -> str:
        # UDP-connect trick: finds the address of the interface that routes
        # externally (gethostbyname(hostname) often resolves to 127.0.1.1).
        s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        try:
            s.connect(("8.8.8.8", 80))
            return s.getsockname()[0]
        except OSError:
            return "127.0.0.1"
        finally:
            s.close()

    def set_env_vars(self, env: Dict[str, str]):
        os.environ.update(env)

    def set_context(self, **ctx):
        self._ctx.update(ctx)

    # -- preemption -------------------------------------------------------
    def preempt(self, grace_s: float = 30.0) -> bool:
        """Deliver a preemption notice: the train loop finishes its
        in-flight microbatch, writes a final checkpoint at the next step
        boundary, and exits clean (a planned handoff, not a failure).
        Callable as an actor RPC (max_concurrency > 1 lets it land while
        the loop runs); the preempt_notice fault delivers the same signal
        in-process for chaos tests."""
        self._preempt_deadline = time.monotonic() + float(grace_s)
        return True

    def _preempt_deadline_check(self) -> Optional[float]:
        """The active grace deadline, arming the fault-injected notice on
        first observation past its fire time.  Consulted by the session at
        every report (step boundary)."""
        if self._preempt_deadline is None:
            from ray_tpu.util import fault_injection
            notice = fault_injection.preempt_notice_at(
                self._ctx.get("world_rank", 0))
            if notice is not None and time.monotonic() >= notice[0]:
                self._preempt_deadline = notice[0] + notice[1]
        return self._preempt_deadline

    # -- training ---------------------------------------------------------
    def start_training(self, train_fn: Callable,
                       config: Optional[Dict[str, Any]],
                       checkpoint: Optional[Checkpoint]):
        ctx = self._ctx
        q = self._queue
        worker = self

        class _TrainSession(air_session._SessionBase):
            world_rank = ctx.get("world_rank", 0)
            world_size = ctx.get("world_size", 1)
            local_rank = ctx.get("local_rank", 0)
            local_world_size = ctx.get("local_world_size", 1)
            node_rank = ctx.get("node_rank", 0)
            trial_name = ctx.get("trial_name", "")
            trial_id = ctx.get("trial_id", "")
            experiment_name = ctx.get("experiment_name", "")

            def report(self, metrics, ckpt=None):
                q.put(("report", metrics, ckpt))
                # Step boundary = the preemption exit point: leave after
                # the first checkpoint-bearing report once noticed, or at
                # any report past the grace deadline (the platform is
                # about to SIGKILL us; clean exit without a fresh
                # checkpoint still beats an unplanned death).
                deadline = worker._preempt_deadline_check()
                if deadline is not None and (
                        ckpt is not None or time.monotonic() >= deadline):
                    raise Preempted(
                        f"rank={self.world_rank} preempted "
                        f"(grace deadline {deadline:.1f})")

            def get_checkpoint(self):
                return checkpoint

        def _run():
            air_session._set_session(_TrainSession())
            try:
                # Match the reference's construct_train_func: a loop taking a
                # parameter receives the (possibly empty) config dict.
                import inspect
                takes_arg = bool(
                    inspect.signature(train_fn).parameters)
                if takes_arg:
                    result = train_fn(config if config is not None else {})
                else:
                    result = train_fn()
                q.put(("done", result, None))
            # rtlint: disable=cancellation-safety - thread boundary: the
            # preemption is forwarded over the result queue and re-raised
            # driver-side by the supervisor, not swallowed.
            except Preempted as e:
                q.put(("preempted", str(e), None))
            # rtlint: disable=cancellation-safety - thread boundary:
            # forwarded to the driver over the result queue; raising here
            # would kill the train thread with no report.
            except BaseException as e:  # noqa: BLE001 - forwarded to driver
                q.put(("error", repr(e), traceback.format_exc()))
            finally:
                air_session._set_session(None)

        self._thread = threading.Thread(target=_run, daemon=True,
                                        name="train_loop")
        self._thread.start()
        return True

    def get_next(self):
        """Block until the train loop reports, finishes, or errors."""
        return self._queue.get()

    def shutdown(self):
        return True


class Worker:
    def __init__(self, actor, rank: int):
        self.actor = actor
        self.rank = rank
        self.actor_id: str = getattr(actor, "_actor_id_hex", "")
        self.ip: str = ""
        self.node_rank: int = 0
        self.local_rank: int = 0


class WorkerGroup:
    def __init__(self, num_workers: int,
                 resources_per_worker: Dict[str, float],
                 placement_group=None,
                 bundle_offset: int = 0,
                 group_id: Optional[str] = None):
        self._num_workers = num_workers
        # Workers get GCS-registered names (_train:<gang>:<rank>) so the
        # gang supervisor's death watch and chaos's kill_train_worker can
        # target them by identity — ActorInfo carries no class name.
        self.group_id = group_id or uuid.uuid4().hex[:8]
        cls = ray_tpu.remote(RayTrainWorker)
        self.workers: List[Worker] = []
        for rank in range(num_workers):
            opts: Dict[str, Any] = {
                "num_cpus": resources_per_worker.get("CPU", 1.0),
                "num_tpus": resources_per_worker.get("TPU", 0.0),
                "max_concurrency": 4,
                "name": f"_train:{self.group_id}:{rank}",
            }
            extra = {k: v for k, v in resources_per_worker.items()
                     if k not in ("CPU", "TPU")}
            if extra:
                opts["resources"] = extra
            if placement_group is not None:
                from ray_tpu.util.scheduling_strategies import (
                    PlacementGroupSchedulingStrategy)
                opts["scheduling_strategy"] = PlacementGroupSchedulingStrategy(
                    placement_group,
                    placement_group_bundle_index=bundle_offset + rank)
            actor = cls.options(**opts).remote()
            self.workers.append(Worker(actor, rank))
        # Resolve IPs and derive node/local ranks (reference
        # backend_executor.py:245 _create_rank_map).
        ips = ray_tpu.get([w.actor.node_ip.remote() for w in self.workers])
        node_order: List[str] = []
        local_counts: Dict[str, int] = {}
        for w, ip in zip(self.workers, ips):
            w.ip = ip
            if ip not in node_order:
                node_order.append(ip)
            w.node_rank = node_order.index(ip)
            w.local_rank = local_counts.get(ip, 0)
            local_counts[ip] = w.local_rank + 1
        self._local_world = local_counts

    def __len__(self):
        return self._num_workers

    def execute(self, fn: Callable, *args, **kwargs) -> List[Any]:
        return ray_tpu.get(self.execute_async(fn, *args, **kwargs))

    def execute_async(self, fn: Callable, *args, **kwargs):
        return [w.actor.execute.remote(fn, *args, **kwargs)
                for w in self.workers]

    def execute_single(self, rank: int, fn: Callable, *args, **kwargs) -> Any:
        return ray_tpu.get(
            self.workers[rank].actor.execute.remote(fn, *args, **kwargs))

    def local_world_size(self, ip: str) -> int:
        return self._local_world.get(ip, 1)

    def shutdown(self):
        for w in self.workers:
            try:
                ray_tpu.kill(w.actor)
            except Exception:
                pass
        self.workers = []
