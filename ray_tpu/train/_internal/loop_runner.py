"""Direct (non-Tune) trainer execution.

Design analog: the reference always routes Trainer.fit through a
single-trial Tune run (base_trainer.py:339).  Here the direct path is
first-class -- a driver-side session collects session.report calls from
training_loop and materializes an air.Result -- while Tuner(trainer) still
layers the full Tune machinery on top.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

from ray_tpu.air import session as air_session
from ray_tpu.air.checkpoint import Checkpoint
from ray_tpu.air.result import Result


class StopTraining(Exception):
    """Raised into the trainer's loop when RunConfig.stop criteria are met;
    the training_loop treats it as a clean early exit."""


class _DriverSession(air_session._SessionBase):
    """Accumulates reports made by the trainer's training_loop."""

    def __init__(self, stop: Optional[Dict[str, Any]] = None):
        self.history: List[Dict[str, Any]] = []
        self.latest_checkpoint: Optional[Checkpoint] = None
        self._stop = stop or {}
        self.iteration = 0

    def report(self, metrics: Dict[str, Any],
               checkpoint: Optional[Checkpoint] = None):
        self.iteration += 1
        metrics = dict(metrics)
        metrics.setdefault("training_iteration", self.iteration)
        self.history.append(metrics)
        if checkpoint is not None:
            self.latest_checkpoint = checkpoint
        if self._should_stop(metrics):
            raise StopTraining()

    def _should_stop(self, metrics: Dict[str, Any]) -> bool:
        for key, threshold in self._stop.items():
            if key in metrics and metrics[key] >= threshold:
                return True
        return False


def run_trainer_directly(trainer) -> Result:
    from ray_tpu.train.base_trainer import TrainingFailedError

    prev = air_session._get_session()
    sess = _DriverSession(stop=trainer.run_config.stop)
    air_session._set_session(sess)
    error: Optional[Exception] = None
    try:
        trainer.training_loop()
    except StopTraining:
        pass  # RunConfig.stop criteria met: clean early exit
    except Exception as e:  # noqa: BLE001 - surfaced in Result + raised
        error = e
    finally:
        air_session._set_session(prev)

    result = Result(
        metrics=sess.history[-1] if sess.history else {},
        checkpoint=sess.latest_checkpoint,
        error=error,
        metrics_history=sess.history,
    )
    if error is not None:
        raise TrainingFailedError(
            f"training loop failed: {error}") from error
    return result
