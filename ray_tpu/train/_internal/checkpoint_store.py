"""Crash-consistent training checkpoints + the async off-step-loop writer.

The availability story for long training runs is checkpoint/resume (the
Ray paper's checkpoint-based actor recovery, at the scale of "Scalable
Training of Language Models using JAX pjit and TPUv4" — a preempted pool
must cost minutes of recompute, not the run).  Three invariants:

* **Crash consistency.**  A checkpoint directory is committed by its
  ``MANIFEST.json``, written LAST via the PR-2 durable-spill pattern
  (tmp → fsync(file) → rename → fsync(dir)).  Shard files are fsynced
  before the manifest is, so a crash at ANY point leaves either no
  manifest (directory ignored as partial) or a complete, verifiable
  checkpoint — never a torn one a naive restore would load.

* **Integrity.**  The manifest records every shard's size + crc32;
  restore re-verifies before handing state back and falls back to the
  previous intact checkpoint on any mismatch (bit-rot, post-commit
  truncation), bumping ``ckpt_corrupt_skipped``.

* **Determinism.**  A checkpoint captures model/optimizer state, host
  RNG state (numpy + python), an explicit JAX PRNG key, and the
  data-iterator position, so a run killed mid-training and resumed
  produces a bit-identical loss trajectory to an uninterrupted run.

Writes happen **off the step loop**: ``AsyncCheckpointWriter`` snapshots
device arrays to host at a step boundary (the only synchronous cost) and
runs the IO on a single-thread executor with at most one write in
flight — a second ``submit()`` while one is active first waits for it
(bounded backpressure, counted in ``stalls``) so the store can never
accumulate unbounded dirty state.
"""

from __future__ import annotations

import io
import json
import logging
import os
import pickle
import random
import re
import shutil
import time
import zlib
from concurrent.futures import Future, ThreadPoolExecutor
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

logger = logging.getLogger(__name__)

MANIFEST_NAME = "MANIFEST.json"
_CKPT_DIR_RE = re.compile(r"^ckpt-(\d{12})$")


class CorruptCheckpointError(Exception):
    """A checkpoint directory failed verification: missing/torn manifest,
    missing shard, size mismatch, or crc32 mismatch.  Restore treats it
    as 'this checkpoint does not exist' and falls back."""


# -- durable small-file writes (PR-2 write_spill_file pattern) ------------

def write_file_durable(path: str, data: bytes) -> float:
    """tmp → fsync(file) → rename → fsync(dir).  A crash leaves either
    the previous state or the complete new file, never a torn one.
    Returns seconds spent in fsync."""
    tmp = path + ".tmp"
    fsync_s = 0.0
    with open(tmp, "wb") as f:
        f.write(data)
        f.flush()
        t0 = time.perf_counter()
        os.fsync(f.fileno())
        fsync_s += time.perf_counter() - t0
    os.replace(tmp, path)
    # The rename itself must be durable: without the directory fsync a
    # crash can keep the (fsynced) inode but lose the directory entry.
    t0 = time.perf_counter()
    dfd = os.open(os.path.dirname(path) or ".", os.O_RDONLY)
    try:
        os.fsync(dfd)
    finally:
        os.close(dfd)
    fsync_s += time.perf_counter() - t0
    return fsync_s


def write_json_durable(path: str, obj: Any) -> float:
    return write_file_durable(
        path, json.dumps(obj, sort_keys=True).encode("utf-8"))


def file_crc32(path: str, chunk: int = 1 << 20) -> int:
    crc = 0
    with open(path, "rb") as f:
        while True:
            buf = f.read(chunk)
            if not buf:
                break
            crc = zlib.crc32(buf, crc)
    return crc & 0xFFFFFFFF


# -- host snapshot / RNG capture ------------------------------------------

def snapshot_to_host(tree: Any) -> Any:
    """Device→host snapshot of a pytree at a step boundary.  This is the
    only part of a checkpoint that runs on the step loop; everything
    after it is executor IO on the copied arrays."""
    import numpy as np
    try:
        import jax
        leaves, treedef = jax.tree_util.tree_flatten(tree)
        return jax.tree_util.tree_unflatten(
            treedef, [np.asarray(leaf).copy() for leaf in leaves])
    except ImportError:  # plain numpy trees work without jax
        if isinstance(tree, dict):
            return {k: snapshot_to_host(v) for k, v in tree.items()}
        return np.asarray(tree).copy()


def capture_rng_state() -> Dict[str, Any]:
    """Host RNG state (numpy global + python random).  The JAX key is
    explicit functional state — pass it through ``save(extra=...)`` or
    keep it in the train state tree."""
    import numpy as np
    return {"numpy": np.random.get_state(), "python": random.getstate()}


def restore_rng_state(state: Dict[str, Any]) -> None:
    import numpy as np
    if "numpy" in state:
        np.random.set_state(state["numpy"])
    if "python" in state:
        random.setstate(state["python"])


def _bump(name: str, value: float = 1.0) -> None:
    try:
        from ray_tpu.train import metrics as train_metrics
        train_metrics.bump(name, value)
    except Exception:
        pass


@dataclass
class RestoredCheckpoint:
    """What restore hands back: verified state + everything needed for a
    deterministic resume."""

    step: int
    path: str
    tree: Any
    rng_state: Optional[Dict[str, Any]] = None
    data_state: Optional[Any] = None
    meta: Dict[str, Any] = field(default_factory=dict)

    def restore_host_rng(self) -> None:
        if self.rng_state is not None:
            restore_rng_state(self.rng_state)


class CheckpointStore:
    """A directory of ``ckpt-<step>`` checkpoints with manifest-committed
    writes and CRC-verified restores.

    Layout per checkpoint::

        ckpt-000000000042/
          leaf_0.npy ... leaf_N.npy   # pytree leaves (np.save format)
          treedef.pkl                 # pytree structure
          aux.pkl                     # rng state / data-iterator position
          MANIFEST.json               # written LAST: step + files{size,crc32}
    """

    def __init__(self, root: str, keep: int = 2):
        self.root = os.path.abspath(root)
        self.keep = max(1, int(keep))
        os.makedirs(self.root, exist_ok=True)

    # -- write ------------------------------------------------------------

    def save(self, step: int, tree: Any, *,
             rng_state: Optional[Dict[str, Any]] = None,
             data_state: Optional[Any] = None,
             meta: Optional[Dict[str, Any]] = None) -> str:
        """Write one checkpoint durably; returns its directory path.
        ``tree`` must already be host arrays (see snapshot_to_host).
        Blocking — call from AsyncCheckpointWriter's executor, not the
        step loop."""
        import numpy as np

        from ray_tpu.util import fault_injection

        t0 = time.perf_counter()
        name = f"ckpt-{step:012d}"
        path = os.path.join(self.root, name)
        tmp_dir = path + ".writing"
        shutil.rmtree(tmp_dir, ignore_errors=True)
        os.makedirs(tmp_dir)

        slow_s = fault_injection.slow_ckpt_io_s()
        try:
            import jax
            leaves, treedef = jax.tree_util.tree_flatten(tree)
        except ImportError:
            leaves, treedef = [tree], None
        files: Dict[str, Dict[str, int]] = {}

        def _write_shard(fname: str, blob: bytes) -> None:
            if slow_s > 0.0:
                time.sleep(slow_s)
            write_file_durable(os.path.join(tmp_dir, fname), blob)
            files[fname] = {"size": len(blob),
                            "crc32": zlib.crc32(blob) & 0xFFFFFFFF}

        for i, leaf in enumerate(leaves):
            buf = io.BytesIO()
            np.save(buf, np.asarray(leaf), allow_pickle=False)
            _write_shard(f"leaf_{i}.npy", buf.getvalue())
        _write_shard("treedef.pkl",
                     pickle.dumps(treedef, pickle.HIGHEST_PROTOCOL))
        aux = {"rng_state": rng_state, "data_state": data_state}
        _write_shard("aux.pkl", pickle.dumps(aux, pickle.HIGHEST_PROTOCOL))

        # Commit point: the manifest is the LAST durable write; a crash
        # anywhere above leaves a manifest-less directory that restore
        # ignores and a later save of the same step overwrites.
        manifest = {"format": 1, "step": int(step),
                    "num_leaves": len(leaves),
                    "files": files, "meta": meta or {},
                    "created_at": time.time()}
        write_json_durable(os.path.join(tmp_dir, MANIFEST_NAME), manifest)
        # Publish under the canonical name.  rename(dir) is atomic on the
        # same filesystem; the manifest inside is already durable.
        shutil.rmtree(path, ignore_errors=True)
        os.replace(tmp_dir, path)
        dfd = os.open(self.root, os.O_RDONLY)
        try:
            os.fsync(dfd)
        finally:
            os.close(dfd)

        self._gc()
        _bump("ckpt_write_ms", (time.perf_counter() - t0) * 1000.0)
        return path

    def _gc(self) -> None:
        """Keep the newest ``keep`` committed checkpoints (never fewer —
        the previous intact one is the corruption fallback) and sweep
        orphaned .writing/.tmp debris from crashed writers."""
        steps = self.list_steps()
        for step in steps[:-self.keep]:
            shutil.rmtree(os.path.join(self.root, f"ckpt-{step:012d}"),
                          ignore_errors=True)
        for name in os.listdir(self.root):
            if name.endswith(".writing") or name.endswith(".tmp"):
                full = os.path.join(self.root, name)
                # A concurrent writer owns at most the newest one; stale
                # debris is from a crashed process.
                if time.time() - os.path.getmtime(full) > 300:
                    shutil.rmtree(full, ignore_errors=True)

    # -- read -------------------------------------------------------------

    def list_steps(self) -> List[int]:
        """Committed (manifest-bearing) checkpoint steps, ascending."""
        out = []
        try:
            names = os.listdir(self.root)
        except OSError:
            return []
        for name in names:
            m = _CKPT_DIR_RE.match(name)
            if m and os.path.exists(
                    os.path.join(self.root, name, MANIFEST_NAME)):
                out.append(int(m.group(1)))
        return sorted(out)

    def verify(self, step: int) -> Dict[str, Any]:
        """Verify one checkpoint's manifest + every shard CRC; returns the
        manifest.  Raises CorruptCheckpointError on any mismatch."""
        path = os.path.join(self.root, f"ckpt-{step:012d}")
        return verify_checkpoint_dir(path)

    def restore(self, step: int) -> RestoredCheckpoint:
        """Load one verified checkpoint (raises CorruptCheckpointError)."""
        import numpy as np

        t0 = time.perf_counter()
        path = os.path.join(self.root, f"ckpt-{step:012d}")
        manifest = verify_checkpoint_dir(path)
        with open(os.path.join(path, "treedef.pkl"), "rb") as f:
            treedef = pickle.load(f)
        leaves = []
        for i in range(int(manifest["num_leaves"])):
            leaves.append(np.load(os.path.join(path, f"leaf_{i}.npy"),
                                  allow_pickle=False))
        if treedef is None:
            tree = leaves[0] if leaves else None
        else:
            import jax
            tree = jax.tree_util.tree_unflatten(treedef, leaves)
        with open(os.path.join(path, "aux.pkl"), "rb") as f:
            aux = pickle.load(f)
        _bump("ckpt_restore_ms", (time.perf_counter() - t0) * 1000.0)
        return RestoredCheckpoint(
            step=int(manifest["step"]), path=path, tree=tree,
            rng_state=aux.get("rng_state"),
            data_state=aux.get("data_state"),
            meta=manifest.get("meta", {}))

    def restore_latest(self) -> Optional[RestoredCheckpoint]:
        """Newest checkpoint that verifies; corrupt/partial ones are
        skipped (counted in ``ckpt_corrupt_skipped``) and the previous
        intact one is returned instead.  None when nothing restorable."""
        for step in reversed(self.list_steps()):
            try:
                return self.restore(step)
            except (CorruptCheckpointError, OSError, ValueError,
                    pickle.UnpicklingError) as e:
                _bump("ckpt_corrupt_skipped")
                logger.warning(
                    "checkpoint step=%d failed verification (%s); falling "
                    "back to the previous intact one", step, e)
        return None


def verify_checkpoint_dir(path: str) -> Dict[str, Any]:
    """Manifest + CRC verification of one checkpoint directory; returns
    the parsed manifest.  Raises CorruptCheckpointError when the manifest
    is missing/torn or any listed shard is missing, short, or fails its
    crc32."""
    mpath = os.path.join(path, MANIFEST_NAME)
    try:
        with open(mpath, "rb") as f:
            manifest = json.loads(f.read().decode("utf-8"))
    except FileNotFoundError:
        raise CorruptCheckpointError(
            f"{path}: no {MANIFEST_NAME} (partial write)") from None
    except (OSError, ValueError, UnicodeDecodeError) as e:
        raise CorruptCheckpointError(f"{path}: torn manifest: {e}") from e
    files = manifest.get("files")
    if not isinstance(files, dict):
        raise CorruptCheckpointError(f"{path}: manifest lists no files")
    for fname, rec in files.items():
        fpath = os.path.join(path, fname)
        try:
            size = os.path.getsize(fpath)
        except OSError:
            raise CorruptCheckpointError(
                f"{path}: shard {fname} missing") from None
        if size != int(rec["size"]):
            raise CorruptCheckpointError(
                f"{path}: shard {fname} is {size} bytes, manifest says "
                f"{rec['size']} (torn write)")
        if file_crc32(fpath) != int(rec["crc32"]):
            raise CorruptCheckpointError(
                f"{path}: shard {fname} failed crc32 verification")
    return manifest


class AsyncCheckpointWriter:
    """Checkpoint IO off the step loop, at most one write in flight.

    ``submit()`` is called from the training thread at a step boundary
    with an ALREADY host-snapshotted tree (snapshot_to_host is the
    caller's only synchronous cost).  The write runs on a dedicated
    single-thread executor; a second submit while one is in flight first
    waits for it — the loop stalls only when IO is slower than the
    checkpoint cadence, and ``stalls`` counts exactly those events so
    tests and the release bench can assert on overlap."""

    def __init__(self, store: CheckpointStore):
        self.store = store
        self._ex = ThreadPoolExecutor(max_workers=1,
                                      thread_name_prefix="rt-ckpt-io")
        self._inflight: Optional[Future] = None
        self.stalls = 0
        self.submitted = 0

    def in_flight(self) -> bool:
        return self._inflight is not None and not self._inflight.done()

    def submit(self, step: int, host_tree: Any, **save_kwargs) -> Future:
        if self.in_flight():
            self.stalls += 1
            self._inflight.result()      # backpressure: one in flight
        elif self._inflight is not None:
            self._inflight.result()      # surface a failed previous write
        self._inflight = self._ex.submit(
            self.store.save, step, host_tree, **save_kwargs)
        self.submitted += 1
        return self._inflight

    def wait(self) -> None:
        """Block until the in-flight write (if any) is durable; re-raises
        its error.  Call before reporting a checkpoint as complete and
        before clean preemption exit."""
        if self._inflight is not None:
            self._inflight.result()

    def close(self) -> None:
        try:
            self.wait()
        finally:
            self._ex.shutdown(wait=True)
