"""Training resilience observability counters.

Same dual-sink shape as ``ray_tpu.serve.metrics`` — one ``bump()``
feeds:

* a plain in-process dict (``stats()``) — the raylet folds it into its
  node-stats report so head-side consumers (``state.train_totals()``,
  the dashboard) see per-node values, and unit tests can assert on it
  without a cluster;
* lazily-created ``ray_tpu.util.metrics`` Counters — the processes
  where training actually happens (train-worker actors, the driver
  supervisor) flush these to the GCS, which aggregates them across
  processes into ``/api/metrics`` as ``ray_tpu_<name>`` series.

Counters are created on first bump, not at import, so importing the
train package never starts the metrics flusher thread in processes that
never train.

The five counters tell the elastic-training story end to end:

* ``train_recoveries``     — gang teardown+restarts after an unplanned
  worker death (each one consumed restart budget);
* ``preemptions``          — planned handoffs: a preempt notice was
  delivered, the worker checkpointed and exited clean, and the gang
  restarted without burning budget;
* ``ckpt_write_ms``        — cumulative wall-clock of durable checkpoint
  writes (shards + manifest commit, off the step loop);
* ``ckpt_restore_ms``      — cumulative wall-clock of verified restores;
* ``ckpt_corrupt_skipped`` — checkpoints rejected at restore (missing/
  torn manifest, shard CRC mismatch) and skipped in favor of the
  previous intact one.
"""

from __future__ import annotations

import threading
from typing import Dict

COUNTER_NAMES = ("train_recoveries", "preemptions", "ckpt_write_ms",
                 "ckpt_restore_ms", "ckpt_corrupt_skipped")

_lock = threading.Lock()
_stats: Dict[str, float] = {k: 0.0 for k in COUNTER_NAMES}
_user_counters = None     # name -> util.metrics.Counter, created lazily


def _counters():
    global _user_counters
    if _user_counters is None:
        try:
            from ray_tpu.util.metrics import Counter
            _user_counters = {
                "train_recoveries": Counter(
                    "train_recoveries",
                    "train gang teardown+restarts after an unplanned "
                    "worker death"),
                "preemptions": Counter(
                    "preemptions",
                    "planned preemption handoffs (checkpoint + clean "
                    "exit, no restart budget burned)"),
                "ckpt_write_ms": Counter(
                    "ckpt_write_ms",
                    "cumulative durable checkpoint write wall-clock"),
                "ckpt_restore_ms": Counter(
                    "ckpt_restore_ms",
                    "cumulative verified checkpoint restore wall-clock"),
                "ckpt_corrupt_skipped": Counter(
                    "ckpt_corrupt_skipped",
                    "checkpoints failing CRC/manifest verification, "
                    "skipped at restore"),
            }
        except Exception:
            _user_counters = {}
    return _user_counters


def bump(name: str, value: float = 1.0) -> None:
    with _lock:
        _stats[name] = _stats.get(name, 0.0) + value
    c = _counters().get(name)
    if c is not None:
        try:
            c.inc(value)
        except Exception:
            pass


def stats() -> Dict[str, float]:
    """Snapshot of this process's train counters (ints where whole)."""
    with _lock:
        return {k: (int(v) if float(v).is_integer() else round(v, 3))
                for k, v in _stats.items()}


def reset() -> None:
    """Test hook."""
    with _lock:
        for k in list(_stats):
            _stats[k] = 0.0
