"""GBDT + sklearn trainers over the AIR trainer contract.

Design analog: reference ``python/ray/train/gbdt_trainer.py:105``
(GBDTTrainer: xgboost/lightgbm over actor gangs with Dataset ingest) and
``python/ray/train/sklearn/sklearn_trainer.py`` (SklearnTrainer: one
actor, joblib parallelism inside the estimator).  This image carries no
xgboost, so GBDTTrainer's booster is sklearn's native
HistGradientBoosting* — a real histogram gradient booster — trained
round-by-round via ``warm_start`` so every boosting round reports
metrics through ``session.report`` and checkpoints the booster
(resumable mid-boost, the reference's checkpoint-per-iteration
behavior).

Both trainers ride the existing BackendExecutor gang machinery
(DataParallelTrainer): ingest is a ray_tpu Dataset materialized on the
training worker; extra gang members (if scaled) hold dataset shards for
parallel ingest and rank 0 fits — matching the reference's centralized
sklearn path.
"""

from __future__ import annotations

import io
import pickle
from typing import Any, Dict, Optional

import numpy as np

from ray_tpu.air.checkpoint import Checkpoint
from ray_tpu.air.config import RunConfig, ScalingConfig
from ray_tpu.train.data_parallel_trainer import DataParallelTrainer

_ESTIMATOR_KEY = "estimator_pkl"


def _dataset_to_xy(ds, label_column: str):
    """Materialize a ray_tpu Dataset (of dict rows or a table) into
    (X, y) numpy arrays."""
    try:
        table = ds.to_arrow()
        cols = {name: np.asarray(table[name]) for name in table.column_names}
    except Exception:
        rows = ds.take_all()
        if not rows:
            raise ValueError("empty dataset")
        cols = {k: np.asarray([r[k] for r in rows]) for k in rows[0]}
    y = cols.pop(label_column)
    X = np.column_stack([cols[k] for k in sorted(cols)])
    return X, y


def _estimator_checkpoint(est) -> Checkpoint:
    buf = io.BytesIO()
    pickle.dump(est, buf)
    return Checkpoint.from_dict({_ESTIMATOR_KEY: buf.getvalue()})


def load_estimator(checkpoint: Checkpoint):
    """Recover the fitted estimator from a trainer checkpoint (reference:
    ``SklearnCheckpoint.get_estimator``)."""
    return pickle.loads(checkpoint.to_dict()[_ESTIMATOR_KEY])


class SklearnTrainer(DataParallelTrainer):
    """Fit any sklearn estimator on a ray_tpu Dataset.

    ``datasets={"train": ds[, "valid": ds]}``; reports train/valid scores
    via session.report and checkpoints the pickled estimator.
    Parallelism comes from the estimator itself (n_jobs) — the gang has
    one training member, like the reference's sklearn trainer.
    """

    def __init__(self, *, estimator, label_column: str,
                 scaling_config: Optional[ScalingConfig] = None,
                 run_config: Optional[RunConfig] = None,
                 datasets: Optional[Dict[str, Any]] = None,
                 resume_from_checkpoint: Optional[Checkpoint] = None):

        def loop(config=None):
            from ray_tpu.air import session
            from ray_tpu.train.data_parallel_trainer import \
                get_dataset_shard
            est = pickle.loads(config["estimator_pkl"])
            ckpt = session.get_checkpoint()
            if ckpt is not None:
                est = load_estimator(ckpt)
            X, y = _dataset_to_xy(get_dataset_shard("train"),
                                  config["label_column"])
            if not _is_fitted(est):
                est.fit(X, y)
            metrics = {"train_score": float(est.score(X, y))}
            try:
                vds = get_dataset_shard("valid")
            except KeyError:
                vds = None
            if vds is not None:
                Xv, yv = _dataset_to_xy(vds, config["label_column"])
                metrics["valid_score"] = float(est.score(Xv, yv))
            session.report(metrics, checkpoint=_estimator_checkpoint(est))

        super().__init__(
            loop,
            train_loop_config={
                "estimator_pkl": pickle.dumps(estimator),
                "label_column": label_column,
            },
            scaling_config=scaling_config or ScalingConfig(num_workers=1),
            run_config=run_config, datasets=datasets,
            resume_from_checkpoint=resume_from_checkpoint)


def _is_fitted(est) -> bool:
    from sklearn.exceptions import NotFittedError
    from sklearn.utils.validation import check_is_fitted
    try:
        check_is_fitted(est)
        return True
    except NotFittedError:
        return False


class GBDTTrainer(DataParallelTrainer):
    """Gradient-boosted trees with per-round reporting and resumable
    checkpoints (reference GBDTTrainer shape, xgboost-free).

    ``params`` follow sklearn's HistGradientBoosting{Classifier,
    Regressor} (learning_rate, max_depth, ...); ``num_boost_round`` maps
    to trees.  Each round extends the booster via warm_start, reports
    train/valid scores, and checkpoints — resume_from_checkpoint picks
    up mid-boost exactly where it stopped.
    """

    def __init__(self, *, label_column: str,
                 params: Optional[Dict[str, Any]] = None,
                 num_boost_round: int = 32,
                 objective: str = "classification",
                 rounds_per_report: int = 4,
                 scaling_config: Optional[ScalingConfig] = None,
                 run_config: Optional[RunConfig] = None,
                 datasets: Optional[Dict[str, Any]] = None,
                 resume_from_checkpoint: Optional[Checkpoint] = None):

        def loop(config=None):
            from ray_tpu.air import session
            if config["objective"] == "classification":
                from sklearn.ensemble import HistGradientBoostingClassifier \
                    as Booster
            else:
                from sklearn.ensemble import HistGradientBoostingRegressor \
                    as Booster
            from ray_tpu.train.data_parallel_trainer import \
                get_dataset_shard
            total = config["num_boost_round"]
            chunk = max(1, config["rounds_per_report"])
            ckpt = session.get_checkpoint()
            if ckpt is not None:
                est = load_estimator(ckpt)
                est.set_params(warm_start=True)
                done = est.max_iter
            else:
                est = None          # built on the first chunk (sklearn
                done = 0            # rejects max_iter=0)
            X, y = _dataset_to_xy(get_dataset_shard("train"),
                                  config["label_column"])
            try:
                vds = get_dataset_shard("valid")
            except KeyError:
                vds = None
            Xv = yv = None
            if vds is not None:
                Xv, yv = _dataset_to_xy(vds, config["label_column"])
            if est is not None and done >= total:
                # Checkpoint already covers the requested rounds: still
                # report once, or fit() returns an empty Result and the
                # caller's load_estimator(result.checkpoint) breaks.
                metrics = {"boost_round": done,
                           "train_score": float(est.score(X, y))}
                if Xv is not None:
                    metrics["valid_score"] = float(est.score(Xv, yv))
                session.report(metrics,
                               checkpoint=_estimator_checkpoint(est))
            while done < total:
                done = min(done + chunk, total)
                if est is None:
                    est = Booster(**config["params"], warm_start=True,
                                  max_iter=done, early_stopping=False)
                else:
                    est.set_params(max_iter=done)
                est.fit(X, y)
                metrics = {"boost_round": done,
                           "train_score": float(est.score(X, y))}
                if Xv is not None:
                    metrics["valid_score"] = float(est.score(Xv, yv))
                session.report(metrics,
                               checkpoint=_estimator_checkpoint(est))

        super().__init__(
            loop,
            train_loop_config={
                "label_column": label_column,
                "params": dict(params or {}),
                "num_boost_round": num_boost_round,
                "objective": objective,
                "rounds_per_report": rounds_per_report,
            },
            scaling_config=scaling_config or ScalingConfig(num_workers=1),
            run_config=run_config, datasets=datasets,
            resume_from_checkpoint=resume_from_checkpoint)
