"""BaseTrainer: the fit() entry point.

Design analog: reference ``python/ray/train/base_trainer.py`` (BaseTrainer,
fit:339, as_trainable:500).  fit() runs the training loop and returns an
air.Result; ``as_trainable()`` adapts any trainer into the Tune Trainable
contract so Tuner(trainer) composes exactly like the reference.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Type

from ray_tpu.air.checkpoint import Checkpoint
from ray_tpu.air.config import RunConfig, ScalingConfig
from ray_tpu.air.result import Result


class TrainingFailedError(RuntimeError):
    """fit() failed after exhausting FailureConfig.max_failures."""


class BaseTrainer:
    def __init__(self,
                 *,
                 scaling_config: Optional[ScalingConfig] = None,
                 run_config: Optional[RunConfig] = None,
                 datasets: Optional[Dict[str, Any]] = None,
                 resume_from_checkpoint: Optional[Any] = None):
        self.scaling_config = scaling_config or ScalingConfig()
        self.run_config = run_config or RunConfig()
        self.datasets = datasets or {}
        # A string is treated as a storage URI (file://, gs://, ...) —
        # reference base_trainer accepts Checkpoint objects whose storage
        # may be remote; here the URI form is explicit.
        if isinstance(resume_from_checkpoint, str):
            resume_from_checkpoint = Checkpoint.from_uri(
                resume_from_checkpoint)
        self.resume_from_checkpoint = resume_from_checkpoint

    def setup(self) -> None:
        """Pre-fit hook (reference base_trainer.py:287)."""

    def training_loop(self) -> None:
        """Subclass hook: run training, calling tune.report via session.
        Must be driven through _run_training_loop below."""
        raise NotImplementedError

    def fit(self) -> Result:
        from ray_tpu.train._internal.loop_runner import run_trainer_directly
        self.setup()
        return run_trainer_directly(self)

    def as_trainable(self) -> Type:
        """Wrap this trainer as a Tune Trainable class (reference
        base_trainer.py:500) so it can be passed to Tuner."""
        from ray_tpu.tune.trainable import wrap_trainer_as_trainable
        return wrap_trainer_as_trainable(self)
