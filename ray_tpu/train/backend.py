"""Backend plug-in contract for worker-group process setup.

Design analog: reference ``python/ray/train/backend.py`` (Backend with
on_start/on_training_start/on_shutdown hooks called by BackendExecutor).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import TYPE_CHECKING

if TYPE_CHECKING:
    from ray_tpu.train._internal.worker_group import WorkerGroup


@dataclass
class BackendConfig:
    """Base config; subclasses carry framework-specific knobs."""

    def backend_cls(self):
        return Backend


class Backend:
    """Hooks run by BackendExecutor around the worker group lifecycle."""

    share_env_vars: tuple = ()

    def on_start(self, worker_group: "WorkerGroup",
                 backend_config: BackendConfig):
        """Called after all workers started, before the train fn runs."""

    def on_training_start(self, worker_group: "WorkerGroup",
                          backend_config: BackendConfig):
        """Called right before start_training on each worker."""

    def on_shutdown(self, worker_group: "WorkerGroup",
                    backend_config: BackendConfig):
        """Called before the worker group is torn down."""
