from ray_tpu.train.torch.config import (TorchConfig, prepare_data_loader,
                                        prepare_model)
from ray_tpu.train.torch.torch_trainer import TorchTrainer

__all__ = ["TorchConfig", "TorchTrainer", "prepare_data_loader",
           "prepare_model"]
