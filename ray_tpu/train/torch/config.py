"""Torch distributed backend: gloo process groups over the worker gang.

Design analog: reference ``python/ray/train/torch/config.py``
(``_TorchBackend.on_start:132`` -> ``_setup_torch_process_group:69`` ->
``dist.init_process_group:113``).  On this framework torch is the
host-CPU side path (the TPU compute path is JAX — see
``train/jax/config.py``); the gang setup is the same rank-0 TCP
rendezvous, with gloo instead of NCCL.
"""

from __future__ import annotations

import logging
import os
from dataclasses import dataclass
from typing import Optional

from ray_tpu.train.backend import Backend, BackendConfig

logger = logging.getLogger(__name__)


@dataclass
class TorchConfig(BackendConfig):
    backend: str = "gloo"
    init_timeout_s: float = 120.0

    def backend_cls(self):
        return _TorchBackend


def _setup_torch_process_group(backend: str, init_method: str,
                               rank: int, world_size: int,
                               timeout_s: float) -> bool:
    import datetime

    import torch.distributed as dist
    if dist.is_initialized():
        return True
    dist.init_process_group(
        backend=backend, init_method=init_method, rank=rank,
        world_size=world_size,
        timeout=datetime.timedelta(seconds=timeout_s))
    return dist.is_initialized()


def _shutdown_torch_process_group():
    import torch.distributed as dist
    if dist.is_initialized():
        dist.destroy_process_group()


def _free_port() -> int:
    import socket
    s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    s.bind(("", 0))
    port = s.getsockname()[1]
    s.close()
    return port


class _TorchBackend(Backend):
    def on_start(self, worker_group, backend_config: TorchConfig):
        if len(worker_group) <= 1:
            return
        import ray_tpu
        ip = worker_group.workers[0].ip
        port = worker_group.execute_single(0, _free_port)
        init_method = f"tcp://{ip}:{port}"
        logger.info("torch.distributed %s rendezvous at %s",
                    backend_config.backend, init_method)
        refs = [
            w.actor.execute.remote(
                _setup_torch_process_group, backend_config.backend,
                init_method, w.rank, len(worker_group),
                backend_config.init_timeout_s)
            for w in worker_group.workers
        ]
        ray_tpu.get(refs, timeout=backend_config.init_timeout_s + 30)

    def on_shutdown(self, worker_group, backend_config: TorchConfig):
        try:
            worker_group.execute(_shutdown_torch_process_group)
        except Exception:
            pass


def prepare_model(model, parallel_strategy: Optional[str] = "ddp"):
    """Wrap a torch.nn.Module for data-parallel training (reference:
    ``train/torch/train_loop_utils.py prepare_model:70`` — DDP wrap; FSDP
    maps to the JAX fsdp path in this framework, not torch FSDP)."""
    import torch.distributed as dist
    if parallel_strategy and dist.is_initialized() and \
            dist.get_world_size() > 1:
        from torch.nn.parallel import DistributedDataParallel
        return DistributedDataParallel(model)
    return model


def prepare_data_loader(dataset, batch_size: int, shuffle: bool = True):
    """DataLoader with a DistributedSampler when a process group is up
    (reference: train_loop_utils.py prepare_data_loader)."""
    import torch.distributed as dist
    from torch.utils.data import DataLoader, DistributedSampler
    sampler = None
    if dist.is_initialized() and dist.get_world_size() > 1:
        sampler = DistributedSampler(dataset)
    return DataLoader(dataset, batch_size=batch_size, sampler=sampler,
                      shuffle=shuffle if sampler is None else False)
