"""TorchTrainer: DataParallelTrainer with the gloo process-group backend.

Design analog: reference ``python/ray/train/torch/torch_trainer.py``.
The train_loop_per_worker runs inside an initialized torch.distributed
group; ``prepare_model``/``prepare_data_loader`` give the reference's
DDP conveniences.  Torch here is the CPU/host path — TPU training goes
through JaxTrainer.
"""

from __future__ import annotations

from ray_tpu.train.data_parallel_trainer import DataParallelTrainer
from ray_tpu.train.torch.config import TorchConfig


class TorchTrainer(DataParallelTrainer):
    _backend_config_cls = TorchConfig
