"""TransformersTrainer: fine-tune Hugging Face Flax models on the gang.

Design analog: reference ``python/ray/train/huggingface/
huggingface_trainer.py`` (HuggingFaceTrainer: wraps transformers'
Trainer inside a DataParallelTrainer worker loop).  TPU-first deltas: no
torch Trainer underneath — the worker loop jits ONE optax train step
over the Flax model's ``__call__`` (causal-LM shifted cross-entropy),
so the whole update is a single XLA program; data arrives through the
framework's Dataset shards (host numpy -> device).

The model is constructed inside each worker by a user ``model_init_fn``
(e.g. ``lambda: FlaxGPT2LMHeadModel(GPT2Config(...))``) — constructing
from a config works fully offline; loading pretrained weights works
wherever HF's cache/network does.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional

from ray_tpu.air.checkpoint import Checkpoint
from ray_tpu.air.config import RunConfig, ScalingConfig
from ray_tpu.train.data_parallel_trainer import DataParallelTrainer
from ray_tpu.train.jax.config import JaxConfig


def _default_loop(config: Dict[str, Any]) -> None:
    """Per-worker loop: jitted causal-LM fine-tuning over the shard."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    from ray_tpu.air import session

    model = config["model_init_fn"]()
    params = model.params
    tx = optax.adamw(config.get("lr", 5e-4),
                     weight_decay=config.get("weight_decay", 0.0))
    opt_state = tx.init(params)

    def loss_fn(params, tokens):
        # Causal LM: predict token t+1 from prefix <= t.
        logits = model(tokens[:, :-1], params=params).logits
        targets = tokens[:, 1:]
        logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
        ll = jnp.take_along_axis(
            logp, targets[..., None].astype(jnp.int32), axis=-1)[..., 0]
        return -jnp.mean(ll)

    @jax.jit
    def step(params, opt_state, tokens):
        loss, grads = jax.value_and_grad(loss_fn)(params, tokens)
        updates, opt_state = tx.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, loss

    ckpt = session.get_checkpoint()
    start_epoch = 0
    if ckpt is not None:
        state = ckpt.to_dict()
        params = jax.tree.map(jnp.asarray, state["params"])
        opt_state = tx.init(params)     # optimizer restarts (moments are
        start_epoch = state["epoch"] + 1   # cheap to rebuild at this scale)

    from ray_tpu.train.data_parallel_trainer import get_dataset_shard
    batch_size = config.get("batch_size", 8)
    shard = get_dataset_shard("train")
    for epoch in range(start_epoch, config.get("epochs", 1)):
        losses = []
        for batch in shard.iter_batches(batch_size=batch_size,
                                        batch_format="numpy"):
            tokens = jnp.asarray(np.asarray(batch["tokens"], np.int32))
            params, opt_state, loss = step(params, opt_state, tokens)
            losses.append(float(loss))
        session.report(
            {"loss": float(np.mean(losses)), "epoch": epoch},
            checkpoint=Checkpoint.from_dict({
                "params": jax.tree.map(np.asarray, params),
                "epoch": epoch}))


class TransformersTrainer(DataParallelTrainer):
    """Fine-tune a HF Flax model with the default causal-LM loop, or any
    user loop via ``train_loop_per_worker`` (same contract as
    DataParallelTrainer — the reference's trainer_init_per_worker
    pattern maps to ``model_init_fn``)."""

    _backend_config_cls = JaxConfig

    def __init__(self, *,
                 model_init_fn: Callable[[], Any],
                 train_loop_per_worker: Optional[Callable] = None,
                 train_loop_config: Optional[Dict[str, Any]] = None,
                 scaling_config: Optional[ScalingConfig] = None,
                 run_config: Optional[RunConfig] = None,
                 datasets: Optional[Dict[str, Any]] = None,
                 resume_from_checkpoint: Optional[Checkpoint] = None):
        cfg = dict(train_loop_config or {})
        cfg["model_init_fn"] = model_init_fn
        super().__init__(
            train_loop_per_worker or _default_loop,
            train_loop_config=cfg,
            scaling_config=scaling_config,
            run_config=run_config,
            datasets=datasets,
            resume_from_checkpoint=resume_from_checkpoint)


def load_model(checkpoint: Checkpoint, model_init_fn: Callable[[], Any]):
    """Rebuild a fine-tuned model from a TransformersTrainer checkpoint
    (reference: HuggingFaceCheckpoint.get_model)."""
    import jax.numpy as jnp
    import jax
    model = model_init_fn()
    state = checkpoint.to_dict()
    model.params = jax.tree.map(jnp.asarray, state["params"])
    return model
