"""BatchPredictor: offline batch inference of a Checkpoint over a Dataset.

Design analog: reference ``python/ray/train/batch_predictor.py`` — wraps a
Predictor class in a callable "scoring wrapper" mapped over the dataset with
an actor pool, so each scoring actor loads the model once and scores many
blocks.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Type, Union

import numpy as np

from ray_tpu.air.checkpoint import Checkpoint
from ray_tpu.data.dataset import ActorPoolStrategy, Dataset
from ray_tpu.train.predictor import Predictor


class _ScoringWrapper:
    """Callable class instantiated once per scoring actor; holds the
    restored predictor (reference batch_predictor.py ScoringWrapper)."""

    def __init__(self, predictor_cls, checkpoint_data: Dict,
                 predictor_kwargs: Dict, feature_columns, keep_columns,
                 prediction_column: str):
        self._predictor = predictor_cls.from_checkpoint(
            Checkpoint.from_dict(checkpoint_data), **predictor_kwargs)
        self._feature_columns = feature_columns
        self._keep_columns = keep_columns
        self._prediction_column = prediction_column

    def __call__(self, batch):
        if isinstance(batch, dict):
            if self._feature_columns:
                if len(self._feature_columns) == 1:
                    feats = batch[self._feature_columns[0]]
                else:
                    feats = np.stack(
                        [batch[c] for c in self._feature_columns], axis=-1)
            elif len(batch) == 1:
                feats = next(iter(batch.values()))
            else:
                feats = batch
        else:
            feats = batch
        pred = self._predictor.predict(feats)
        out = {self._prediction_column: np.asarray(pred)}
        if self._keep_columns and isinstance(batch, dict):
            for c in self._keep_columns:
                out[c] = batch[c]
        return out


class BatchPredictor:
    def __init__(self, checkpoint: Checkpoint,
                 predictor_cls: Type[Predictor], **predictor_kwargs):
        self._checkpoint = checkpoint
        self._predictor_cls = predictor_cls
        self._predictor_kwargs = predictor_kwargs

    @classmethod
    def from_checkpoint(cls, checkpoint: Checkpoint,
                        predictor_cls: Type[Predictor],
                        **predictor_kwargs) -> "BatchPredictor":
        return cls(checkpoint, predictor_cls, **predictor_kwargs)

    def predict(self, dataset: Dataset, *,
                batch_size: int = 4096,
                min_scoring_workers: int = 1,
                max_scoring_workers: int = 4,
                feature_columns: Optional[list] = None,
                keep_columns: Optional[list] = None,
                prediction_column: str = "predictions") -> Dataset:
        """Score every row; returns a Dataset of prediction batches."""
        # Ship the checkpoint by value: a directory checkpoint's local path
        # does not exist on remote nodes, so materialize it to a dict
        # (to_dict handles both forms).
        return dataset.map_batches(
            _ScoringWrapper,
            batch_size=batch_size,
            compute=ActorPoolStrategy(min_size=min_scoring_workers,
                                      max_size=max_scoring_workers),
            fn_constructor_args=(self._predictor_cls,
                                 self._checkpoint.to_dict(),
                                 self._predictor_kwargs, feature_columns,
                                 keep_columns, prediction_column),
        )
