"""JAX distributed backend: the TPU-native process-group setup.

Design analog: reference ``python/ray/train/torch/config.py`` --
_TorchBackend.on_start:132 -> _setup_torch_process_group:69 ->
dist.init_process_group(nccl):113.  TPU replacement: rank 0 publishes a
coordinator address; every worker calls ``jax.distributed.initialize`` so
the gang becomes one multi-controller JAX program.  After that, in-slice
collectives are *compiled into* the pjit step over ICI -- there is no NCCL
ring to manage at runtime.
"""

from __future__ import annotations

import logging
import os
from dataclasses import dataclass
from typing import Optional

from ray_tpu.train.backend import Backend, BackendConfig

logger = logging.getLogger(__name__)


@dataclass
class JaxConfig(BackendConfig):
    """distributed: None = auto (initialize when num_workers > 1).
    platform: override JAX_PLATFORMS in workers ("tpu", "cpu")."""

    distributed: Optional[bool] = None
    platform: Optional[str] = None
    coordinator_port: Optional[int] = None

    def backend_cls(self):
        return _JaxBackend


def _init_jax_distributed(coordinator: str, num_processes: int,
                          process_id: int, platform: Optional[str]):
    if platform:
        os.environ["JAX_PLATFORMS"] = platform
    import jax
    if platform:
        # A sitecustomize-injected TPU plugin may have pinned jax_platforms
        # at interpreter start; config.update wins as long as no backend has
        # been initialized yet (workers call this before any jax use).
        jax.config.update("jax_platforms", platform)
    from ray_tpu.util import jax_compat

    jax_compat.install()
    jax.distributed.initialize(
        coordinator_address=coordinator,
        num_processes=num_processes,
        process_id=process_id,
    )
    return len(jax.devices())


def _shutdown_jax_distributed():
    import jax
    try:
        jax.distributed.shutdown()
    except Exception:
        pass


class _JaxBackend(Backend):
    def on_start(self, worker_group, backend_config: JaxConfig):
        n = len(worker_group)
        distributed = backend_config.distributed
        if distributed is None:
            distributed = n > 1
        if not distributed:
            if backend_config.platform:
                worker_group.execute(
                    _set_platform, backend_config.platform)
            return
        # Rank 0 owns the coordinator (reference: rank-0 addr/port handshake
        # at train/torch/config.py:137-141).
        ip = worker_group.workers[0].ip
        port = backend_config.coordinator_port or \
            worker_group.execute_single(0, _free_port)
        coordinator = f"{ip}:{port}"
        logger.info("jax.distributed coordinator at %s (%d processes)",
                    coordinator, n)
        import ray_tpu
        refs = [
            w.actor.execute.remote(_init_jax_distributed, coordinator, n,
                                   w.rank, backend_config.platform)
            for w in worker_group.workers
        ]
        device_counts = ray_tpu.get(refs, timeout=120.0)
        logger.info("jax.distributed up: global devices per proc %s",
                    device_counts)

    def on_shutdown(self, worker_group, backend_config: JaxConfig):
        if len(worker_group) > 1 and backend_config.distributed is not False:
            try:
                worker_group.execute(_shutdown_jax_distributed)
            except Exception:
                pass


def _set_platform(platform: str):
    os.environ["JAX_PLATFORMS"] = platform


def _free_port() -> int:
    import socket
    s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    s.bind(("", 0))
    port = s.getsockname()[1]
    s.close()
    return port
