from ray_tpu.train.jax.config import JaxConfig
from ray_tpu.train.jax.jax_trainer import JaxTrainer
from ray_tpu.train.jax.orbax_checkpoint import (JaxCheckpoint,
                                                restore_sharded,
                                                save_sharded)

__all__ = ["JaxCheckpoint", "JaxConfig", "JaxTrainer",
           "restore_sharded", "save_sharded"]
