"""Orbax-backed sharded checkpointing for JAX train states.

Design analog: reference framework checkpoint flavors
(``train/torch/torch_checkpoint.py`` TorchCheckpoint etc.) — here the
TPU-idiomatic one: Orbax writes each array's shards from the devices
that hold them (every host saves only its addressable shards, the
standard multi-controller pattern), and restore places shards directly
onto the target mesh without materializing full arrays on one host.
Wraps into the AIR ``Checkpoint`` envelope so Train/Tune plumbing
(session.report, resume_from_checkpoint, Result.checkpoint) is
unchanged.
"""

from __future__ import annotations

import os
from typing import Any, Optional

from ray_tpu.air.checkpoint import Checkpoint


def save_sharded(path: str, tree: Any) -> str:
    """Write a (possibly sharded) pytree of jax.Arrays with Orbax.

    Under a Mesh each process writes only its addressable shards;
    single-process saves degrade to a normal array dump."""
    import orbax.checkpoint as ocp
    path = os.path.abspath(path)
    ckptr = ocp.StandardCheckpointer()
    ckptr.save(path, tree, force=True)
    ckptr.wait_until_finished()
    return path


def restore_sharded(path: str, target: Optional[Any] = None) -> Any:
    """Restore an Orbax checkpoint.

    ``target``: a pytree of abstract shapes/arrays carrying shardings
    (e.g. the freshly-initialized, mesh-sharded params) — shards load
    straight onto their devices.  Without it, arrays restore replicated
    on the default device."""
    import jax
    import orbax.checkpoint as ocp
    path = os.path.abspath(path)
    ckptr = ocp.StandardCheckpointer()
    if target is not None:
        abstract = jax.tree.map(
            lambda x: jax.ShapeDtypeStruct(
                x.shape, x.dtype, sharding=getattr(x, "sharding", None)),
            target)
        return ckptr.restore(path, abstract)
    return ckptr.restore(path)


class JaxCheckpoint(Checkpoint):
    """AIR Checkpoint flavor holding an Orbax directory (reference:
    framework Checkpoint subclasses).  ``from_sharded_state`` saves the
    live (sharded) train state; ``load_state(target=...)`` restores it
    onto a mesh."""

    @classmethod
    def from_sharded_state(cls, tree: Any, *, path: Optional[str] = None,
                           **extra) -> "JaxCheckpoint":
        import json
        import tempfile
        path = path or tempfile.mkdtemp(prefix="rt-orbax-")
        save_sharded(os.path.join(path, "state"), tree)
        if extra:
            with open(os.path.join(path, "meta.json"), "w") as f:
                json.dump(extra, f, default=str)
        return cls.from_directory(path)

    def meta(self) -> dict:
        import json
        p = os.path.join(self.to_directory(), "meta.json")
        if os.path.exists(p):
            with open(p) as f:
                return json.load(f)
        return {}

    def load_state(self, target: Optional[Any] = None) -> Any:
        root = self.to_directory()
        return restore_sharded(os.path.join(root, "state"), target)
