"""Orbax-backed sharded checkpointing for JAX train states.

Design analog: reference framework checkpoint flavors
(``train/torch/torch_checkpoint.py`` TorchCheckpoint etc.) — here the
TPU-idiomatic one: Orbax writes each array's shards from the devices
that hold them (every host saves only its addressable shards, the
standard multi-controller pattern), and restore places shards directly
onto the target mesh without materializing full arrays on one host.
Wraps into the AIR ``Checkpoint`` envelope so Train/Tune plumbing
(session.report, resume_from_checkpoint, Result.checkpoint) is
unchanged.

Crash consistency (same protocol as _internal/checkpoint_store.py):
Orbax writes land in a ``.writing`` sibling first; every file is fsynced
and recorded (size + crc32) in an ``RT_MANIFEST.json`` written LAST via
the durable tmp→fsync→rename pattern, then the whole directory renames
into place.  A crash at any point leaves either the previous checkpoint
or a ``.writing`` orphan — never a torn directory at the committed path.
Restore re-verifies the manifest and raises ``CorruptCheckpointError``
on any mismatch so callers (the gang supervisor's verified-checkpoint
gate) fall back instead of loading garbage.
"""

from __future__ import annotations

import json
import os
import shutil
from typing import Any, Dict, Optional

from ray_tpu.air.checkpoint import Checkpoint
from ray_tpu.train._internal.checkpoint_store import (
    CorruptCheckpointError, file_crc32, write_file_durable)

RT_MANIFEST = "RT_MANIFEST.json"


def _seal_dir(root: str) -> None:
    """fsync every file under ``root`` and commit an RT_MANIFEST.json
    (relative path → size + crc32) as the LAST durable write.  The
    manifest must never attest to data still in the page cache, hence
    the per-file fsync before it is written."""
    files: Dict[str, Dict[str, int]] = {}
    for dirpath, _, names in os.walk(root):
        for name in names:
            full = os.path.join(dirpath, name)
            rel = os.path.relpath(full, root)
            fd = os.open(full, os.O_RDONLY)
            try:
                os.fsync(fd)
            finally:
                os.close(fd)
            files[rel] = {"size": os.path.getsize(full),
                          "crc32": file_crc32(full)}
    write_file_durable(
        os.path.join(root, RT_MANIFEST),
        json.dumps({"format": 1, "files": files},
                   sort_keys=True).encode("utf-8"))


def _publish_dir(tmp: str, path: str) -> None:
    """Atomically rename the sealed ``tmp`` directory to ``path`` and make
    the rename itself durable (parent-directory fsync)."""
    shutil.rmtree(path, ignore_errors=True)
    os.replace(tmp, path)
    dfd = os.open(os.path.dirname(path) or ".", os.O_RDONLY)
    try:
        os.fsync(dfd)
    finally:
        os.close(dfd)


def verify_sharded(path: str) -> Dict[str, Any]:
    """Verify a sealed checkpoint directory against its RT_MANIFEST.json;
    returns the manifest.  Raises CorruptCheckpointError when the manifest
    is missing/torn or any listed file is missing, short, or fails crc32
    (a manifest-less directory at a committed path means the writer
    predates the seal protocol or the manifest itself was lost — treat it
    as partial either way)."""
    mpath = os.path.join(path, RT_MANIFEST)
    try:
        with open(mpath, "rb") as f:
            manifest = json.loads(f.read().decode("utf-8"))
    except FileNotFoundError:
        raise CorruptCheckpointError(
            f"{path}: no {RT_MANIFEST} (partial/unsealed write)") from None
    except (OSError, ValueError, UnicodeDecodeError) as e:
        raise CorruptCheckpointError(f"{path}: torn manifest: {e}") from e
    for rel, rec in (manifest.get("files") or {}).items():
        full = os.path.join(path, rel)
        try:
            size = os.path.getsize(full)
        except OSError:
            raise CorruptCheckpointError(
                f"{path}: file {rel} missing") from None
        if size != int(rec["size"]):
            raise CorruptCheckpointError(
                f"{path}: file {rel} is {size} bytes, manifest says "
                f"{rec['size']} (torn write)")
        if file_crc32(full) != int(rec["crc32"]):
            raise CorruptCheckpointError(
                f"{path}: file {rel} failed crc32 verification")
    return manifest


def save_sharded(path: str, tree: Any) -> str:
    """Write a (possibly sharded) pytree of jax.Arrays with Orbax,
    crash-consistently.

    Under a Mesh each process writes only its addressable shards;
    single-process saves degrade to a normal array dump.  The write goes
    to ``<path>.writing``, is sealed (fsync + CRC manifest), and renames
    into place — a crash never leaves a torn directory at ``path``."""
    import orbax.checkpoint as ocp
    path = os.path.abspath(path)
    tmp = path + ".writing"
    shutil.rmtree(tmp, ignore_errors=True)
    ckptr = ocp.StandardCheckpointer()
    ckptr.save(tmp, tree, force=True)
    ckptr.wait_until_finished()
    _seal_dir(tmp)
    _publish_dir(tmp, path)
    return path


def restore_sharded(path: str, target: Optional[Any] = None) -> Any:
    """Restore an Orbax checkpoint, verifying its seal first (raises
    CorruptCheckpointError on a torn/corrupt directory so callers fall
    back to a previous intact checkpoint instead of loading garbage).

    ``target``: a pytree of abstract shapes/arrays carrying shardings
    (e.g. the freshly-initialized, mesh-sharded params) — shards load
    straight onto their devices.  Without it, arrays restore replicated
    on the default device."""
    import jax
    import orbax.checkpoint as ocp
    path = os.path.abspath(path)
    verify_sharded(path)
    ckptr = ocp.StandardCheckpointer()
    if target is not None:
        abstract = jax.tree.map(
            lambda x: jax.ShapeDtypeStruct(
                x.shape, x.dtype, sharding=getattr(x, "sharding", None)),
            target)
        return ckptr.restore(path, abstract)
    return ckptr.restore(path)


class JaxCheckpoint(Checkpoint):
    """AIR Checkpoint flavor holding an Orbax directory (reference:
    framework Checkpoint subclasses).  ``from_sharded_state`` saves the
    live (sharded) train state; ``load_state(target=...)`` restores it
    onto a mesh."""

    @classmethod
    def from_sharded_state(cls, tree: Any, *, path: Optional[str] = None,
                           **extra) -> "JaxCheckpoint":
        import tempfile
        path = os.path.abspath(path or tempfile.mkdtemp(prefix="rt-orbax-"))
        # Assemble state + meta in a sibling and rename the WHOLE envelope
        # at once, so a crash can't publish state without its meta (or
        # either half torn).
        tmp = path + ".writing"
        shutil.rmtree(tmp, ignore_errors=True)
        os.makedirs(tmp)
        save_sharded(os.path.join(tmp, "state"), tree)
        if extra:
            write_file_durable(
                os.path.join(tmp, "meta.json"),
                json.dumps(extra, default=str).encode("utf-8"))
        _publish_dir(tmp, path)
        return cls.from_directory(path)

    def meta(self) -> dict:
        p = os.path.join(self.to_directory(), "meta.json")
        if os.path.exists(p):
            with open(p) as f:
                return json.load(f)
        return {}

    def load_state(self, target: Optional[Any] = None) -> Any:
        root = self.to_directory()
        return restore_sharded(os.path.join(root, "state"), target)
