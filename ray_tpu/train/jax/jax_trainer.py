"""JaxTrainer: the flagship DataParallelTrainer flavour.

Design analog: reference ``python/ray/train/torch/torch_trainer.py``
(TorchTrainer = DataParallelTrainer + TorchConfig).  The worker fn is the
per-process half of an SPMD program: build a Mesh over jax.devices(),
shard the batch on the data axis with pjit, and let XLA emit ICI
collectives -- see ray_tpu.parallel for mesh/sharding helpers.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional

from ray_tpu.air.checkpoint import Checkpoint
from ray_tpu.air.config import RunConfig, ScalingConfig
from ray_tpu.train.data_parallel_trainer import DataParallelTrainer
from ray_tpu.train.jax.config import JaxConfig


class JaxTrainer(DataParallelTrainer):
    _backend_config_cls = JaxConfig

    def __init__(self,
                 train_loop_per_worker: Callable,
                 *,
                 train_loop_config: Optional[Dict[str, Any]] = None,
                 jax_config: Optional[JaxConfig] = None,
                 scaling_config: Optional[ScalingConfig] = None,
                 run_config: Optional[RunConfig] = None,
                 datasets: Optional[Dict[str, Any]] = None,
                 resume_from_checkpoint: Optional[Checkpoint] = None):
        super().__init__(
            train_loop_per_worker,
            train_loop_config=train_loop_config,
            backend_config=jax_config or JaxConfig(),
            scaling_config=scaling_config,
            run_config=run_config,
            datasets=datasets,
            resume_from_checkpoint=resume_from_checkpoint)
