"""Real pipeline parallelism: microbatched GPipe schedule over the ``pp`` axis.

The reference has no pipeline parallelism at all (SURVEY §2.4 — its scaling
story is DDP/FSDP only); this is new capability, built the TPU way rather
than as host-level stage actors: the whole pipeline is ONE SPMD program.
``shard_map`` places one stage per device along the ``pp`` mesh axis, layer
weights are sharded on their stacked ``[L]`` dim, and microbatch activations
flow stage-to-stage with ``lax.ppermute`` over ICI.  The schedule is a
``lax.scan`` over ``num_microbatches + pp - 1`` ticks, which keeps it
reverse-mode differentiable — autodiff through the scan + ppermute yields the
backward pipeline (activations replay in reverse, gradient traffic rides the
inverse permutation), so one forward definition gives the full GPipe
fill/steady/drain schedule for training with no hand-written backward pass.

Stages compose with the rest of the model zoo (round-3, VERDICT r2 #10):

  * any local attention body runs inside a stage — dense, the Pallas
    flash kernels, or RING attention with the sp axis threaded through
    the schedule (activations seq-sharded inside the pipeline shard_map,
    the ring collective riding the same mesh);
  * MoE blocks run with their load-balance aux loss CARRIED through the
    schedule (gated so fill/drain garbage ticks contribute zero), and
    expert weights shard over a ``pp x ep`` mesh via moe_mlp's shard_map
    mode (experts local to each ep member, all_gather reassembly);
  * training uses a FUSED loss epilogue: the last stage computes the
    cross-entropy of each microbatch as it drains, so the collective at
    the end of the program is a scalar psum — not the old full
    [M, mb, S, D] output-buffer psum around the pp ring.

Bubble fraction is the usual (pp-1)/(M+pp-1); raise ``num_microbatches`` to
amortize.  Weight grads for each stage stay device-local (the transpose of a
sharded-in param is a sharded-out grad), so the only cross-stage traffic is
the [mb, S, D] activation/grad hop per tick — exactly the wire pattern of a
1F1B/GPipe implementation, but emitted by XLA.
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Dict, Optional

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ray_tpu.util import jax_compat

jax_compat.install()


def _stage_machinery(axis_name: str):
    pp = jax.lax.psum(1, axis_name)
    idx = jax.lax.axis_index(axis_name)
    shift = [(i, (i + 1) % pp) for i in range(pp)]
    return pp, idx, shift


def gpipe_spmd(block_fn: Callable, local_params, x_mbs, *,
               axis_name: str = "pp", aux_axes=None, remat: bool = True):
    """Per-device GPipe loop (call inside ``shard_map`` over ``axis_name``).

    block_fn:      (x, layer_params) -> (x, aux scalar), one block.
    local_params:  this stage's stacked params, leading dim [L/pp].
    x_mbs:         [M, mb, ...] microbatched activations (valid on stage 0;
                   other stages' values are ignored).
    aux_axes:      mesh axes the aux sum reduces over (defaults to just
                   ``axis_name``; pass the data axes too when the batch is
                   sharded, or each shard only reports its own aux).
    Returns ([M, mb, ...] outputs, aux_sum) — outputs replicated across the
    pp axis, aux summed over every REAL (stage, microbatch) pass (fill and
    drain ticks processing garbage state are masked out).
    """
    pp, idx, shift = _stage_machinery(axis_name)
    M = x_mbs.shape[0]
    T = M + pp - 1

    body = jax.checkpoint(block_fn) if remat else block_fn

    def apply_stage(x):
        def scan_body(c, lp):
            y, aux = body(c, lp)
            return y, aux
        y, auxs = jax.lax.scan(scan_body, x, local_params)
        return y, jnp.sum(auxs)

    def tick(carry, t):
        state, out, aux_acc = carry
        # Fill: stage 0 ingests microbatch t (clamped once the pipe drains).
        inp = jax.lax.dynamic_index_in_dim(
            x_mbs, jnp.clip(t, 0, M - 1), 0, keepdims=False)
        state = jnp.where(idx == 0, inp, state)
        y, aux = apply_stage(state)
        # This stage is processing microbatch t - idx; only count its aux
        # when that's a real microbatch (not fill/drain garbage).
        m_here = t - idx
        aux_acc = aux_acc + jnp.where(
            (m_here >= 0) & (m_here < M), aux, 0.0)
        # Drain: the last stage emits microbatch t-(pp-1) once it's real.
        m = t - (pp - 1)
        write = (idx == pp - 1) & (m >= 0)
        out = jnp.where(
            write,
            jax.lax.dynamic_update_index_in_dim(
                out, y, jnp.clip(m, 0, M - 1), 0),
            out)
        state = jax.lax.ppermute(y, axis_name, shift)
        return (state, out, aux_acc), None

    init = (jnp.zeros_like(x_mbs[0]), jnp.zeros_like(x_mbs),
            jnp.zeros((), jnp.float32))
    (_, out, aux_acc), _ = jax.lax.scan(tick, init, jnp.arange(T))
    # Non-final stages never wrote, so their buffers are zero: a psum both
    # combines and replicates the result across the pp ring in one
    # collective.  (Training avoids this full-buffer epilogue entirely —
    # see gpipe_fused_loss_spmd.)
    return (jax.lax.psum(out, axis_name),
            jax.lax.psum(aux_acc, aux_axes or (axis_name,)))


def gpipe_fused_loss_spmd(block_fn: Callable, loss_mb_fn: Callable,
                          local_params, head_params, x_mbs, tgt_mbs, *,
                          axis_name: str = "pp", all_axes, repl_factor: float,
                          remat: bool = True):
    """GPipe schedule with the loss fused into the drain.

    As each real microbatch leaves the last stage, ``loss_mb_fn(
    head_params, y, tgt) -> ll_sum`` computes its log-likelihood sum right
    there — so no [M, mb, S, D] output buffer is ever materialized or
    psummed around the ring; the program's epilogue collectives are two
    SCALAR psums (ll and aux) over the mesh.

    ``repl_factor`` is the number of mesh devices holding a redundant copy
    of this computation (product of axis sizes not carrying pp or data):
    locals are pre-divided by it so the all-axis psum both totals the
    distinct contributions and keeps the transpose (gradient) math
    consistent for replicated inputs.
    Returns (ll_sum, aux_sum) as replicated scalars.
    """
    pp, idx, shift = _stage_machinery(axis_name)
    M = x_mbs.shape[0]
    T = M + pp - 1
    body = jax.checkpoint(block_fn) if remat else block_fn

    def apply_stage(x):
        y, auxs = jax.lax.scan(lambda c, lp: body(c, lp), x, local_params)
        return y, jnp.sum(auxs)

    def tick(carry, t):
        state, ll_acc, aux_acc = carry
        inp = jax.lax.dynamic_index_in_dim(
            x_mbs, jnp.clip(t, 0, M - 1), 0, keepdims=False)
        state = jnp.where(idx == 0, inp, state)
        y, aux = apply_stage(state)
        m_here = t - idx
        aux_acc = aux_acc + jnp.where(
            (m_here >= 0) & (m_here < M), aux, 0.0)
        m = t - (pp - 1)
        tgt = jax.lax.dynamic_index_in_dim(
            tgt_mbs, jnp.clip(m, 0, M - 1), 0, keepdims=False)
        # Gate the head (LM-head matmul + CE, the priciest op here at real
        # vocab sizes) so only the last stage pays it: under shard_map the
        # predicate is a per-device scalar, so lax.cond lowers to a real
        # branch and non-final stages skip the FLOPs instead of computing
        # and discarding through a where-mask.
        ll = jax.lax.cond(
            (idx == pp - 1) & (m >= 0),
            lambda: loss_mb_fn(head_params, y, tgt).astype(jnp.float32),
            lambda: jnp.zeros((), jnp.float32))
        ll_acc = ll_acc + ll
        state = jax.lax.ppermute(y, axis_name, shift)
        return (state, ll_acc, aux_acc), None

    zero = jnp.zeros((), jnp.float32)
    (_, ll_acc, aux_acc), _ = jax.lax.scan(
        tick, (jnp.zeros_like(x_mbs[0]), zero, zero), jnp.arange(T))
    ll = jax.lax.psum(ll_acc / repl_factor, all_axes)
    aux = jax.lax.psum(aux_acc / repl_factor, all_axes)
    return ll, aux


# ---------------------------------------------------------- 1F1B schedule

def one_f_one_b_spmd(block_fn: Callable, loss_mb_fn: Callable,
                     local_params, head_params, x_mbs, tgt_mbs, *,
                     axis_name: str = "pp", ll_cot: float, aux_cot: float,
                     remat: bool = True):
    """1F1B pipeline schedule with the backward pass written OUT, not
    autodiffed: activation memory O(pp), not O(M).

    GPipe-via-autodiff (``gpipe_spmd``) must keep every tick's carry alive
    for the reverse sweep — O(M + pp) stage inputs per device.  Here each
    tick runs one forward AND one backward block application per stage
    (masked during fill/drain), with microbatch m's backward at stage i
    scheduled ``2(pp-1-i)`` ticks after its forward — so at most
    ``2(pp-1)`` stage inputs are ever stashed, in a fixed ring buffer.
    Weight gradients accumulate in-place; the input cotangent rides the
    inverse ppermute.  (New capability — the reference has no pipeline
    parallelism; schedule follows the PipeDream-flush/Megatron 1F1B
    pattern, re-derived for a single SPMD ``lax.scan`` program.)

    ``ll_cot``/``aux_cot`` are d(final_loss)/d(per-microbatch ll / aux) —
    the caller folds its normalization in, so this function returns
    gradients OF THE FINAL SCALAR LOSS.

    Returns (ll_sum, aux_sum, g_layers, g_head, g_x_mbs) — ll/aux/grads
    are per-device partials; the caller psums (g_layers stays
    pp-sharded).
    """
    pp, idx, shift = _stage_machinery(axis_name)
    rshift = [(i, (i - 1) % pp) for i in range(pp)]
    M = x_mbs.shape[0]
    T = M + 2 * pp - 2
    R = 2 * pp                     # ring slots >= max in-flight (2pp-2) + 1
    body = jax.checkpoint(block_fn) if remat else block_fn

    def stage_fn(params, x):
        y, auxs = jax.lax.scan(lambda c, lp: body(c, lp), x, params)
        return y, jnp.sum(auxs)

    f32 = jnp.float32

    def tick(carry, t):
        (fwd_msg, bwd_msg, stash, ll_acc, aux_acc,
         g_layers, g_head, g_x) = carry

        # ---- forward: stage idx runs microbatch mf = t - idx
        mf = t - idx
        f_valid = (mf >= 0) & (mf < M)
        inp = jax.lax.dynamic_index_in_dim(
            x_mbs, jnp.clip(mf, 0, M - 1), 0, keepdims=False)
        x_in = jnp.where(idx == 0, inp, fwd_msg)
        y, aux = stage_fn(local_params, x_in)
        aux_acc = aux_acc + jnp.where(f_valid, aux.astype(f32), 0.0)
        # Stash the stage INPUT (remat: backward recomputes the body).
        # Write-protect with where: an invalid tick must not clobber a
        # live slot.
        slot = jnp.where(f_valid, mf % R, 0)
        stash = jnp.where(
            f_valid,
            jax.lax.dynamic_update_index_in_dim(stash, x_in, slot, 0),
            stash)

        # ---- last stage: loss of THIS microbatch + its cotangent (1F1B:
        # the last stage's backward immediately follows its forward).
        # lax.cond, not a where-mask: the head matmul + its VJP is the
        # priciest op in the tick at real vocab sizes, and the predicate
        # is a per-device scalar under shard_map, so non-final stages and
        # fill/drain ticks genuinely skip the FLOPs.
        tgt = jax.lax.dynamic_index_in_dim(
            tgt_mbs, jnp.clip(mf, 0, M - 1), 0, keepdims=False)
        is_last = idx == pp - 1

        def head_branch():
            ll, loss_vjp = jax.vjp(
                lambda yy, hh: loss_mb_fn(hh, yy, tgt), y, head_params)
            dy, dh = loss_vjp(jnp.asarray(ll_cot, ll.dtype))
            return ll.astype(f32), dy, dh

        def skip_branch():
            return (jnp.zeros((), f32), jnp.zeros_like(y),
                    jax.tree.map(jnp.zeros_like, head_params))

        ll, dy_loss, dhead = jax.lax.cond(
            is_last & f_valid, head_branch, skip_branch)
        ll_acc = ll_acc + ll
        g_head = jax.tree.map(
            lambda g, d: g + d.astype(g.dtype), g_head, dhead)

        # ---- backward: stage idx runs microbatch mb = t - (2pp - 2 - idx)
        mb = t - (2 * pp - 2 - idx)
        b_valid = (mb >= 0) & (mb < M)
        x_saved = jax.lax.dynamic_index_in_dim(
            stash, jnp.where(b_valid, mb % R, 0), 0, keepdims=False)
        cot_y = jnp.where(is_last, dy_loss, bwd_msg)
        (_, _), stage_vjp = jax.vjp(stage_fn, local_params, x_saved)
        dparams, dx = stage_vjp(
            (cot_y, jnp.asarray(aux_cot, aux.dtype)))
        bsel = jnp.where(b_valid, 1.0, 0.0)
        g_layers = jax.tree.map(
            lambda g, d: g + bsel * d.astype(g.dtype), g_layers, dparams)
        dx = bsel * dx
        # Each valid (stage 0, tick) writes a distinct microbatch slot;
        # the where guards fill/drain ticks from clobbering slot 0.
        g_x = jnp.where(
            (idx == 0) & b_valid,
            jax.lax.dynamic_update_index_in_dim(
                g_x, dx.astype(jnp.float32), jnp.clip(mb, 0, M - 1), 0),
            g_x)

        # ---- move activations downstream, cotangents upstream
        fwd_next = jax.lax.ppermute(y, axis_name, shift)
        bwd_next = jax.lax.ppermute(dx, axis_name, rshift)
        return (fwd_next, bwd_next, stash, ll_acc, aux_acc,
                g_layers, g_head, g_x), None

    zero_mb = jnp.zeros_like(x_mbs[0])
    init = (
        zero_mb, zero_mb,
        jnp.zeros((R,) + x_mbs.shape[1:], x_mbs.dtype),
        jnp.zeros((), f32), jnp.zeros((), f32),
        jax.tree.map(lambda a: jnp.zeros(a.shape, f32), local_params),
        jax.tree.map(lambda a: jnp.zeros(a.shape, f32), head_params),
        jnp.zeros_like(x_mbs, jnp.float32),
    )
    (_, _, _, ll_acc, aux_acc, g_layers, g_head, g_x), _ = jax.lax.scan(
        tick, init, jnp.arange(T))
    return ll_acc, aux_acc, g_layers, g_head, g_x


# ------------------------------------------------------- GPT integration

def _pipeline_head(params):
    """The params the fused drain epilogue needs (shared by both
    pipeline loss paths — keep their numerics in ONE place)."""
    return {"wte": params["wte"], "ln_f": params["ln_f"]}


def _make_loss_mb(cfg):
    """Per-microbatch fused epilogue: final LN + LM head + summed target
    log-likelihoods for one drained microbatch."""
    from ray_tpu.models.gpt import _layer_norm, token_loglikes
    dt = cfg.dtype

    def loss_mb(head, y, tgt):
        y = _layer_norm(y, head["ln_f"]["scale"], head["ln_f"]["bias"])
        logits = jnp.einsum("bsd,vd->bsv", y, head["wte"].astype(dt))
        return jnp.sum(token_loglikes(logits, tgt))

    return loss_mb


def _attn_fn_for(cfg, mesh=None):
    """Same head-major (bnsh) selections the non-pipelined block uses —
    pipelined stages must not silently keep the relayout-paying path.
    ``ring`` threads the sp axis through the stage body: stages see
    [mb, S/sp, ...] activation shards and the ring collective runs inside
    the same shard_map as the pipeline (VERDICT r3 #6)."""
    from ray_tpu.models.gpt import (_dense_causal_attention_bnsh,
                                    _flash_profitable)

    attention = cfg.attention
    if attention == "auto":
        attention = ("flash" if _flash_profitable(cfg.max_seq_len)
                     else "dense")
    assert attention in ("dense", "flash", "ring"), (
        f"pipelined stages support dense/flash/ring attention, got "
        f"{attention!r}")
    cfg = type(cfg)(**{**cfg.__dict__, "attention": attention})
    if cfg.attention == "ring":
        assert mesh is not None and mesh.shape.get("sp", 1) > 1, (
            "ring attention in a pipeline needs an sp mesh axis > 1")
        from ray_tpu.ops.ring_attention import ring_attention_sharded

        def attn_fn(q, k, v):
            return ring_attention_sharded(q, k, v, axis_name="sp")
        return attn_fn
    if cfg.attention == "flash":
        from ray_tpu.ops.flash_attention import flash_attention

        def attn_fn(q, k, v):
            return flash_attention(q, k, v, True, None, None, None, None,
                                   "bnsh")
        attn_fn._layout = "bnsh"
        return attn_fn
    return _dense_causal_attention_bnsh


def _layer_in_specs(cfg, mesh) -> Any:
    """PartitionSpec pytree for the stacked layer params: the [L] dim maps
    to pp, and (when the mesh has a real ep axis) expert dims map to ep —
    translated straight from the model's logical annotations."""
    from ray_tpu.models.gpt import gpt_param_axes

    use_ep = cfg.num_experts and mesh.shape.get("ep", 1) > 1

    def to_spec(ann):
        axes = []
        for a in ann:
            if a == "layers":
                axes.append("pp")
            elif a == "expert" and use_ep:
                axes.append("ep")
            else:
                axes.append(None)
        return P(*axes)

    return jax.tree_util.tree_map(
        to_spec, gpt_param_axes(cfg)["layers"],
        is_leaf=lambda x: isinstance(x, tuple))


def _check_pipeline_shapes(cfg, mesh, B, M):
    pp = mesh.shape.get("pp", 1)
    assert cfg.num_layers % pp == 0, (
        f"num_layers {cfg.num_layers} not divisible by pp={pp}")
    assert B % M == 0, f"batch {B} not divisible by microbatches {M}"
    dsize = mesh.shape.get("dp", 1) * mesh.shape.get("fsdp", 1)
    assert (B // M) % dsize == 0, (
        f"microbatch size {B // M} not divisible by data-axis size {dsize}")
    if cfg.num_experts and mesh.shape.get("ep", 1) > 1:
        assert cfg.num_experts % mesh.shape["ep"] == 0, (
            f"num_experts {cfg.num_experts} not divisible by "
            f"ep={mesh.shape['ep']}")
    return dsize


def gpt_forward_pipelined(params: Dict[str, Any], tokens, cfg, mesh, *,
                          num_microbatches: int):
    """GPT forward (logits) with the block stack pipelined over ``pp``.

    Embedding and LM head run outside the pipeline (replicated over pp).
    Supports dense/flash attention and MoE stages; returns
    (logits, aux_sum).  Training should use gpt_loss_pipelined, whose
    fused epilogue avoids this function's full-output psum.
    """
    from ray_tpu.models.gpt import _block, _layer_norm

    B, S = tokens.shape
    M = num_microbatches
    _check_pipeline_shapes(cfg, mesh, B, M)
    dt = cfg.dtype

    x = params["wte"].astype(dt)[tokens] + params["wpe"].astype(dt)[:S][None]
    x_mbs = x.reshape(M, B // M, S, -1)

    use_ep = cfg.num_experts and mesh.shape.get("ep", 1) > 1
    block = functools.partial(_block, cfg, None, _attn_fn_for(cfg, mesh),
                              moe_ep_axis="ep" if use_ep else None)
    data = tuple(a for a in ("dp", "fsdp") if a in mesh.shape)
    use_sp = cfg.attention == "ring" and mesh.shape.get("sp", 1) > 1
    seq_axes = ("sp",) if use_sp else ()
    spsize = mesh.shape.get("sp", 1) if use_sp else 1
    mb_spec = P(None, data, "sp" if use_sp else None, None)
    dsize = mesh.shape.get("dp", 1) * mesh.shape.get("fsdp", 1)
    piped = jax.shard_map(
        functools.partial(gpipe_spmd, block, remat=cfg.remat,
                          aux_axes=("pp",) + data + seq_axes),
        mesh=mesh, in_specs=(_layer_in_specs(cfg, mesh), mb_spec),
        out_specs=(mb_spec, P()), check_vma=False)
    y, aux = piped(params["layers"], x_mbs)

    y = y.reshape(B, S, -1)
    y = _layer_norm(y, params["ln_f"]["scale"], params["ln_f"]["bias"])
    logits = jnp.einsum("bsd,vd->bsv", y, params["wte"].astype(dt))
    # Normalize the (stage, microbatch, shard)-summed aux to the same
    # scale as gpt_forward_with_aux: sum over layers of full-batch means
    # (seq shards contribute one local mean each under sp).
    return logits.astype(jnp.float32), aux / (M * dsize * spsize)


def gpt_loss_pipelined(params, batch, cfg, mesh, *, num_microbatches: int):
    """Pipelined next-token cross-entropy with the fused drain epilogue.

    Numerically matches ``gpt_loss`` on the same params/batch: per-token
    mean CE plus ``moe_aux_coef`` times the per-(layer, full-batch) aux
    mean (microbatch routing is per-row, so splitting the batch doesn't
    change dispatch decisions).
    """
    from ray_tpu.models.gpt import _block, _layer_norm

    toks = batch["tokens"]
    tokens, targets = toks[:, :-1], toks[:, 1:]
    B, S = tokens.shape
    M = num_microbatches
    dsize = _check_pipeline_shapes(cfg, mesh, B, M)
    dt = cfg.dtype

    x = params["wte"].astype(dt)[tokens] + params["wpe"].astype(dt)[:S][None]
    x_mbs = x.reshape(M, B // M, S, -1)
    tgt_mbs = targets.reshape(M, B // M, S)

    use_ep = cfg.num_experts and mesh.shape.get("ep", 1) > 1
    block = functools.partial(_block, cfg, None, _attn_fn_for(cfg, mesh),
                              moe_ep_axis="ep" if use_ep else None)

    loss_mb = _make_loss_mb(cfg)

    data = tuple(a for a in ("dp", "fsdp") if a in mesh.shape)
    # Ring stages thread sp through the schedule: activations/targets are
    # seq-sharded inside the pipeline shard_map, each sp member computes
    # its chunk's partial ll, and the all-axes psum totals them — sp
    # stops being a replication axis (VERDICT r3 #6).
    use_sp = cfg.attention == "ring" and mesh.shape.get("sp", 1) > 1
    seq = "sp" if use_sp else None
    spsize = mesh.shape.get("sp", 1) if use_sp else 1
    mb_spec = P(None, data, seq, None)
    repl = mesh.size // (mesh.shape.get("pp", 1) * dsize * spsize)
    head = _pipeline_head(params)
    piped = jax.shard_map(
        functools.partial(gpipe_fused_loss_spmd, block, loss_mb,
                          all_axes=tuple(mesh.axis_names),
                          repl_factor=float(repl), remat=cfg.remat),
        mesh=mesh,
        in_specs=(_layer_in_specs(cfg, mesh), P(), mb_spec,
                  P(None, data, seq)),
        out_specs=(P(), P()), check_vma=False)
    ll_sum, aux_sum = piped(params["layers"], head, x_mbs, tgt_mbs)

    ce = -ll_sum / (B * S)
    # aux_sum totals per-(stage-layer, microbatch, data-shard, seq-shard)
    # means; the full-batch equivalent is their mean over those.
    aux = aux_sum / (M * dsize * spsize)
    return ce + cfg.moe_aux_coef * aux


def gpt_loss_1f1b(params, batch, cfg, mesh, *, num_microbatches: int):
    """Pipelined loss on the 1F1B schedule (activation memory O(pp)).

    Numerically matches ``gpt_loss`` / ``gpt_loss_pipelined``; gradients
    come from the hand-scheduled backward inside ``one_f_one_b_spmd``,
    surfaced to autodiff through a custom_vjp whose residuals ARE the
    gradients.  v1 scope: dense/flash stages, dp/fsdp data sharding (use
    the GPipe path for pp x ep MoE or sp ring stages).
    """
    from ray_tpu.models.gpt import _block

    toks = batch["tokens"]
    tokens, targets = toks[:, :-1], toks[:, 1:]
    B, S = tokens.shape
    M = num_microbatches
    dsize = _check_pipeline_shapes(cfg, mesh, B, M)
    assert not (cfg.num_experts and mesh.shape.get("ep", 1) > 1), (
        "1F1B v1 does not compose with ep; use the GPipe path")
    if cfg.attention == "auto":
        from ray_tpu.models.gpt import _flash_profitable
        cfg = type(cfg)(**{**cfg.__dict__, "attention": (
            "flash" if _flash_profitable(cfg.max_seq_len) else "dense")})
    assert cfg.attention in ("dense", "flash"), (
        "1F1B v1 supports dense/flash stages; ring/sp uses the GPipe path")
    dt = cfg.dtype

    block = functools.partial(_block, cfg, None, _attn_fn_for(cfg),
                              moe_ep_axis=None)
    loss_mb = _make_loss_mb(cfg)

    data = tuple(a for a in ("dp", "fsdp") if a in mesh.shape)
    mb_spec = P(None, data, None, None)
    all_axes = tuple(mesh.axis_names)
    non_pp = tuple(a for a in all_axes if a != "pp")
    non_mb = tuple(a for a in all_axes if a not in data)
    layer_spec = _layer_in_specs(cfg, mesh)
    repl = float(mesh.size // (mesh.shape.get("pp", 1) * dsize))
    # Cotangents of the FINAL loss wrt each microbatch's ll / stage aux:
    # loss = -ll_total/(B*S) + coef * aux_total/(M*dsize).
    ll_cot = -1.0 / (B * S)
    aux_cot = cfg.moe_aux_coef / (M * dsize)

    def spmd(layers, head, x_mbs, tgt_mbs):
        ll, aux, gl, gh, gx = one_f_one_b_spmd(
            block, loss_mb, layers, head, x_mbs, tgt_mbs,
            ll_cot=ll_cot, aux_cot=aux_cot, remat=cfg.remat)
        def red(v, axes):
            return jax.lax.psum(v / repl, axes) if axes else v / repl
        ll = red(ll, all_axes)
        aux = red(aux, all_axes)
        gl = jax.tree.map(lambda g: red(g, non_pp), gl)
        gh = jax.tree.map(lambda g: red(g, all_axes), gh)
        # Accumulated in f32 for accuracy; the custom_vjp bwd must hand
        # back a cotangent with the PRIMAL's dtype (bf16 activations by
        # default) or jax rejects the rule.
        gx = red(gx, non_mb).astype(x_mbs.dtype)
        return ll, aux, gl, gh, gx

    core_spmd = jax.shard_map(
        spmd, mesh=mesh,
        in_specs=(layer_spec, P(), mb_spec, P(None, data, None)),
        out_specs=(P(), P(), layer_spec, P(), mb_spec), check_vma=False)

    def _loss_of(ll, aux):
        return -ll / (B * S) + cfg.moe_aux_coef * aux / (M * dsize)

    @jax.custom_vjp
    def core(layers, head, x_mbs, tgt_mbs):
        ll, aux, _, _, _ = core_spmd(layers, head, x_mbs, tgt_mbs)
        return _loss_of(ll, aux)

    def core_fwd(layers, head, x_mbs, tgt_mbs):
        ll, aux, gl, gh, gx = core_spmd(layers, head, x_mbs, tgt_mbs)
        return _loss_of(ll, aux), (gl, gh, gx, tgt_mbs.shape)

    def core_bwd(res, g):
        import numpy as np
        gl, gh, gx, tgt_shape = res
        scale = lambda t: jax.tree.map(lambda a: g * a, t)  # noqa: E731
        return (scale(gl), scale(gh), scale(gx),
                np.zeros(tgt_shape, jax.dtypes.float0))

    core.defvjp(core_fwd, core_bwd)

    x = params["wte"].astype(dt)[tokens] + params["wpe"].astype(dt)[:S][None]
    x_mbs = x.reshape(M, B // M, S, -1)
    tgt_mbs = targets.reshape(M, B // M, S)
    return core(params["layers"], _pipeline_head(params), x_mbs, tgt_mbs)


def make_1f1b_train_step(cfg, tx, mesh, *, num_microbatches: int,
                         donate: bool = True):
    """Jittable 1F1B train step — drop-in for make_pipeline_train_step
    with O(pp) activation memory (the dryrun reports both schedules'
    compiled temp sizes)."""
    from ray_tpu.models.gpt import make_train_step

    def loss_fn(params, batch):
        return gpt_loss_1f1b(params, batch, cfg, mesh,
                             num_microbatches=num_microbatches)

    return make_train_step(cfg, tx, donate=donate, loss_fn=loss_fn)


def make_pipeline_train_step(cfg, tx, mesh, *, num_microbatches: int,
                             donate: bool = True):
    """Jittable GPipe train step: (params, opt_state, batch) -> same + metrics.

    The reference's closest analog is torch DDP's per-bucket allreduce hook
    (`train/torch/train_loop_utils.py:70`) — here the entire fill/drain
    schedule, the fused per-microbatch loss, and gradient reduction are
    compiled into one XLA program.
    """
    from ray_tpu.models.gpt import make_train_step

    def loss_fn(params, batch):
        return gpt_loss_pipelined(params, batch, cfg, mesh,
                                  num_microbatches=num_microbatches)

    return make_train_step(cfg, tx, donate=donate, loss_fn=loss_fn)


def dryrun_pipeline(n_devices: int) -> None:
    """Driver check: three pipeline configs train a step on a virtual mesh.

    1. pp x dp dense — fused-epilogue loss matches the non-pipelined step;
    2. pp x dp FLASH attention inside the stages (Pallas interpret mode);
    3. pp x ep MoE — expert weights sharded over ep within each stage,
       aux loss preserved (vs. the GSPMD reference loss).
    """
    import numpy as np
    import optax

    from ray_tpu.models.gpt import GPTConfig, gpt_init, gpt_loss
    from ray_tpu.parallel.mesh import MeshSpec

    if n_devices % 2:
        print(f"pipeline dryrun SKIPPED (n={n_devices} odd; pp needs an "
              f"even split)")
        return

    def one(cfg, spec, tag, mbs=4):
        mesh = spec.build()
        params = gpt_init(jax.random.PRNGKey(0), cfg)
        params["layers"] = jax.device_put(
            params["layers"], jax.sharding.NamedSharding(mesh, P("pp")))
        dsize = spec.dp * spec.fsdp
        batch = {"tokens": jnp.asarray(
            np.random.RandomState(0).randint(
                0, cfg.vocab_size, (mbs * max(dsize, 1), 65)), jnp.int32)}
        ref = float(gpt_loss(params, batch, cfg))
        tx = optax.adamw(1e-3)
        step = make_pipeline_train_step(cfg, tx, mesh,
                                        num_microbatches=mbs)
        _, _, metrics = step(params, tx.init(params), batch)
        got = float(metrics["loss"])
        assert abs(got - ref) < 1e-3, (tag, got, ref)
        print(f"pipeline dryrun[{tag}]: mesh={spec.axis_sizes} M={mbs} "
              f"loss={got:.4f} (matches reference {ref:.4f})")

    dense = GPTConfig(vocab_size=256, max_seq_len=64, num_layers=4,
                      num_heads=4, embed_dim=64, dtype=jnp.float32)
    one(dense, MeshSpec(dp=n_devices // 2, pp=2), "dense pp x dp")

    flash = GPTConfig(vocab_size=256, max_seq_len=64, num_layers=4,
                      num_heads=4, embed_dim=64, dtype=jnp.float32,
                      attention="flash")
    one(flash, MeshSpec(dp=n_devices // 2, pp=2), "flash pp x dp")

    if n_devices % 4 == 0:
        moe = GPTConfig(vocab_size=256, max_seq_len=64, num_layers=4,
                        num_heads=4, embed_dim=64, dtype=jnp.float32,
                        num_experts=4, expert_top_k=2)
        one(moe, MeshSpec(dp=n_devices // 4, pp=2, ep=2), "moe pp x ep")
    else:
        print("pipeline dryrun[moe pp x ep] SKIPPED (needs n % 4 == 0)")

    # 1F1B: same numerics as GPipe, O(pp) activation memory -- report the
    # measured compiled temp sizes at a microbatch count where it matters.
    spec = MeshSpec(dp=n_devices // 2, pp=2)
    mesh = spec.build()
    params = gpt_init(jax.random.PRNGKey(0), dense)
    params["layers"] = jax.device_put(
        params["layers"], jax.sharding.NamedSharding(mesh, P("pp")))
    M = 16
    batch = {"tokens": jnp.asarray(
        np.random.RandomState(0).randint(
            0, dense.vocab_size, (M * max(spec.dp, 1), 65)), jnp.int32)}
    ref = float(gpt_loss(params, batch, dense))
    tx = optax.adamw(1e-3)
    step_1f1b = make_1f1b_train_step(dense, tx, mesh, num_microbatches=M,
                                     donate=False)
    opt = tx.init(params)
    _, _, metrics = jax.jit(step_1f1b)(params, opt, batch)
    got = float(metrics["loss"])
    assert abs(got - ref) < 1e-3, ("1f1b", got, ref)
    try:
        mem_1f1b = jax.jit(step_1f1b).lower(params, opt, batch) \
            .compile().memory_analysis().temp_size_in_bytes
        step_gp = make_pipeline_train_step(dense, tx, mesh,
                                           num_microbatches=M, donate=False)
        mem_gp = jax.jit(step_gp).lower(params, opt, batch) \
            .compile().memory_analysis().temp_size_in_bytes
        print(f"pipeline dryrun[1f1b pp x dp]: M={M} loss={got:.4f} "
              f"(matches reference {ref:.4f}); activation temp "
              f"{mem_1f1b / 1e6:.1f}MB vs gpipe {mem_gp / 1e6:.1f}MB "
              f"({mem_gp / max(mem_1f1b, 1):.1f}x less)")
    except Exception:   # memory_analysis availability is backend-dependent
        print(f"pipeline dryrun[1f1b pp x dp]: M={M} loss={got:.4f} "
              f"(matches reference {ref:.4f})")
