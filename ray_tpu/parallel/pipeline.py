"""Real pipeline parallelism: microbatched GPipe schedule over the ``pp`` axis.

The reference has no pipeline parallelism at all (SURVEY §2.4 — its scaling
story is DDP/FSDP only); this is new capability, built the TPU way rather
than as host-level stage actors: the whole pipeline is ONE SPMD program.
``shard_map`` places one stage per device along the ``pp`` mesh axis, layer
weights are sharded on their stacked ``[L]`` dim, and microbatch activations
flow stage-to-stage with ``lax.ppermute`` over ICI.  The schedule is a
``lax.scan`` over ``num_microbatches + pp - 1`` ticks, which keeps it
reverse-mode differentiable — autodiff through the scan + ppermute yields the
backward pipeline (activations replay in reverse, gradient traffic rides the
inverse permutation), so one forward definition gives the full GPipe
fill/steady/drain schedule for training with no hand-written backward pass.

Bubble fraction is the usual (pp-1)/(M+pp-1); raise ``num_microbatches`` to
amortize.  Weight grads for each stage stay device-local (the transpose of a
sharded-in param is a sharded-out grad), so the only cross-stage traffic is
the [mb, S, D] activation/grad hop per tick — exactly the wire pattern of a
1F1B/GPipe implementation, but emitted by XLA.
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Dict, Optional

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P


def gpipe_spmd(block_fn: Callable, local_params, x_mbs, *,
               axis_name: str = "pp", remat: bool = True):
    """Per-device GPipe loop (call inside ``shard_map`` over ``axis_name``).

    block_fn:      (x, layer_params) -> x, one transformer block.
    local_params:  this stage's stacked params, leading dim [L/pp].
    x_mbs:         [M, mb, ...] microbatched activations (valid on stage 0;
                   other stages' values are ignored).
    Returns [M, mb, ...] outputs, replicated across the pp axis.
    """
    pp = jax.lax.psum(1, axis_name)
    idx = jax.lax.axis_index(axis_name)
    M = x_mbs.shape[0]
    T = M + pp - 1
    shift = [(i, (i + 1) % pp) for i in range(pp)]

    body = jax.checkpoint(block_fn) if remat else block_fn

    def apply_stage(x):
        def scan_body(c, lp):
            return body(c, lp), None
        y, _ = jax.lax.scan(scan_body, x, local_params)
        return y

    def tick(carry, t):
        state, out = carry
        # Fill: stage 0 ingests microbatch t (clamped once the pipe drains).
        inp = jax.lax.dynamic_index_in_dim(
            x_mbs, jnp.clip(t, 0, M - 1), 0, keepdims=False)
        state = jnp.where(idx == 0, inp, state)
        y = apply_stage(state)
        # Drain: the last stage emits microbatch t-(pp-1) once it's real.
        m = t - (pp - 1)
        write = (idx == pp - 1) & (m >= 0)
        out = jnp.where(
            write,
            jax.lax.dynamic_update_index_in_dim(
                out, y, jnp.clip(m, 0, M - 1), 0),
            out)
        state = jax.lax.ppermute(y, axis_name, shift)
        return (state, out), None

    init = (jnp.zeros_like(x_mbs[0]), jnp.zeros_like(x_mbs))
    (_, out), _ = jax.lax.scan(tick, init, jnp.arange(T))
    # Non-final stages never wrote, so their buffers are zero: a psum both
    # combines and replicates the result across the pp ring in one collective.
    return jax.lax.psum(out, axis_name)


# ------------------------------------------------------- GPT integration

def gpt_forward_pipelined(params: Dict[str, Any], tokens, cfg, mesh, *,
                          num_microbatches: int):
    """GPT forward with the block stack pipelined over the ``pp`` mesh axis.

    Embedding and LM head run outside the pipeline (replicated over pp);
    the scanned [L] layer dim is split into pp contiguous stages.  Within
    the pipeline the batch dim stays sharded over the data axes, so pp and
    dp/fsdp compose; tp/sp inside a pipelined block is future work.
    """
    from ray_tpu.models.gpt import _block, _dense_causal_attention

    assert cfg.attention == "dense", (
        f"pipelined forward only supports dense attention for now, got "
        f"{cfg.attention!r} (ring/flash inside a pipeline stage is future "
        f"work — use a pp=1 mesh with sp/tp for long sequences)")
    assert not cfg.num_experts, (
        "MoE inside a pipeline stage is not supported yet (the load-balance "
        "aux loss would be silently dropped) — use ep on a pp=1 mesh")
    pp = mesh.shape.get("pp", 1)
    assert cfg.num_layers % pp == 0, (
        f"num_layers {cfg.num_layers} not divisible by pp={pp}")
    dt = cfg.dtype
    B, S = tokens.shape
    M = num_microbatches
    assert B % M == 0, f"batch {B} not divisible by microbatches {M}"
    dsize = mesh.shape.get("dp", 1) * mesh.shape.get("fsdp", 1)
    assert (B // M) % dsize == 0, (
        f"microbatch size {B // M} not divisible by data-axis size {dsize}")

    x = params["wte"].astype(dt)[tokens] + params["wpe"].astype(dt)[:S][None]
    x_mbs = x.reshape(M, B // M, S, -1)

    raw_block = functools.partial(_block, cfg, None, _dense_causal_attention)
    block = lambda x, lp: raw_block(x, lp)[0]  # noqa: E731  (drop dense aux=0)
    data = tuple(a for a in ("dp", "fsdp") if a in mesh.shape)
    mb_spec = P(None, data, None, None)
    piped = jax.shard_map(
        functools.partial(gpipe_spmd, block, remat=cfg.remat),
        mesh=mesh, in_specs=(P("pp"), mb_spec), out_specs=mb_spec,
        check_vma=False)
    y = piped(params["layers"], x_mbs)

    from ray_tpu.models.gpt import _layer_norm
    y = y.reshape(B, S, -1)
    y = _layer_norm(y, params["ln_f"]["scale"], params["ln_f"]["bias"])
    logits = jnp.einsum("bsd,vd->bsv", y, params["wte"].astype(dt))
    return logits.astype(jnp.float32)


def _pipelined_forward_fn(cfg, mesh, num_microbatches):
    return functools.partial(gpt_forward_pipelined, cfg=cfg, mesh=mesh,
                             num_microbatches=num_microbatches)


def gpt_loss_pipelined(params, batch, cfg, mesh, *, num_microbatches):
    from ray_tpu.models.gpt import gpt_loss
    fwd = _pipelined_forward_fn(cfg, mesh, num_microbatches)
    return gpt_loss(params, batch, cfg, forward_fn=fwd)


def make_pipeline_train_step(cfg, tx, mesh, *, num_microbatches: int,
                             donate: bool = True):
    """Jittable GPipe train step: (params, opt_state, batch) -> same + metrics.

    The reference's closest analog is torch DDP's per-bucket allreduce hook
    (`train/torch/train_loop_utils.py:70`) — here the entire fill/1F1B-like
    drain schedule plus gradient reduction is compiled into one XLA program.
    Delegates to the model's `make_train_step` with the pipelined forward so
    optimizer/metric changes stay in one place.
    """
    from ray_tpu.models.gpt import make_train_step
    fwd = _pipelined_forward_fn(cfg, mesh, num_microbatches)
    return make_train_step(cfg, tx, donate=donate, forward_fn=fwd)


def dryrun_pipeline(n_devices: int) -> None:
    """Driver check: pp=2 microbatched pipeline trains one step on a virtual
    mesh and its loss matches the non-pipelined step to fp32 tolerance."""
    import numpy as np
    import optax

    from ray_tpu.models.gpt import GPTConfig, gpt_init, gpt_loss
    from ray_tpu.parallel.mesh import MeshSpec

    if n_devices % 2:
        print(f"pipeline dryrun SKIPPED (n={n_devices} odd; pp needs an "
              f"even split)")
        return
    spec = MeshSpec(dp=n_devices // 2, pp=2)
    mesh = spec.build()
    cfg = GPTConfig(vocab_size=256, max_seq_len=64, num_layers=4,
                    num_heads=4, embed_dim=64, dtype=jnp.float32)
    params = gpt_init(jax.random.PRNGKey(0), cfg)
    # Stage-shard the stacked layer weights; everything else replicated.
    params["layers"] = jax.device_put(
        params["layers"], jax.sharding.NamedSharding(mesh, P("pp")))
    # microbatch size must divide over dp: B = M * dp
    batch = {"tokens": jnp.asarray(
        np.random.RandomState(0).randint(0, 256, (4 * spec.dp, 65)),
        jnp.int32)}

    ref = float(gpt_loss(params, batch, cfg))
    tx = optax.adamw(1e-3)
    step = make_pipeline_train_step(cfg, tx, mesh, num_microbatches=4)
    _, _, metrics = step(params, tx.init(params), batch)
    got = float(metrics["loss"])
    assert abs(got - ref) < 1e-4, (got, ref)
    print(f"pipeline dryrun: pp=2 x dp={n_devices // 2} GPipe "
          f"M=4 loss={got:.4f} (matches dense {ref:.4f})")
