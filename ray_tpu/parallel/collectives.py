"""Thin functional collectives for use inside shard_map-ped code.

Reference analogue: `ray.util.collective` op surface (allreduce/allgather/
reducescatter/broadcast/send/recv/barrier, `util/collective/collective.py:
258-615`).  There the ops are runtime NCCL calls between actor processes; here
they are `jax.lax` primitives that XLA lowers to ICI collectives inside a
compiled program.  The host-driven, actor-to-actor veneer with the reference's
exact API shape lives in `ray_tpu.util.collective`.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax


def all_reduce(x, axis_name: str, op: str = "sum"):
    if op == "sum":
        return lax.psum(x, axis_name)
    if op == "mean":
        return lax.pmean(x, axis_name)
    if op == "max":
        return lax.pmax(x, axis_name)
    if op == "min":
        return lax.pmin(x, axis_name)
    raise ValueError(f"unknown reduce op {op!r}")


def all_gather(x, axis_name: str, axis: int = 0, tiled: bool = True):
    return lax.all_gather(x, axis_name, axis=axis, tiled=tiled)


def psum_scatter(x, axis_name: str, scatter_dimension: int = 0):
    """Reduce-scatter: the building block of efficient DP gradient sync."""
    return lax.psum_scatter(
        x, axis_name, scatter_dimension=scatter_dimension, tiled=True)


def all_to_all(x, axis_name: str, split_axis: int, concat_axis: int):
    """Ulysses-style head<->sequence reshuffle, MoE token dispatch."""
    return lax.all_to_all(
        x, axis_name, split_axis=split_axis, concat_axis=concat_axis,
        tiled=True)


def ppermute_ring(x, axis_name: str, shift: int = 1):
    """Rotate shards around the ring — the ring-attention KV step."""
    n = lax.psum(1, axis_name)
    perm = [(i, (i + shift) % n) for i in range(n)]
    return lax.ppermute(x, axis_name, perm=perm)


def barrier_sum(axis_name: str):
    """Cheapest full-axis synchronization inside a program."""
    return lax.psum(jnp.zeros((), jnp.int32), axis_name)
