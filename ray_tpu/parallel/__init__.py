"""ray_tpu.parallel: mesh construction, sharding rules, and collectives.

This package is the TPU-native replacement for the reference's entire
communication/parallelism stack (`ray.util.collective` NCCL groups,
`util/collective/collective.py:120-615`; torch DDP/FSDP wrapping,
`train/torch/train_loop_utils.py:24-74`).  On TPU, parallelism is not a
runtime library but a *compilation strategy*: you pick a `jax.sharding.Mesh`
over the slice, annotate array shardings, and XLA emits the ICI collectives
inside the step function.  The classes here make that recipe declarative:

    spec = MeshSpec(dp=2, fsdp=2, tp=2)        # 8 chips
    mesh = spec.build()
    rules = LogicalAxisRules.for_transformer(spec)
    train_step = jit_with_shardings(step_fn, mesh, rules, ...)

Axes (any may be 1 / absent):
    dp    data parallel           — batch sharding, gradient psum
    fsdp  fully-sharded DP (ZeRO) — batch + parameter sharding on one axis
    tp    tensor parallel         — hidden/heads sharding (Megatron layout)
    pp    pipeline parallel       — layer-stage sharding via shard_map loop
    sp    sequence/context        — sequence-axis sharding (ring attention)
    ep    expert parallel         — MoE expert sharding, all-to-all dispatch
"""

from ray_tpu.parallel.mesh import (  # noqa: F401
    MeshSpec,
    make_mesh,
    mesh_shape_for_devices,
)
from ray_tpu.parallel.sharding import (  # noqa: F401
    LogicalAxisRules,
    init_sharded,
    logical_sharding,
    shard_params,
    with_logical_constraint,
)
from ray_tpu.parallel.multislice import (  # noqa: F401
    assert_slice_aligned,
    dcn_axes,
    ici_axes,
    slice_mesh,
)
from ray_tpu.parallel.collectives import (  # noqa: F401
    all_gather,
    all_reduce,
    all_to_all,
    barrier_sum,
    ppermute_ring,
    psum_scatter,
)
