"""Multi-slice meshes: dp over DCN, tp/sp/pp/ep/fsdp inside each slice.

Design analog: the reference scales past one machine by layering DDP over
NCCL rings per node (``train/torch/config.py`` + NCCL groups); the TPU
equivalent is a multi-controller JAX program (one process per host/slice,
``jax.distributed.initialize``) with a single global Mesh whose OUTERMOST
axis crosses slice boundaries.  ICI only exists within a slice, so the
axis layout is a correctness-of-performance contract:

  * dp (gradient allreduce, latency-tolerant, once per step) -> DCN
  * fsdp/pp/ep/sp/tp (per-layer gathers/exchanges)            -> ICI

``slice_mesh`` builds that mesh: devices are grouped process-major, the dp
axis enumerates (slice, dp_per_slice) with slice as the outer factor, and
every inner-axis neighborhood stays inside one slice.  This is the "How to
Scale Your Model" recipe (dp across pods, model axes within) expressed as
one helper.  ``assert_slice_aligned`` verifies the invariant against the
actual device.process_index values.
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import numpy as np

from ray_tpu.parallel.mesh import AXIS_ORDER, MeshSpec


def slice_mesh(*, num_slices: Optional[int] = None, dp_per_slice: int = 1,
               fsdp: Optional[int] = None, pp: int = 1, ep: int = 1,
               sp: int = 1, tp: int = 1,
               devices: Optional[Sequence] = None
               ) -> Tuple["jax.sharding.Mesh", MeshSpec]:
    """Build a slice-aligned global mesh; returns (mesh, spec).

    num_slices defaults to ``jax.process_count()`` (one controller process
    per slice).  fsdp=None auto-fills the per-slice residual.  The returned
    spec has ``dp = num_slices * dp_per_slice`` — LogicalAxisRules built
    for it apply unchanged, so the same model/trainer code runs single- or
    multi-slice.
    """
    import jax
    from jax.sharding import Mesh

    if num_slices is None:
        num_slices = jax.process_count()
    if devices is None:
        devices = sorted(jax.devices(),
                         key=lambda d: (d.process_index, d.id))
    n = len(devices)
    if n % num_slices:
        raise ValueError(f"{n} devices not divisible into {num_slices} "
                         f"slices")
    per_slice = n // num_slices
    inner_used = dp_per_slice * pp * ep * sp * tp
    if per_slice % inner_used:
        raise ValueError(
            f"slice size {per_slice} not divisible by "
            f"dp_per_slice*pp*ep*sp*tp={inner_used}")
    resid = per_slice // inner_used
    if fsdp is None:
        fsdp = resid
    elif fsdp != resid:
        raise ValueError(f"fsdp={fsdp} but per-slice residual is {resid}")

    spec = MeshSpec(dp=num_slices * dp_per_slice, fsdp=fsdp, pp=pp, ep=ep,
                    sp=sp, tp=tp)
    # Group process-major, shard the inner axes within each slice, then
    # fold (slice, dp_per_slice) into the single global dp axis.
    inner_shape = (dp_per_slice, fsdp, pp, ep, sp, tp)
    arr = np.empty((num_slices,) + inner_shape, dtype=object)
    for s in range(num_slices):
        chunk = devices[s * per_slice:(s + 1) * per_slice]
        arr[s] = np.asarray(chunk, dtype=object).reshape(inner_shape)
    arr = arr.reshape((num_slices * dp_per_slice,) + inner_shape[1:])
    return Mesh(arr, axis_names=AXIS_ORDER), spec


def assert_slice_aligned(mesh, num_slices: Optional[int] = None) -> None:
    """Verify no inner-axis neighborhood crosses a slice (process) boundary.

    For each dp-outer index (slice), all devices in the sub-mesh must
    report the same ``process_index`` — i.e. collectives on fsdp/pp/ep/
    sp/tp ride ICI, and only dp traffic crosses DCN.  No-op for
    single-process meshes (virtual slicing can't be checked there).
    """
    import jax

    if num_slices is None:
        num_slices = jax.process_count()
    if num_slices <= 1:
        return
    dp = mesh.devices.shape[0]
    if dp % num_slices:
        raise AssertionError(
            f"dp axis {dp} not divisible by num_slices {num_slices}")
    per = dp // num_slices
    for s in range(num_slices):
        sub = mesh.devices[s * per:(s + 1) * per]
        procs = {d.process_index for d in sub.flat}
        if len(procs) != 1:
            raise AssertionError(
                f"slice {s} spans processes {sorted(procs)}: inner axes "
                f"would put per-layer collectives on DCN")


def dcn_axes() -> Tuple[str, ...]:
    """Mesh axes whose collectives cross DCN in a slice_mesh layout."""
    return ("dp",)


def ici_axes() -> Tuple[str, ...]:
    return tuple(a for a in AXIS_ORDER if a != "dp")
