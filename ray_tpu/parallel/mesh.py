"""Mesh construction over TPU slices.

Reference analogue: NCCL communicator setup (`util/collective/collective_group/
nccl_collective_group.py:127`) and torch process-group init (`train/torch/
config.py:69-113`).  On TPU neither exists: the `jax.sharding.Mesh` *is* the
communicator, and XLA compiles the collectives.  The only real design work is
axis ordering — axes that carry the most traffic (tp, sp) must map to the
fastest ICI dimension, while dp/pp can ride the slower outer dimensions or
DCN.  `MeshSpec` encodes that ordering convention once.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

# Innermost-first: highest-bandwidth-need axes placed on contiguous devices.
# mesh_utils.create_device_mesh puts the *last* mesh dims on nearest neighbors,
# so we order axes slowest-traffic-first.
AXIS_ORDER: Tuple[str, ...] = ("dp", "fsdp", "pp", "ep", "sp", "tp")


@dataclasses.dataclass(frozen=True)
class MeshSpec:
    """Declarative mesh: sizes for each standard parallelism axis.

    Sizes of 1 are kept in the mesh (zero cost, lets sharding rules be
    written once regardless of which axes are active).
    """

    dp: int = 1
    fsdp: int = 1
    pp: int = 1
    ep: int = 1
    sp: int = 1
    tp: int = 1

    @property
    def axis_sizes(self) -> Dict[str, int]:
        return {a: getattr(self, a) for a in AXIS_ORDER}

    @property
    def num_devices(self) -> int:
        return math.prod(self.axis_sizes.values())

    @property
    def data_axes(self) -> Tuple[str, ...]:
        """Axes over which the global batch is sharded."""
        return ("dp", "fsdp")

    @property
    def batch_shard_size(self) -> int:
        return self.dp * self.fsdp

    def build(self, devices: Optional[Sequence] = None) -> "jax.sharding.Mesh":
        import jax
        from jax.sharding import Mesh

        if devices is None:
            devices = jax.devices()
        n = self.num_devices
        if len(devices) < n:
            raise ValueError(
                f"MeshSpec needs {n} devices, only {len(devices)} available")
        devices = list(devices)[:n]
        shape = tuple(self.axis_sizes.values())
        try:
            from jax.experimental import mesh_utils
            dev_array = mesh_utils.create_device_mesh(
                shape, devices=devices)
        except Exception:
            dev_array = np.asarray(devices).reshape(shape)
        return Mesh(dev_array, axis_names=tuple(self.axis_sizes.keys()))

    @staticmethod
    def for_devices(n: int, *, tp: int = 1, pp: int = 1, sp: int = 1,
                    ep: int = 1, fsdp: Optional[int] = None) -> "MeshSpec":
        """Fill the remaining device budget with data parallelism."""
        used = tp * pp * sp * ep
        if n % used:
            raise ValueError(f"{n} devices not divisible by tp*pp*sp*ep={used}")
        rest = n // used
        if fsdp is None:
            fsdp, dp = rest, 1
        else:
            if rest % fsdp:
                raise ValueError(f"residual {rest} not divisible by fsdp={fsdp}")
            dp = rest // fsdp
        return MeshSpec(dp=dp, fsdp=fsdp, pp=pp, ep=ep, sp=sp, tp=tp)


def mesh_shape_for_devices(n: int) -> Tuple[int, ...]:
    """Near-square 2D factorization of n (helper for ad-hoc meshes)."""
    a = int(math.sqrt(n))
    while n % a:
        a -= 1
    return (n // a, a)


def make_mesh(axis_sizes: Dict[str, int],
              devices: Optional[Sequence] = None) -> "jax.sharding.Mesh":
    """Build a Mesh from an arbitrary {axis: size} dict (order preserved)."""
    import jax
    from jax.sharding import Mesh

    if devices is None:
        devices = jax.devices()
    n = math.prod(axis_sizes.values())
    dev_array = np.asarray(list(devices)[:n]).reshape(tuple(axis_sizes.values()))
    return Mesh(dev_array, axis_names=tuple(axis_sizes.keys()))
