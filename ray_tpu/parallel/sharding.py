"""Logical-axis sharding rules (GSPMD partitioning tables).

Replaces the reference's model wrapping (DDP/FSDP at `train/torch/
train_loop_utils.py:70-74`): instead of wrapping modules at runtime, arrays
carry *logical* axis names ("batch", "embed", "mlp", "heads", ...) and a rule
table maps each logical axis to zero or more mesh axes.  This is the t5x/
MaxText-style recipe and is what lets one model definition run under any
combination of dp/fsdp/tp/pp/sp/ep without code changes.
"""

from __future__ import annotations

from typing import Dict, Optional, Sequence, Tuple, Union

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

MeshAxes = Union[None, str, Tuple[str, ...]]


class LogicalAxisRules:
    """Ordered mapping logical-axis-name -> mesh axis (or axes, or None).

    The first rule whose mesh axes are still unused by the current spec wins,
    so rules act like t5x's `logical_axis_rules` priority list.
    """

    def __init__(self, rules: Sequence[Tuple[str, MeshAxes]]):
        self.rules = list(rules)

    def spec_for(self, logical_axes: Sequence[Optional[str]]) -> P:
        """PartitionSpec for an array whose dims have these logical names."""
        out = []
        used: set = set()
        for name in logical_axes:
            assignment: MeshAxes = None
            if name is not None:
                for lname, maxes in self.rules:
                    if lname != name or maxes is None:
                        continue
                    cand = (maxes,) if isinstance(maxes, str) else tuple(maxes)
                    if any(m in used for m in cand):
                        continue
                    assignment = cand if len(cand) > 1 else cand[0]
                    used.update(cand)
                    break
            out.append(assignment)
        while out and out[-1] is None:
            out.pop()
        return P(*out)

    @staticmethod
    def for_transformer(spec=None) -> "LogicalAxisRules":
        """Standard Megatron-style layout over the MeshSpec axes.

        batch    -> (dp, fsdp)   activations' leading dim
        seq      -> sp           sequence/context parallelism
        embed    -> fsdp         ZeRO-3 weight sharding on the data axis
        heads    -> tp           attention heads (Megatron col-parallel)
        kv       -> None         head_dim stays replicated
        mlp      -> tp           FFN hidden (col-parallel in, row-parallel out)
        vocab    -> tp           embedding/LM-head vocab sharding
        expert   -> ep           MoE expert dim
        layers   -> pp           stacked-layer dim (pipeline stages)
        """
        return LogicalAxisRules([
            ("batch", ("dp", "fsdp")),
            ("seq", "sp"),
            ("embed", "fsdp"),
            ("heads", "tp"),
            ("kv", None),
            ("mlp", "tp"),
            ("vocab", "tp"),
            ("expert", "ep"),
            ("layers", "pp"),
            ("norm", None),
        ])


def logical_sharding(mesh: Mesh, rules: LogicalAxisRules,
                     logical_axes: Sequence[Optional[str]]) -> NamedSharding:
    return NamedSharding(mesh, rules.spec_for(logical_axes))


def with_logical_constraint(x, rules: LogicalAxisRules,
                            logical_axes: Sequence[Optional[str]]):
    """`lax.with_sharding_constraint` by logical names (inside jit)."""
    return jax.lax.with_sharding_constraint(
        x, rules.spec_for(logical_axes))


def init_sharded(init_fn, mesh: Mesh, rules: LogicalAxisRules, annotations,
                 *args):
    """Multi-controller-safe sharded init.

    ``device_put`` cannot span another process's devices, so on a
    multi-host mesh params must be BORN sharded: run ``init_fn`` inside
    ``jit`` with ``out_shardings`` derived from the logical annotations —
    every process traces the same program and receives its addressable
    shards of one global array per leaf.
    """
    shardings = jax.tree_util.tree_map(
        lambda ann: logical_sharding(mesh, rules, ann), annotations,
        is_leaf=lambda x: x is None or isinstance(x, tuple))
    return jax.jit(init_fn, out_shardings=shardings)(*args)


def shard_params(params, mesh: Mesh, rules: LogicalAxisRules, annotations):
    """Device-put a param pytree according to per-leaf logical annotations.

    `annotations` mirrors `params` with tuples of logical axis names
    (None entries for replicated dims).
    """
    def _place(p, ann):
        return jax.device_put(p, logical_sharding(mesh, rules, ann))

    return jax.tree_util.tree_map(
        _place, params, annotations,
        is_leaf=lambda x: not isinstance(x, dict))
