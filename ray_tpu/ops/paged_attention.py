"""Paged attention for KV-cache decode (reference: vLLM PagedAttention;
JAX analog `jax.experimental.pallas.ops.tpu.paged_attention`).

Serving many concurrent sequences from one replica needs a KV cache that
is neither per-sequence-contiguous (internal fragmentation kills batch
size) nor re-run-the-prefix (quadratic decode).  Instead K/V live in a
pool of fixed-size **pages** shared by all sequences, and each sequence
maps its positions to pages through a small **page table** — exactly
virtual memory for attention.  The layouts follow the TPU reference op:

    q                [B, N, H]           one query token per sequence
    k_pages, v_pages [NKV, P, page, H]   KV-head-major page pools
    lengths          [B] int32           valid positions per sequence
    page_table       [B, maxp] int32     page ids per sequence

KV-head-major pages make the GQA sharding trivial: shard dim 0 of the
pools and the head dim of q over the model axis (SNIPPETS [1]'s
``sharded_paged_attention``) and every chip decodes its head slice of
ALL sequences with no cross-chip traffic.

This file is the jnp reference implementation (gather + masked softmax
— the decode working set is one token per sequence, so XLA's fused
gather is adequate on CPU and fine on TPU at small batch; a Pallas
HBM-resident kernel like flash_attention.py's is the upgrade path when
pools outgrow VMEM).  It is exact: given identical page contents it
reproduces dense attention bit-for-bit in f32, which is what the
paged-vs-dense CPU equivalence tests assert.
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ray_tpu.util import jax_compat

jax_compat.install()


def paged_attention(q: jax.Array, k_pages: jax.Array, v_pages: jax.Array,
                    lengths: jax.Array, page_table: jax.Array, *,
                    sm_scale: Optional[float] = None) -> jax.Array:
    """Single-token decode attention against paged K/V.

    ``q`` [B, N, H]; ``k_pages``/``v_pages`` [NKV, P, page, H];
    ``lengths`` [B] (positions < length attend, so the current token's
    K/V must already be written at position length-1); ``page_table``
    [B, maxp].  GQA when N > NKV (N % NKV == 0).  Returns [B, N, H] in
    q's dtype; softmax runs in f32.
    """
    B, N, H = q.shape
    NKV, _P, page, _H = k_pages.shape
    if N % NKV:
        raise ValueError(f"query heads {N} not a multiple of KV heads {NKV}")
    rep = N // NKV
    scale = sm_scale if sm_scale is not None else 1.0 / np.sqrt(H)
    maxp = page_table.shape[1]
    S = maxp * page

    # Gather each sequence's pages: [NKV, B, maxp, page, H] -> [NKV, B, S, H]
    k = k_pages[:, page_table].reshape(NKV, B, S, H)
    v = v_pages[:, page_table].reshape(NKV, B, S, H)

    qg = q.reshape(B, NKV, rep, H)
    scores = jnp.einsum("bkrh,kbsh->bkrs", qg, k) * scale
    valid = jnp.arange(S)[None] < lengths[:, None]          # [B, S]
    scores = jnp.where(valid[:, None, None],
                       scores.astype(jnp.float32), -1e30)
    probs = jax.nn.softmax(scores, axis=-1).astype(q.dtype)
    out = jnp.einsum("bkrs,kbsh->bkrh", probs, v)
    return out.reshape(B, N, H)


def append_kv(k_pages: jax.Array, v_pages: jax.Array, k_new: jax.Array,
              v_new: jax.Array, pos: jax.Array, page_table: jax.Array):
    """Scatter one token's K/V per sequence into the pools.

    ``k_new``/``v_new`` [B, NKV, H]; ``pos`` [B] target positions;
    ``page_table`` [B, maxp].  Sequences route through their own pages so
    the scatter never conflicts; callers park inactive batch slots on
    page 0 (the scratch sink the allocator reserves) by handing them an
    all-zero page-table row and pos 0.
    """
    page = k_pages.shape[2]
    pid = jnp.take_along_axis(page_table, (pos // page)[:, None],
                              axis=1)[:, 0]                  # [B]
    slot = pos % page
    k_new = jnp.swapaxes(k_new, 0, 1).astype(k_pages.dtype)  # [NKV, B, H]
    v_new = jnp.swapaxes(v_new, 0, 1).astype(v_pages.dtype)
    return (k_pages.at[:, pid, slot].set(k_new),
            v_pages.at[:, pid, slot].set(v_new))


def prefill_kv(k_pages: jax.Array, v_pages: jax.Array, k_seq: jax.Array,
               v_seq: jax.Array, length: jax.Array, page_table_row):
    """Scatter a whole (padded) prompt's K/V for ONE sequence.

    ``k_seq``/``v_seq`` [NKV, S, H] with S a multiple of the page size;
    ``length`` scalar int32 true length; ``page_table_row`` [maxp].
    Positions >= length (padding) are routed to scratch page 0 so the
    sequence only dirties the pages it reserved.
    """
    page = k_pages.shape[2]
    S = k_seq.shape[1]
    pos = jnp.arange(S)
    pid = jnp.where(pos < length, page_table_row[pos // page], 0)
    slot = pos % page
    return (k_pages.at[:, pid, slot].set(k_seq.astype(k_pages.dtype)),
            v_pages.at[:, pid, slot].set(v_seq.astype(v_pages.dtype)))


def sharded_paged_attention(mesh, *, model_axis: str = "model",
                            sm_scale: Optional[float] = None
                            ) -> Callable[..., Any]:
    """GQA paged attention shard_mapped over KV heads (SNIPPETS [1]):
    q shards its head dim, the pools shard their leading KV-head dim,
    lengths/page tables replicate — per-chip decode with zero collective
    traffic (each output head needs only its own KV head group)."""
    from jax.sharding import PartitionSpec as P

    in_specs = (
        P(None, model_axis, None),         # q [B, N, H]
        P(model_axis, None, None, None),   # k_pages [NKV, P, page, H]
        P(model_axis, None, None, None),   # v_pages
        P(),                               # lengths
        P(),                               # page_table
    )
    out_specs = P(None, model_axis, None)

    def _paged(q, k_pages, v_pages, lengths, page_table):
        return paged_attention(q, k_pages, v_pages, lengths, page_table,
                               sm_scale=sm_scale)

    return jax.jit(jax.shard_map(_paged, mesh=mesh, in_specs=in_specs,
                                 out_specs=out_specs, check_vma=False))
