"""ray_tpu.ops: TPU kernels (Pallas) and sharded attention primitives.

New capability vs. the reference (SURVEY §5.7: no sequence/context
parallelism exists in Ray): flash attention as a Pallas TPU kernel, ring
attention over the `sp` mesh axis, and a Ulysses-style all-to-all
alternative.  Everything here runs on the CPU backend too (Pallas interpret
mode / plain lax), so the test suite exercises it on the virtual 8-device
mesh.
"""

from ray_tpu.ops.flash_attention import flash_attention  # noqa: F401
from ray_tpu.ops.paged_attention import (  # noqa: F401
    append_kv,
    paged_attention,
    prefill_kv,
    sharded_paged_attention,
)
from ray_tpu.ops.ring_attention import (  # noqa: F401
    ring_attention,
    ring_attention_sharded,
    ulysses_attention,
)
