"""Flash (blockwise, online-softmax) causal attention as a Pallas TPU kernel.

The reference has no fused attention of its own (it defers to torch); on TPU
the memory-bound step is reading the [S, S] score matrix from HBM, so we
never materialize it: the kernel streams K/V blocks through VMEM, keeping the
running max/denominator in f32 scratch (the FlashAttention recurrence), and
writes only the [block_q, head_dim] output tile.  Grid = (batch*heads,
q_blocks); K/V blocks iterate in the innermost grid dim so Pallas
double-buffers their HBM->VMEM DMAs automatically.

Backward pass: fwd is wrapped in `jax.custom_vjp` with a recompute-based bwd
(dense blockwise attention under `jax.checkpoint` semantics) — correct
gradients, O(S) memory off-chip.

On non-TPU backends the same kernel runs in Pallas interpret mode, keeping
CPU tests honest.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

_NEG_INF = -1e30


def _attn_kernel(q_ref, k_ref, v_ref, o_ref, *, block_k: int, causal: bool,
                 sm_scale: float, seq_len: int):
    # q_ref: [block_q, H]; k_ref/v_ref: [S, H]; o_ref: [block_q, H]
    block_q, head_dim = q_ref.shape
    qi = pl.program_id(1)
    q = q_ref[:].astype(jnp.float32) * sm_scale

    m0 = jnp.full((block_q, 1), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((block_q, 1), jnp.float32)
    o0 = jnp.zeros((block_q, head_dim), jnp.float32)

    num_kb = seq_len // block_k
    q_start = qi * block_q

    def body(kb, carry):
        m, l, o = carry
        k = k_ref[pl.ds(kb * block_k, block_k), :].astype(jnp.float32)
        v = v_ref[pl.ds(kb * block_k, block_k), :].astype(jnp.float32)
        s = jnp.dot(q, k.T, preferred_element_type=jnp.float32)
        if causal:
            rows = q_start + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            cols = kb * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(rows >= cols, s, _NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m - m_new)
        l = l * alpha + jnp.sum(p, axis=-1, keepdims=True)
        o = o * alpha + jnp.dot(p, v, preferred_element_type=jnp.float32)
        return m_new, l, o

    if causal:
        # skip key blocks entirely above the diagonal
        num_live = jax.lax.div(q_start + block_q - 1, block_k) + 1
        m, l, o = jax.lax.fori_loop(0, num_live, body, (m0, l0, o0))
    else:
        m, l, o = jax.lax.fori_loop(0, num_kb, body, (m0, l0, o0))
    o_ref[:] = (o / jnp.maximum(l, 1e-30)).astype(o_ref.dtype)


def _flash_fwd_impl(q, k, v, *, causal: bool, block_q: int, block_k: int,
                    sm_scale: Optional[float], interpret: bool):
    """q,k,v: [B, S, N, H] -> o: [B, S, N, H]."""
    B, S, N, H = q.shape
    scale = sm_scale if sm_scale is not None else 1.0 / np.sqrt(H)
    block_q = min(block_q, S)
    block_k = min(block_k, S)
    assert S % block_q == 0 and S % block_k == 0, (
        f"seq {S} must divide blocks ({block_q},{block_k})")

    # [B,S,N,H] -> [B*N, S, H]
    def _fold(x):
        return x.transpose(0, 2, 1, 3).reshape(B * N, S, H)

    qf, kf, vf = _fold(q), _fold(k), _fold(v)
    kernel = functools.partial(
        _attn_kernel, block_k=block_k, causal=causal, sm_scale=scale,
        seq_len=S)
    of = pl.pallas_call(
        kernel,
        grid=(B * N, S // block_q),
        in_specs=[
            pl.BlockSpec((None, block_q, H), lambda b, i: (b, i, 0)),
            pl.BlockSpec((None, S, H), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((None, S, H), lambda b, i: (b, 0, 0)),
        ],
        out_specs=pl.BlockSpec((None, block_q, H), lambda b, i: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct((B * N, S, H), q.dtype),
        interpret=interpret,
    )(qf, kf, vf)
    return of.reshape(B, N, S, H).transpose(0, 2, 1, 3)


def _dense_reference(q, k, v, causal, sm_scale):
    scale = sm_scale if sm_scale is not None else 1.0 / np.sqrt(q.shape[-1])
    s = jnp.einsum("bqnh,bknh->bnqk", q, k).astype(jnp.float32) * scale
    if causal:
        S = q.shape[1]
        mask = jnp.tril(jnp.ones((S, S), bool))
        s = jnp.where(mask[None, None], s, _NEG_INF)
    p = jax.nn.softmax(s, axis=-1).astype(q.dtype)
    return jnp.einsum("bnqk,bknh->bqnh", p, v)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def flash_attention(q, k, v, causal: bool = True, block_q: int = 128,
                    block_k: int = 128, sm_scale: Optional[float] = None,
                    interpret: Optional[bool] = None):
    """Fused causal attention. q,k,v: [batch, seq, heads, head_dim]."""
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    return _flash_fwd_impl(q, k, v, causal=causal, block_q=block_q,
                           block_k=block_k, sm_scale=sm_scale,
                           interpret=interpret)


def _fwd(q, k, v, causal, block_q, block_k, sm_scale, interpret):
    out = flash_attention(q, k, v, causal, block_q, block_k, sm_scale,
                          interpret)
    return out, (q, k, v)


def _bwd(causal, block_q, block_k, sm_scale, interpret, res, g):
    q, k, v = res
    _, vjp = jax.vjp(
        lambda q, k, v: _dense_reference(q, k, v, causal, sm_scale), q, k, v)
    return vjp(g)


flash_attention.defvjp(_fwd, _bwd)
