"""Flash (blockwise, online-softmax) causal attention as Pallas TPU kernels.

The reference has no fused attention of its own (it defers to torch); on TPU
the memory-bound step is reading the [S, S] score matrix from HBM, so we
never materialize it.  All three kernels use a 3-D grid — (batch*heads,
out_block, streamed_block) — with the streamed operand (K/V for the q-side
kernels, Q/dO for the k-side kernel) delivered one VMEM tile per inner grid
step, so VMEM stays O(block) no matter the sequence length; Pallas
double-buffers the inner-dim DMAs automatically.  Online-softmax /
gradient accumulators live in f32 VMEM scratch across inner steps.

Backward is the FlashAttention-2 recurrence, also in Pallas — NOT a dense
vjp.  Residuals are q, k, v, o, lse (all O(S) off-chip).  Two kernels:

  * dq kernel    — grid over q blocks; streams K/V blocks, recomputes the
    probability tile from (q, k, lse) and accumulates dq.
  * dk/dv kernel — grid over k blocks; streams Q/dO blocks, recomputes the
    probability tile and accumulates dk and dv via dim-0 contractions
    (implicitly-transposed matmuls the MXU executes natively).

lse and D ride into the kernels as [*, seq, _LANES] tiles (row value
broadcast along a narrow minor dim) so they slice as native sublane column
vectors — the same layout trick as jax.experimental.pallas.ops.tpu
.flash_attention's l/m tensors, but 8 lanes wide instead of 128.

Causal skipping: dead diagonal blocks are jumped with `pl.when`, so the
wall-clock cost of the mask is ~half the non-causal kernel, not equal to it.

On non-TPU backends the same kernels run in Pallas interpret mode, keeping
CPU tests honest.

Design analog: the reference defers attention to torch SDPA/flash-attn CUDA
kernels; this is the TPU-native replacement (SURVEY §5.7).
"""

from __future__ import annotations

import functools
import os
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_NEG_INF = -1e30
_LANES = 8     # minor-dim width of the lse/D carrier tensors
_SCR = 128     # lane width of VMEM scratch accumulators

# (backend, B, S, N, H, dtype, causal) -> (block_q, block_k); filled by
# tune_flash_blocks and consulted when callers pass block_q/block_k = None.
_TUNED: dict = {}


def _default_blocks(S: int, H: int, strict: bool = True) -> tuple:
    """Heuristic block sizes: large blocks amortize the K/V stream and the
    grid launch; 128-lane alignment keeps the MXU full.  Overridable via
    RT_FLASH_BLOCK_Q / RT_FLASH_BLOCK_K or per-call arguments."""
    # Swept on v5e (see round-3 notes): 1024x1024 wins at every S in
    # {1024..8192} — the [bq,bk] f32 probability tile (4MB) still fits VMEM
    # and larger tiles amortize the grid/DMA overhead.
    bq = int(os.environ.get("RT_FLASH_BLOCK_Q", 0)) or 1024
    bk = int(os.environ.get("RT_FLASH_BLOCK_K", 0)) or 1024
    # Halve until the block divides S.  Mosaic rejects sub-tile (<8)
    # blocks on real TPU with an opaque compile error, so fail loudly
    # here instead: sequence lengths with small odd factors must be
    # padded by the caller.
    while S % bq:
        bq //= 2
    while S % bk:
        bk //= 2
    if strict and (bq < 8 or bk < 8):
        # strict=False (interpret mode) permits sub-tile blocks: the
        # interpreter has no Mosaic tiling constraint.
        from ray_tpu.autotune.search import suggest_blocks
        S_pad, sq, sk = suggest_blocks(S)
        raise ValueError(
            f"flash_attention: sequence length {S} only admits block sizes "
            f"({bq}, {bk}) < 8, which the TPU compiler rejects. Pad the "
            f"sequence to {S_pad} and use block_q={sq}, block_k={sk} "
            f"(mask the tail), or pass explicit block_q/block_k >= 8 that "
            f"divide {S}.")
    return bq, bk


# ---------------------------------------------------------------- forward

def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, m_scr, l_scr, o_scr, *,
                causal: bool, sm_scale: float):
    # q_ref: [block_q, H]; k_ref/v_ref: [block_k, H] (streamed on grid dim 2)
    # o_ref: [block_q, H]; lse_ref: [block_q, _LANES]
    # scratch: m/l [block_q, _SCR], o [block_q, H] — all f32
    block_q, head_dim = q_ref.shape
    block_k = k_ref.shape[0]
    qi, kb = pl.program_id(1), pl.program_id(2)
    num_kb = pl.num_programs(2)
    q_start, k_start = qi * block_q, kb * block_k

    @pl.when(kb == 0)
    def _init():
        m_scr[:] = jnp.full((block_q, _SCR), _NEG_INF, jnp.float32)
        l_scr[:] = jnp.zeros((block_q, _SCR), jnp.float32)
        o_scr[:] = jnp.zeros((block_q, head_dim), jnp.float32)

    live = True if not causal else k_start <= q_start + block_q - 1

    @pl.when(live)
    def _compute():
        # matmuls run in the input dtype (bf16-native on the MXU) with f32
        # accumulation; softmax statistics stay f32.
        q = q_ref[:]
        k = k_ref[:]
        v = v_ref[:]
        s = lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32) * sm_scale
        if causal:
            rows = q_start + lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            cols = k_start + lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(rows >= cols, s, _NEG_INF)
        m = m_scr[:, 0:1]
        l = l_scr[:, 0:1]
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m - m_new)
        l_new = l * alpha + jnp.sum(p, axis=-1, keepdims=True)
        o_scr[:] = o_scr[:] * alpha + jnp.dot(
            p.astype(v.dtype), v, preferred_element_type=jnp.float32)
        m_scr[:, 0:1] = m_new
        l_scr[:, 0:1] = l_new

    @pl.when(kb == num_kb - 1)
    def _finalize():
        l = jnp.maximum(l_scr[:, 0:1], 1e-30)
        o_ref[:] = (o_scr[:] / l).astype(o_ref.dtype)
        lse = m_scr[:, 0:1] + jnp.log(l)
        lse_ref[:] = jnp.broadcast_to(lse, (block_q, _LANES))


def _layout_views(shape, layout):
    """(B, N, S, H, fold, unfold) for a q-shape under the given layout —
    the ONE place the fwd and bwd impls get their layout handling from."""
    if layout == "bnsh":
        B, N, S, H = shape

        def fold(x):
            return x.reshape(B * N, S, H)

        def unfold(x):
            return x.reshape(B, N, S, H)
    else:
        B, S, N, H = shape

        def fold(x):
            return x.transpose(0, 2, 1, 3).reshape(B * N, S, H)

        def unfold(x):
            return x.reshape(B, N, S, H).transpose(0, 2, 1, 3)
    return B, N, S, H, fold, unfold


def _flash_fwd_impl(q, k, v, *, causal: bool, block_q: int, block_k: int,
                    sm_scale: Optional[float], interpret: bool,
                    layout: str = "bsnh"):
    """layout "bsnh": q,k,v [B, S, N, H] (folding costs a transpose).
    layout "bnsh": q,k,v [B, N, S, H] — folding to the kernel's
    [B*N, S, H] view is a FREE reshape; models that keep attention in
    bnsh (the GPT block does) skip ~25% of attention wall-clock that
    the bsnh relayouts cost at bench scale.
    Returns (o in the input layout, lse [B*N, S] f32)."""
    B, N, S, H, _fold, _unfold = _layout_views(q.shape, layout)
    scale = sm_scale if sm_scale is not None else 1.0 / np.sqrt(H)
    block_q = min(block_q, S)
    block_k = min(block_k, S)
    assert S % block_q == 0 and S % block_k == 0, (
        f"seq {S} must divide blocks ({block_q},{block_k})")

    qf, kf, vf = _fold(q), _fold(k), _fold(v)
    kernel = functools.partial(_fwd_kernel, causal=causal, sm_scale=scale)
    of, lse = pl.pallas_call(
        kernel,
        grid=(B * N, S // block_q, S // block_k),
        in_specs=[
            pl.BlockSpec((None, block_q, H), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((None, block_k, H), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((None, block_k, H), lambda b, i, j: (b, j, 0)),
        ],
        out_specs=[
            pl.BlockSpec((None, block_q, H), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((None, block_q, _LANES), lambda b, i, j: (b, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B * N, S, H), q.dtype),
            jax.ShapeDtypeStruct((B * N, S, _LANES), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, _SCR), jnp.float32),
            pltpu.VMEM((block_q, _SCR), jnp.float32),
            pltpu.VMEM((block_q, H), jnp.float32),
        ],
        interpret=interpret,
    )(qf, kf, vf)
    return _unfold(of), lse[:, :, 0]


# ---------------------------------------------------------------- backward
#
# FlashAttention-2 recurrence.  With P = exp(S*scale - lse) the true softmax
# probabilities and D_i = sum_h dO_ih * O_ih:
#   dV = P^T dO;   dP = dO V^T;   dS = P * (dP - D) * scale
#   dQ = dS K;     dK = dS^T Q

def _dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref,
               dq_scr, *, causal: bool, sm_scale: float):
    # q_ref/do_ref/dq_ref: [block_q, H]; k_ref/v_ref: [block_k, H] (streamed);
    # lse_ref/delta_ref: [block_q, _LANES]; dq_scr: [block_q, H] f32
    block_q, head_dim = q_ref.shape
    block_k = k_ref.shape[0]
    qi, kb = pl.program_id(1), pl.program_id(2)
    num_kb = pl.num_programs(2)
    q_start, k_start = qi * block_q, kb * block_k

    @pl.when(kb == 0)
    def _init():
        dq_scr[:] = jnp.zeros((block_q, head_dim), jnp.float32)

    live = True if not causal else k_start <= q_start + block_q - 1

    @pl.when(live)
    def _compute():
        q = q_ref[:]
        do = do_ref[:]
        lse = lse_ref[:, 0:1]
        delta = delta_ref[:, 0:1]
        k = k_ref[:]
        v = v_ref[:]
        s = lax.dot_general(                       # q @ k^T
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * sm_scale
        if causal:
            rows = q_start + lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            cols = k_start + lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(rows >= cols, s, _NEG_INF)
        p = jnp.exp(s - lse)                       # [bq, bk]
        dp = lax.dot_general(                      # do @ v^T
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        ds = (p * (dp - delta) * sm_scale).astype(k.dtype)
        dq_scr[:] = dq_scr[:] + jnp.dot(
            ds, k, preferred_element_type=jnp.float32)

    @pl.when(kb == num_kb - 1)
    def _finalize():
        dq_ref[:] = dq_scr[:].astype(dq_ref.dtype)


def _dkv_kernel(k_ref, v_ref, q_ref, do_ref, lse_ref, delta_ref, dk_ref,
                dv_ref, dk_scr, dv_scr, *, causal: bool, sm_scale: float):
    # k_ref/v_ref/dk_ref/dv_ref: [block_k, H]; q_ref/do_ref: [block_q, H]
    # (streamed); lse_ref/delta_ref: [block_q, _LANES]
    block_k, head_dim = k_ref.shape
    block_q = q_ref.shape[0]
    ki, jb = pl.program_id(1), pl.program_id(2)
    num_qb = pl.num_programs(2)
    k_start, q_start = ki * block_k, jb * block_q

    @pl.when(jb == 0)
    def _init():
        dk_scr[:] = jnp.zeros((block_k, head_dim), jnp.float32)
        dv_scr[:] = jnp.zeros((block_k, head_dim), jnp.float32)

    live = True if not causal else q_start + block_q - 1 >= k_start

    @pl.when(live)
    def _compute():
        k = k_ref[:]
        v = v_ref[:]
        q = q_ref[:]
        do = do_ref[:]
        lse = lse_ref[:, 0:1]
        delta = delta_ref[:, 0:1]
        s = lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * sm_scale
        if causal:
            rows = q_start + lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            cols = k_start + lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(rows >= cols, s, _NEG_INF)
        p = jnp.exp(s - lse)                       # [bq, bk]
        # dv += p^T @ do   (contract dim 0 of both: implicit transpose)
        dv_scr[:] = dv_scr[:] + lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dp = lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        ds = (p * (dp - delta) * sm_scale).astype(q.dtype)
        dk_scr[:] = dk_scr[:] + lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(jb == num_qb - 1)
    def _finalize():
        dk_ref[:] = dk_scr[:].astype(dk_ref.dtype)
        dv_ref[:] = dv_scr[:].astype(dv_ref.dtype)


def _flash_bwd_impl(q, k, v, o, lse, g, *, causal: bool, block_q: int,
                    block_k: int, sm_scale: Optional[float],
                    interpret: bool, layout: str = "bsnh"):
    B, N, S, H, _fold, _unfold = _layout_views(q.shape, layout)
    scale = sm_scale if sm_scale is not None else 1.0 / np.sqrt(H)
    block_q = min(block_q, S)
    block_k = min(block_k, S)
    assert S % block_q == 0 and S % block_k == 0, (
        f"seq {S} must divide blocks ({block_q},{block_k})")
    qf, kf, vf, dof = _fold(q), _fold(k), _fold(v), _fold(g)
    # D_i = sum_h dO_ih O_ih — cheap elementwise reduce, leave it to XLA.
    delta = jnp.sum(dof.astype(jnp.float32) *
                    _fold(o).astype(jnp.float32), axis=-1)      # [B*N, S]
    lse_l = jnp.broadcast_to(lse[:, :, None], (B * N, S, _LANES))
    delta_l = jnp.broadcast_to(delta[:, :, None], (B * N, S, _LANES))

    dq_kernel = functools.partial(_dq_kernel, causal=causal, sm_scale=scale)
    dqf = pl.pallas_call(
        dq_kernel,
        grid=(B * N, S // block_q, S // block_k),
        in_specs=[
            pl.BlockSpec((None, block_q, H), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((None, block_k, H), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((None, block_k, H), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((None, block_q, H), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((None, block_q, _LANES), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((None, block_q, _LANES), lambda b, i, j: (b, i, 0)),
        ],
        out_specs=pl.BlockSpec((None, block_q, H), lambda b, i, j: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct((B * N, S, H), q.dtype),
        scratch_shapes=[pltpu.VMEM((block_q, H), jnp.float32)],
        interpret=interpret,
    )(qf, kf, vf, dof, lse_l, delta_l)

    dkv_kernel = functools.partial(_dkv_kernel, causal=causal, sm_scale=scale)
    dkf, dvf = pl.pallas_call(
        dkv_kernel,
        grid=(B * N, S // block_k, S // block_q),
        in_specs=[
            pl.BlockSpec((None, block_k, H), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((None, block_k, H), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((None, block_q, H), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((None, block_q, H), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((None, block_q, _LANES), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((None, block_q, _LANES), lambda b, i, j: (b, j, 0)),
        ],
        out_specs=[
            pl.BlockSpec((None, block_k, H), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((None, block_k, H), lambda b, i, j: (b, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B * N, S, H), q.dtype),
            jax.ShapeDtypeStruct((B * N, S, H), q.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_k, H), jnp.float32),
            pltpu.VMEM((block_k, H), jnp.float32),
        ],
        interpret=interpret,
    )(kf, vf, qf, dof, lse_l, delta_l)

    return _unfold(dqf), _unfold(dkf), _unfold(dvf)


# ---------------------------------------------------------------- public API

def _dense_reference(q, k, v, causal, sm_scale):
    scale = sm_scale if sm_scale is not None else 1.0 / np.sqrt(q.shape[-1])
    s = jnp.einsum("bqnh,bknh->bnqk", q, k).astype(jnp.float32) * scale
    if causal:
        S = q.shape[1]
        mask = jnp.tril(jnp.ones((S, S), bool))
        s = jnp.where(mask[None, None], s, _NEG_INF)
    p = jax.nn.softmax(s, axis=-1).astype(q.dtype)
    return jnp.einsum("bnqk,bknh->bqnh", p, v)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7, 8))
def flash_attention(q, k, v, causal: bool = True,
                    block_q: Optional[int] = None,
                    block_k: Optional[int] = None,
                    sm_scale: Optional[float] = None,
                    interpret: Optional[bool] = None,
                    layout: str = "bsnh"):
    """Fused causal attention.

    layout "bsnh" (default): q,k,v [batch, seq, heads, head_dim].
    layout "bnsh": q,k,v [batch, heads, seq, head_dim] — the kernels'
    native view; models that produce attention inputs head-major skip
    the fold transposes entirely (~25% of attention time at short seq).
    block_q/block_k default to a per-shape heuristic (see _default_blocks)
    and honor any entry recorded by `tune_flash_blocks`.
    """
    out, _ = _fwd(q, k, v, causal, block_q, block_k, sm_scale, interpret,
                  layout)
    return out


# Shape keys whose autotune-cache consultation already happened (and was
# counted): repeat _resolve calls for the same shape skip the counters so
# the hot path doesn't inflate hit counts per kernel invocation.
_CACHE_CONSULTED: set = set()


def _cached_blocks(B, S, N, H, dtype, causal):
    """Best (block_q, block_k) from the persistent autotune cache, or
    None.  Never raises into the kernel call path."""
    try:
        from ray_tpu.autotune.cache import attention_key, get_cache
        key = attention_key(B, S, N, H, dtype, causal)
        first = key not in _CACHE_CONSULTED
        if first:
            _CACHE_CONSULTED.add(key)
        rec = get_cache().lookup("flash_attention", key, count=first)
        if rec:
            cfg = rec.get("config") or {}
            bq, bk = cfg.get("block_q"), cfg.get("block_k")
            if bq and bk and S % int(bq) == 0 and S % int(bk) == 0:
                return int(bq), int(bk)
    except Exception:
        pass
    return None


def _resolve(q, causal, block_q, block_k, interpret, layout):
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    if block_q is None or block_k is None:
        if layout == "bnsh":
            B, N, S, H = q.shape
        else:
            B, S, N, H = q.shape
        key = (jax.default_backend(), B, S, N, H, str(q.dtype), causal)
        bqbk = (_TUNED.get(key)
                or _cached_blocks(B, S, N, H, q.dtype, causal)
                or _default_blocks(S, H, strict=not interpret))
        bq, bk = bqbk
        block_q = block_q or bq
        block_k = block_k or bk
    return block_q, block_k, interpret


def _fwd(q, k, v, causal, block_q, block_k, sm_scale, interpret,
         layout="bsnh"):
    bq, bk, interp = _resolve(q, causal, block_q, block_k, interpret,
                              layout)
    out, lse = _flash_fwd_impl(q, k, v, causal=causal, block_q=bq,
                               block_k=bk, sm_scale=sm_scale,
                               interpret=interp, layout=layout)
    return out, (q, k, v, out, lse)


def _bwd(causal, block_q, block_k, sm_scale, interpret, layout, res, g):
    q, k, v, o, lse = res
    bq, bk, interp = _resolve(q, causal, block_q, block_k, interpret,
                              layout)
    return _flash_bwd_impl(q, k, v, o, lse, g, causal=causal, block_q=bq,
                           block_k=bk, sm_scale=sm_scale, interpret=interp,
                           layout=layout)


flash_attention.defvjp(_fwd, _bwd)


def tune_flash_blocks(B, S, N, H, dtype=jnp.bfloat16, causal=True,
                      candidates=(128, 256, 512), steps=3):
    """Thin shim over the autotune subsystem (ray_tpu.autotune): time
    fwd+bwd for each (block_q, block_k) candidate pair on the live
    backend, persist the winner to the shared autotune cache, and record
    it in _TUNED for subsequent block_q=None calls in this process.

    Returns ((block_q, block_k), best_seconds_per_step) —
    best_seconds_per_step is None when the answer came from a cache
    (process-local _TUNED or the persistent file) rather than a fresh
    sweep, preserving the original contract.
    """
    from ray_tpu.autotune import search as _search
    from ray_tpu.autotune.cache import attention_key, get_cache

    key = (jax.default_backend(), B, S, N, H, str(jnp.dtype(dtype)), causal)
    if key in _TUNED:
        return _TUNED[key], None
    ckey = attention_key(B, S, N, H, dtype, causal)
    cached = get_cache().lookup("flash_attention", ckey) is not None
    cands = [{"block_q": bq, "block_k": bk}
             for bq in candidates for bk in candidates
             if not (S % bq or S % bk or bq > S or bk > S)]
    rec = _search.tune("flash_attention", ckey, candidates=cands,
                       iters=steps) if cands else None
    if rec is None:
        best, best_t = _default_blocks(S, H), None
    else:
        cfg = rec.get("config") or {}
        best = (int(cfg.get("block_q", 0)) or _default_blocks(S, H)[0],
                int(cfg.get("block_k", 0)) or _default_blocks(S, H)[1])
        best_t = None if cached or rec.get("ms") is None \
            else rec["ms"] / 1e3
    _TUNED[key] = best
    return best, best_t
