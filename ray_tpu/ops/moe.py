"""Mixture-of-Experts with expert parallelism over the ``ep`` mesh axis.

The reference has no MoE/expert parallelism (SURVEY §2.4 lists it as a
must-build for the TPU framework).  This is the GShard/Switch recipe, which
is the idiomatic TPU formulation: instead of a hand-written ragged
all-to-all (the GPU/NCCL way), tokens are routed into a dense
capacity-bounded dispatch tensor and moved between data- and expert-sharded
layouts by two einsums.  When the expert dim carries the ``ep`` mesh axis,
XLA lowers those einsums to all-to-all collectives over ICI — the dispatch
is compiler-emitted, fused, and overlappable, with no runtime library.

Shapes (S = tokens per batch row, E = experts, C = per-expert capacity):
  router logits  [B, S, E]        (f32 for a stable softmax)
  dispatch       [B, S, E, C]     0/1, token -> (expert, slot)
  combine        [B, S, E, C]     gate-weighted dispatch
  expert input   [E, B, C, D]     = einsum(x, dispatch)   <- all-to-all
  expert output  [E, B, C, D]     FFN per expert
  result         [B, S, D]        = einsum(ye, combine)   <- all-to-all back

Tokens beyond an expert's capacity are dropped (their combine weight is 0 and
the residual connection carries them through unchanged) — standard Switch
behavior; raise ``capacity_factor`` to trade memory for fewer drops.
"""

from __future__ import annotations

from typing import Callable, Optional, Tuple

import jax
import jax.numpy as jnp


def moe_router(x, router_w, *, top_k: int, capacity: int):
    """Top-k routing with per-expert capacity.

    x [B,S,D] (any float dtype), router_w [D,E] (f32).
    Returns (dispatch [B,S,E,C] bool-ish, combine [B,S,E,C], aux_loss scalar).
    """
    B, S, D = x.shape
    E = router_w.shape[-1]
    C = capacity

    logits = jnp.einsum("bsd,de->bse", x.astype(jnp.float32), router_w)
    probs = jax.nn.softmax(logits, axis=-1)                      # [B,S,E]
    gate_vals, gate_idx = jax.lax.top_k(probs, top_k)            # [B,S,k]
    # Renormalize the selected gates so the combine weights sum to 1.
    gate_vals = gate_vals / jnp.maximum(
        jnp.sum(gate_vals, axis=-1, keepdims=True), 1e-9)

    # Sequentially assign slots: k=0 choices get priority, then k=1, ...
    # (matches t5x/GShard ordering so top-1 picks are never bumped by
    # someone's secondary expert).
    counts = jnp.zeros((B, E), jnp.int32)
    dispatch = jnp.zeros((B, S, E, C), x.dtype)
    combine = jnp.zeros((B, S, E, C), jnp.float32)
    for i in range(top_k):
        oh = jax.nn.one_hot(gate_idx[:, :, i], E, dtype=jnp.int32)  # [B,S,E]
        pos = jnp.cumsum(oh, axis=1) - 1 + counts[:, None, :]       # [B,S,E]
        counts = counts + jnp.sum(oh, axis=1)
        within = (pos < C) & (oh > 0)                               # [B,S,E]
        slot = jax.nn.one_hot(jnp.clip(pos, 0, C - 1), C,
                              dtype=jnp.float32)                    # [B,S,E,C]
        sel = within.astype(jnp.float32)[..., None] * slot
        dispatch = dispatch + sel.astype(x.dtype)
        combine = combine + gate_vals[:, :, i, None, None] * sel

    # Switch load-balance loss: E * sum_e fraction_dispatched_e * mean_prob_e
    # (computed on top-1 assignments; differentiable through probs).
    top1 = jax.nn.one_hot(gate_idx[:, :, 0], E, dtype=jnp.float32)
    frac = jnp.mean(top1, axis=(0, 1))                              # [E]
    mean_prob = jnp.mean(probs, axis=(0, 1))                        # [E]
    aux = E * jnp.sum(frac * mean_prob)
    return dispatch, combine, aux


def moe_mlp(x, p, *, top_k: int, capacity_factor: float,
            lc: Optional[Callable] = None,
            ep_axis: Optional[str] = None) -> Tuple[jax.Array, jax.Array]:
    """Expert-parallel FFN (drop-in for the dense MLP body of a block).

    x [B,S,D]; p = {"router": [D,E] f32, "wi": [E,D,M], "bi": [E,M],
    "wo": [E,M,D], "bo": [E,D]} (expert dim carries the "expert" logical
    axis -> ep mesh axis).  ``lc(array, logical_axes)`` applies sharding
    constraints (identity when running unsharded / inside shard_map).

    Two expert-parallel modes:
      * GSPMD (default): expert weights/activations carry the "expert"
        logical axis; XLA emits the dispatch all-to-alls.
      * shard_map (``ep_axis`` set): weights arrive PRE-SHARDED on their
        leading expert dim ([E/ep, ...]); each ep member runs its local
        experts on the (ep-replicated) token batch and an all_gather over
        ``ep_axis`` reassembles expert outputs.  This is how MoE composes
        inside manually-mapped programs like the GPipe pipeline, where
        GSPMD constraints don't apply.
    Returns (y [B,S,D], aux_loss).
    """
    if lc is None:
        lc = lambda a, ax: a  # noqa: E731
    B, S, D = x.shape
    E = p["router"].shape[-1]
    dt = x.dtype
    capacity = max(1, int(capacity_factor * S * top_k / E))

    dispatch, combine, aux = moe_router(
        x, p["router"].astype(jnp.float32), top_k=top_k, capacity=capacity)

    # Data-sharded -> expert-sharded: XLA emits the all-to-all here.
    if ep_axis is not None:
        # Slice the dispatch tensor to this member's experts BEFORE the
        # contraction: 1/ep of the dispatch FLOPs and no full-E [E,B,C,D]
        # buffer per pipeline tick.
        e_local = p["wi"].shape[0]
        idx = jax.lax.axis_index(ep_axis)
        disp_local = jax.lax.dynamic_slice_in_dim(
            dispatch, idx * e_local, e_local, 2)
        xe = jnp.einsum("bsd,bsec->ebcd", x, disp_local.astype(dt))
    else:
        xe = jnp.einsum("bsd,bsec->ebcd", x, dispatch.astype(dt))
        xe = lc(xe, ("expert", "batch", None, "embed"))
    h = jnp.einsum("ebcd,edm->ebcm", xe, p["wi"].astype(dt)) \
        + p["bi"].astype(dt)[:, None, None, :]
    h = lc(h, ("expert", "batch", None, "mlp"))
    h = jax.nn.gelu(h)
    ye = jnp.einsum("ebcm,emd->ebcd", h, p["wo"].astype(dt)) \
        + p["bo"].astype(dt)[:, None, None, :]
    ye = lc(ye, ("expert", "batch", None, "embed"))
    if ep_axis is not None:
        ye = jax.lax.all_gather(ye, ep_axis, axis=0, tiled=True)
    # Expert-sharded -> data-sharded: the return all-to-all.
    y = jnp.einsum("ebcd,bsec->bsd", ye, combine.astype(dt))
    return lc(y, ("batch", "seq", "embed")), aux
