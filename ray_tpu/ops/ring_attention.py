"""Ring attention: causal attention with the sequence sharded over a mesh axis.

New capability vs. the reference (SURVEY §5.7 — Ray has *no* sequence/context
parallelism; sequence length is bounded by one GPU's memory).  Here each
device of the `sp` axis holds one contiguous sequence chunk of Q/K/V; K/V
chunks rotate around the ICI ring via `lax.ppermute` while every device
accumulates blockwise online-softmax partial results for its local queries.
Peak memory per device is O(S/sp), and with sp devices the compute/comm
pipeline overlaps (XLA schedules the ppermute DMA alongside the matmuls).

`ulysses_attention` is the all-to-all alternative (DeepSpeed-Ulysses layout):
reshuffle [seq-sharded, all heads] -> [all seq, head-sharded], run any dense
kernel per head group, and shuffle back.  Cheaper at moderate sequence
lengths; ring wins at very long context.

Both are written against a bare `axis_name`, so they run identically inside
`shard_map` on the CPU test mesh and on a real slice.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ray_tpu.util import jax_compat

jax_compat.install()

_NEG_INF = -1e30


def _block_update(q, k, v, m, l, o, mask):
    """One online-softmax accumulation step. q:[B,Sq,N,H] k,v:[B,Sk,N,H],
    mask:[Sq,Sk] bool or None; carries m,l:[B,N,Sq,1], o:[B,Sq,N,H]."""
    scale = 1.0 / np.sqrt(q.shape[-1])
    s = jnp.einsum("bqnh,bknh->bnqk", q, k).astype(jnp.float32) * scale
    if mask is not None:
        s = jnp.where(mask[None, None], s, _NEG_INF)
    m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
    p = jnp.exp(s - m_new)
    alpha = jnp.exp(m - m_new)  # [B,N,Sq,1]
    l_new = l * alpha + jnp.sum(p, axis=-1, keepdims=True)
    o_new = o * jnp.moveaxis(alpha, 1, 2) + jnp.moveaxis(
        jnp.einsum("bnqk,bknh->bnqh", p, v.astype(jnp.float32)), 1, 2)
    return m_new, l_new, o_new


def ring_attention_sharded(q, k, v, axis_name: str = "sp"):
    """Causal ring attention; call inside shard_map with seq sharded on
    `axis_name`.  q,k,v: per-device [B, S_local, N, H] chunks (chunk i holds
    positions [i*S_local, (i+1)*S_local))."""
    B, Sq, N, H = q.shape
    sp = lax.axis_size(axis_name)
    my = lax.axis_index(axis_name)

    m = jnp.full((B, N, Sq, 1), _NEG_INF, jnp.float32)
    l = jnp.zeros((B, N, Sq, 1), jnp.float32)
    o = jnp.zeros((B, Sq, N, H), jnp.float32)

    causal_mask = jnp.tril(jnp.ones((Sq, Sq), bool))
    ones_mask = jnp.ones((Sq, Sq), bool)
    zeros_mask = jnp.zeros((Sq, Sq), bool)

    def step(i, carry):
        m, l, o, k, v = carry
        # kv chunk currently held arrived from device (my - i) mod sp
        src = (my - i) % sp
        # causal relation of my q-chunk vs. this kv-chunk:
        #   src < my  -> full attention; src == my -> causal; src > my -> skip
        mask = jnp.where(
            src == my, causal_mask, jnp.where(src < my, ones_mask,
                                              zeros_mask))
        m, l, o = _block_update(q, k, v, m, l, o, mask)
        perm = [(d, (d + 1) % sp) for d in range(sp)]
        k = lax.ppermute(k, axis_name, perm)
        v = lax.ppermute(v, axis_name, perm)
        return m, l, o, k, v

    m, l, o, _, _ = lax.fori_loop(0, sp, step, (m, l, o, k, v))
    out = o / jnp.maximum(jnp.moveaxis(l, 1, 2), 1e-30)
    return out.astype(q.dtype)


def ring_attention(q, k, v, mesh, axis_name: str = "sp",
                   batch_axes=("dp", "fsdp"), head_axis: Optional[str] = "tp"):
    """Driver-side wrapper: shard_map `ring_attention_sharded` over `mesh`.

    q,k,v: global [B, S, N, H].  Sequence is sharded over `axis_name`, batch
    over `batch_axes`, heads over `head_axis`.
    """
    from jax.sharding import PartitionSpec as P

    spec = P(tuple(batch_axes), axis_name, head_axis, None)
    fn = _shard_map(
        functools.partial(ring_attention_sharded, axis_name=axis_name),
        mesh, (spec, spec, spec), spec)
    return fn(q, k, v)


def _shard_map(f, mesh, in_specs, out_specs):
    """jax.shard_map (stable API, check_vma) with a fallback to the
    pre-graduation jax.experimental.shard_map (check_rep) so ring/Ulysses
    run on both sides of the rename."""
    if hasattr(jax, "shard_map"):
        return jax.shard_map(f, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_vma=False)
    from jax.experimental.shard_map import shard_map as _sm
    return _sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
               check_rep=False)


def ulysses_attention(q, k, v, axis_name: str = "sp", causal: bool = True):
    """All-to-all (DeepSpeed-Ulysses) attention; call inside shard_map.

    In: per-device [B, S/sp, N, H] (seq sharded).  all_to_all to
    [B, S, N/sp, H] (heads sharded), dense attention locally, all_to_all
    back.  Requires N % sp == 0.
    """
    sp = lax.axis_size(axis_name)
    # [B, S/sp, N, H] -> heads sharded, seq gathered
    qh = lax.all_to_all(q, axis_name, split_axis=2, concat_axis=1, tiled=True)
    kh = lax.all_to_all(k, axis_name, split_axis=2, concat_axis=1, tiled=True)
    vh = lax.all_to_all(v, axis_name, split_axis=2, concat_axis=1, tiled=True)
    scale = 1.0 / np.sqrt(q.shape[-1])
    s = jnp.einsum("bqnh,bknh->bnqk", qh, kh).astype(jnp.float32) * scale
    if causal:
        S = qh.shape[1]
        mask = jnp.tril(jnp.ones((S, S), bool))
        s = jnp.where(mask[None, None], s, _NEG_INF)
    p = jax.nn.softmax(s, axis=-1).astype(qh.dtype)
    oh = jnp.einsum("bnqk,bknh->bqnh", p, vh)
    return lax.all_to_all(oh, axis_name, split_axis=1, concat_axis=2,
                          tiled=True)


def make_ring_attention_fn(mesh, axis_name: str = "sp",
                           batch_axes=("dp", "fsdp"),
                           head_axis: Optional[str] = "tp"):
    """Autotune-dispatch hook: close over the mesh/axis topology once and
    return an `(q, k, v) -> o` callable with the plain attention
    signature the dispatcher (ray_tpu.autotune.dispatch) and the timing
    harness expect.  Raises ValueError up front when the mesh cannot
    carry a ring (no `axis_name` axis, or size 1 — a 1-wide ring is just
    dense attention with extra collectives)."""
    sizes = dict(zip(mesh.axis_names, mesh.devices.shape))
    sp = sizes.get(axis_name, 1)
    if sp <= 1:
        raise ValueError(
            f"ring attention needs mesh axis {axis_name!r} with size > 1 "
            f"(got {sizes})")

    def fn(q, k, v):
        return ring_attention(q, k, v, mesh, axis_name=axis_name,
                              batch_axes=batch_axes, head_axis=head_axis)
    return fn
