"""@ray_tpu.remote functions.

Design analog: reference ``python/ray/remote_function.py`` (RemoteFunction,
``_remote:241``) and option plumbing (``_private/ray_option_utils.py``).
"""

from __future__ import annotations

import functools
from typing import Any, Dict, Optional

from ray_tpu._private.worker import get_core

_DEFAULTS = dict(
    num_returns=1,
    num_cpus=1.0,
    num_tpus=0.0,
    resources=None,
    max_retries=None,   # None -> config().task_max_retries
    retry_exceptions=False,
    scheduling_strategy=None,
    runtime_env=None,
    accelerator_type=None,
    name=None,
)


def _build_resources(opts: Dict[str, Any]) -> Dict[str, float]:
    res = dict(opts.get("resources") or {})
    if opts.get("num_cpus") is not None:
        res["CPU"] = float(opts["num_cpus"])
    if opts.get("num_tpus"):
        res["TPU"] = float(opts["num_tpus"])
    if opts.get("accelerator_type"):
        # Constrain placement to nodes advertising this TPU generation
        # (reference: @ray.remote(accelerator_type=...)).
        res[f"accelerator_type:{opts['accelerator_type']}"] = 0.001
    return res


def _build_scheduling(opts: Dict[str, Any]) -> Dict[str, Any]:
    strategy = opts.get("scheduling_strategy")
    from ray_tpu.util.scheduling_strategies import (
        NodeAffinitySchedulingStrategy,
        PlacementGroupSchedulingStrategy,
    )
    out: Dict[str, Any] = {}
    if isinstance(strategy, PlacementGroupSchedulingStrategy):
        out = {
            "placement_group_id": strategy.placement_group.id.hex(),
            "bundle_index": strategy.placement_group_bundle_index,
        }
    elif isinstance(strategy, NodeAffinitySchedulingStrategy):
        out = {"node_id": strategy.node_id, "soft": strategy.soft}
    elif isinstance(strategy, str):
        out = {"strategy": strategy}
    renv = opts.get("runtime_env")
    if renv:
        from ray_tpu.runtime_env import env_hash, normalize_runtime_env
        norm = normalize_runtime_env(renv)
        if norm:
            out["runtime_env"] = norm
            out["env_key"] = env_hash(norm)
    return out


class RemoteFunction:
    def __init__(self, func, options: Optional[Dict[str, Any]] = None):
        self._function = func
        self._options = {**_DEFAULTS, **(options or {})}
        functools.update_wrapper(self, func)

    def __call__(self, *args, **kwargs):
        raise TypeError(
            f"remote function '{self._function.__name__}' cannot be called "
            f"directly; use {self._function.__name__}.remote()")

    def options(self, **kwargs) -> "RemoteFunction":
        return RemoteFunction(self._function, {**self._options, **kwargs})

    def bind(self, *args, **kwargs):
        """Lazy DAG node for this call (reference dag_node build surface:
        remote_function.py bind -> FunctionNode)."""
        from ray_tpu.dag import FunctionNode
        return FunctionNode(self, args, kwargs)

    def remote(self, *args, **kwargs):
        core = get_core()
        opts = self._options
        refs = core.submit_task(
            self._function, args, kwargs,
            num_returns=opts["num_returns"],
            resources=_build_resources(opts),
            max_retries=opts["max_retries"],
            retry_exceptions=opts["retry_exceptions"],
            scheduling=_build_scheduling(opts),
            name=opts["name"] or self._function.__name__,
        )
        if opts["num_returns"] in (1, "dynamic", "streaming"):
            return refs[0]
        return refs

    @property
    def func(self):
        return self._function
