"""Dataset creation APIs.

Design analog: reference ``python/ray/data/read_api.py`` (range:80,
from_items, read_parquet/csv/json via datasource classes at
read_datasource:235).  File reads fan out one task per file.
"""

from __future__ import annotations

import builtins
from typing import Any, List, Optional

import numpy as np

import ray_tpu
from ray_tpu.data.block import BlockMetadata
from ray_tpu.data.dataset import Dataset


def _put_blocks(blocks: List[Any]) -> Dataset:
    refs = [ray_tpu.put(b) for b in blocks]
    return Dataset(refs, [BlockMetadata.for_block(b) for b in blocks])


def _split_seq(seq, parallelism):
    n = len(seq)
    parallelism = max(1, min(parallelism, n or 1))
    per = n // parallelism
    extra = n % parallelism
    out, i = [], 0
    for p in builtins.range(parallelism):
        take = per + (1 if p < extra else 0)
        out.append(seq[i:i + take])
        i += take
    return out


def from_items(items: List[Any], *, parallelism: int = 16) -> Dataset:
    return _put_blocks(_split_seq(list(items), parallelism))


def range(n: int, *, parallelism: int = 16) -> Dataset:  # noqa: A001
    return from_items(list(builtins.range(n)), parallelism=parallelism)


def range_tensor(n: int, *, shape=(1,), parallelism: int = 16) -> Dataset:
    splits = _split_seq(np.arange(n), parallelism)
    blocks = []
    for s in splits:
        data = np.broadcast_to(
            s.reshape((len(s),) + (1,) * len(shape)),
            (len(s),) + tuple(shape)).copy()
        blocks.append({"data": data})
    return _put_blocks(blocks)


def from_numpy(arr: np.ndarray, *, parallelism: int = 16) -> Dataset:
    chunks = np.array_split(arr, max(1, min(parallelism, len(arr) or 1)))
    return _put_blocks([{"data": c} for c in chunks if len(c)])


def from_pandas(df, *, parallelism: int = 4) -> Dataset:
    n = len(df)
    parallelism = max(1, min(parallelism, n or 1))
    bounds = np.linspace(0, n, parallelism + 1, dtype=int)
    blocks = []
    for a, b in builtins.zip(bounds[:-1], bounds[1:]):
        part = df.iloc[a:b]
        blocks.append({c: part[c].to_numpy() for c in part.columns})
    return _put_blocks(blocks)


# -- file readers (one task per file) -------------------------------------

def _expand_paths(paths, suffix: Optional[str] = None) -> List[str]:
    import os
    if isinstance(paths, str):
        paths = [paths]
    out = []
    for p in paths:
        if os.path.isdir(p):
            out.extend(sorted(
                os.path.join(p, f) for f in os.listdir(p)
                if suffix is None or f.endswith(suffix)))
        else:
            out.append(p)
    if not out:
        raise FileNotFoundError(f"no input files under {paths}")
    return out


def _read_csv_file(path):
    import pandas as pd
    df = pd.read_csv(path)
    return {c: df[c].to_numpy() for c in df.columns}


def _read_json_file(path):
    import pandas as pd
    df = pd.read_json(path, orient="records", lines=True)
    return {c: df[c].to_numpy() for c in df.columns}


def _read_parquet_file(path):
    # Native arrow block: no pandas round-trip, zero-copy column slicing
    # downstream (reference: parquet datasource yields Arrow tables).
    import pyarrow.parquet as pq
    return pq.read_table(path)


def _read_numpy_file(path):
    return {"data": np.load(path)}


def _read_text_file(path):
    with open(path) as f:
        return [line.rstrip("\n") for line in f]


def _read_files(paths, reader, suffix) -> Dataset:
    files = _expand_paths(paths, suffix)
    task = ray_tpu.remote(reader)
    return Dataset([task.remote(f) for f in files])


def read_csv(paths, **_) -> Dataset:
    return _read_files(paths, _read_csv_file, ".csv")


def read_json(paths, **_) -> Dataset:
    return _read_files(paths, _read_json_file, ".json")


def read_parquet(paths, **_) -> Dataset:
    return _read_files(paths, _read_parquet_file, ".parquet")


def read_numpy(paths, **_) -> Dataset:
    return _read_files(paths, _read_numpy_file, ".npy")


def read_text(paths, **_) -> Dataset:
    return _read_files(paths, _read_text_file, None)


def _read_binary_file(path, include_paths):
    with open(path, "rb") as f:
        data = f.read()
    return [{"path": path, "bytes": data} if include_paths
            else {"bytes": data}]


def read_binary_files(paths, *, include_paths: bool = False,
                      **_) -> Dataset:
    """One row per file with its raw bytes (reference:
    ``ray.data.read_binary_files``)."""
    files = _expand_paths(paths, None)
    task = ray_tpu.remote(_read_binary_file)
    return Dataset([task.remote(f, include_paths) for f in files])


def from_arrow(tables, *, parallelism: int = 0) -> Dataset:
    """Dataset from pyarrow Table(s) (reference: ray.data.from_arrow).

    Default: one block per table.  ``parallelism`` > number of tables
    re-slices them (zero-copy) into ~parallelism blocks."""
    import pyarrow as pa
    if isinstance(tables, pa.Table):
        tables = [tables]
    tables = list(tables)
    if parallelism > len(tables):
        per = max(1, parallelism // max(1, len(tables)))
        out = []
        for t in tables:
            rows = t.num_rows
            bounds = np.linspace(0, rows, per + 1, dtype=int)
            out.extend(t.slice(a, b - a)
                       for a, b in builtins.zip(bounds[:-1], bounds[1:])
                       if b > a)
        tables = out or tables
    return Dataset([ray_tpu.put(t) for t in tables])
