"""Distributed datasets (Ray Data equivalent).

Design analog: reference ``python/ray/data/`` -- Dataset (dataset.py:146),
blocks as objects in the shared store (block.py), lazy-free eager stage
execution as remote tasks (_internal/compute.py TaskPoolStrategy /
ActorPoolStrategy), read_api.py datasources, DatasetPipeline
(dataset_pipeline.py:64).  TPU-first: ``iter_batches`` yields host numpy
ready for device put, and ``split`` aligns shards with a train worker gang.
"""

from ray_tpu.data.dataset import (
    ActorPoolStrategy,
    AggregateFn,
    Count,
    Dataset,
    GroupedData,
    Max,
    Mean,
    Min,
    Std,
    Sum,
)
from ray_tpu.data.dataset_pipeline import DatasetPipeline
from ray_tpu.data.read_api import (
    from_arrow,
    from_items,
    from_numpy,
    from_pandas,
    range,  # noqa: A001 - mirrors reference API name
    range_tensor,
    read_binary_files,
    read_csv,
    read_json,
    read_numpy,
    read_parquet,
    read_text,
)

from ray_tpu.data.push_shuffle import RandomAccessDataset

__all__ = [
    "ActorPoolStrategy", "AggregateFn", "Count", "Dataset", "DatasetPipeline",
    "GroupedData", "Max", "Mean", "Min", "RandomAccessDataset", "Std", "Sum",
    "from_arrow", "from_items", "from_numpy", "from_pandas", "range",
    "range_tensor",
    "read_binary_files", "read_csv", "read_json", "read_numpy", "read_parquet", "read_text",
]

from ray_tpu._private.usage_stats import record_library_usage as _rlu
_rlu("data")
del _rlu
