"""DatasetPipeline: windowed, optionally repeating execution.

Design analog: reference ``python/ray/data/dataset_pipeline.py:64`` --
a pipeline is a sequence of windows (small Datasets); per-window transforms
run while downstream consumes earlier windows, overlapping ingest with
compute (the host->TPU input pipelining pattern, SURVEY.md §7 hard part (d)).
"""

from __future__ import annotations

from typing import Any, Callable, Iterator, List, Optional

from ray_tpu.data.dataset import Dataset


class DatasetPipeline:
    def __init__(self, windows: List[Dataset],
                 stages: Optional[List[Callable[[Dataset], Dataset]]] = None,
                 repeat: Optional[int] = 1):
        self._windows = windows
        self._stages = list(stages or [])
        self._repeat = repeat

    @classmethod
    def from_dataset(cls, ds: Dataset, blocks_per_window: int,
                     repeat: Optional[int] = 1) -> "DatasetPipeline":
        windows = []
        refs = ds._blocks
        for i in range(0, len(refs), blocks_per_window):
            windows.append(Dataset(refs[i:i + blocks_per_window]))
        return cls(windows or [Dataset([])], repeat=repeat)

    def _with_stage(self, stage) -> "DatasetPipeline":
        return DatasetPipeline(self._windows, self._stages + [stage],
                               self._repeat)

    def map(self, fn, **kw):
        return self._with_stage(lambda ds: ds.map(fn, **kw))

    def map_batches(self, fn, **kw):
        return self._with_stage(lambda ds: ds.map_batches(fn, **kw))

    def filter(self, fn, **kw):
        return self._with_stage(lambda ds: ds.filter(fn, **kw))

    def flat_map(self, fn, **kw):
        return self._with_stage(lambda ds: ds.flat_map(fn, **kw))

    def random_shuffle_each_window(self, **kw):
        return self._with_stage(lambda ds: ds.random_shuffle(**kw))

    def repeat(self, times: Optional[int] = None) -> "DatasetPipeline":
        return DatasetPipeline(self._windows, self._stages, times)

    def iter_windows(self) -> Iterator[Dataset]:
        """Apply stages lazily; launch window k+1's tasks before consuming
        window k so stage execution overlaps consumption."""
        epoch = 0
        while self._repeat is None or epoch < self._repeat:
            pending: Optional[Dataset] = None
            for w in self._windows:
                nxt = w
                for stage in self._stages:
                    nxt = stage(nxt)  # tasks launch eagerly
                if pending is not None:
                    yield pending
                pending = nxt
            if pending is not None:
                yield pending
            epoch += 1

    def iter_rows(self) -> Iterator[Any]:
        for w in self.iter_windows():
            yield from w.iter_rows()

    def iter_batches(self, **kw) -> Iterator[Any]:
        for w in self.iter_windows():
            yield from w.iter_batches(**kw)

    def take(self, n: int = 20) -> List[Any]:
        out = []
        for row in self.iter_rows():
            out.append(row)
            if len(out) >= n:
                break
        return out

    def __repr__(self):
        return (f"DatasetPipeline(windows={len(self._windows)}, "
                f"stages={len(self._stages)}, repeat={self._repeat})")
