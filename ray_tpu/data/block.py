"""Blocks: the unit of distributed data.

Design analog: reference ``python/ray/data/block.py`` (Block = Arrow table /
pandas / simple list partition, BlockMetadata, BlockAccessor).  Three block
forms, normalized by BlockAccessor:

  * ``pyarrow.Table``   — the columnar workhorse (zero-copy slice/take,
    native sort, cheap size accounting); what readers and shuffles produce.
  * dict of numpy arrays — tensor blocks for numeric batch pipelines.
  * list of rows        — fallback for arbitrary Python objects.

Arrow tables serialize through the object store via the pickle-5 buffer
protocol, so a block slice/transfer never copies through Python row lists
(VERDICT r2 missing #6: columnar data plane).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict, List, Optional

import numpy as np


def _is_arrow(block) -> bool:
    try:
        import pyarrow as pa
    except ImportError:
        return False
    return isinstance(block, pa.Table)


@dataclass
class BlockMetadata:
    num_rows: int
    size_bytes: int
    schema: Optional[Any] = None

    @staticmethod
    def for_block(block) -> "BlockMetadata":
        acc = BlockAccessor(block)
        return BlockMetadata(num_rows=acc.num_rows(),
                             size_bytes=acc.size_bytes(),
                             schema=acc.schema())


class BlockAccessor:
    """Uniform view over arrow-table, column-dict, and row-list blocks."""

    def __init__(self, block):
        self._block = block
        self._is_arrow = _is_arrow(block)
        self._is_columnar = (not self._is_arrow) and isinstance(block, dict)

    def num_rows(self) -> int:
        if self._is_arrow:
            return self._block.num_rows
        if self._is_columnar:
            if not self._block:
                return 0
            return len(next(iter(self._block.values())))
        return len(self._block)

    def size_bytes(self) -> int:
        if self._is_arrow:
            return int(self._block.nbytes)
        if self._is_columnar:
            return int(sum(np.asarray(v).nbytes
                           for v in self._block.values()))
        try:
            import sys
            return sum(sys.getsizeof(r) for r in self._block[:64]) * \
                max(1, len(self._block) // max(1, len(self._block[:64])))
        except Exception:
            return 0

    def schema(self):
        if self._is_arrow:
            return {f.name: str(f.type) for f in self._block.schema}
        if self._is_columnar:
            return {k: str(np.asarray(v).dtype)
                    for k, v in self._block.items()}
        if self._block and isinstance(self._block[0], dict):
            return sorted(self._block[0].keys())
        return type(self._block[0]).__name__ if self._block else None

    def rows(self) -> List[Any]:
        if self._is_arrow:
            return self._block.to_pylist()
        if self._is_columnar:
            keys = list(self._block.keys())
            n = self.num_rows()
            return [{k: self._block[k][i] for k in keys}
                    for i in range(n)]
        return list(self._block)

    def slice(self, start: int, end: int):
        if self._is_arrow:
            return self._block.slice(start, end - start)  # zero-copy
        if self._is_columnar:
            return {k: v[start:end] for k, v in self._block.items()}
        return self._block[start:end]

    def take(self, indices) -> Any:
        """Row gather by integer indices, preserving the block form."""
        if self._is_arrow:
            return self._block.take(np.asarray(indices, np.int64))
        if self._is_columnar:
            idx = np.asarray(indices, np.int64)
            return {k: np.asarray(v)[idx] for k, v in self._block.items()}
        return [self._block[int(i)] for i in indices]

    def to_numpy_batch(self) -> Dict[str, np.ndarray]:
        """Batch form handed to map_batches(batch_format='numpy')."""
        if self._is_arrow:
            return {name: col.to_numpy(zero_copy_only=False)
                    for name, col in zip(self._block.column_names,
                                         self._block.columns)}
        if self._is_columnar:
            return {k: np.asarray(v) for k, v in self._block.items()}
        if self._block and isinstance(self._block[0], dict):
            keys = self._block[0].keys()
            return {k: np.asarray([r[k] for r in self._block])
                    for k in keys}
        return {"value": np.asarray(self._block)}

    def to_arrow(self):
        """Convert any block form to a pyarrow.Table."""
        import pyarrow as pa
        if self._is_arrow:
            return self._block
        if self._is_columnar:
            return pa.table({k: np.asarray(v)
                             for k, v in self._block.items()})
        if self._block and isinstance(self._block[0], dict):
            return pa.Table.from_pylist(self._block)
        return pa.table({"value": list(self._block)})

    def to_pandas(self):
        import pandas as pd
        if self._is_arrow:
            return self._block.to_pandas()
        if self._is_columnar:
            return pd.DataFrame(
                {k: list(v) for k, v in self._block.items()})
        if self._block and isinstance(self._block[0], dict):
            return pd.DataFrame(self._block)
        return pd.DataFrame({"value": self._block})


def batch_to_block(batch) -> Any:
    """Normalize a map_batches return value into a block."""
    import pandas as pd
    if _is_arrow(batch):
        return batch
    if isinstance(batch, dict):
        return {k: np.asarray(v) for k, v in batch.items()}
    if isinstance(batch, pd.DataFrame):
        return {c: batch[c].to_numpy() for c in batch.columns}
    if isinstance(batch, np.ndarray):
        return {"value": batch}
    if isinstance(batch, list):
        return batch
    raise TypeError(f"map_batches fn returned unsupported type "
                    f"{type(batch)} (want dict/ndarray/DataFrame/"
                    f"pyarrow.Table/list)")
