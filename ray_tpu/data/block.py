"""Blocks: the unit of distributed data.

Design analog: reference ``python/ray/data/block.py`` (Block = Arrow table /
pandas / simple list partition, BlockMetadata, BlockAccessor).  A block here
is a list of rows (dicts or scalars) or a dict of numpy column arrays;
BlockAccessor normalizes between formats.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict, List, Optional

import numpy as np


@dataclass
class BlockMetadata:
    num_rows: int
    size_bytes: int
    schema: Optional[Any] = None

    @staticmethod
    def for_block(block) -> "BlockMetadata":
        acc = BlockAccessor(block)
        return BlockMetadata(num_rows=acc.num_rows(),
                             size_bytes=acc.size_bytes(),
                             schema=acc.schema())


class BlockAccessor:
    """Uniform view over list-blocks and column-dict (tensor) blocks."""

    def __init__(self, block):
        self._block = block
        self._is_columnar = isinstance(block, dict)

    def num_rows(self) -> int:
        if self._is_columnar:
            if not self._block:
                return 0
            return len(next(iter(self._block.values())))
        return len(self._block)

    def size_bytes(self) -> int:
        if self._is_columnar:
            return int(sum(np.asarray(v).nbytes
                           for v in self._block.values()))
        try:
            import sys
            return sum(sys.getsizeof(r) for r in self._block[:64]) * \
                max(1, len(self._block) // max(1, len(self._block[:64])))
        except Exception:
            return 0

    def schema(self):
        if self._is_columnar:
            return {k: str(np.asarray(v).dtype)
                    for k, v in self._block.items()}
        if self._block and isinstance(self._block[0], dict):
            return sorted(self._block[0].keys())
        return type(self._block[0]).__name__ if self._block else None

    def rows(self) -> List[Any]:
        if self._is_columnar:
            keys = list(self._block.keys())
            n = self.num_rows()
            return [{k: self._block[k][i] for k in keys}
                    for i in range(n)]
        return list(self._block)

    def slice(self, start: int, end: int):
        if self._is_columnar:
            return {k: v[start:end] for k, v in self._block.items()}
        return self._block[start:end]

    def to_numpy_batch(self) -> Dict[str, np.ndarray]:
        """Batch form handed to map_batches(batch_format='numpy')."""
        if self._is_columnar:
            return {k: np.asarray(v) for k, v in self._block.items()}
        if self._block and isinstance(self._block[0], dict):
            keys = self._block[0].keys()
            return {k: np.asarray([r[k] for r in self._block])
                    for k in keys}
        return {"value": np.asarray(self._block)}

    def to_pandas(self):
        import pandas as pd
        if self._is_columnar:
            return pd.DataFrame(
                {k: list(v) for k, v in self._block.items()})
        if self._block and isinstance(self._block[0], dict):
            return pd.DataFrame(self._block)
        return pd.DataFrame({"value": self._block})


def batch_to_block(batch) -> Any:
    """Normalize a map_batches return value into a block."""
    import pandas as pd
    if isinstance(batch, dict):
        return {k: np.asarray(v) for k, v in batch.items()}
    if isinstance(batch, pd.DataFrame):
        return {c: batch[c].to_numpy() for c in batch.columns}
    if isinstance(batch, np.ndarray):
        return {"value": batch}
    if isinstance(batch, list):
        return batch
    raise TypeError(f"map_batches fn returned unsupported type "
                    f"{type(batch)} (want dict/ndarray/DataFrame/list)")
